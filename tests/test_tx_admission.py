"""Device-batched CheckTx admission (ISSUE 11): the signed-tx envelope,
the RequestCheckTx.sig_precheck ABCI split, the mempool's admission-lane
precheck (single and gossip-batch paths), and the end-to-end proof that a
signed flood admits through the scheduler with the app consuming verdicts
instead of paying serial verifies."""

from __future__ import annotations

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci import wire
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.abci.kvstore import SignedKVStoreApplication
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.crypto.scheduler import VerifyScheduler
from tendermint_tpu.mempool.mempool import Mempool, TxTooLargeError
from tendermint_tpu.types import signed_tx as stx

PRIV = gen_ed25519(b"\x2a" * 32)


def make_mp(app=None, **kw):
    app = app or SignedKVStoreApplication()
    sched = VerifyScheduler(backend="cpu")
    mp = Mempool(LocalClient(app), scheduler=sched, sig_precheck=True, **kw)
    return mp, app, sched


# -- envelope ------------------------------------------------------------------


def test_signed_tx_roundtrip_and_tamper():
    tx = stx.encode_signed_tx(PRIV, b"hello=world")
    env = stx.decode_signed_tx(tx)
    assert env is not None
    assert env.pubkey == PRIV.pub_key().bytes()
    assert env.payload == b"hello=world"
    assert stx.verify_signed_tx(env)
    # tampered payload fails (domain-separated sign bytes)
    bad = stx.decode_signed_tx(tx[:-1] + b"!")
    assert bad is not None and not stx.verify_signed_tx(bad)
    # tampered signature fails
    t2 = bytearray(tx)
    t2[40] ^= 0xFF
    assert not stx.verify_signed_tx(stx.decode_signed_tx(bytes(t2)))
    # non-envelopes decode to None
    assert stx.decode_signed_tx(b"plain=1") is None
    assert stx.decode_signed_tx(b"") is None
    assert stx.decode_signed_tx(stx.MAGIC + b"short") is None


def test_signed_tx_signature_is_domain_separated():
    """A signed-tx signature must not verify over the raw payload (and vice
    versa) — the envelope can never replay a consensus signature."""
    tx = stx.encode_signed_tx(PRIV, b"payload")
    env = stx.decode_signed_tx(tx)
    from tendermint_tpu.crypto.keys import Ed25519PubKey

    assert not Ed25519PubKey(env.pubkey).verify(b"payload", env.signature)


def test_sig_precheck_wire_roundtrip():
    req = abci.RequestCheckTx(tx=b"abc", sig_precheck=abci.SIG_PRECHECK_BAD)
    enc = wire.encode_msg(req)
    dec = wire.decode_msg(abci.RequestCheckTx, enc)
    assert dec.tx == b"abc" and dec.sig_precheck == abci.SIG_PRECHECK_BAD
    # default NONE survives (proto3 zero default)
    dec2 = wire.decode_msg(abci.RequestCheckTx,
                           wire.encode_msg(abci.RequestCheckTx(tx=b"x")))
    assert dec2.sig_precheck == abci.SIG_PRECHECK_NONE


# -- mempool precheck ----------------------------------------------------------


def test_precheck_verdict_consumed_by_app():
    mp, app, sched = make_mp()
    try:
        res = mp.check_tx(stx.encode_signed_tx(PRIV, b"k=v"))
        assert res.code == abci.CODE_TYPE_OK
        assert app.precheck_consumed == 1 and app.serial_verifies == 0
        assert mp.prechecked_total == 1
        assert sched.stats()["lanes"]["admission"]["rows_total"] == 1
    finally:
        sched.close()


def test_precheck_bad_signature_rejected_without_serial_verify():
    mp, app, sched = make_mp()
    try:
        tx = bytearray(stx.encode_signed_tx(PRIV, b"k=v"))
        tx[40] ^= 0xFF  # corrupt the signature
        res = mp.check_tx(bytes(tx))
        assert res.code == SignedKVStoreApplication.CODE_BAD_SIGNATURE
        assert app.serial_verifies == 0  # verdict consumed, not recomputed
        assert mp.size() == 0
    finally:
        sched.close()


def test_plain_and_oversized_txs_skip_the_lane():
    mp, app, sched = make_mp(max_tx_bytes=256)
    try:
        # non-envelope: no lane row, app sees NONE (and rejects the format)
        res = mp.check_tx(b"plain=1")
        assert res.code == SignedKVStoreApplication.CODE_BAD_ENVELOPE
        # oversized: rejected before any signature work
        with pytest.raises(TxTooLargeError):
            mp.check_tx(stx.encode_signed_tx(PRIV, b"x" * 500))
        assert sched.stats()["lanes"]["admission"]["rows_total"] == 0
    finally:
        sched.close()


def test_duplicate_resident_tx_pays_no_second_verify():
    mp, app, sched = make_mp()
    try:
        tx = stx.encode_signed_tx(PRIV, b"dup=1")
        assert mp.check_tx(tx).code == abci.CODE_TYPE_OK
        rows0 = sched.stats()["lanes"]["admission"]["rows_total"]
        # duplicate via gossip: cache peek skips the device row entirely
        assert mp.check_tx(tx, sender="peerA") is None
        assert sched.stats()["lanes"]["admission"]["rows_total"] == rows0
    finally:
        sched.close()


def test_check_tx_batch_single_lane_submit():
    """The gossip-reactor path: N txs -> ONE admission-lane submit, each tx
    still individually admitted/rejected."""
    mp, app, sched = make_mp()
    try:
        txs = [stx.encode_signed_tx(PRIV, b"b=%d" % i) for i in range(8)]
        bad = bytearray(txs[3])
        bad[40] ^= 0xFF
        txs[3] = bytes(bad)
        out = mp.check_tx_batch(txs, sender="peerB")
        codes = [r.code if r is not None else None for r in out]
        assert codes[3] == SignedKVStoreApplication.CODE_BAD_SIGNATURE
        assert all(c == abci.CODE_TYPE_OK for i, c in enumerate(codes) if i != 3)
        assert mp.size() == 7
        # one submit covered the whole batch
        lane = sched.stats()["lanes"]["admission"]
        assert lane["rows_total"] == 8
        adm = [f for f in list(sched.flush_log) if "admission" in f["rows"]]
        assert len(adm) == 1 and adm[0]["rows"]["admission"] == 8
        assert app.serial_verifies == 0
    finally:
        sched.close()


def test_precheck_degrades_to_app_verify_without_scheduler():
    app = SignedKVStoreApplication()
    mp = Mempool(LocalClient(app))  # no scheduler wired
    assert not mp.sig_precheck
    assert mp.check_tx(stx.encode_signed_tx(PRIV, b"k=v")).code == abci.CODE_TYPE_OK
    assert app.serial_verifies == 1 and app.precheck_consumed == 0


def test_precheck_survives_broken_scheduler():
    """A scheduler that raises must degrade to NONE verdicts (the app
    verifies), never lose txs."""

    class Broken:
        closed = False

        def verify_rows(self, *a, **kw):
            raise RuntimeError("device on fire")

    app = SignedKVStoreApplication()
    mp = Mempool(LocalClient(app), scheduler=Broken(), sig_precheck=True)
    res = mp.check_tx(stx.encode_signed_tx(PRIV, b"k=v"))
    assert res.code == abci.CODE_TYPE_OK
    assert app.serial_verifies == 1  # degraded, not dropped


def test_recheck_rides_the_admission_lane():
    """Post-commit recheck re-verifies every resident envelope in ONE
    admission-lane batch (residents are cache-resident, so the duplicate
    peek is skipped) — the app consumes verdicts at recheck too."""
    mp, app, sched = make_mp()
    try:
        txs = [stx.encode_signed_tx(PRIV, b"r=%d" % i) for i in range(5)]
        for tx in txs:
            assert mp.check_tx(tx).code == abci.CODE_TYPE_OK
        serial0 = app.serial_verifies
        rows0 = sched.stats()["lanes"]["admission"]["rows_total"]
        with mp._lock:
            mp.update(1, [txs[0]], [abci.ResponseDeliverTx(code=0)])
        assert mp.size() == 4  # committed tx removed, rest rechecked
        assert app.serial_verifies == serial0  # recheck consumed verdicts
        assert sched.stats()["lanes"]["admission"]["rows_total"] == rows0 + 4
    finally:
        sched.close()


# -- host-side RLC (the CPU-backend fast path behind the admission lane) -------


def _rows(n, corrupt=()):
    privs = [gen_ed25519(bytes([i % 250 + 1, i // 250]) + b"\x0b" * 30) for i in range(n)]
    pk, ms, sg = [], [], []
    for i, p in enumerate(privs):
        m = b"hostrlc-%d" % i
        s = bytearray(p.sign(m))
        if i in corrupt:
            s[2] ^= 0xFF
        pk.append(p.pub_key().bytes())
        ms.append(m)
        sg.append(bytes(s))
    return pk, ms, sg


def test_host_rlc_byte_identical_to_serial():
    from tendermint_tpu.crypto import batch as B

    n = max(64, B._HOST_RLC_MIN)
    pk, ms, sg = _rows(n, corrupt=(3, n - 1))
    got = B.verify_batch_cpu(pk, ms, sg)
    expect = [B.verify_batch_cpu([pk[i]], [ms[i]], [sg[i]])[0] for i in range(n)]
    assert list(got) == expect
    assert got.sum() == n - 2
    # the all-pass batch takes the combined check, flagged in flush detail
    pk2, ms2, sg2 = _rows(n)
    B.LAST_FLUSH_DETAIL.clear()
    assert B.verify_batch_cpu(pk2, ms2, sg2).all()
    assert B.LAST_FLUSH_DETAIL.get("host_rlc") is True


def test_host_rlc_rejects_invalid_encodings():
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto.ed25519_ref import point_decompress

    n = max(64, B._HOST_RLC_MIN)
    pk, ms, sg = _rows(n)
    # a 32-byte non-point pubkey and a non-point R must read False without
    # poisoning their batchmates
    bad_pk = bytes([2]) + b"\x00" * 30 + bytes([0])
    assert point_decompress(bad_pk) is None or True  # shape only
    pk[5] = bad_pk
    sg[9] = b"\xff" * 32 + sg[9][32:]
    got = B.verify_batch_cpu(pk, ms, sg)
    expect = [B.verify_batch_cpu([pk[i]], [ms[i]], [sg[i]])[0] for i in range(n)]
    assert list(got) == expect
    assert not got[5] and not got[9]


def test_host_rlc_gated_off_in_cofactorless_mode():
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto import keys as K
    from tendermint_tpu.crypto.keys import set_verify_mode

    prev = "cofactorless" if K.cofactorless_mode() else "cofactored"
    n = max(64, B._HOST_RLC_MIN)
    pk, ms, sg = _rows(n)
    try:
        set_verify_mode("cofactorless")
        B.LAST_FLUSH_DETAIL.clear()
        assert B.verify_batch_cpu(pk, ms, sg).all()
        # reference-exact mode: the serial loop, never the cofactored
        # combined check
        assert B.LAST_FLUSH_DETAIL.get("host_rlc") is None
    finally:
        set_verify_mode(prev)


def test_wal_replay_readmits_signed_txs(tmp_path):
    mp, app, sched = make_mp(wal_path=str(tmp_path / "wal"))
    try:
        txs = [stx.encode_signed_tx(PRIV, b"w=%d" % i) for i in range(3)]
        for tx in txs:
            assert mp.check_tx(tx).code == abci.CODE_TYPE_OK
        mp.flush()  # drop pool + cache, keep the WAL
        assert mp.replay_wal() == 3
        assert mp.size() == 3
    finally:
        sched.close()
