"""BlockPool: concurrent per-height block requesters for fast sync
(reference: blockchain/v0/pool.go:62,107).

The pool tracks peers' reported heights, keeps up to `request_window` heights
in flight, assigns each height to a peer, and exposes a sliding window of
downloaded blocks to the reactor (peek_two_blocks / pop_request).

Peer quality is tracked per peer as an EWMA score fed by three signals —
request timeouts, bad blocks (failed commit verification), and response
latency — and drives three decisions:

  * routing: `_pick_peer` weights the random peer choice by score, so a
    slow-but-honest peer keeps serving while a flaky one drains to zero
    traffic instead of being re-picked at uniform odds;
  * backoff: each failure puts the peer in an exponentially growing
    cool-down (reset by the next good block) during which it is not
    assigned new heights;
  * ban: when the score falls below `ban_threshold` the peer is removed
    from the pool and punished through the reactor's punish callback (the
    switch routes that to the trust scorer, which disconnects).

A single timeout therefore no longer disconnects a peer (the pre-ISSUE-12
behavior): during a mass rejoin every serving peer is slow, and evicting the
whole peer set on first timeout left the pool with nobody to sync from.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

logger = logging.getLogger("tendermint_tpu.blocksync")

# Max heights in flight (reference: maxPendingRequests-ish). Sized to feed
# the reactor's 64-block super-batch runs (VERIFY_BATCH_BLOCKS) with
# fetch-ahead to spare — a window smaller than the run cap can never
# assemble a full run, silently shrinking every super-batch.
REQUEST_WINDOW = 96
# defaults for the [fastsync] peer_timeout / retry_sleep config knobs
# (kept as module constants for tests and non-config callers)
PEER_TIMEOUT = 10.0
RETRY_SLEEP = 0.05

# peer-score knobs (module constants: the defaults survived the rejoin soak;
# promote to config only when a deployment actually needs to tune them)
SCORE_ALPHA = 0.25          # EWMA step per observation
BAD_BLOCK_WEIGHT = 2        # a bad block counts this many failure steps
BAN_THRESHOLD = 0.25        # score below this => remove + punish
BACKOFF_BASE = 0.5          # first failure cool-down (seconds)
BACKOFF_MAX = 15.0
MAX_PENDING_PER_PEER = 20


@dataclass
class _PoolPeer:
    peer_id: str
    height: int = 0
    base: int = 0
    pending: int = 0
    # -- quality tracking --------------------------------------------------
    score: float = 1.0           # EWMA of success(1)/failure(0) observations
    latency_s: float = 0.0       # EWMA of block response latency
    failures: int = 0            # consecutive failures (drives backoff)
    backoff_until: float = 0.0   # monotonic deadline; not assignable before
    timeouts: int = 0
    bad_blocks: int = 0
    blocks_served: int = 0

    def record_good(self, latency: float) -> None:
        self.score += SCORE_ALPHA * (1.0 - self.score)
        self.latency_s = (
            latency if self.blocks_served == 0
            else self.latency_s + SCORE_ALPHA * (latency - self.latency_s)
        )
        self.blocks_served += 1
        self.failures = 0
        self.backoff_until = 0.0

    def record_failure(self, weight: int = 1) -> None:
        for _ in range(weight):
            self.score -= SCORE_ALPHA * self.score
        self.failures += 1
        self.backoff_until = time.monotonic() + min(
            BACKOFF_BASE * (2 ** (self.failures - 1)), BACKOFF_MAX
        )

    def banned(self) -> bool:
        return self.score < BAN_THRESHOLD


@dataclass
class _Requester:
    height: int
    peer_id: str = ""
    block: Optional[object] = None
    requested_at: float = field(default_factory=lambda: time.monotonic())


class BlockPool:
    def __init__(self, start_height: int, send_request: Callable, punish_peer: Callable,
                 metrics=None, peer_timeout: float = PEER_TIMEOUT,
                 retry_sleep: float = RETRY_SLEEP):
        """send_request(peer_id, height) -> awaitable; punish_peer(peer_id, reason);
        metrics: an optional BlockSyncMetrics (num_peers / latest_block_height);
        peer_timeout/retry_sleep: [fastsync] knobs (defaults unchanged)."""
        self.height = start_height  # next height to pop
        self.metrics = metrics
        self.peer_timeout = peer_timeout
        self.retry_sleep = retry_sleep
        self._peers: Dict[str, _PoolPeer] = {}
        self._requesters: Dict[int, _Requester] = {}
        self._send_request = send_request
        self._punish_peer = punish_peer
        self._task: Optional[asyncio.Task] = None
        self._running = False

    def start(self) -> None:
        self._running = True
        self._task = asyncio.create_task(self._make_requests_routine(), name="pool-requests")

    def stop(self) -> None:
        self._running = False
        if self._task:
            self._task.cancel()

    # -- peers -------------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        p = self._peers.get(peer_id)
        if p is None:
            p = self._peers[peer_id] = _PoolPeer(peer_id)
        p.base, p.height = base, height
        if self.metrics is not None:
            self.metrics.num_peers.set(len(self._peers))

    def remove_peer(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)
        if self.metrics is not None:
            self.metrics.num_peers.set(len(self._peers))
        for req in self._requesters.values():
            if req.peer_id == peer_id and req.block is None:
                req.peer_id = ""
                req.requested_at = time.monotonic()

    def max_peer_height(self) -> int:
        return max((p.height for p in self._peers.values()), default=0)

    def num_peers(self) -> int:
        return len(self._peers)

    def peer_stats(self) -> Dict[str, dict]:
        """Per-peer quality snapshot (reactor metrics sampling + /debug)."""
        return {
            pid: {
                "score": round(p.score, 4),
                "latency_ms": round(p.latency_s * 1e3, 3),
                "pending": p.pending,
                "timeouts": p.timeouts,
                "bad_blocks": p.bad_blocks,
                "blocks_served": p.blocks_served,
                "backoff_s": round(max(0.0, p.backoff_until - time.monotonic()), 3),
            }
            for pid, p in self._peers.items()
        }

    # -- blocks ------------------------------------------------------------

    def add_block(self, peer_id: str, block) -> bool:
        req = self._requesters.get(block.header.height)
        if req is None or req.block is not None:
            return False
        if req.peer_id != peer_id:
            # only the assigned requester's peer may fill the slot — otherwise
            # a bad block is unattributable and an attacker can pre-fill
            # heights with junk that is never re-requested (reference:
            # pool.go AddBlock checks the requester's peer)
            return False
        req.block = block
        p = self._peers.get(peer_id)
        if p:
            p.pending = max(0, p.pending - 1)
            p.record_good(time.monotonic() - req.requested_at)
        return True

    def get_block(self, height: int):
        """Downloaded block at height, or None."""
        req = self._requesters.get(height)
        return req.block if req else None

    def pop_request(self) -> None:
        """first block was applied: advance (reference: pool.go PopRequest)."""
        self._requesters.pop(self.height, None)
        self.height += 1
        if self.metrics is not None:
            self.metrics.latest_block_height.set(self.height)

    def _unassign(self, req: _Requester) -> str:
        """Return a request to the unassigned state, keeping the previous
        peer's pending count consistent (the pre-ISSUE-12 redo leaked one
        pending slot per redo, eventually wedging the peer at the
        MAX_PENDING_PER_PEER cap with zero real requests in flight)."""
        prev = req.peer_id
        if prev:
            p = self._peers.get(prev)
            if p is not None and req.block is None:
                p.pending = max(0, p.pending - 1)
        req.block = None
        req.peer_id = ""
        req.requested_at = time.monotonic()
        return prev

    def redo_request(self, height: int) -> str:
        """Block failed validation: unassign + requeue the height, record a
        bad block against the sender (reference: pool.go RedoRequest). The
        caller decides whether to punish (only a head-of-window failure is
        attributable — see reactor._verify_run_batched)."""
        req = self._requesters.get(height)
        if req is None:
            return ""
        bad_peer = req.peer_id
        if req.block is not None:
            # the peer's pending slot was already released at add_block; undo
            # the `record_good` optimism with a weighted failure
            p = self._peers.get(bad_peer)
            if p is not None:
                p.bad_blocks += 1
                p.record_failure(BAD_BLOCK_WEIGHT)
            req.block = None
            req.peer_id = ""
            req.requested_at = time.monotonic()
        else:
            # in-flight redo (e.g. the partner height of a failed pair):
            # release the assigned peer's pending slot too
            self._unassign(req)
        if self.metrics is not None:
            self.metrics.redos_total.inc()
        return bad_peer

    # -- request scheduling -------------------------------------------------

    def _pick_peer(self, height: int) -> Optional[_PoolPeer]:
        now = time.monotonic()
        candidates = [
            p for p in self._peers.values()
            if p.base <= height <= p.height
            and p.pending < MAX_PENDING_PER_PEER
            and p.backoff_until <= now
        ]
        if not candidates:
            return None
        # score-weighted routing: a peer at score 1.0 is ~20x likelier than
        # one hovering just above the ban threshold
        weights = [max(p.score, 0.05) for p in candidates]
        return random.choices(candidates, weights=weights, k=1)[0]

    async def _ban_if_bad(self, p: _PoolPeer, reason: str) -> bool:
        if not p.banned():
            return False
        logger.info("blocksync peer %s score %.2f below ban threshold (%s)",
                    p.peer_id[:10], p.score, reason)
        await self._punish_peer(p.peer_id, reason)
        self.remove_peer(p.peer_id)
        return True

    async def _make_requests_routine(self) -> None:
        try:
            while self._running:
                # spawn requesters for the window
                max_h = self.max_peer_height()
                next_h = self.height
                while (
                    len(self._requesters) < REQUEST_WINDOW
                    and next_h <= max_h
                ):
                    if next_h not in self._requesters:
                        self._requesters[next_h] = _Requester(next_h, "")
                    next_h += 1
                # assign unassigned / timed-out requesters
                now = time.monotonic()
                for req in list(self._requesters.values()):
                    if req.block is not None:
                        continue
                    if req.peer_id and now - req.requested_at > self.peer_timeout:
                        if self.metrics is not None:
                            self.metrics.peer_timeouts.inc()
                        p = self._peers.get(req.peer_id)
                        timed_out = self._unassign(req)
                        if p is not None:
                            p.timeouts += 1
                            p.record_failure()
                            # ban only a peer whose EWMA proves a pattern: a
                            # single timeout during a rejoin storm is backoff,
                            # not a disconnect
                            await self._ban_if_bad(
                                p, f"block request timeout (height {req.height})"
                            )
                        else:
                            logger.debug("timeout for departed peer %s", timed_out[:10])
                    if not req.peer_id and req.block is None:
                        peer = self._pick_peer(req.height)
                        if peer is None:
                            continue
                        req.peer_id = peer.peer_id
                        req.requested_at = time.monotonic()
                        peer.pending += 1
                        await self._send_request(peer.peer_id, req.height)
                await asyncio.sleep(self.retry_sleep)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("pool request routine died")
