"""Streamed flush planner (ISSUE 13) — chunked super-batch verification.

The planner decomposes any over-budget row set into fixed-bucket chunks
streamed through the RLC pipeline with double-buffered host prep and
on-device partial accumulation (crypto/batch.py). These tests pin the
CONTRACT on real curve points with the device kernels replaced by host
twins computing the identical math through ed25519_ref (tier-1 pays no
XLA compile — the pattern of tests/test_rlc_fallback.py):

- chunk-streamed verdicts byte-identical to a single-flush verify_batch
  across chunk geometries (exact multiple, ragged tail, passthrough);
- corrupted rows AT chunk boundaries recover the exact per-row mask;
- sharded-streamed ≡ unsharded bit-for-bit (a host-twin mesh runner
  consuming the REAL prepare_rlc_shards output);
- scheduler preemption between chunks (a vote flush interleaves a 3-chunk
  catch-up flush);
- the flush-budget extension: peak lanes in flight <= 2 chunks (double
  buffer, never more) — tracked by the planner AND independently by the
  stub's own outstanding-submission counter;
- the chunked host-RLC path of verify_batch_cpu stays byte-identical and
  reuses the decompressed-point cache across chunks.
"""

import os
import threading

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.crypto import batch
from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.ops import msm_jax


# ---------------------------------------------------------------------------
# Host twins: the exact kernel math on ed25519_ref points (no device, no
# compile). The planner treats the returned handles opaquely, so plain
# numpy arrays / point tuples stand in for device arrays.


def _scalar_list(scalars):
    if isinstance(scalars, np.ndarray):
        return [int.from_bytes(bytes(row), "little") for row in scalars]
    return [int(s) for s in scalars]


class _InFlightTracker:
    """Counts submitted-but-unsynced chunks via the lane-flag handles the
    planner syncs: an independent witness of the double-buffer bound."""

    def __init__(self):
        self.outstanding = 0
        self.peak = 0
        self.lock = threading.Lock()

    def submit(self):
        with self.lock:
            self.outstanding += 1
            self.peak = max(self.peak, self.outstanding)

    def sync(self):
        with self.lock:
            self.outstanding -= 1


class _LazyOk:
    """Lane-validity handle whose np.asarray() marks the chunk synced."""

    def __init__(self, arr, tracker):
        self.arr = arr
        self.tracker = tracker
        self._synced = False

    def __array__(self, dtype=None, copy=None):
        if not self._synced:
            self._synced = True
            self.tracker.sync()
        return self.arr if dtype is None else self.arr.astype(dtype)


def _install_host_twins(monkeypatch, tracker=None):
    """Replace the partial-kernel entry points AND the single-flush RLC
    submit with ed25519_ref host twins (identical math, real curve points).
    """

    def partial_submit(pts_bytes, scalars, zero16_from=0, presorted=None):
        n = pts_bytes.shape[0]
        sc = _scalar_list(scalars)
        if presorted is not None:
            # the prep WORKER's window sort must encode exactly these
            # scalars (free validation of the off-thread sort)
            perm, ends = presorted
            assert _scalars_from_windows(np.asarray(perm), np.asarray(ends)) == sc
        ok = np.zeros(n, dtype=bool)
        pairs = []
        for i in range(n):
            p = ref.point_decompress(bytes(pts_bytes[i]))
            ok[i] = p is not None
            if p is not None and sc[i]:
                pairs.append((p, sc[i]))
        total = batch._host_msm(pairs)
        if total is None:
            total = ref.IDENTITY
        if tracker is not None:
            tracker.submit()
            return total, _LazyOk(ok, tracker)
        return total, ok

    def fold(acc, part):
        return ref.point_add(acc, part)

    def ident(acc):
        return np.asarray(
            bool(acc[2] % ref.P != 0 and ref.point_equal(acc, ref.IDENTITY))
        )

    def full_submit(pts_bytes, scalars, zero16_from=0, presorted=None):
        total, ok = partial_submit(pts_bytes, scalars, presorted=presorted)
        if tracker is not None:
            ok = np.asarray(ok)
        bok = bool(total[2] % ref.P != 0 and ref.point_equal(total, ref.IDENTITY))
        return np.concatenate([np.array([bok]), ok])

    def host_verify_prepared(a, r, s_bits, h_bits):
        """Exact per-signature twin: reconstruct s, h from the radix-16
        digits and check [8]([s]B - R - [h]A) == O (the cofactored kernel
        equation) per lane."""
        nb = a.shape[1]
        out = np.zeros(nb, dtype=bool)
        for i in range(nb):
            A = ref.point_decompress(bytes(a[:, i]))
            R = ref.point_decompress(bytes(r[:, i]))
            if A is None or R is None:
                continue
            s = sum(int(d) << (4 * j) for j, d in enumerate(s_bits[:, i]))
            h = sum(int(d) << (4 * j) for j, d in enumerate(h_bits[:, i]))
            neg = lambda p: (ref.P - p[0], p[1], p[2], ref.P - p[3])
            d_pt = ref.point_add(
                ref.point_add(ref.point_mul(s % ref.L, ref.BASE), neg(R)),
                ref.point_mul(h % ref.L, neg(A)),
            )
            out[i] = ref.point_equal(ref.point_mul(8, d_pt), ref.IDENTITY)
        return out

    from tendermint_tpu.ops import ed25519_jax

    monkeypatch.setattr(msm_jax, "rlc_partial_submit", partial_submit)
    monkeypatch.setattr(msm_jax, "partial_fold_submit", fold)
    monkeypatch.setattr(msm_jax, "partial_identity_submit", ident)
    monkeypatch.setattr(msm_jax, "rlc_check_submit", full_submit)
    monkeypatch.setattr(ed25519_jax, "verify_prepared", host_verify_prepared)
    # keep the single-flush comparator on the PLAIN kernel (the cached-A
    # fill would jit the decompress kernel — a real compile)
    monkeypatch.setattr(batch, "_fill_a_cache", lambda *a, **k: None)


@pytest.fixture
def planner(monkeypatch):
    monkeypatch.setattr(batch, "RLC_MIN", 8)
    prev = batch.planner_budget()
    batch.configure_planner(max_flush_lanes=64)  # 31 rows per chunk
    yield 31
    batch.configure_planner(max_flush_lanes=prev)
    batch.set_device_fault_hook(None)


def _signed_rows(n, seed=b"\x11"):
    priv = gen_ed25519(seed * 32 if len(seed) == 1 else seed)
    pk = priv.pub_key().bytes()
    msgs = [b"planner-%05d" % i for i in range(n)]
    return [pk] * n, msgs, [priv.sign(m) for m in msgs]


# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "n",
    [93, 67, 31],  # exact 3-chunk multiple, ragged tail, passthrough
    ids=["exact-multiple", "ragged-tail", "single-chunk-passthrough"],
)
def test_streamed_verdicts_byte_identical(planner, monkeypatch, n):
    """Chunk-streamed verify_batch == single-flush verify_batch ==
    verify_batch_cpu, bit for bit, across chunk geometries."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(n)
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)

    streamed = batch.verify_batch(pks, msgs, sigs, backend="jax")
    streamed_path = batch.LAST_JAX_PATH[0]

    # single-flush comparator: a budget no row set here can exceed
    batch.configure_planner(max_flush_lanes=1 << 16)
    single = batch.verify_batch(pks, msgs, sigs, backend="jax")
    batch.configure_planner(max_flush_lanes=64)

    assert streamed.tobytes() == single.tobytes() == cpu.tobytes()
    assert streamed.all()
    if n > 31:
        assert streamed_path == "rlc-streamed"
    else:
        # at/below the chunk budget the planner must stay OUT of the way
        assert streamed_path == "rlc"


def test_streamed_flush_detail_and_trace_fields(planner, monkeypatch):
    """A streamed flush records chunks / chunk_lanes / prep_overlap_ms in
    the flight recorder (docs/OBSERVABILITY.md fields)."""
    from tendermint_tpu.libs import trace as _trace

    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(70)  # 3 chunks of <=31 rows
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.all()
    assert batch.LAST_FLUSH_DETAIL["chunks"] == 3
    assert batch.LAST_FLUSH_DETAIL["chunk_lanes"] == 64
    last = _trace.verify_stats()["last_flush"]
    assert last["chunks"] == 3
    assert last["chunk_lanes"] == 64
    assert "prep_overlap_ms" in last


@pytest.mark.parametrize(
    "bad_rows",
    [
        (0,),  # head of chunk 0
        (30, 31),  # last row of chunk 0 + first row of chunk 1 (boundary)
        (62, 92),  # chunk 2 boundary + final row
        (0, 31, 62, 92),  # every boundary at once
    ],
)
def test_corrupt_rows_at_chunk_boundaries_exact_mask(
    planner, monkeypatch, bad_rows
):
    """A corrupted row anywhere — including exactly AT chunk boundaries —
    fails the streamed combined check and the chunked recovery returns the
    EXACT per-row mask, byte-identical to the CPU reference."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(93)
    pks, sigs = list(pks), list(sigs)
    for j, i in enumerate(bad_rows):
        kind = j % 3
        if kind == 0:
            # valid encodings, wrong signature: only the curve check fails
            sigs[i] = sigs[i][:32] + (1).to_bytes(32, "little")
        elif kind == 1:
            sigs[i] = sigs[i][:32] + ref.L.to_bytes(32, "little")  # s >= L
        else:
            pks[i] = pks[i][:16]  # precheck reject

    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")

    assert mask.tobytes() == cpu.tobytes()
    for i in bad_rows:
        assert not mask[i]
    assert mask.sum() == 93 - len(bad_rows)
    assert batch.LAST_FLUSH_DETAIL.get("rlc_fallback") is True
    assert batch.LAST_JAX_PATH[0] == "rlc-streamed-recovery"


def test_peak_lanes_in_flight_bounded_at_two_chunks(planner, monkeypatch):
    """Flush-budget extension: the double buffer never holds more than 2
    chunks of lanes in flight — pinned by the planner's own accounting AND
    by the stub's independent outstanding-submission counter."""
    tracker = _InFlightTracker()
    _install_host_twins(monkeypatch, tracker=tracker)
    pks, msgs, sigs = _signed_rows(31 * 7 + 5)  # 8 chunks
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.all()
    detail = batch.LAST_FLUSH_DETAIL
    assert detail["chunks"] == 8
    assert detail["peak_lanes_in_flight"] <= 2 * detail["chunk_lanes"]
    assert tracker.peak <= 2  # submitted-but-unsynced chunks, ever
    assert tracker.outstanding == 0  # every chunk synced by flush end


def test_oversized_submit_handle_routes_through_planner(planner, monkeypatch):
    """verify_batch_submit on an over-budget row set must NOT dispatch a
    monolithic async RLC call — it resolves eagerly through the streamed
    path with an identical verdict."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(80)
    h = batch.verify_batch_submit(pks, msgs, sigs, backend="jax")
    mask = batch.verify_batch_finish(h)
    assert mask.all() and len(mask) == 80
    assert batch.LAST_JAX_PATH[0] == "rlc-streamed"


# ---------------------------------------------------------------------------
# Sharded-streamed ≡ unsharded, through the REAL host prep + lane split.


def _scalars_from_windows(perm, ends):
    """Invert sort_windows: reconstruct each lane's scalar from the sorted
    permutation + bucket boundaries (window w = byte w of the scalar)."""
    T, n = perm.shape
    scal = [0] * n
    pos = np.arange(n)
    for t in range(T):
        digits_sorted = np.searchsorted(ends[t], pos, side="right")
        for p in range(n):
            d = int(digits_sorted[p])
            if d:
                scal[int(perm[t, p])] += d << (8 * t)
    return scal


def _fake_mesh_env(nd, tracker=None):
    """A host-twin sharded_rlc_stream runner consuming the REAL
    prepare_rlc_shards output (pts/perm/ends per shard)."""

    def run_chunk(pts, perm, ends, acc):
        assert pts.shape[0] == nd
        if acc is None:
            acc = [ref.IDENTITY] * nd
        oks = []
        for d in range(nd):
            sc = _scalars_from_windows(perm[d], ends[d])
            rows = pts[d].T  # (n, 32)
            ok = np.zeros(rows.shape[0], dtype=bool)
            pairs = []
            for i in range(rows.shape[0]):
                p = ref.point_decompress(bytes(rows[i]))
                ok[i] = p is not None
                if p is not None and sc[i]:
                    pairs.append((p, sc[i]))
            part = batch._host_msm(pairs)
            if part is not None:
                acc[d] = ref.point_add(acc[d], part)
            oks.append(ok)
        out_ok = np.stack(oks)
        if tracker is not None:
            tracker.submit()
            out_ok = _LazyOk(out_ok, tracker)
        return acc, out_ok

    def finish(acc):
        total = acc[0]
        for d in range(1, nd):
            total = ref.point_add(total, acc[d])
        return np.asarray(
            bool(total[2] % ref.P != 0 and ref.point_equal(total, ref.IDENTITY))
        )

    return (nd, None, None, (run_chunk, finish))


def test_sharded_streamed_equals_unsharded_bit_for_bit(planner, monkeypatch):
    """The mesh arm — per-shard partials over prepare_rlc_shards slices,
    per-shard accumulation, one final fold — produces the identical mask."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(93)
    unsharded = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert batch.LAST_JAX_PATH[0] == "rlc-streamed"

    env = _fake_mesh_env(4)
    monkeypatch.setattr(batch, "_sharded_env", lambda: env)
    sharded = batch._verify_batch_streamed(pks, msgs, sigs)
    assert batch.LAST_JAX_PATH[0] == "rlc-sharded-streamed"
    assert sharded.tobytes() == unsharded.tobytes()
    assert sharded.all()
    assert batch.LAST_FLUSH_DETAIL["chunks"] == 3

    # a bad signature on a chunk boundary: sharded recovery == cpu
    sigs = list(sigs)
    sigs[31] = sigs[31][:32] + (1).to_bytes(32, "little")
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    mask = batch._verify_batch_streamed(pks, msgs, sigs)
    assert mask.tobytes() == cpu.tobytes()
    assert not mask[31] and mask.sum() == 92


def test_sharded_stream_shard_alignment(planner, monkeypatch):
    """When the lane budget doesn't tile the mesh, the sharded arm bumps
    the chunk bucket to the next shard multiple (never truncates rows)."""
    _install_host_twins(monkeypatch)
    batch.configure_planner(max_flush_lanes=60)  # 2*na_c=60 % 8 != 0
    env = _fake_mesh_env(8)
    monkeypatch.setattr(batch, "_sharded_env", lambda: env)
    pks, msgs, sigs = _signed_rows(75)
    mask = batch._verify_batch_streamed(pks, msgs, sigs)
    assert mask.all() and len(mask) == 75
    assert batch.LAST_FLUSH_DETAIL["chunk_lanes"] % 8 == 0


# ---------------------------------------------------------------------------
# Scheduler: preemption points between planner chunks.


def test_scheduler_vote_flush_interleaves_catchup_chunks(planner, monkeypatch):
    """A 3-chunk catch-up flush on the dispatch thread yields to queued
    vote rows BETWEEN chunks: call order is chunk, votes, chunk, chunk."""
    from tendermint_tpu.crypto.scheduler import VerifyScheduler

    calls = []
    first_chunk_started = threading.Event()
    release_first_chunk = threading.Event()

    def fake_verify_batch(pks, msgs, sigs, backend=None, key_types=None):
        calls.append(len(pks))
        if len(calls) == 1:
            first_chunk_started.set()
            assert release_first_chunk.wait(5)
        return np.ones(len(pks), dtype=bool)

    monkeypatch.setattr(batch, "verify_batch", fake_verify_batch)
    sched = VerifyScheduler()
    try:
        rows = 31 * 3  # exactly 3 planner chunks
        pk = b"\x01" * 32
        result = {}

        def consumer():
            result["mask"] = sched.verify_rows(
                "catchup", [pk] * rows, [b"m"] * rows, [b"s" * 64] * rows
            )

        t = threading.Thread(target=consumer)
        t.start()
        assert first_chunk_started.wait(5)
        vt = sched.submit("votes", [pk] * 2, [b"v"] * 2, [b"s" * 64] * 2)
        release_first_chunk.set()
        assert vt.wait(5).all()
        t.join(5)
        assert calls == [31, 2, 31, 31]  # votes between chunk 1 and 2
        assert result["mask"].shape == (rows,)
        assert result["mask"].all()
        assert sched.preemptions >= 1
    finally:
        sched.close()


def test_scheduler_chunked_slices_byte_identical(planner, monkeypatch):
    """Ticket slices across a chunk-split scheduler flush reassemble in row
    order — two consumers' verdicts land byte-identical to standalone
    verification of their own rows."""
    from tendermint_tpu.crypto.scheduler import VerifyScheduler

    _install_host_twins(monkeypatch)
    pks_a, msgs_a, sigs_a = _signed_rows(40, seed=b"\x21")
    pks_b, msgs_b, sigs_b = _signed_rows(40, seed=b"\x22")
    sigs_b = list(sigs_b)
    sigs_b[7] = sigs_b[7][:32] + (1).to_bytes(32, "little")  # one bad row
    cpu_a = batch.verify_batch_cpu(pks_a, msgs_a, sigs_a)
    cpu_b = batch.verify_batch_cpu(pks_b, msgs_b, sigs_b)

    sched = VerifyScheduler()
    try:
        ta = sched.submit("catchup", pks_a, msgs_a, sigs_a)
        tb = sched.submit("catchup", pks_b, msgs_b, sigs_b)
        ma = ta.wait(30)
        mb = tb.wait(30)
        assert ma.tobytes() == cpu_a.tobytes()
        assert mb.tobytes() == cpu_b.tobytes()
        assert not mb[7]
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# Chunked host-RLC (verify_batch_cpu — this container's fast path).


def test_host_rlc_chunked_byte_identical_and_cache_reuse(planner, monkeypatch):
    """The chunked host Pippenger stays byte-identical to the serial loop
    (valid + corrupted rows) and decompresses each distinct key ONCE per
    flush — the point cache is shared across chunks."""
    pks, msgs, sigs = _signed_rows(93)
    batch.LAST_FLUSH_DETAIL.clear()
    batch._HOST_PT_CACHE.clear()
    calls = []
    orig = ref.point_decompress

    def counting(b):
        calls.append(bytes(b))
        return orig(b)

    monkeypatch.setattr(ref, "point_decompress", counting)
    mask = batch.verify_batch_cpu(pks, msgs, sigs)
    monkeypatch.setattr(ref, "point_decompress", orig)
    assert mask.all()
    assert batch.LAST_FLUSH_DETAIL.get("host_rlc") is True
    assert batch.LAST_FLUSH_DETAIL.get("chunks") == 3
    # ONE decompression of the shared pubkey despite 3 chunks
    assert calls.count(pks[0]) == 1

    # corrupted rows at a chunk boundary: serial-loop fallback, exact mask
    sigs = list(sigs)
    for i in (30, 31):
        sigs[i] = sigs[i][:32] + (1).to_bytes(32, "little")
    mask2 = batch.verify_batch_cpu(pks, msgs, sigs)
    assert not mask2[30] and not mask2[31]
    assert mask2.sum() == 91


# ---------------------------------------------------------------------------
# Slow lane: the REAL kernels (XLA:CPU compiles for minutes — the tier-1
# tests above prove the math through host twins; these prove the wiring).


@pytest.mark.slow
def test_streamed_real_kernels_single_device(planner):
    """rlc_partial_submit + partial_fold_submit + partial_identity_submit
    through the real jit pipeline: streamed == CPU on valid rows, and a
    corrupt row fails the combined check into exact recovery."""
    pks, msgs, sigs = _signed_rows(60)
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.all() and batch.LAST_JAX_PATH[0] == "rlc-streamed"
    sigs = list(sigs)
    sigs[31] = sigs[31][:32] + (1).to_bytes(32, "little")
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.tobytes() == cpu.tobytes()
    assert not mask[31]


@pytest.mark.slow
def test_streamed_real_kernels_sharded(planner):
    """sharded_rlc_stream's real shard_map jits (chunk with/without acc +
    the all_gather finisher) on 2 virtual devices: identity verdict on a
    valid 2-chunk stream, REJECT with a corrupted row."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (XLA_FLAGS virtual CPU devices)")
    from tendermint_tpu.parallel.sharded import make_mesh, sharded_rlc_stream

    mesh = make_mesh(jax.devices()[:2], axis_names=("vals",))
    run_chunk, finish = sharded_rlc_stream(mesh)
    na_c = 32
    pks, msgs, sigs = _signed_rows(60)

    def stream(sig_rows):
        acc = None
        flags = []
        for lo, hi in batch._planner_chunks(60):
            pc, shards, _ = batch._prep_stream_chunk_sharded(
                pks, msgs, sig_rows, lo, hi, na_c, 2
            )
            acc, ok = run_chunk(*shards, acc)
            ok = np.asarray(ok).reshape(-1)
            c = hi - lo
            flags.append(bool(ok[:c][pc].all() and ok[na_c : na_c + c][pc].all()))
        return bool(np.asarray(finish(acc))), flags

    bok, flags = stream(sigs)
    assert bok and all(flags)
    sigs = list(sigs)
    sigs[31] = sigs[31][:32] + (1).to_bytes(32, "little")
    bok, _ = stream(sigs)
    assert not bok


def test_planner_config_and_engagement(planner):
    assert batch.planner_budget() == 64
    assert batch.planner_chunk_rows() == 31
    assert not batch.planner_engaged(31)
    assert batch.planner_engaged(32)
    assert batch._planner_chunks(93) == [(0, 31), (31, 62), (62, 93)]
    assert batch._planner_chunks(67) == [(0, 31), (31, 62), (62, 67)]
    with pytest.raises(ValueError):
        batch.configure_planner(max_flush_lanes=4)
