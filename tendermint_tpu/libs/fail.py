"""Fail-point crash injection (reference: libs/fail/fail.go).

Set TMTPU_FAIL_INDEX=<n>; the n-th fail point hit in the process aborts it
hard (os._exit), simulating a crash at that exact ordering point. Used by the
crash-recovery test matrix around the commit/apply sequence
(reference: state/execution.go:143-189, consensus/state.go:746,
test/persist/test_failure_indices.sh)."""

from __future__ import annotations

import os

_counter = 0


def fail_index() -> int:
    try:
        return int(os.environ.get("TMTPU_FAIL_INDEX", "-1"))
    except ValueError:
        return -1


def reset() -> None:
    global _counter
    _counter = 0


def fail_point(name: str = "") -> None:
    global _counter
    target = fail_index()
    if target < 0:
        return
    if _counter == target:
        os.write(2, f"FAIL_POINT {_counter} {name}: crashing\n".encode())
        os._exit(77)
    _counter += 1
