#!/usr/bin/env python
"""Standalone runner for the perf-trajectory ledger.

Aggregates BENCH_r*.json / MULTICHIP_r*.json into one markdown + JSON
trajectory table with lost-datapoint flags and a headline budget check; the
implementation lives in tendermint_tpu/tools/perf_ledger.py. Usage:

    python tools/perf_ledger.py [--root DIR] [--json OUT] [--check]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from tendermint_tpu.tools.perf_ledger import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
