"""Minimal Prometheus-style metrics: counters/gauges/histograms with labels
and text exposition — no external dependency.

reference: the per-service metrics.go files (consensus/metrics.go:28,
p2p/metrics.go, mempool/metrics.go, state/metrics.go) and the go-kit
prometheus provider wired in node/node.go:106-121.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

NAMESPACE = "tendermint"


def _fmt_labels(label_names: Sequence[str], label_values: Tuple[str, ...]) -> str:
    if not label_names:
        return ""
    pairs = ", ".join(
        f'{n}="{v}"' for n, v in zip(label_names, label_values)
    )
    return "{" + pairs + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str) -> "_Bound":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} labels, got {len(values)}"
            )
        return _Bound(self, tuple(str(v) for v in values))

    # unlabeled shortcuts
    def _key(self) -> Tuple[str, ...]:
        return ()

    def replace_series(self, values: Dict[Tuple[str, ...], float]) -> None:
        """Atomically replace EVERY labeled series with `values` (label
        tuple -> value). For gauges sampled from a live membership (e.g.
        per-peer clock skew): departed members' series drop out instead of
        exposing stale values and growing without bound over churn."""
        clean = {
            tuple(str(v) for v in k): float(val) for k, val in values.items()
        }
        for k in clean:
            if len(k) != len(self.label_names):
                raise ValueError(
                    f"{self.name}: expected {len(self.label_names)} labels, got {len(k)}"
                )
        with self._lock:
            self._values = clean

    def expose(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for label_values, v in items:
            out.append(
                f"{self.name}{_fmt_labels(self.label_names, label_values)} {_num(v)}"
            )
        return out


def _num(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(v)


class _Bound:
    __slots__ = ("metric", "values")

    def __init__(self, metric: _Metric, values: Tuple[str, ...]):
        self.metric = metric
        self.values = values

    def inc(self, amount: float = 1.0) -> None:
        with self.metric._lock:
            self.metric._values[self.values] = (
                self.metric._values.get(self.values, 0.0) + amount
            )

    def set(self, value: float) -> None:
        with self.metric._lock:
            self.metric._values[self.values] = float(value)

    def observe(self, value: float) -> None:
        self.metric.observe_labels(self.values, value)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        if self.label_names:
            raise ValueError(f"{self.name} is labeled; use .labels(...).inc()")
        _Bound(self, ()).inc(amount)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float) -> None:
        _Bound(self, ()).set(value)

    def inc(self, amount: float = 1.0) -> None:
        _Bound(self, ()).inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        _Bound(self, ()).inc(-amount)


class Histogram(_Metric):
    """Cumulative-bucket histogram (prometheus semantics)."""

    kind = "histogram"
    DEFAULT_BUCKETS = (
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0,
    )

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float) -> None:
        self.observe_labels((), value)

    def observe_labels(self, label_values: Tuple[str, ...], value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(label_values, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[label_values] = self._sums.get(label_values, 0.0) + value
            self._totals[label_values] = self._totals.get(label_values, 0) + 1

    def expose(self) -> List[str]:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            items = sorted(self._counts.items())
            for label_values, counts in items:
                names = self.label_names + ("le",)
                for i, b in enumerate(self.buckets):
                    out.append(
                        f"{self.name}_bucket{_fmt_labels(names, label_values + (_num(b),))} {counts[i]}"
                    )
                out.append(
                    f"{self.name}_bucket{_fmt_labels(names, label_values + ('+Inf',))} "
                    f"{self._totals[label_values]}"
                )
                out.append(
                    f"{self.name}_sum{_fmt_labels(self.label_names, label_values)} "
                    f"{_num(self._sums[label_values])}"
                )
                out.append(
                    f"{self.name}_count{_fmt_labels(self.label_names, label_values)} "
                    f"{self._totals[label_values]}"
                )
        return out


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if any(m.name == metric.name for m in self._metrics):
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics.append(metric)
        return metric

    def counter(self, name, help_, labels=()) -> Counter:
        return self.register(Counter(name, help_, labels))

    def gauge(self, name, help_, labels=()) -> Gauge:
        return self.register(Gauge(name, help_, labels))

    def histogram(self, name, help_, labels=(), buckets=None) -> Histogram:
        return self.register(Histogram(name, help_, labels, buckets))

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Compact JSON-able dump of every series that has recorded data:
        {name: {"type", "series": {label_str: value | {"count","sum"}}}}.
        Histograms collapse to count+sum (the bucket layout is an exposition
        concern); series never written are omitted to keep snapshots small
        (bench.py attaches this as `extra.node_metrics`)."""
        with self._lock:
            metrics = list(self._metrics)
        out: Dict[str, dict] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                with m._lock:
                    series = {
                        _fmt_labels(m.label_names, lv).strip("{}"): {
                            "count": m._totals[lv],
                            "sum": round(m._sums[lv], 6),
                        }
                        for lv in m._totals
                    }
            else:
                with m._lock:
                    series = {
                        _fmt_labels(m.label_names, lv).strip("{}"): v
                        for lv, v in m._values.items()
                    }
            if series:
                out[m.name] = {"type": m.kind, "series": series}
        return out


def parse_exposition(text: str) -> Dict[str, dict]:
    """Strict parser for the Prometheus text format Registry.expose emits:
    {family: {"help", "type", "samples": [(name, labels_dict, value)]}}.
    Sample names carry the _bucket/_sum/_count suffixes; shared by the
    exposition lint test and tools/loadtest.py's /metrics scrape."""
    import re as _re

    families: Dict[str, dict] = {}
    sample_re = _re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$")
    label_re = _re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            families.setdefault(name, {"help": None, "type": None, "samples": []})
            families[name]["help"] = help_
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            families.setdefault(name, {"help": None, "type": None, "samples": []})
            families[name]["type"] = kind.strip()
        elif line.startswith("#"):
            raise ValueError(f"unknown comment line: {line!r}")
        else:
            m = sample_re.match(line)
            if m is None:
                raise ValueError(f"unparseable sample line: {line!r}")
            name, _, labels_s, value_s = m.groups()
            labels = dict(label_re.findall(labels_s)) if labels_s else {}
            value = float("inf") if value_s == "+Inf" else float(value_s)
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and families.get(base, {}).get("type") == "histogram":
                    family = base
                    break
            if family not in families:
                raise ValueError(f"sample {name!r} before HELP/TYPE")
            families[family]["samples"].append((name, labels, value))
    return families


# ------------------------------------------------- per-subsystem metric sets


class ConsensusMetrics:
    """reference: consensus/metrics.go:28."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_consensus"
        self.height = reg.gauge(f"{ns}_height", "Height of the chain.")
        self.rounds = reg.gauge(f"{ns}_rounds", "Number of rounds at the latest height.")
        self.validators = reg.gauge(f"{ns}_validators", "Number of validators.")
        self.validators_power = reg.gauge(
            f"{ns}_validators_power", "Total voting power of validators."
        )
        self.missing_validators = reg.gauge(
            f"{ns}_missing_validators", "Validators absent from the last commit."
        )
        self.byzantine_validators = reg.gauge(
            f"{ns}_byzantine_validators", "Validators with evidence this height."
        )
        self.num_txs = reg.gauge(f"{ns}_num_txs", "Transactions in the latest block.")
        self.block_size_bytes = reg.gauge(
            f"{ns}_block_size_bytes", "Size of the latest block."
        )
        self.total_txs = reg.counter(f"{ns}_total_txs", "Total committed transactions.")
        self.block_interval_seconds = reg.histogram(
            f"{ns}_block_interval_seconds", "Time between this and the last block."
        )
        self.commit_verify_seconds = reg.histogram(
            f"{ns}_commit_verify_seconds",
            "Wall time of (batched) commit signature verification.",
        )
        # step/round latency (reference: CometBFT consensus/metrics.go
        # StepDurationSeconds/RoundDurationSeconds, added v0.38)
        step_buckets = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
        self.step_duration_seconds = reg.histogram(
            f"{ns}_step_duration_seconds",
            "Wall seconds spent in each consensus step.",
            ("step",), buckets=step_buckets,
        )
        self.round_duration_seconds = reg.histogram(
            f"{ns}_round_duration_seconds",
            "Wall seconds from round entry to commit or round escalation.",
            buckets=step_buckets,
        )
        self.quorum_prevote_delay = reg.gauge(
            f"{ns}_quorum_prevote_delay",
            "Seconds from the proposal timestamp to +2/3 prevote quorum (last round).",
        )
        self.full_prevote_delay = reg.gauge(
            f"{ns}_full_prevote_delay",
            "Seconds from the proposal timestamp to 100% of prevotes (last round).",
        )
        self.proposal_receive_count = reg.counter(
            f"{ns}_proposal_receive_count",
            "Proposals processed, by outcome.", ("status",)
        )
        self.proposal_create_count = reg.counter(
            f"{ns}_proposal_create_count", "Proposals created by this node."
        )
        self.proposal_timeout_total = reg.counter(
            f"{ns}_proposal_timeout_total",
            "Propose-step timeouts (the node prevoted nil for lack of a proposal).",
        )
        self.late_votes = reg.counter(
            f"{ns}_late_votes_total",
            "Votes received for an earlier height.", ("vote_type",)
        )
        self.duplicate_votes = reg.counter(
            f"{ns}_duplicate_votes_total", "Exact-duplicate votes dropped."
        )
        self.block_parts = reg.counter(
            f"{ns}_block_parts_total",
            "Block parts received from peer gossip.", ("matches_current",)
        )
        self.block_gossip_receive_latency = reg.histogram(
            f"{ns}_block_gossip_receive_latency",
            "Seconds from the proposal timestamp (round start before the "
            "proposal arrives) to each gossiped block part's arrival.",
            buckets=step_buckets,
        )
        # cross-node trace propagation (chain observatory, ISSUE 8): per-hop
        # latencies from the origin stamp carried in the p2p envelope,
        # clock-skew corrected against the direct peer's ping/pong estimate
        self.proposal_propagation_seconds = reg.histogram(
            f"{ns}_proposal_propagation_seconds",
            "Seconds from a proposal's origin stamp to its first local "
            "receipt (skew-corrected).",
            buckets=step_buckets,
        )
        self.vote_propagation_seconds = reg.histogram(
            f"{ns}_vote_propagation_seconds",
            "Seconds from a vote's origin stamp to its local receipt "
            "(skew-corrected).",
            buckets=step_buckets,
        )


class MempoolMetrics:
    """reference: mempool/metrics.go."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_mempool"
        self.size = reg.gauge(f"{ns}_size", "Transactions in the mempool.")
        self.size_bytes = reg.gauge(
            f"{ns}_size_bytes", "Total bytes of transactions in the mempool."
        )
        self.tx_size_bytes = reg.histogram(
            f"{ns}_tx_size_bytes", "Transaction sizes.",
            buckets=(32, 128, 512, 2048, 8192, 65536, 1048576),
        )
        self.failed_txs = reg.counter(f"{ns}_failed_txs", "CheckTx failures.")
        self.recheck_times = reg.counter(f"{ns}_recheck_times", "Recheck runs.")
        # admission control (mempool/mempool.py overload protection)
        self.evicted_txs = reg.counter(
            f"{ns}_evicted_txs_total",
            "Resident txs evicted (LRU/lowest-priority) to admit new ones.",
        )
        self.expired_txs = reg.counter(
            f"{ns}_expired_txs_total", "Txs purged by TTL on the post-commit update."
        )
        self.rejected_txs = reg.counter(
            f"{ns}_rejected_txs_total",
            "Txs refused at admission, by reason (full/cache/quota/too_large).",
            ("reason",),
        )
        self.full = reg.gauge(
            f"{ns}_full", "1 while the mempool is at capacity (the reactor sheds gossip)."
        )


class P2PMetrics:
    """reference: p2p/metrics.go."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_p2p"
        self.peers = reg.gauge(f"{ns}_peers", "Connected peers.")
        self.peer_receive_bytes_total = reg.counter(
            f"{ns}_peer_receive_bytes_total", "Bytes received per channel.", ("chID",)
        )
        self.peer_send_bytes_total = reg.counter(
            f"{ns}_peer_send_bytes_total", "Bytes sent per channel.", ("chID",)
        )
        # flowrate gauges fed from the MConnection Monitors (libs/flowrate.py)
        # by the switch's periodic sampler (p2p/switch.py _flowrate_routine)
        self.send_rate_bytes = reg.gauge(
            f"{ns}_send_rate_bytes",
            "EWMA aggregate send rate across all peers (bytes/s).",
        )
        self.recv_rate_bytes = reg.gauge(
            f"{ns}_recv_rate_bytes",
            "EWMA aggregate receive rate across all peers (bytes/s).",
        )
        self.pending_send_messages = reg.gauge(
            f"{ns}_pending_send_messages",
            "Messages waiting in per-channel send queues, summed over peers.",
        )
        self.reconnect_attempts = reg.counter(
            f"{ns}_reconnect_attempts_total",
            "Persistent-peer reconnect dial attempts (p2p/switch.py backoff loop).",
        )
        # inbound admission control (p2p/conn/connection.py token buckets)
        self.oversized_msgs = reg.counter(
            f"{ns}_oversized_msgs_total",
            "Inbound messages that exceeded their channel's recv_message_capacity.",
            ("chID",),
        )
        self.rate_limited_msgs = reg.counter(
            f"{ns}_rate_limited_msgs_total",
            "Inbound messages shed by a sheddable channel's token bucket.",
            ("chID",),
        )
        self.rate_limit_disconnects = reg.counter(
            f"{ns}_rate_limit_disconnects_total",
            "Peers reported for persistent rate-limit misbehavior.",
        )
        # per-peer wall-clock skew from timestamped ping/pong (conn/
        # connection.py), sampled by the switch's flowrate routine; the
        # correction applied to cross-node propagation latencies
        self.clock_skew_seconds = reg.gauge(
            f"{ns}_clock_skew_seconds",
            "Estimated remote-minus-local wall-clock offset per peer.",
            ("peer",),
        )


class StateMetrics:
    """reference: state/metrics.go."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_state"
        self.block_processing_time = reg.histogram(
            f"{ns}_block_processing_time", "ApplyBlock wall seconds.",
        )


class BlockSyncMetrics:
    """reference: blocksync/metrics.go (Syncing gauge) plus the TPU path's
    batched-verification timing that the reference's serial loop lacks."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_blocksync"
        self.syncing = reg.gauge(
            f"{ns}_syncing", "1 while block sync (fast sync) is running."
        )
        self.num_peers = reg.gauge(
            f"{ns}_num_peers", "Peers the block pool can request from."
        )
        self.blocks_applied_total = reg.counter(
            f"{ns}_blocks_applied_total", "Blocks applied by block sync."
        )
        self.latest_block_height = reg.gauge(
            f"{ns}_latest_block_height", "Next height the pool will fetch."
        )
        self.verify_seconds = reg.histogram(
            f"{ns}_verify_seconds",
            "Wall seconds per batched commit-verification run (blocks x validators).",
        )
        self.peer_timeouts = reg.counter(
            f"{ns}_peer_timeouts_total",
            "Block requests that timed out (blocksync/pool.py; the peer "
            "backs off and is banned only on a sustained pattern).",
        )
        # -- ISSUE 12: pipelined catch-up ---------------------------------
        self.redos_total = reg.counter(
            f"{ns}_redos_total",
            "Heights requeued after a failed validation or in-flight redo "
            "(blocksync/pool.py redo_request).",
        )
        self.peer_score = reg.gauge(
            f"{ns}_peer_score",
            "EWMA quality score per block-sync peer (1.0 = perfect; peers "
            "below the ban threshold are disconnected). Series replaced "
            "each status pass so departed peers drop out.",
            ("peer",),
        )
        self.super_batch_rows = reg.histogram(
            f"{ns}_super_batch_rows",
            "Signature rows per cross-height super-batch verification "
            "(blocks x validators in one catch-up-lane flush).",
        )
        self.resume_events_total = reg.counter(
            f"{ns}_resume_events_total",
            "Crash-resume events: restarts that re-entered the catch-up "
            "pipeline from a checkpointed verified window without "
            "re-verifying it.",
        )
        self.degraded_runs_total = reg.counter(
            f"{ns}_degraded_runs_total",
            "Verify runs shrunk to single-block CPU verification because "
            "the verify circuit breaker was OPEN.",
        )


class StateSyncMetrics:
    """reference: the statesync half of node monitoring (the reference has
    no statesync metrics.go; series names follow its conventions)."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_statesync"
        self.syncing = reg.gauge(
            f"{ns}_syncing", "1 while a state sync (snapshot restore) is running."
        )
        self.snapshots_discovered_total = reg.counter(
            f"{ns}_snapshots_discovered_total", "Distinct snapshots offered by peers."
        )
        self.snapshot_height = reg.gauge(
            f"{ns}_snapshot_height", "Height of the snapshot being restored."
        )
        self.snapshot_chunks_total = reg.gauge(
            f"{ns}_snapshot_chunks_total", "Chunk count of the snapshot being restored."
        )
        self.chunks_applied_total = reg.counter(
            f"{ns}_chunks_applied_total", "Snapshot chunks applied via ABCI."
        )
        # -- ISSUE 12: statesync hardening --------------------------------
        self.chunk_retries_total = reg.counter(
            f"{ns}_chunk_retries_total",
            "Chunk fetches re-requested after a timeout or app-demanded "
            "refetch (exponential backoff, different peer).",
        )
        self.bad_chunks_total = reg.counter(
            f"{ns}_bad_chunks_total",
            "Chunks the app refused as corrupt/torn (sender punished, "
            "chunk re-queued from another peer).",
        )
        self.resume_events_total = reg.counter(
            f"{ns}_resume_events_total",
            "Restores resumed from a crash checkpoint (already-applied "
            "chunks skipped on the re-offer).",
        )
        self.fallbacks_total = reg.counter(
            f"{ns}_fallbacks_total",
            "State syncs abandoned for the structured blocksync-from-"
            "genesis fallback (no viable snapshots/peers left).",
        )


class RPCMetrics:
    """rpc/server.py load-shedding gate + per-method request telemetry. No
    reference counterpart — the reference bounds connections at the listener
    (MaxOpenConnections); here the gate is per-request so health/consensus
    routes stay served while broadcast/query traffic sheds, and every
    dispatched request is attributed to its method (ISSUE 10: "why was my
    request slow?"). Method label cardinality is bounded to the declared
    route table — unknown methods fold into `_other` (rpc/server.py
    _method_label)."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_rpc"
        self.inflight_requests = reg.gauge(
            f"{ns}_inflight_requests",
            "Sheddable RPC requests currently executing under the gate.",
        )
        self.shed_requests = reg.counter(
            f"{ns}_shed_requests_total",
            "Requests refused with 429 (gate full or overload pressure), by method.",
            ("method",),
        )
        self.request_duration = reg.histogram(
            f"{ns}_request_duration_seconds",
            "Wall seconds from dispatch to response per method (all "
            "transports + LocalClient route through the shared _dispatch).",
            ("method",),
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5,
                     5.0, 10.0),
        )
        self.requests = reg.counter(
            f"{ns}_requests_total",
            "Dispatched RPC requests by method and outcome "
            "(ok/shed/reject/error).",
            ("method", "outcome"),
        )


class OverloadMetrics:
    """node/overload.py pressure controller: sampled queue depths folded
    into a pressure level and shed switches (docs/ROBUSTNESS.md,
    'Overload protection')."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_overload"
        self.pressure_level = reg.gauge(
            f"{ns}_pressure_level",
            "Overload pressure: 0=normal 1=elevated (txs shed) 2=critical "
            "(non-critical gossip shed too). Votes are never shed.",
        )
        self.pressure = reg.gauge(
            f"{ns}_pressure",
            "Saturation [0,1] of each sampled signal.",
            ("signal",),
        )
        self.transitions = reg.counter(
            f"{ns}_transitions_total",
            "Pressure-level changes, by direction (up/down).",
            ("direction",),
        )
        self.shed = reg.counter(
            f"{ns}_shed_total",
            "Work units shed by surface (mempool_gossip/rpc/p2p arrivals "
            "dropped while the corresponding switch was flipped).",
            ("surface",),
        )


class BatchVerifyMetrics:
    """The batch-verify pipeline's flight-recorder metrics (crypto/batch.py,
    ops/aot_cache.py) plus device-health gauges. No reference counterpart —
    the reference's serial loop (types/validator_set.go:680-702) has no
    batch/fallback/compile dynamics to observe. Series catalogue:
    docs/OBSERVABILITY.md. Registered on the PROCESS-GLOBAL registry (the
    crypto pipeline is process-global state, shared by every in-process
    node), which NodeMetrics.expose appends to each node's exposition."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_batch_verify"
        self.flushes = reg.counter(
            f"{ns}_flushes_total", "Batch-verify flushes.", ("backend", "path")
        )
        self.sigs = reg.counter(
            f"{ns}_sigs_total", "Signatures submitted per flush path.",
            ("backend", "path"),
        )
        self.batch_size = reg.histogram(
            f"{ns}_batch_size", "Flush batch sizes (signatures per flush).",
            buckets=(1, 8, 64, 256, 512, 1024, 2048, 4096, 8192, 16384, 65536),
        )
        self.flush_seconds = reg.histogram(
            f"{ns}_flush_seconds", "End-to-end flush wall seconds.", ("path",)
        )
        self.prep_seconds = reg.histogram(
            f"{ns}_prep_seconds",
            "Host-prep wall seconds (hashing, scalar math, sorting).",
        )
        self.jit_bucket = reg.gauge(
            f"{ns}_jit_bucket", "Padded jit shape bucket of the last flush."
        )
        self.padding_lanes = reg.gauge(
            f"{ns}_padding_lanes",
            "Pad lanes wasted by shape bucketing in the last flush.",
        )
        self.pubkey_cache_hits = reg.counter(
            f"{ns}_pubkey_cache_hits_total", "Decompressed-pubkey cache hits."
        )
        self.pubkey_cache_misses = reg.counter(
            f"{ns}_pubkey_cache_misses_total", "Decompressed-pubkey cache misses."
        )
        self.rlc_fallbacks = reg.counter(
            f"{ns}_rlc_fallbacks_total",
            "RLC combined-check failures recovered via the per-signature path.",
        )
        # adversarial flush defense (crypto/batch.py bisection recovery +
        # crypto/provenance.py suspicion scoring, docs/ROBUSTNESS.md)
        self.recovery_flushes = reg.counter(
            f"{ns}_recovery_flushes_total",
            "Device/host flushes spent isolating bad rows after a combined-"
            "check failure (RLC bisection sub-checks + per-sig leaves).",
        )
        self.quarantined_rows = reg.counter(
            f"{ns}_quarantined_rows_total",
            "Rows verified while their source was quarantined (routed "
            "through the scheduler's quarantine lane).",
        )
        self.poisoned_sources = reg.gauge(
            f"{ns}_poisoned_sources",
            "Sources currently quarantined by the suspicion scorer "
            "(peer:/sender:/lane: tags whose rows recently failed).",
        )
        # signature-scheme attribution (ISSUE 14): BLS rows must never fold
        # into the ed25519 RLC headline — perf_ledger grows the matching
        # backend column from bench results, this is the live-node series
        self.backend_rows = reg.counter(
            f"{ns}_backend_rows_total",
            "Verification rows by signature backend (ed25519/sr25519/"
            "bls12_381; an aggregate-commit verify counts each covered "
            "signer as one row).",
            ("backend",),
        )
        self.backend_flushes = reg.counter(
            f"{ns}_backend_flushes_total",
            "Flushes/verifies that carried rows of each signature backend.",
            ("backend",),
        )
        self.aggregate_size = reg.gauge(
            f"{ns}_aggregate_size",
            "Validators covered by the last BLS aggregate-commit "
            "verification (one 96-byte signature regardless of this value).",
        )
        # streamed flush planner (crypto/batch.py ISSUE 13)
        self.chunks_per_flush = reg.histogram(
            f"{ns}_chunks_per_flush",
            "Planner chunks per STREAMED flush (unstreamed flushes are not "
            "observed here — count those via flushes_total by path).",
            buckets=(1, 2, 3, 4, 6, 9, 17, 33, 65),
        )
        self.prep_overlap_seconds = reg.counter(
            f"{ns}_prep_overlap_seconds_total",
            "Host-prep seconds overlapped with device execution by the "
            "streamed planner's double buffer.",
        )
        # stage-overlapped prep + verified-row memo (crypto/batch.py ISSUE 18)
        self.prep_hidden_ratio = reg.gauge(
            f"{ns}_prep_hidden_ratio",
            "Fraction of the last flush's host-prep wall hidden behind "
            "device/MSM execution (prep_overlap_s / prep_s; streamed, "
            "pipelined and striped host-RLC flushes all feed it).",
        )
        self.memo_hits = reg.counter(
            f"{ns}_memo_hits_total",
            "Rows answered from the cross-flush verified-row memo without "
            "re-verification (deferred-verified commit rows, light/catch-up "
            "re-verifies).",
        )
        self.compile_seconds = reg.counter(
            f"{ns}_compile_seconds_total",
            "Seconds spent tracing/exporting (export) or loading (deserialize) kernels.",
            ("kind",),
        )
        self.transfer_seconds = reg.counter(
            f"{ns}_transfer_seconds_total",
            "Seconds blocked in device result sync/fetch.",
        )
        # device health (read by bench.py's stall detector and node liveness
        # via libs.trace.device_health)
        self.device_up = reg.gauge(
            f"{NAMESPACE}_device_up",
            "1 when the last device call succeeded, 0 after a failure/stall.",
        )
        self.device_init_seconds = reg.gauge(
            f"{NAMESPACE}_device_init_seconds",
            "Wall seconds of jax device/backend initialization.",
        )
        self.device_last_call_timestamp = reg.gauge(
            f"{NAMESPACE}_device_last_call_timestamp_seconds",
            "Unix time of the last successful device call (age = now - this).",
        )
        # verify-path circuit breaker (crypto/circuit_breaker.py): trips flip
        # default-routed verification TPU->CPU-serial until a health probe
        # passes (docs/ROBUSTNESS.md)
        self.breaker_state = reg.gauge(
            f"{ns}_breaker_state",
            "Circuit breaker state: 0=closed (TPU), 1=open (CPU), 2=half-open (probing).",
        )
        self.breaker_trips = reg.counter(
            f"{ns}_breaker_trips_total",
            "Circuit-breaker trips (verify path degraded TPU->CPU).",
            ("reason",),
        )
        self.breaker_probes = reg.counter(
            f"{ns}_breaker_probes_total",
            "Device health-probe attempts while the breaker is tripped.",
            ("result",),
        )


class PubSubMetrics:
    """libs/pubsub.py subscription-buffer health. No reference counterpart —
    the reference CANCELS a slow subscriber on overflow; here overflow
    drops-oldest and this counter is how an operator notices."""

    def __init__(self, reg: Registry):
        self.dropped = reg.counter(
            f"{NAMESPACE}_pubsub_dropped_messages_total",
            "Events dropped oldest-first from a slow subscriber's full buffer.",
            ("subscriber",),
        )


class MeshMetrics:
    """Multi-chip mesh telemetry (parallel/sharded.py via
    parallel/telemetry.py). No reference counterpart — the reference has no
    device mesh. These series exist because every MULTICHIP round to date
    failed with zero per-shard evidence (ROADMAP item 2); the fed values
    come from the sharded submit/finish wrappers and the AOT artifact
    cache."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_mesh"
        self.devices = reg.gauge(
            f"{ns}_devices", "Devices in the active sharding mesh."
        )
        self.shard_lanes = reg.gauge(
            f"{ns}_shard_lanes",
            "Lanes per device shard in the last sharded flush.",
            ("device",),
        )
        self.pad_waste_fraction = reg.gauge(
            f"{ns}_pad_waste_fraction",
            "Fraction of lanes that were padding in the last sharded flush.",
        )
        self.flushes = reg.counter(
            f"{ns}_flushes_total", "Sharded device flushes.", ("kind",)
        )
        self.submit_seconds = reg.counter(
            f"{ns}_submit_seconds_total",
            "Wall seconds dispatching sharded programs (host-side submit).",
        )
        self.finish_seconds = reg.counter(
            f"{ns}_finish_seconds_total",
            "Wall seconds blocked syncing sharded results (D2H + stragglers).",
        )
        self.all_gathers = reg.counter(
            f"{ns}_all_gathers_total",
            "Cross-chip all_gather collectives issued by sharded flushes.",
        )
        self.all_gather_bytes = reg.counter(
            f"{ns}_all_gather_bytes_total",
            "Logical bytes moved by sharded all_gather collectives.",
        )
        self.prep_seconds = reg.counter(
            f"{ns}_prep_seconds_total",
            "Host wall seconds in per-shard RLC prep (window sort + bounds).",
        )
        self.aot_cache = reg.counter(
            f"{ns}_aot_cache_total",
            "AOT artifact-cache outcomes (hit=deserialized, miss=fresh "
            "export, corrupt=deleted+re-exported); machine-scoped keys make "
            "a foreign host's artifacts misses, never loader failures.",
            ("result",),
        )
        # Elastic mesh (ISSUE 19): per-device health + degrade ladder.
        self.device_health = reg.gauge(
            f"{ns}_device_health",
            "Per-device mesh health: 1=healthy, 0.5=dead-but-probing-clean "
            "(mid-rejoin), 0=dead. replace_series'd from the health "
            "snapshot, so a departed device's series drops instead of "
            "freezing.",
            ("device",),
        )
        self.rebuilds = reg.counter(
            f"{ns}_rebuilds_total",
            "Mesh topology rebuilds (device loss shrank the mesh, or a "
            "recovered device re-joined after clean probes).",
        )
        self.ladder_state = reg.gauge(
            f"{ns}_ladder_state",
            "Verification degrade-ladder rung: 0=full mesh, 1=survivor "
            "mesh, 2=single-chip, 3=host (breaker open).",
        )


class ObservatoryMetrics:
    """Profiler-capture and stall-forensics accounting (libs/profiler.py,
    libs/forensics.py): how often the observatory itself was used — a
    FORENSICS capture incrementing here is the signal a round hit a hard
    hang and left a diagnosis file behind."""

    def __init__(self, reg: Registry):
        self.profiler_actions = reg.counter(
            f"{NAMESPACE}_profiler_actions_total",
            "Profiler session actions (start/stop/trace_function).",
            ("action",),
        )
        self.forensics_captures = reg.counter(
            f"{NAMESPACE}_forensics_captures_total",
            "FORENSICS_*.json captures written, by trigger "
            "(watchdog/signal/timeout/manual).",
            ("kind",),
        )


class SLOMetrics:
    """SLO burn-rate engine accounting (libs/slo.py): declared budgets,
    good/breach classification, per-window burn rates, and guard trips —
    the tendermint_slo_* series a fleet dashboard alerts on. Node-local
    (each node declares and evaluates its own budgets)."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_slo"
        self.budget_seconds = reg.gauge(
            f"{ns}_budget_seconds",
            "Declared latency budget per objective ([slo] config).",
            ("slo",),
        )
        self.observations = reg.counter(
            f"{ns}_observations_total",
            "Latency observations classified against their budget.",
            ("slo", "verdict"),
        )
        self.burn_rate = reg.gauge(
            f"{ns}_burn_rate",
            "Error-budget burn rate per objective and window (1.0 consumes "
            "the budget exactly at the target rate).",
            ("slo", "window"),
        )
        self.tripped = reg.gauge(
            f"{ns}_tripped",
            "1 while the objective's multi-window burn-rate guard is tripped.",
            ("slo",),
        )
        self.trips = reg.counter(
            f"{ns}_trips_total",
            "Burn-rate guard trips (armed-to-tripped transitions).",
            ("slo",),
        )


class LightServiceMetrics:
    """Light-client-as-a-service accounting (light/service.py): the
    tendermint_light_* series a serving fleet's dashboard reads. Node-local
    (each node runs its own service over its own chain data). No reference
    counterpart — the reference's light client is client-side only."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_light"
        self.requests = reg.counter(
            f"{ns}_requests_total",
            "Light verification requests by outcome (cache/flush/bisection/"
            "shed/conflict/error).",
            ("outcome",),
        )
        self.cache_hits = reg.counter(
            f"{ns}_cache_hits_total",
            "Requests answered from the verified-header cache (includes "
            "single-flight followers).",
        )
        self.coalesced_lanes = reg.histogram(
            f"{ns}_coalesced_lanes_per_flush",
            "Signature lanes accumulated per coalesced cross-height device "
            "flush (many clients x many heights sharing one flush).",
            buckets=(1, 8, 64, 256, 1024, 4096, 16384, 65536),
        )
        self.shed = reg.counter(
            f"{ns}_shed_total",
            "Requests refused by the service-level max_pending backstop "
            "(the RPC LoadGate's sheds are counted separately).",
        )
        self.conflicting_headers = reg.counter(
            f"{ns}_conflicting_headers_total",
            "Conflicting-header detections (client-expected hash or a "
            "second verification path disagreed with the verified header).",
        )


class SchedulerMetrics:
    """Global verification scheduler accounting (crypto/scheduler.py): the
    tendermint_verify_lane_* series behind the QoS story — per-lane queue
    depth, queue waits, rows per combined flush, and how often the vote
    lane preempted queued bulk work. No reference counterpart — the
    reference has no shared device to schedule."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_verify_lane"
        self.lane_depth = reg.gauge(
            f"{ns}_depth",
            "Signature rows currently queued per scheduler lane "
            "(votes/light/admission/catchup).",
            ("lane",),
        )
        self.lane_wait = reg.histogram(
            f"{ns}_wait_seconds",
            "Seconds the oldest queued row of a lane waited before its "
            "combined flush started (one sample per flush per lane).",
            ("lane",),
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 1.0, 5.0),
        )
        self.lane_flush_rows = reg.histogram(
            f"{ns}_flush_rows",
            "Rows a lane contributed to each combined flush it rode.",
            ("lane",),
            buckets=(1, 8, 64, 256, 1024, 4096, 16384, 65536),
        )
        self.preemptions = reg.counter(
            f"{ns}_preemptions_total",
            "Vote-lane flushes dispatched while bulk-lane work was queued "
            "(the queued work waited; the votes did not).",
        )


class TxLifecycleMetrics:
    """Transaction lifecycle accounting (libs/txtrace.py): per-stage
    transition latencies and terminal outcomes of the tx journey
    received -> checked -> admitted -> gossiped -> proposed -> committed ->
    delivered. No reference counterpart — the reference's tx story ends at
    the mempool gauge; this is the layer that answers "where is my
    transaction?" per hash (the `tx_status` route reads the same ring)."""

    def __init__(self, reg: Registry):
        ns = f"{NAMESPACE}_tx"
        self.stage_seconds = reg.histogram(
            f"{ns}_stage_seconds",
            "Wall seconds spent reaching each lifecycle stage from the "
            "previous one (received/checked/admitted/first_gossiped/"
            "proposed/committed/delivered + terminal rejects).",
            ("stage",),
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5,
                     5.0, 15.0, 60.0),
        )
        self.terminal_total = reg.counter(
            f"{ns}_terminal_total",
            "Tx journeys ended, by outcome (delivered/rejected/evicted/"
            "expired).",
            ("outcome",),
        )
        self.tracked = reg.gauge(
            f"{ns}_tracked",
            "Tx journeys currently held in the lifecycle ring.",
        )


class ChaosMetrics:
    """tendermint_tpu/chaos engine accounting: how many faults a soak/smoke
    injected per level. Exposed so a chaos run's /metrics scrape shows the
    injected load next to the recovery counters it caused (breaker trips,
    reconnects, rlc fallbacks)."""

    def __init__(self, reg: Registry):
        self.faults_injected = reg.counter(
            f"{NAMESPACE}_chaos_faults_injected_total",
            "Faults injected by the chaos engine.",
            ("level",),
        )


class FleetMetrics:
    """Fleet-soak referee accounting (tools/fleet_referee.py + chaos/fleet.py):
    how many nodes each role contributed, how many cross-node safety
    comparisons the referee ran, and which verdicts it handed down. Global
    (not per-Node) because the referee sits OUTSIDE any single node — it
    audits all of them."""

    def __init__(self, reg: Registry):
        self.nodes_by_role = reg.gauge(
            f"{NAMESPACE}_fleet_nodes_by_role",
            "Live fleet nodes per role (validator/full/light_edge).",
            ("role",),
        )
        self.safety_checks = reg.counter(
            f"{NAMESPACE}_fleet_safety_checks_total",
            "Per-height cross-node block-hash comparisons run by the "
            "fleet referee's safety auditor.",
        )
        self.referee_verdicts = reg.counter(
            f"{NAMESPACE}_fleet_referee_verdicts_total",
            "Fleet-referee verdicts handed down, by verdict "
            "(pass/partial/slo_tripped/safety_violation/no_data).",
            ("verdict",),
        )


# Process-global registry: series owned by process-global subsystems (the
# crypto batch pipeline, the AOT kernel cache, pubsub overflow accounting)
# rather than a Node instance.
_GLOBAL_LOCK = threading.Lock()
_GLOBAL_REGISTRY: Optional[Registry] = None
_BATCH_METRICS: Optional[BatchVerifyMetrics] = None
_PUBSUB_METRICS: Optional[PubSubMetrics] = None
_CHAOS_METRICS: Optional[ChaosMetrics] = None
_MESH_METRICS: Optional[MeshMetrics] = None
_OBSERVATORY_METRICS: Optional[ObservatoryMetrics] = None
_FLEET_METRICS: Optional[FleetMetrics] = None


def global_registry() -> Registry:
    global _GLOBAL_REGISTRY, _BATCH_METRICS, _PUBSUB_METRICS, _CHAOS_METRICS
    global _MESH_METRICS, _OBSERVATORY_METRICS, _FLEET_METRICS
    with _GLOBAL_LOCK:
        if _GLOBAL_REGISTRY is None:
            _GLOBAL_REGISTRY = Registry()
            _BATCH_METRICS = BatchVerifyMetrics(_GLOBAL_REGISTRY)
            _PUBSUB_METRICS = PubSubMetrics(_GLOBAL_REGISTRY)
            _CHAOS_METRICS = ChaosMetrics(_GLOBAL_REGISTRY)
            _MESH_METRICS = MeshMetrics(_GLOBAL_REGISTRY)
            _OBSERVATORY_METRICS = ObservatoryMetrics(_GLOBAL_REGISTRY)
            _FLEET_METRICS = FleetMetrics(_GLOBAL_REGISTRY)
        return _GLOBAL_REGISTRY


def batch_metrics() -> BatchVerifyMetrics:
    global_registry()
    return _BATCH_METRICS


def pubsub_metrics() -> PubSubMetrics:
    global_registry()
    return _PUBSUB_METRICS


def chaos_metrics() -> ChaosMetrics:
    global_registry()
    return _CHAOS_METRICS


def mesh_metrics() -> MeshMetrics:
    global_registry()
    return _MESH_METRICS


def observatory_metrics() -> ObservatoryMetrics:
    global_registry()
    return _OBSERVATORY_METRICS


def fleet_metrics() -> FleetMetrics:
    global_registry()
    return _FLEET_METRICS


class NodeMetrics:
    """One registry + all subsystem metric sets
    (reference: node/node.go:106 DefaultMetricsProvider)."""

    _latest: Optional["NodeMetrics"] = None

    def __init__(self):
        self.registry = Registry()
        self.consensus = ConsensusMetrics(self.registry)
        self.mempool = MempoolMetrics(self.registry)
        self.p2p = P2PMetrics(self.registry)
        self.state = StateMetrics(self.registry)
        self.blocksync = BlockSyncMetrics(self.registry)
        self.statesync = StateSyncMetrics(self.registry)
        self.rpc = RPCMetrics(self.registry)
        self.overload = OverloadMetrics(self.registry)
        self.slo = SLOMetrics(self.registry)
        self.light = LightServiceMetrics(self.registry)
        self.scheduler = SchedulerMetrics(self.registry)
        self.txtrace = TxLifecycleMetrics(self.registry)
        NodeMetrics._latest = self

    @classmethod
    def latest(cls) -> Optional["NodeMetrics"]:
        """Most recently constructed instance (bench.py snapshots the node
        its sub-benchmarks ran, without plumbing the object out)."""
        return cls._latest

    def snapshot(self) -> dict:
        """Node-local written series only (the process-global batch-verify
        series ride bench's `extra.verify_stats` already)."""
        return self.registry.snapshot()

    def expose(self) -> str:
        # node-local series + the process-global batch-verify/device series
        # (every in-process node shares the one crypto pipeline)
        return self.registry.expose() + global_registry().expose()


