"""Perf-trajectory ledger: every round's datapoint in one table, forever.

Aggregates the driver's per-round artifacts — `BENCH_r*.json` (wrapper:
`{"n", "cmd", "rc", "tail", "parsed": <bench JSON line or null>}`) and
`MULTICHIP_r*.json` (`{"n_devices", "rc", "ok", "skipped", "tail"}`) — into
one trajectory table rendered as markdown + JSON:

- headline metric/value/speedup per round, with **lost** datapoints flagged
  and diagnosed (r01: no parseable JSON; r05: `value: -1` device-init
  stall) instead of silently skipped;
- per-scenario speedups so a regression names its scenario;
- the machine fingerprint + jax versions each round ran on (stamped by
  bench.py since round 7; older rounds show `—`), because the r04→r05 AOT
  failures were cross-host artifacts that BENCH json couldn't expose;
- multichip round diagnoses (rc-124 timeout, cpu_aot_loader
  machine-feature mismatch, skip, ok).

`--check` turns the ledger into a budget guard in the spirit of
tests/test_hotpath_guard.py: exit nonzero when the newest non-lost,
non-degraded headline regressed by more than `--tolerance` (default 25%)
against the best earlier round of the same metric — so a perf regression
fails loudly at ledger time, not three rounds later in someone's memory.

    python tools/perf_ledger.py [--root DIR] [--json OUT] [--markdown OUT]
                                [--check] [--tolerance 0.25]
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

# The trajectory's north-star datapoint. bench.py now prepends this config
# to every scenario-scoped plan, and the ledger flags any round that still
# lacks it (BENCH_r06 was a catchup-only round that silently lost the
# headline — the matrix showed it, the headline row did not).
HEADLINE_SCENARIO = "verify_commit_10k"
HEADLINE_METRIC = f"{HEADLINE_SCENARIO}_latency"


def _round_of(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def _round_label(row: dict) -> str:
    """`r04` — or the filename stem for artifacts with no numeric round
    suffix (BENCH_rerun.json matches the glob but not _ROUND_RE); the
    ledger flags odd artifacts, it never dies on them."""
    if row.get("round") is not None:
        return f"r{row['round']:02d}"
    return os.path.splitext(row.get("file") or "?")[0]


def _load(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        return {"_load_error": f"{type(e).__name__}: {e}"}


def _scenario_speedups(extra: dict) -> Dict[str, Any]:
    """Per-scenario comparable numbers out of a bench `extra` blob."""
    out: Dict[str, Any] = {}
    for name, res in (extra or {}).items():
        if not isinstance(res, dict):
            continue
        entry: Dict[str, Any] = {}
        for key in ("speedup_e2e", "speedup"):
            if isinstance(res.get(key), (int, float)):
                entry["speedup"] = res[key]
                break
        # signature-scheme backend column (ISSUE 14): scenarios stamp
        # `backend` so e.g. BLS aggregate numbers render in their own
        # column and never fold into the ed25519 RLC headline trajectory
        if isinstance(res.get("backend"), str):
            entry["backend"] = res["backend"]
        if isinstance(res.get("tpu_e2e_ms"), (int, float)):
            entry["tpu_e2e_ms"] = res["tpu_e2e_ms"]
        # prep-overlap column (ISSUE 18): fraction of host-prep wall hidden
        # behind device/MSM work for the flush the scenario timed
        if isinstance(res.get("prep_wall_hidden"), (int, float)):
            entry["prep_hidden"] = res["prep_wall_hidden"]
        # elastic-mesh column (ISSUE 19): survivor-mesh throughput as a
        # fraction of the full mesh's, plus the final ladder rung
        if isinstance(res.get("degrade_ratio"), (int, float)):
            entry["degrade_ratio"] = res["degrade_ratio"]
        if isinstance(res.get("mesh_ladder"), str):
            entry["mesh_ladder"] = res["mesh_ladder"]
        # adversarial-flush column (ISSUE 20): vote-path p99 under a 1%
        # signature-poisoning flood as a multiple of the clean baseline
        if isinstance(res.get("p99_ratio_1pct"), (int, float)):
            entry["p99_ratio_1pct"] = res["p99_ratio_1pct"]
        if "quarantine_isolated" in res:
            entry["quarantine_isolated"] = bool(res["quarantine_isolated"])
        if isinstance(res.get("sigs_per_sec"), (int, float)):
            entry["sigs_per_sec"] = res["sigs_per_sec"]
        if res.get("degraded"):
            entry["degraded"] = res["degraded"]
        if res.get("skipped"):
            entry["skipped"] = True
        if entry:
            out[name] = entry
    return out


def parse_bench(path: str) -> dict:
    """One BENCH_r*.json → a ledger row. Accepts both the driver wrapper
    shape and a bare bench JSON line saved to a file."""
    doc = _load(path)
    row: Dict[str, Any] = {
        "round": _round_of(path),
        "file": os.path.basename(path),
        "kind": "bench",
        "lost": False,
        "lost_reason": None,
        "degraded": None,
        "metric": None,
        "value": None,
        "unit": None,
        "vs_baseline": None,
        "fingerprint": None,
        "versions": None,
        "scenarios": {},
        "fleet_gate": None,
        "fleet_gate_missing": True,
        "mesh_degrade": None,
        "poison_defense": None,
    }
    if doc is None or "_load_error" in (doc or {}):
        row["lost"] = True
        row["lost_reason"] = (doc or {}).get("_load_error", "unreadable file")
        return row
    parsed = doc.get("parsed", doc if "metric" in doc else None)
    row["rc"] = doc.get("rc")
    if parsed is None:
        row["lost"] = True
        row["lost_reason"] = (
            f"no parseable bench JSON (rc={doc.get('rc')})"
            if "rc" in doc
            else "no parseable bench JSON"
        )
        return row
    row["metric"] = parsed.get("metric")
    row["value"] = parsed.get("value")
    row["unit"] = parsed.get("unit")
    row["vs_baseline"] = parsed.get("vs_baseline")
    row["degraded"] = parsed.get("degraded")
    extra = parsed.get("extra") or {}
    host = extra.get("host") or parsed.get("host") or {}
    if host:
        row["fingerprint"] = host.get("machine_fingerprint")
        row["versions"] = {
            k: host.get(k) for k in ("jax", "jaxlib", "python", "git_sha")
            if host.get(k)
        }
    row["scenarios"] = _scenario_speedups(extra)
    # headline prep-overlap trajectory (ISSUE 18): rounds before the staged
    # prep pipeline simply show "—"
    head = row["scenarios"].get(HEADLINE_SCENARIO) or {}
    row["prep_hidden"] = head.get("prep_hidden")
    # fleet-gate column (ISSUE 17): rounds that ran the `fleet_soak`
    # scenario carry the referee verdict + heights + safety-violation count;
    # rounds that didn't are flagged like headline_missing — a silently
    # skipped fleet gate must read as a gap, not a pass
    fs = extra.get("fleet_soak")
    if isinstance(fs, dict) and fs.get("verdict"):
        row["fleet_gate"] = {
            "verdict": fs.get("verdict"),
            "heights": fs.get("heights"),
            "violations": fs.get("safety_violations"),
        }
        row["fleet_gate_missing"] = False
    # mesh-degrade column (ISSUE 19): rounds that ran the `mesh_failover`
    # scenario carry the survivor/full throughput ratio, the final ladder
    # rung, the rebuild wall and the lost-verdict count; rounds that
    # didn't show "—" (a gap, not a pass)
    mf = extra.get("mesh_failover")
    if isinstance(mf, dict) and (
        mf.get("degrade_ratio") is not None or mf.get("mesh_ladder")
    ):
        row["mesh_degrade"] = {
            "ratio": mf.get("degrade_ratio"),
            "ladder": mf.get("mesh_ladder"),
            "rebuild_s": mf.get("rebuild_s"),
            "lost_verdicts": (mf.get("during") or {}).get("lost_verdicts"),
        }
    # quarantine/recovery column (ISSUE 20): rounds that ran the
    # `poisoned_flush` scenario carry the vote-path p99 ratio under a 1%
    # signature-poisoning flood, the bisection-vs-naive recovery speedup,
    # the recovery-flush count at 1%, and whether the quarantine lane
    # isolated exactly the poisoner; rounds that didn't show "—"
    pf = extra.get("poisoned_flush")
    if isinstance(pf, dict) and (
        pf.get("p99_ratio_1pct") is not None
        or pf.get("quarantine_isolated") is not None
    ):
        one_pct = (pf.get("rates") or {}).get("0.01") or {}
        row["poison_defense"] = {
            "p99_ratio_1pct": pf.get("p99_ratio_1pct"),
            "speedup": pf.get("speedup"),
            "recovery_flushes_1pct": one_pct.get("recovery_flushes"),
            "quarantined_rows_1pct": one_pct.get("quarantined_rows"),
            "quarantine_isolated": pf.get("quarantine_isolated"),
        }
    # a parsed round that carries NEITHER the headline metric nor a
    # headline scenario datapoint lost the trajectory point — flag it
    # explicitly instead of leaving a silent gap in the matrix
    row["headline_missing"] = (
        row["metric"] != HEADLINE_METRIC
        and HEADLINE_SCENARIO not in row["scenarios"]
        and HEADLINE_SCENARIO not in (extra or {})
    )
    if not isinstance(row["value"], (int, float)) or row["value"] < 0:
        row["lost"] = True
        err = extra.get("error") or parsed.get("degrade_reason")
        row["lost_reason"] = (
            f"value {row['value']!r}" + (f" ({err})" if err else "")
        )
    elif doc.get("rc") not in (0, None):
        # the datapoint parsed but the run exited nonzero — keep the value,
        # flag the round
        row["lost_reason"] = f"bench exited rc={doc['rc']} (value salvaged)"
    return row


def diagnose_multichip(doc: dict) -> str:
    if doc.get("skipped"):
        return "skipped"
    tail = doc.get("tail") or ""
    if doc.get("rc") == 124:
        return "timeout (rc 124): hard deadline with no diagnosis — "\
               "the forensics watchdog (libs/forensics.py) now captures these"
    if "cpu_aot_loader" in tail or "machine feature" in tail.lower():
        return "AOT machine-feature mismatch (foreign-host artifact loaded; "\
               "fixed by machine-fingerprint cache scoping)"
    if doc.get("ok"):
        return "ok"
    if doc.get("rc", 0) != 0:
        return f"failed rc={doc.get('rc')}"
    return "failed (no diagnosis in tail)"


def parse_multichip(path: str) -> dict:
    doc = _load(path)
    row: Dict[str, Any] = {
        "round": _round_of(path),
        "file": os.path.basename(path),
        "kind": "multichip",
    }
    if doc is None or "_load_error" in (doc or {}):
        row.update(ok=False, lost=True,
                   diagnosis=(doc or {}).get("_load_error", "unreadable file"))
        return row
    row.update(
        n_devices=doc.get("n_devices"),
        rc=doc.get("rc"),
        ok=bool(doc.get("ok")),
        skipped=bool(doc.get("skipped")),
        lost=not doc.get("ok") and not doc.get("skipped"),
        diagnosis=diagnose_multichip(doc),
    )
    return row


def load_ledger(root: str) -> dict:
    bench = sorted(
        (parse_bench(p) for p in glob.glob(os.path.join(root, "BENCH_r*.json"))),
        key=lambda r: (r["round"] is None, r["round"] or 0, r.get("file") or ""),
    )
    multichip = sorted(
        (parse_multichip(p) for p in glob.glob(os.path.join(root, "MULTICHIP_r*.json"))),
        key=lambda r: (r["round"] is None, r["round"] or 0, r.get("file") or ""),
    )
    return {
        "root": os.path.abspath(root),
        "bench": bench,
        "multichip": multichip,
        "lost_datapoints": [
            r["file"] for r in bench + multichip if r.get("lost")
        ],
        "headline_missing_rounds": [
            r["file"] for r in bench if r.get("headline_missing")
        ],
        "fleet_gate_missing_rounds": [
            r["file"] for r in bench if r.get("fleet_gate_missing")
        ],
    }


def check_regressions(ledger: dict, tolerance: float = 0.25) -> List[str]:
    """Headline budget guard: the newest healthy bench round must not be
    slower than the best earlier healthy round of the SAME metric by more
    than `tolerance`. Returns human-readable failures (empty = pass)."""
    healthy = [
        r for r in ledger["bench"]
        if not r["lost"] and not r.get("degraded")
        and isinstance(r.get("value"), (int, float))
    ]
    failures: List[str] = []
    latest = healthy[-1] if healthy else None
    prior = (
        [r for r in healthy[:-1] if r["metric"] == latest["metric"]]
        if len(healthy) >= 2 else []
    )
    if prior:
        best = min(prior, key=lambda r: r["value"])
        budget = best["value"] * (1.0 + tolerance)
        if latest["value"] > budget:
            failures.append(
                f"headline regression: {latest['metric']} = "
                f"{latest['value']:.3f}{latest['unit'] or ''} in "
                f"{latest['file']} vs best {best['value']:.3f} in "
                f"{best['file']} (budget {budget:.3f}, tolerance "
                f"{tolerance:.0%})"
            )
    # fleet gate (ISSUE 17): the newest round that ran the fleet soak must
    # have a passing referee verdict with zero safety violations
    ran_fleet = [r for r in ledger["bench"] if r.get("fleet_gate")]
    if ran_fleet:
        latest_fg = ran_fleet[-1]
        fg = latest_fg["fleet_gate"]
        if fg.get("verdict") != "pass" or (fg.get("violations") or 0) > 0:
            failures.append(
                f"fleet gate failed in {latest_fg['file']}: "
                f"verdict={fg.get('verdict')} heights={fg.get('heights')} "
                f"violations={fg.get('violations')}"
            )
    # poison defense (ISSUE 20): the newest round that ran the poisoned
    # flood must keep vote-path p99 within 2x of the clean baseline AND
    # the quarantine lane must have isolated exactly the poisoner
    ran_poison = [r for r in ledger["bench"] if r.get("poison_defense")]
    if ran_poison:
        latest_pd = ran_poison[-1]
        pd = latest_pd["poison_defense"]
        ratio = pd.get("p99_ratio_1pct")
        if isinstance(ratio, (int, float)) and ratio > 2.0:
            failures.append(
                f"poison defense failed in {latest_pd['file']}: vote-path "
                f"p99 under 1% poison flood is {ratio:.2f}x the clean "
                f"baseline (budget 2.00x)"
            )
        if pd.get("quarantine_isolated") is False:
            failures.append(
                f"poison defense failed in {latest_pd['file']}: quarantine "
                f"lane did not isolate the poisoner "
                f"(quarantine_isolated=false)"
            )
    return failures


def _fmt_versions(v: Optional[dict]) -> str:
    if not v:
        return "—"
    bits = []
    if v.get("jax"):
        bits.append(f"jax {v['jax']}")
    if v.get("git_sha"):
        bits.append(v["git_sha"][:9])
    return ", ".join(bits) or "—"


def render_markdown(ledger: dict) -> str:
    lines = [
        "# Perf trajectory ledger",
        "",
        f"Source: `{ledger['root']}` — {len(ledger['bench'])} bench rounds, "
        f"{len(ledger['multichip'])} multichip rounds, "
        f"{len(ledger['lost_datapoints'])} lost/failed datapoints.",
        "",
        "## Bench rounds",
        "",
        "| round | metric | value | speedup | prep hidden | fleet gate | mesh degrade | poison defense | host | status |",
        "|---:|---|---:|---:|---:|---|---|---|---|---|",
    ]
    for r in ledger["bench"]:
        if r["lost"]:
            status = f"**LOST** — {r['lost_reason']}"
            if r.get("headline_missing"):
                status += "; headline MISSING"
            value = "—"
            speed = "—"
        else:
            status = "degraded (cpu-fallback)" if r.get("degraded") else "ok"
            if r.get("lost_reason"):
                status += f"; {r['lost_reason']}"
            if r.get("headline_missing"):
                status += "; **headline MISSING**"
            value = (
                f"{r['value']:.1f} {r['unit'] or ''}".strip()
                if isinstance(r["value"], (int, float))
                else "—"
            )
            speed = (
                f"{r['vs_baseline']:.2f}×"
                if isinstance(r["vs_baseline"], (int, float)) and r["vs_baseline"]
                else "—"
            )
        fg = r.get("fleet_gate")
        if fg:
            mark = "" if fg.get("verdict") == "pass" else "**"
            fleet = (
                f"{mark}{fg.get('verdict')}{mark}·{fg.get('heights') or '?'}h·"
                f"{fg.get('violations') if fg.get('violations') is not None else '?'}v"
            )
        else:
            fleet = "missing"
        md = r.get("mesh_degrade")
        if md:
            ratio = md.get("ratio")
            mesh = (
                f"{ratio:.2f}×" if isinstance(ratio, (int, float)) else "?"
            ) + f"·{md.get('ladder') or '?'}"
            lost = md.get("lost_verdicts")
            if lost:  # nonzero lost verdicts is a failover BUG — shout
                mesh += f"·**{lost} lost**"
        else:
            mesh = "—"
        pd = r.get("poison_defense")
        if pd:
            ratio = pd.get("p99_ratio_1pct")
            poison = (
                f"{ratio:.2f}×" if isinstance(ratio, (int, float)) else "?"
            )
            rec = pd.get("recovery_flushes_1pct")
            if rec is not None:
                poison += f"·{rec}rf"
            if pd.get("quarantine_isolated") is False:
                poison += "·**LEAK**"  # quarantine missed the poisoner — BUG
        else:
            poison = "—"
        host = r["fingerprint"] or "—"
        if r.get("versions"):
            host += f" ({_fmt_versions(r['versions'])})"
        hidden = (
            f"{r['prep_hidden']:.0%}"
            if isinstance(r.get("prep_hidden"), (int, float))
            else "—"
        )
        lines.append(
            f"| {_round_label(r)} | {r['metric'] or '—'} | {value} "
            f"| {speed} | {hidden} | {fleet} | {mesh} | {poison} "
            f"| {host} | {status} |"
        )
    lines += ["", "### Per-scenario speedups", ""]
    scen_names: List[str] = []
    for r in ledger["bench"]:
        for name in r["scenarios"]:
            if name not in scen_names:
                scen_names.append(name)
    if scen_names:
        lines.append("| scenario | backend | " + " | ".join(
            _round_label(r) for r in ledger["bench"]) + " |")
        lines.append("|---|---|" + "---:|" * len(ledger["bench"]))
        for name in scen_names:
            cells = []
            backend = "ed25519"  # pre-ISSUE-14 scenarios are all ed25519/RLC
            for r in ledger["bench"]:
                s = r["scenarios"].get(name)
                if s and s.get("backend"):
                    backend = s["backend"]
                hid = (
                    f"·h{s['prep_hidden']:.0%}"
                    if s and isinstance(s.get("prep_hidden"), (int, float))
                    else ""
                )
                if not s:
                    cells.append("—")
                elif s.get("degraded"):
                    cells.append("cpu!" + hid)
                elif "speedup" in s:
                    cells.append(f"{s['speedup']:.2f}×{hid}")
                elif "sigs_per_sec" in s:
                    cells.append(f"{s['sigs_per_sec']:,}/s")
                else:
                    cells.append("·")
            lines.append(f"| {name} | {backend} | " + " | ".join(cells) + " |")
    else:
        lines.append("(no per-scenario data)")
    lines += [
        "",
        "## Multichip rounds",
        "",
        "| round | devices | rc | status |",
        "|---:|---:|---:|---|",
    ]
    for r in ledger["multichip"]:
        lines.append(
            f"| {_round_label(r)} | {r.get('n_devices', '—')} "
            f"| {r.get('rc', '—')} | {r.get('diagnosis', '—')} |"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--root", default=".",
        help="directory holding BENCH_r*.json / MULTICHIP_r*.json (repo root)",
    )
    ap.add_argument("--json", help="write the ledger as JSON here")
    ap.add_argument("--markdown", help="write the markdown table here")
    ap.add_argument(
        "--check", action="store_true",
        help="exit 2 on a headline budget regression (see --tolerance)",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.25,
        help="allowed headline slowdown vs the best prior round (default 0.25)",
    )
    args = ap.parse_args(argv)
    ledger = load_ledger(args.root)
    if not ledger["bench"] and not ledger["multichip"]:
        print(f"error: no BENCH_r*/MULTICHIP_r* files under {args.root!r}",
              file=sys.stderr)
        return 1
    failures = check_regressions(ledger, args.tolerance)
    ledger["regressions"] = failures
    md = render_markdown(ledger)
    if failures:
        md += "\n## REGRESSIONS\n\n" + "\n".join(f"- {f}" for f in failures) + "\n"
    sys.stdout.write(md)
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(md)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(ledger, f, indent=1)
    if args.check and failures:
        for f in failures:
            print(f"REGRESSION: {f}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
