"""ABCI: the 17-method application boundary (reference: abci/types/application.go:9-32).

Requests/responses are plain dataclasses (the local in-process path needs no
serialization; the socket/grpc transports marshal them). Method set and
semantics mirror ABCI 0.17 / Tendermint v0.34:

  Info/SetOption/Query            — query connection
  CheckTx                         — mempool connection
  InitChain/BeginBlock/DeliverTx/EndBlock/Commit — consensus connection
  ListSnapshots/OfferSnapshot/LoadSnapshotChunk/ApplySnapshotChunk — snapshot
  Echo/Flush                      — transport plumbing
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

CODE_TYPE_OK = 0


@dataclass
class Event:
    type: str = ""
    attributes: List[Tuple[bytes, bytes, bool]] = field(default_factory=list)
    # (key, value, index)


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_bytes: bytes
    power: int


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class RequestSetOption:
    key: str = ""
    value: str = ""


@dataclass
class ResponseSetOption:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""


@dataclass
class RequestInitChain:
    time_ns: int = 0
    chain_id: str = ""
    consensus_params: Optional[object] = None  # types.ConsensusParams
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass
class ResponseInitChain:
    consensus_params: Optional[object] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: Optional[object] = None
    height: int = 0
    codespace: str = ""


@dataclass
class LastCommitInfo:
    round: int = 0
    votes: List[Tuple[bytes, int, bool]] = field(default_factory=list)
    # (validator address, power, signed_last_block)


@dataclass
class EvidenceABCI:
    type: int = 0  # 1 = duplicate vote
    validator_address: bytes = b""
    validator_power: int = 0
    height: int = 0
    time_ns: int = 0
    total_voting_power: int = 0


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: Optional[object] = None  # types.Header
    last_commit_info: LastCommitInfo = field(default_factory=LastCommitInfo)
    byzantine_validators: List[EvidenceABCI] = field(default_factory=list)


@dataclass
class ResponseBeginBlock:
    events: List[Event] = field(default_factory=list)


CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1

# Node-side signature-precheck verdict riding RequestCheckTx (the ABCI
# split behind device-batched tx admission, crypto/scheduler.py): the node
# decoded a signed-tx envelope (types/signed_tx.py) and batch-verified its
# signature through the admission lane, so the app consumes the verdict
# instead of paying a serial per-tx verify. NONE means the node did not
# pre-verify (plain tx, precheck disabled, or a remote submitter) — the
# app must verify itself exactly as before.
SIG_PRECHECK_NONE = 0
SIG_PRECHECK_OK = 1
SIG_PRECHECK_BAD = 2


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_NEW
    sig_precheck: int = SIG_PRECHECK_NONE


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""
    sender: str = ""
    priority: int = 0


@dataclass
class RequestDeliverTx:
    tx: bytes = b""


@dataclass
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class RequestEndBlock:
    height: int = 0


@dataclass
class ResponseEndBlock:
    validator_updates: List[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: Optional[object] = None
    events: List[Event] = field(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""  # the app hash
    retain_height: int = 0


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass
class ResponseListSnapshots:
    snapshots: List[Snapshot] = field(default_factory=list)


@dataclass
class RequestOfferSnapshot:
    snapshot: Optional[Snapshot] = None
    app_hash: bytes = b""


OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_ACCEPT


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


APPLY_SNAPSHOT_CHUNK_ACCEPT = 1
APPLY_SNAPSHOT_CHUNK_ABORT = 2
APPLY_SNAPSHOT_CHUNK_RETRY = 3
APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT = 4
APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT = 5


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_SNAPSHOT_CHUNK_ACCEPT
    refetch_chunks: List[int] = field(default_factory=list)
    reject_senders: List[str] = field(default_factory=list)


class Application:
    """Base application: every method is a no-op returning defaults
    (reference: abci/types/application.go BaseApplication)."""

    def info(self, req: RequestInfo) -> ResponseInfo:
        return ResponseInfo()

    def set_option(self, req: RequestSetOption) -> ResponseSetOption:
        return ResponseSetOption()

    def query(self, req: RequestQuery) -> ResponseQuery:
        return ResponseQuery()

    def check_tx(self, req: RequestCheckTx) -> ResponseCheckTx:
        return ResponseCheckTx()

    def init_chain(self, req: RequestInitChain) -> ResponseInitChain:
        return ResponseInitChain()

    def begin_block(self, req: RequestBeginBlock) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, req: RequestDeliverTx) -> ResponseDeliverTx:
        return ResponseDeliverTx()

    def end_block(self, req: RequestEndBlock) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def list_snapshots(self) -> ResponseListSnapshots:
        return ResponseListSnapshots()

    def offer_snapshot(self, req: RequestOfferSnapshot) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot(result=OFFER_SNAPSHOT_ABORT)

    def load_snapshot_chunk(self, req: RequestLoadSnapshotChunk) -> ResponseLoadSnapshotChunk:
        return ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req: RequestApplySnapshotChunk) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk(result=APPLY_SNAPSHOT_CHUNK_ABORT)
