"""ABCI clients (reference: abci/client/client.go:22).

LocalClient: direct in-process calls under one lock (reference:
abci/client/local_client.go:15) — the default for in-proc apps. The socket
client/server for out-of-process apps lives in abci.socket.
"""

from __future__ import annotations

import threading
from typing import Optional

from tendermint_tpu.abci import types as abci


class ABCIClient:
    """Synchronous 17-method client interface. Async pipelining is layered on
    top by callers that need it (the executor batches DeliverTx itself)."""

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def set_option(self, req: abci.RequestSetOption) -> abci.ResponseSetOption:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError

    def echo(self, msg: str) -> str:
        return msg

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class LocalClient(ABCIClient):
    """Direct calls to an in-process Application under a shared mutex —
    mirrors the reference's local_client semantics where all connections to
    one app serialize on one lock (reference: abci/client/local_client.go:23)."""

    def __init__(self, app: abci.Application, lock: Optional[threading.RLock] = None):
        self.app = app
        self.lock = lock or threading.RLock()

    def info(self, req):
        with self.lock:
            return self.app.info(req)

    def set_option(self, req):
        with self.lock:
            return self.app.set_option(req)

    def query(self, req):
        with self.lock:
            return self.app.query(req)

    def check_tx(self, req):
        with self.lock:
            return self.app.check_tx(req)

    def init_chain(self, req):
        with self.lock:
            return self.app.init_chain(req)

    def begin_block(self, req):
        with self.lock:
            return self.app.begin_block(req)

    def deliver_tx(self, req):
        with self.lock:
            return self.app.deliver_tx(req)

    def end_block(self, req):
        with self.lock:
            return self.app.end_block(req)

    def commit(self):
        with self.lock:
            return self.app.commit()

    def list_snapshots(self):
        with self.lock:
            return self.app.list_snapshots()

    def offer_snapshot(self, req):
        with self.lock:
            return self.app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        with self.lock:
            return self.app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        with self.lock:
            return self.app.apply_snapshot_chunk(req)
