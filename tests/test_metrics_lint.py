"""Exposition-format and wiring lints for libs/metrics.py.

Three guards around the node's ~60 Prometheus series:

1. a promtool-style strict lint of `Registry.expose()` output (HELP/TYPE
   ordering, histogram `+Inf` bucket presence, `_sum`/`_count` consistency,
   bucket monotonicity) run over a fully-populated NodeMetrics exposition;
2. a "no dead series" static check: every metric registered on a subsystem
   metrics set must have a write site somewhere in `tendermint_tpu/`
   (catches gauges that get registered but never fed — the original sin
   this PR fixes for the p2p flowrate Monitors);
3. the standalone PrometheusServer and the RPC `/metrics` route must render
   IDENTICAL output for the same NodeMetrics (they share `.expose()` by
   convention only; this pins the convention).
"""

import os
import re

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.libs import metrics as M


def _populated_node_metrics() -> M.NodeMetrics:
    nm = M.NodeMetrics()
    c = nm.consensus
    c.height.set(7)
    c.rounds.set(1)
    c.total_txs.inc(3)
    c.block_interval_seconds.observe(0.5)
    c.step_duration_seconds.labels("propose").observe(0.01)
    c.step_duration_seconds.labels("prevote").observe(0.2)
    c.step_duration_seconds.labels("prevote").observe(4.0)
    c.round_duration_seconds.observe(0.7)
    c.quorum_prevote_delay.set(0.05)
    c.proposal_receive_count.labels("accepted").inc()
    c.late_votes.labels("prevote").inc()
    c.block_parts.labels("true").inc(2)
    c.block_gossip_receive_latency.observe(0.02)
    nm.mempool.size.set(5)
    nm.mempool.size_bytes.set(512)
    nm.mempool.tx_size_bytes.observe(100)
    nm.p2p.peers.set(3)
    nm.p2p.send_rate_bytes.set(1024.5)
    nm.p2p.peer_send_bytes_total.labels("0x22").inc(10)
    nm.state.block_processing_time.observe(0.004)
    nm.blocksync.syncing.set(1)
    nm.blocksync.verify_seconds.observe(0.1)
    nm.statesync.chunks_applied_total.inc()
    # overload-protection series (ISSUE 5)
    nm.mempool.evicted_txs.inc(2)
    nm.mempool.rejected_txs.labels("quota").inc()
    nm.mempool.full.set(1)
    nm.p2p.rate_limited_msgs.labels("0x30").inc(5)
    nm.p2p.oversized_msgs.labels("0x30").inc()
    nm.rpc.inflight_requests.set(3)
    nm.rpc.shed_requests.labels("broadcast_tx_sync").inc()
    nm.overload.pressure_level.set(1)
    nm.overload.pressure.labels("mempool").set(0.8)
    nm.overload.transitions.labels("up").inc()
    nm.blocksync.peer_timeouts.inc()
    return nm


def _lint_exposition(text: str) -> None:
    """Strict promtool-style lint. Raises AssertionError with the offending
    line on any violation."""
    lines = [l for l in text.splitlines() if l.strip()]
    helped, typed = {}, {}
    sample_re = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$")
    prev_help = None
    for line in lines:
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert name not in helped, f"duplicate HELP for {name}"
            helped[name] = True
            prev_help = name
        elif line.startswith("# TYPE "):
            parts = line.split(" ")
            name, kind = parts[2], parts[3]
            assert prev_help == name, f"TYPE {name} not directly after its HELP"
            assert name not in typed, f"duplicate TYPE for {name}"
            assert kind in ("counter", "gauge", "histogram", "untyped"), line
            typed[name] = kind
            prev_help = None
        else:
            m = sample_re.match(line)
            assert m, f"unparseable sample line: {line!r}"
            name = m.group(1)
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and typed.get(name[: -len(suffix)]) == "histogram":
                    family = name[: -len(suffix)]
            assert family in typed and family in helped, (
                f"sample {name} has no preceding HELP/TYPE"
            )
            if typed[family] == "histogram":
                assert name != family, (
                    f"histogram {family} exposes a bare sample (want _bucket/_sum/_count)"
                )

    # histogram consistency from the parsed form
    fams = M.parse_exposition(text)
    for family, fam in fams.items():
        if fam["type"] != "histogram":
            continue
        series = {}
        for name, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            entry = series.setdefault(key, {"buckets": [], "sum": None, "count": None})
            if name.endswith("_bucket"):
                le = labels.get("le")
                assert le is not None, f"{family}: bucket sample without le"
                entry["buckets"].append(
                    (float("inf") if le == "+Inf" else float(le), value)
                )
            elif name.endswith("_sum"):
                entry["sum"] = value
            elif name.endswith("_count"):
                entry["count"] = value
        for key, entry in series.items():
            assert entry["buckets"], f"{family}{dict(key)}: no buckets"
            les = [le for le, _ in entry["buckets"]]
            assert les == sorted(les), f"{family}{dict(key)}: les out of order"
            assert les[-1] == float("inf"), f"{family}{dict(key)}: missing +Inf bucket"
            counts = [c for _, c in entry["buckets"]]
            assert counts == sorted(counts), (
                f"{family}{dict(key)}: bucket counts not cumulative"
            )
            assert entry["count"] is not None, f"{family}{dict(key)}: missing _count"
            assert entry["sum"] is not None, f"{family}{dict(key)}: missing _sum"
            assert counts[-1] == entry["count"], (
                f"{family}{dict(key)}: +Inf bucket != _count"
            )


def test_exposition_format_lint():
    nm = _populated_node_metrics()
    # the node exposition appends the process-global batch-verify series —
    # lint the combined output a scraper actually sees
    _lint_exposition(nm.expose())


def test_exposition_lint_catches_violations():
    """The lint itself must reject malformed expositions (else satellite 1
    is a rubber stamp)."""
    import pytest

    good = _populated_node_metrics().expose()
    # drop every +Inf bucket line
    broken = "\n".join(
        l for l in good.splitlines() if 'le="+Inf"' not in l
    )
    with pytest.raises(AssertionError):
        _lint_exposition(broken)
    # sample with no metadata
    with pytest.raises(AssertionError):
        _lint_exposition("tm_unannounced_total 3\n")


METRICS_SETS = (
    M.ConsensusMetrics,
    M.MempoolMetrics,
    M.P2PMetrics,
    M.StateMetrics,
    M.BlockSyncMetrics,
    M.StateSyncMetrics,
    M.RPCMetrics,
    M.OverloadMetrics,
    M.BatchVerifyMetrics,
    M.PubSubMetrics,
    M.ChaosMetrics,
    # device/mesh observatory (ISSUE 7): the tendermint_mesh_* series fed by
    # parallel/telemetry.py and the profiler/forensics usage counters
    M.MeshMetrics,
    M.ObservatoryMetrics,
    # SLO burn-rate engine (ISSUE 8): tendermint_slo_* fed by libs/slo.py,
    # plus the cross-node propagation series on ConsensusMetrics/P2PMetrics
    # (proposal/vote_propagation_seconds, clock_skew_seconds) which ride the
    # classes above
    M.SLOMetrics,
    # light-client-as-a-service (ISSUE 9): tendermint_light_* fed by
    # light/service.py (requests by outcome, cache hits, coalesced lanes
    # per flush, sheds, conflicting-header detections)
    M.LightServiceMetrics,
    # transaction & request observatory (ISSUE 10): tendermint_tx_* fed by
    # libs/txtrace.py (stage latencies + terminal outcomes), plus the
    # per-method tendermint_rpc_request_* series which ride RPCMetrics above
    M.TxLifecycleMetrics,
    # global verification scheduler (ISSUE 11): tendermint_verify_lane_*
    # fed by crypto/scheduler.py (per-lane depth, queue waits, rows per
    # combined flush, vote-lane preemptions)
    M.SchedulerMetrics,
    # fleet referee (ISSUE 17): tendermint_fleet_* fed by chaos/fleet.py
    # (nodes per role) and tools/fleet_referee.py (safety-audit comparisons,
    # verdicts handed down)
    M.FleetMetrics,
)


def test_no_dead_series():
    """Every series registered on a metrics set must be WRITTEN somewhere in
    tendermint_tpu/ (via .attr.inc/.set/.dec/.observe/.labels). A metric
    nobody feeds silently exposes 0 forever — worse than absent, because
    dashboards trust it."""
    root = os.path.join(os.path.dirname(__file__), "..", "tendermint_tpu")
    sources = []
    for dirpath, _, files in os.walk(os.path.abspath(root)):
        for fn in files:
            if fn.endswith(".py") and fn != "metrics.py":
                with open(os.path.join(dirpath, fn)) as f:
                    sources.append(f.read())
    blob = "\n".join(sources)

    dead = []
    for cls in METRICS_SETS:
        reg = M.Registry()
        inst = cls(reg)
        for attr, val in vars(inst).items():
            if not isinstance(val, M._Metric):
                continue
            pattern = rf"\.{re.escape(attr)}\.(inc|set|dec|observe|labels|replace_series)\("
            if not re.search(pattern, blob):
                dead.append(f"{cls.__name__}.{attr} ({val.name})")
    assert not dead, f"registered but never written anywhere: {dead}"


def test_chain_metrics_delta_from_expositions():
    """tools/loadtest.py's chain-side scrape: _chain_metrics_delta isolates
    the load window by subtracting two /metrics expositions, and degrades
    to None when a scrape is missing (instrumentation disabled)."""
    from tendermint_tpu.tools.loadtest import _chain_metrics_delta

    nm = M.NodeMetrics()
    nm.consensus.block_interval_seconds.observe(1.0)
    nm.consensus.step_duration_seconds.labels("propose").observe(0.25)
    t0 = nm.expose()
    nm.consensus.block_interval_seconds.observe(3.0)
    nm.consensus.block_interval_seconds.observe(1.0)
    nm.consensus.step_duration_seconds.labels("propose").observe(0.75)
    nm.consensus.step_duration_seconds.labels("prevote").observe(0.5)
    t1 = nm.expose()

    cm = _chain_metrics_delta(t0, t1)
    assert cm["block_intervals_observed"] == 2
    assert abs(cm["block_interval_avg_s"] - 2.0) < 1e-6
    assert abs(cm["step_duration_avg_s"]["propose"] - 0.75) < 1e-6
    assert abs(cm["step_duration_avg_s"]["prevote"] - 0.5) < 1e-6
    assert _chain_metrics_delta(None, t1) is None
    assert _chain_metrics_delta(t0, None) is None


def test_registry_snapshot_compact():
    """Registry.snapshot(): only written series, histograms as count+sum —
    the shape bench.py attaches as extra.node_metrics."""
    nm = _populated_node_metrics()
    snap = nm.snapshot()
    assert snap["tendermint_consensus_height"] == {
        "type": "gauge", "series": {"": 7.0}
    }
    sd = snap["tendermint_consensus_step_duration_seconds"]
    assert sd["type"] == "histogram"
    assert sd["series"]['step="prevote"'] == {"count": 2, "sum": 4.2}
    # never-written series are omitted
    assert "tendermint_consensus_missing_validators" not in snap
    assert M.NodeMetrics.latest() is nm


def test_prometheus_server_and_rpc_route_render_identically():
    """The dedicated PrometheusServer listener and the RPC /metrics route
    must serve byte-identical expositions for the same NodeMetrics."""
    import asyncio
    from types import SimpleNamespace

    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.libs.prometheus_server import PrometheusServer
    from tendermint_tpu.rpc.server import RPCServer

    nm = _populated_node_metrics()
    cfg = test_config()
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.instrumentation.prometheus = True
    node = SimpleNamespace(config=cfg, metrics=nm)

    async def run():
        rpc = RPCServer(node)
        prom = PrometheusServer(nm, "127.0.0.1:0")
        rpc_resp = await rpc._handle_metrics(None)
        prom_resp = await prom._handle(None)
        assert rpc_resp.text == prom_resp.text
        assert "tendermint_consensus_step_duration_seconds_bucket" in rpc_resp.text
        assert rpc_resp.content_type == prom_resp.content_type == "text/plain"

    asyncio.run(run())
