"""Multi-validator networks over the real p2p stack
(reference test models: consensus/reactor_test.go, byzantine_test.go:35).

Each node is a full Node (consensus, mempool, evidence, WAL, stores) with a
real Switch listening on 127.0.0.1; peers connect over TCP with secret
connections. This is the analog of randConsensusNet
(consensus/common_test.go:675)."""

import asyncio
import os

import pytest

from tests.conftest import requires_cryptography

# every test here runs a real p2p net (secret connection => the
# `cryptography` wheel); make_net stays importable for other modules
pytestmark = requires_cryptography

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")


def make_net(n: int, tmp_path, chain="multinode-chain", defer_votes=False):
    privs = [FilePV(gen_ed25519(bytes([10 + i]) * 32)) for i in range(n)]
    gen = GenesisDoc(
        chain_id=chain,
        validators=[GenesisValidator(p.get_pub_key(), 10) for p in privs],
    )
    nodes = []
    for i, priv in enumerate(privs):
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.root_dir = ""
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        # each node gets its own WAL dir
        cfg.consensus.wal_path = str(tmp_path / f"wal{i}" / "wal")
        cfg.consensus.defer_vote_verification = defer_votes
        node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
        nodes.append(node)
    return nodes


async def start_and_connect(nodes):
    for node in nodes:
        await node.start()
    # connect in a ring + extra links (full mesh for small n)
    for i, node in enumerate(nodes):
        for j in range(i + 1, len(nodes)):
            peer_addr = f"{nodes[j].node_key.id}@{nodes[j].p2p_addr}"
            await node.switch.dial_peers_async([peer_addr], persistent=True)


async def stop_all(nodes):
    for node in nodes:
        try:
            await node.stop()
        except Exception:
            pass


async def wait_until(pred, nodes, max_new_heights, hard_timeout=600.0, poll=0.1):
    """Progress-based wait (machine-load independent): poll `pred()` and fail
    only once the net has committed `max_new_heights` MORE blocks without the
    predicate holding. Under CPU contention (e.g. concurrent XLA compiles)
    heights stretch and the wait stretches with them; a live net that truly
    never satisfies the predicate still fails deterministically after a
    bounded amount of chain progress. `hard_timeout` only guards total
    deadlock (no progress at all)."""
    loop = asyncio.get_event_loop()
    start_h = max(n.block_store.height for n in nodes)
    t0 = loop.time()
    while True:
        if pred():
            return
        h = max(n.block_store.height for n in nodes)
        if h - start_h >= max_new_heights:
            raise AssertionError(
                f"condition not reached after {h - start_h} new heights "
                f"(started at {start_h})"
            )
        if loop.time() - t0 > hard_timeout:
            raise AssertionError(
                f"hard timeout {hard_timeout}s with chain at height {h} "
                f"(started at {start_h})"
            )
        await asyncio.sleep(poll)


def test_four_validator_net_commits_blocks(tmp_path):
    async def run():
        nodes = make_net(4, tmp_path)
        try:
            await start_and_connect(nodes)
            # all four must reach height 5 (needs +2/3 from 3+ validators)
            await asyncio.gather(*(n.wait_for_height(5, timeout=600) for n in nodes))
            # chains agree
            h = min(n.block_store.height for n in nodes)
            assert h >= 5
            hashes = {n.block_store.load_block(h - 1).hash() for n in nodes}
            assert len(hashes) == 1, "nodes disagree on block hash"
            # every block carries +2/3 commit from the 4-validator set
            commit = nodes[0].block_store.load_seen_commit(h - 1)
            present = sum(1 for s in commit.signatures if not s.absent())
            assert present >= 3
        finally:
            await stop_all(nodes)

    asyncio.run(run())


def test_net_commits_txs_via_gossip(tmp_path):
    async def run():
        nodes = make_net(3, tmp_path, chain="gossip-chain")
        try:
            await start_and_connect(nodes)
            await asyncio.gather(*(n.wait_for_height(1, timeout=600) for n in nodes))
            # submit the tx to node 2 only; mempool gossip must carry it to the
            # proposer eventually
            nodes[2].mempool.check_tx(b"gossip=works")

            def tx_committed():
                for n in nodes:
                    for h in range(1, n.block_store.height + 1):
                        b = n.block_store.load_block(h)
                        if b and b"gossip=works" in b.txs:
                            return True
                return False

            # progress-based: the tx must land within 12 further heights,
            # however long those take under machine load
            await wait_until(tx_committed, nodes, max_new_heights=12, poll=0.05)
        finally:
            await stop_all(nodes)

    asyncio.run(run())


def test_node_catches_up_after_late_join(tmp_path):
    """A validator that joins late must catch up via consensus catchup gossip
    (block parts + commit votes for old heights)."""

    async def run():
        nodes = make_net(4, tmp_path, chain="latejoin-chain")
        late = nodes[3]
        early = nodes[:3]
        try:
            for n in early:
                await n.start()
            for i, n in enumerate(early):
                for j in range(i + 1, 3):
                    await n.switch.dial_peers_async(
                        [f"{early[j].node_key.id}@{early[j].p2p_addr}"], persistent=True
                    )
            # 3 of 4 validators = 30/40 power: exactly +2/3 is NOT enough
            # (strictly greater needed: 30*3 > 40*2 holds, 90 > 80 — ok, blocks flow)
            await asyncio.gather(*(n.wait_for_height(3, timeout=600) for n in early))
            # now the 4th joins
            await late.start()
            await late.switch.dial_peers_async(
                [f"{early[0].node_key.id}@{early[0].p2p_addr}"], persistent=True
            )
            await late.wait_for_height(3, timeout=600)
            assert late.block_store.height >= 3
            b = late.block_store.load_block(2)
            assert b.hash() == early[0].block_store.load_block(2).hash()
        finally:
            await stop_all(nodes)

    asyncio.run(run())


def test_byzantine_equivocator_produces_evidence(tmp_path):
    """One validator prevotes two different blocks per round; honest nodes
    must detect the conflicting votes and commit DuplicateVoteEvidence
    (reference: consensus/byzantine_test.go:35)."""

    async def run():
        nodes = make_net(4, tmp_path, chain="byz-chain")
        byz = nodes[0]
        try:
            await start_and_connect(nodes)

            # swap in byzantine prevote behavior using the hook the state
            # machine exposes for exactly this (cs_state.py decide hooks)
            cs = byz.consensus
            orig_do_prevote = cs._default_do_prevote

            def byz_do_prevote(height, round_):
                # sign the honest prevote first
                orig_do_prevote(height, round_)
                # then equivocate: sign a conflicting nil prevote with the RAW
                # key (a byzantine validator ignores the double-sign guard)
                import time as _time

                from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
                from tendermint_tpu.types.vote import Vote

                rs = cs.rs
                addr = byz.priv_validator.get_pub_key().address()
                idx, _ = rs.validators.get_by_address(addr)
                # A fabricated BlockID: a byzantine validator doesn't need
                # the real proposal to equivocate, and a made-up hash can
                # never equal the honest prevote (nil or the real block) —
                # so EVERY round produces a conflict, even when machine load
                # makes this node miss proposals (the old nil-vote variant
                # silently skipped those rounds, a flake under contention).
                vote = Vote(
                    type=SignedMsgType.PREVOTE, height=height, round=round_,
                    block_id=BlockID(b"\x42" * 32, PartSetHeader(1, b"\x42" * 32)),
                    timestamp_ns=_time.time_ns(),
                    validator_address=addr, validator_index=idx,
                )
                sig = byz.priv_validator.priv_key.sign(vote.sign_bytes(cs.state.chain_id))
                import dataclasses

                vote = dataclasses.replace(vote, signature=sig)
                from tendermint_tpu.consensus.messages import VoteMessage, encode_message
                from tendermint_tpu.consensus.reactor import VOTE_CHANNEL

                async def gossip():
                    await byz.switch.broadcast(VOTE_CHANNEL, encode_message(VoteMessage(vote)))

                asyncio.ensure_future(gossip())

            cs.do_prevote = byz_do_prevote

            # net keeps committing (3 honest validators are enough) and some
            # honest node must commit the duplicate-vote evidence within a
            # bounded number of FURTHER heights (progress-based: wall-clock
            # contention stretches heights, not the verdict)
            def evidence_committed():
                for n in nodes[1:]:
                    for h in range(1, n.block_store.height + 1):
                        b = n.block_store.load_block(h)
                        if b and len(b.evidence) > 0:
                            ev = b.evidence[0]
                            assert ev.vote_a.height == ev.vote_b.height
                            assert (
                                ev.vote_a.validator_address
                                == byz.priv_validator.get_pub_key().address()
                            )
                            return True
                return False

            await wait_until(evidence_committed, nodes, max_new_heights=15)
        finally:
            await stop_all(nodes)

    asyncio.run(run())


def test_deferred_vote_verification_liveness_and_evidence(tmp_path):
    """With defer_vote_verification=true, votes queue unverified and flush as
    device batches on receive-loop batch boundaries (cs_state.py
    _flush_deferred_votes). The net must stay live (blocks commit) AND an
    equivocator's conflicting votes — discovered at flush time, not
    add_vote time — must still become DuplicateVoteEvidence
    (reference semantics: types/vote_set.go:143 conflict detection +
    consensus/state.go:1829 evidence path)."""

    async def run():
        nodes = make_net(4, tmp_path, chain="defer-chain", defer_votes=True)
        byz = nodes[0]
        try:
            await start_and_connect(nodes)

            cs = byz.consensus
            orig_do_prevote = cs._default_do_prevote

            def byz_do_prevote(height, round_):
                orig_do_prevote(height, round_)
                import dataclasses
                import time as _time

                from tendermint_tpu.consensus.messages import VoteMessage, encode_message
                from tendermint_tpu.consensus.reactor import VOTE_CHANNEL
                from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
                from tendermint_tpu.types.vote import Vote

                rs = cs.rs
                addr = byz.priv_validator.get_pub_key().address()
                idx, _ = rs.validators.get_by_address(addr)
                # A fabricated BlockID: a byzantine validator doesn't need
                # the real proposal to equivocate, and a made-up hash can
                # never equal the honest prevote (nil or the real block) —
                # so EVERY round produces a conflict, even when machine load
                # makes this node miss proposals (the old nil-vote variant
                # silently skipped those rounds, a flake under contention).
                vote = Vote(
                    type=SignedMsgType.PREVOTE, height=height, round=round_,
                    block_id=BlockID(b"\x42" * 32, PartSetHeader(1, b"\x42" * 32)),
                    timestamp_ns=_time.time_ns(),
                    validator_address=addr, validator_index=idx,
                )
                sig = byz.priv_validator.priv_key.sign(vote.sign_bytes(cs.state.chain_id))
                vote = dataclasses.replace(vote, signature=sig)

                async def gossip():
                    await byz.switch.broadcast(VOTE_CHANNEL, encode_message(VoteMessage(vote)))

                asyncio.ensure_future(gossip())

            cs.do_prevote = byz_do_prevote

            # liveness: all nodes reach height 4 with deferred verification on
            await asyncio.gather(*(n.wait_for_height(4, timeout=600) for n in nodes))

            # evidence: some honest node commits the equivocation within a
            # bounded number of further heights (see wait_until)
            def evidence_committed():
                for n in nodes[1:]:
                    for h in range(1, n.block_store.height + 1):
                        b = n.block_store.load_block(h)
                        if b and len(b.evidence) > 0:
                            ev = b.evidence[0]
                            assert (
                                ev.vote_a.validator_address
                                == byz.priv_validator.get_pub_key().address()
                            )
                            return True
                return False

            await wait_until(evidence_committed, nodes, max_new_heights=15)
        finally:
            await stop_all(nodes)

    asyncio.run(run())
