"""Flight recorder for the batch-verify pipeline.

Lightweight nested spans and point events into a bounded, thread-safe ring
buffer with JSONL export — the tracing half of the observability story whose
metrics half lives in libs/metrics.py (BatchVerifyMetrics). The reference
wires per-service Prometheus metrics through every subsystem
(consensus/metrics.go, node/node.go:106-121) but has no in-process tracer;
this module exists because the single most important path here —
crypto/batch.py's device pipeline — fails in ways a counter can't localise
(BENCH_r05: `verify_commit_latency = -1`, "device initialization stalled",
with zero insight into WHICH stage stalled).

Three consumers:

- the `/debug/trace` RPC route (rpc/server.py) dumps the ring as JSON;
- `/debug/verify_stats` + bench.py's JSON `extra` read `verify_stats()`,
  the aggregated per-flush breakdown (prep / compile / transfer / total
  per path), so a regression names its stage instead of one opaque number;
- node liveness and the bench's stall detector read `device_health()`
  (device init duration, last-successful-device-call age, `device_up`).

Overhead contract: when `tracer.enabled` is False the instrumented hot
paths make ZERO tracer calls beyond one flag read (they hoist
`tracer if tracer.enabled else None` and skip everything on None), and the
ring buffer never exceeds its configured size (deque maxlen). Configure via
`[instrumentation] trace_enabled / trace_ring_size` (node/node.py) or the
TMTPU_TRACE env default.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_RING_SIZE = 4096


class Span:
    """An in-flight span; records one event into the tracer's ring on exit.

    Use as a context manager (or call __enter__/__exit__ explicitly when the
    caller must survive with tracing disabled — see crypto/batch.py).
    `set(**attrs)` attaches attributes mid-flight (e.g. the chosen path,
    known only at the end of a flush)."""

    __slots__ = ("_tracer", "name", "attrs", "span_id", "parent_id", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = tracer._next_id()
        self.parent_id: Optional[int] = None
        self._t0 = 0.0

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        stack = self._tracer._stack()
        self.parent_id = stack[-1] if stack else None
        stack.append(self.span_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self._tracer._record(
            self.name, self.span_id, self.parent_id, dur, self.attrs
        )


class Tracer:
    """Thread-safe bounded flight recorder: nested spans + point events."""

    def __init__(self, ring_size: int = DEFAULT_RING_SIZE, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(ring_size)))
        self._local = threading.local()
        self._id = 0

    # -- recording ----------------------------------------------------------

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        """A zero-duration point event, parented to the current span."""
        stack = self._stack()
        self._record(name, self._next_id(), stack[-1] if stack else None, None, attrs)

    # -- introspection ------------------------------------------------------

    def dump(self, limit: Optional[int] = None) -> List[dict]:
        """Ring contents, oldest first (most recent `limit` if given)."""
        with self._lock:
            events = list(self._ring)
        if limit is not None and limit >= 0:
            events = events[-limit:] if limit else []
        return events

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(e, sort_keys=True) for e in self.dump())

    @staticmethod
    def from_jsonl(text: str) -> List[dict]:
        return [json.loads(line) for line in text.splitlines() if line.strip()]

    @property
    def ring_size(self) -> int:
        return self._ring.maxlen or 0

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def configure(
        self, enabled: Optional[bool] = None, ring_size: Optional[int] = None
    ) -> None:
        """Apply [instrumentation] config; shrinking keeps the newest events."""
        with self._lock:
            if ring_size is not None and ring_size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, int(ring_size)))
        if enabled is not None:
            self.enabled = bool(enabled)

    # -- internals ----------------------------------------------------------

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, name, span_id, parent_id, dur_s, attrs) -> None:
        event = {
            "name": name,
            "span": span_id,
            "parent": parent_id,
            "ts": time.time(),
        }
        if dur_s is not None:
            event["dur_ms"] = round(dur_s * 1e3, 4)
        if attrs:
            event["attrs"] = dict(attrs)
        with self._lock:
            self._ring.append(event)


tracer = Tracer(enabled=os.environ.get("TMTPU_TRACE", "1") != "0")


# ---------------------------------------------------------------------------
# Aggregated per-flush telemetry (the /debug/verify_stats + bench `extra`
# surface) and device-health state (the `device_up` surface). Both also feed
# the process-global Prometheus series (libs.metrics.batch_metrics) so the
# node's /metrics exposition carries them without any node->crypto plumbing.

_STATS_LOCK = threading.Lock()
_TOTALS: Dict[tuple, Dict[str, float]] = {}  # (backend, path) -> counters
_LAST_FLUSH: Dict[str, Any] = {}
_COUNTS = {
    "rlc_fallbacks": 0,
    "cache_hits": 0,
    "cache_misses": 0,
    "recovery_flushes": 0,
    "quarantined_rows": 0,
}
_STAGE_SECONDS = {"prep": 0.0, "compile": 0.0, "transfer": 0.0, "total": 0.0}
# Slope-methodology raw data (PERF.md: single-sync timings lie on this
# runtime, so per-batch cost is fit from (k, seconds) over k chained
# submits). Two sources, both served by /debug/verify_stats so a live
# node's suspicious slope can be re-fit WITHOUT a bench rerun:
# - the last recorded fit (bench.py rlc_slope_samples calls
#   record_slope_samples with its raw pairs), and
# - a bounded ring of live per-flush (n, seconds) samples for rlc* paths.
_SLOPE_FIT: Dict[str, Any] = {}
_FLUSH_SAMPLES: deque = deque(maxlen=128)  # (n, total_s, path)

_DEVICE_LOCK = threading.Lock()
_DEVICE: Dict[str, Any] = {
    "up": None,  # None = no device call attempted yet
    "init_seconds": None,
    "last_call_monotonic": None,
    "last_error": None,
}


def record_flush(
    *,
    backend: str,
    path: str,
    n: int,
    total_s: float,
    n_valid: Optional[int] = None,
    prep_s: Optional[float] = None,
    compile_s: Optional[float] = None,
    transfer_s: Optional[float] = None,
    jit_bucket: Optional[int] = None,
    padding_lanes: Optional[int] = None,
    cache_hits: Optional[int] = None,
    cache_misses: Optional[int] = None,
    rlc_fallback: bool = False,
    fused: Optional[bool] = None,
    h2d_bytes: Optional[int] = None,
    device_dispatches: Optional[int] = None,
    chunks: Optional[int] = None,
    chunk_lanes: Optional[int] = None,
    prep_overlap_s: Optional[float] = None,
    prep_stages: Optional[dict] = None,
    memo_hits: Optional[int] = None,
    recovery_flushes: Optional[int] = None,
    quarantined: Optional[int] = None,
    tracer_: Optional[Tracer] = None,
) -> None:
    """One batch-verify flush completed. Called by crypto/batch.verify_batch
    for EVERY flush on EVERY backend; `tracer_` is the caller's already-
    resolved tracer (or None when tracing is disabled) so this function adds
    no tracer-flag reads of its own."""
    from tendermint_tpu.libs import metrics as _metrics
    from tendermint_tpu.libs import slo as _slo

    # SLO feed (verify_flush_wall): one None check when no engine registered
    _slo.feed_flush(total_s)

    m = _metrics.batch_metrics()
    m.flushes.labels(backend, path).inc()
    m.sigs.labels(backend, path).inc(n)
    m.batch_size.observe(n)
    m.flush_seconds.labels(path).observe(total_s)
    if prep_s is not None:
        m.prep_seconds.observe(prep_s)
    # compile_s is NOT re-counted into m.compile_seconds here: record_compile
    # already did, at the aot_cache call site; it rides only the breakdown.
    if transfer_s is not None:
        m.transfer_seconds.inc(transfer_s)
    if jit_bucket is not None:
        m.jit_bucket.set(jit_bucket)
    if padding_lanes is not None:
        m.padding_lanes.set(padding_lanes)
    if cache_hits:
        m.pubkey_cache_hits.inc(cache_hits)
    if cache_misses:
        m.pubkey_cache_misses.inc(cache_misses)
    if rlc_fallback:
        m.rlc_fallbacks.inc()
    # adversarial flush defense (crypto/batch.py _bisect_recover +
    # crypto/provenance.py): recovery cost + quarantined-row attribution
    if recovery_flushes:
        m.recovery_flushes.inc(recovery_flushes)
    if quarantined:
        m.quarantined_rows.inc(quarantined)
    # streamed flush planner (crypto/batch.py ISSUE 13): chunk count per
    # flush + the host-prep wall the double buffer hid behind device work
    if chunks is not None:
        m.chunks_per_flush.observe(chunks)
    if prep_overlap_s:
        m.prep_overlap_seconds.inc(prep_overlap_s)
    # ISSUE 18: hidden-prep fraction of THIS flush (streamed, pipelined and
    # striped host-RLC paths all report prep_overlap_s now). memo_hits rides
    # only the last-flush dict — VerifiedRowMemo.lookup owns the counter.
    if prep_s and prep_overlap_s is not None:
        m.prep_hidden_ratio.set(min(1.0, prep_overlap_s / prep_s))

    last = {
        "backend": backend,
        "path": path,
        "n": n,
        "total_ms": round(total_s * 1e3, 4),
    }
    if n_valid is not None:
        last["n_valid"] = n_valid
    if prep_s is not None:
        last["prep_ms"] = round(prep_s * 1e3, 4)
    if compile_s is not None:
        last["compile_ms"] = round(compile_s * 1e3, 4)
    if transfer_s is not None:
        last["transfer_ms"] = round(transfer_s * 1e3, 4)
    if jit_bucket is not None:
        last["jit_bucket"] = jit_bucket
        last["padding_lanes"] = padding_lanes
    if cache_hits is not None or cache_misses is not None:
        hits, misses = cache_hits or 0, cache_misses or 0
        last["pubkey_cache_hits"] = hits
        last["pubkey_cache_misses"] = misses
        if hits + misses:
            last["pubkey_cache_hit_rate"] = round(hits / (hits + misses), 4)
    if rlc_fallback:
        last["rlc_fallback"] = True
    if fused is not None:
        last["fused"] = bool(fused)
    if h2d_bytes is not None:
        last["h2d_bytes"] = h2d_bytes
    if device_dispatches is not None:
        last["device_dispatches"] = device_dispatches
    if chunks is not None:
        last["chunks"] = chunks
    if chunk_lanes is not None:
        last["chunk_lanes"] = chunk_lanes
    if prep_overlap_s is not None:
        last["prep_overlap_ms"] = round(prep_overlap_s * 1e3, 4)
    if prep_stages:
        last["prep_stages_ms"] = {
            k[:-2] if k.endswith("_s") else k: round(v * 1e3, 4)
            for k, v in prep_stages.items()
        }
    if memo_hits is not None:
        last["memo_hits"] = memo_hits
    if recovery_flushes is not None:
        last["recovery_flushes"] = recovery_flushes
    if quarantined is not None:
        last["quarantined"] = quarantined
    with _STATS_LOCK:
        t = _TOTALS.setdefault(
            (backend, path), {"flushes": 0, "sigs": 0, "seconds": 0.0}
        )
        t["flushes"] += 1
        t["sigs"] += n
        t["seconds"] += total_s
        _COUNTS["cache_hits"] += cache_hits or 0
        _COUNTS["cache_misses"] += cache_misses or 0
        if rlc_fallback:
            _COUNTS["rlc_fallbacks"] += 1
        _COUNTS["recovery_flushes"] += recovery_flushes or 0
        _COUNTS["quarantined_rows"] += quarantined or 0
        _STAGE_SECONDS["prep"] += prep_s or 0.0
        _STAGE_SECONDS["compile"] += compile_s or 0.0
        _STAGE_SECONDS["transfer"] += transfer_s or 0.0
        _STAGE_SECONDS["total"] += total_s
        _LAST_FLUSH.clear()
        _LAST_FLUSH.update(last)
        if path.startswith("rlc"):
            _FLUSH_SAMPLES.append((n, round(total_s, 6), path))
    if tracer_ is not None:
        tracer_.event("batch_verify.flush", **last)


def record_slope_samples(
    samples,
    slope_ms: Optional[float] = None,
    fused: Optional[bool] = None,
    source: str = "bench",
) -> None:
    """Record a slope fit's RAW (k, seconds) pairs (bench.py
    rlc_slope_samples) so /debug/verify_stats serves them for post-hoc
    re-fitting — previously bench-JSON-only."""
    with _STATS_LOCK:
        _SLOPE_FIT.clear()
        _SLOPE_FIT.update(
            samples=[list(s) for s in samples],
            slope_ms=slope_ms,
            fused=fused,
            source=source,
            recorded_at=time.time(),
        )


def verify_stats() -> dict:
    """Aggregated flush telemetry: per-(backend, path) totals, the per-stage
    time split, and the last flush's breakdown. Shape documented in
    docs/OBSERVABILITY.md; served by /debug/verify_stats and attached to
    bench.py's JSON `extra`."""
    with _STATS_LOCK:
        totals = {
            f"{backend}/{path}": dict(t) for (backend, path), t in _TOTALS.items()
        }
        out = {
            "totals": totals,
            "stage_seconds": dict(_STAGE_SECONDS),
            "counters": dict(_COUNTS),
            "last_flush": dict(_LAST_FLUSH),
            "slope_samples": {
                "fit": dict(_SLOPE_FIT) or None,
                "flush_samples": [list(s) for s in _FLUSH_SAMPLES],
            },
        }
    out["device"] = device_health()
    try:
        # lazy: batch imports this module at load time; the reverse edge
        # only exists at call time
        from tendermint_tpu.crypto.batch import BREAKER

        out["breaker"] = BREAKER.snapshot()
    except Exception:  # telemetry must never fail the stats read
        pass
    try:
        # mesh telemetry rides along so ONE stats read covers single-chip
        # and sharded pipelines (full snapshot: GET /debug/mesh)
        from tendermint_tpu.parallel import telemetry as _mesh_tm

        out["mesh"] = _mesh_tm.mesh_stats()
    except Exception:
        pass
    try:
        # the global verification scheduler's lane state (process-global
        # default, last node wins): who is queued for the device and under
        # what budgets — the QoS half of the flush totals above
        from tendermint_tpu.crypto import scheduler as _scheduler

        sched = _scheduler.default_scheduler()
        if sched is not None:
            out["scheduler"] = sched.stats()
    except Exception:
        pass
    return out


def reset_stats() -> None:
    """Test hook: zero the aggregated flush telemetry (not the metrics)."""
    with _STATS_LOCK:
        _TOTALS.clear()
        _LAST_FLUSH.clear()
        _SLOPE_FIT.clear()
        _FLUSH_SAMPLES.clear()
        for k in _COUNTS:
            _COUNTS[k] = 0
        for k in _STAGE_SECONDS:
            _STAGE_SECONDS[k] = 0.0


# -- device health -----------------------------------------------------------


def record_device_init(seconds: float, ok: bool = True, error: str = "") -> None:
    """Device/backend initialization finished (or stalled: ok=False)."""
    from tendermint_tpu.libs import metrics as _metrics

    m = _metrics.batch_metrics()
    with _DEVICE_LOCK:
        _DEVICE["init_seconds"] = seconds
        _DEVICE["up"] = bool(ok)
        _DEVICE["last_error"] = error or None
        if ok:
            _DEVICE["last_call_monotonic"] = time.monotonic()
    m.device_init_seconds.set(seconds)
    m.device_up.set(1.0 if ok else 0.0)
    if ok:
        m.device_last_call_timestamp.set(time.time())
    if tracer.enabled:
        tracer.event("device.init", seconds=round(seconds, 4), ok=bool(ok))


def mark_device_call(ok: bool = True, error: str = "") -> None:
    """A device round trip completed (ok) or failed/stalled (not ok) — the
    signal the bench's stall detector and node liveness read as `device_up`."""
    from tendermint_tpu.libs import metrics as _metrics

    m = _metrics.batch_metrics()
    with _DEVICE_LOCK:
        _DEVICE["up"] = bool(ok)
        if ok:
            _DEVICE["last_call_monotonic"] = time.monotonic()
            _DEVICE["last_error"] = None
        else:
            _DEVICE["last_error"] = error or "device call failed"
    m.device_up.set(1.0 if ok else 0.0)
    if ok:
        m.device_last_call_timestamp.set(time.time())


def device_health() -> dict:
    """{"device_up": 0/1/None, "init_seconds", "last_call_age_s", "last_error"}.
    device_up None means no device call has been attempted this process."""
    with _DEVICE_LOCK:
        up = _DEVICE["up"]
        last = _DEVICE["last_call_monotonic"]
        return {
            "device_up": None if up is None else int(up),
            "init_seconds": _DEVICE["init_seconds"],
            "last_call_age_s": (
                round(time.monotonic() - last, 3) if last is not None else None
            ),
            "last_error": _DEVICE["last_error"],
        }


# -- compile accounting ------------------------------------------------------

_COMPILE_LOCK = threading.Lock()
_COMPILE_TOTAL = 0.0  # seconds spent tracing/exporting/deserializing kernels


def record_compile(name: str, seconds: float, kind: str) -> None:
    """ops/aot_cache.py: a kernel trace+export ("export") or artifact load
    ("deserialize") took `seconds`. Feeds the compile-vs-execute split."""
    global _COMPILE_TOTAL
    from tendermint_tpu.libs import metrics as _metrics

    with _COMPILE_LOCK:
        _COMPILE_TOTAL += seconds
    _metrics.batch_metrics().compile_seconds.labels(kind).inc(seconds)
    if tracer.enabled:
        tracer.event(f"aot.{kind}", kernel=name, seconds=round(seconds, 4))


def compile_seconds_total() -> float:
    """Monotonic compile-time counter; diff around a flush to attribute
    compile seconds to it (crypto/batch.verify_batch)."""
    with _COMPILE_LOCK:
        return _COMPILE_TOTAL
