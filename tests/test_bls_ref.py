"""Known-answer + structural tests for crypto/bls_ref.py (ISSUE 14).

Vector provenance: the RFC 9380 known answers below (expand_message_xmd
appendix K.1; BLS12381G2_XMD:SHA-256_SSWU_RO_ appendix J.10.1) pin the
hash-to-curve suite byte-exactly — these are the interop-critical values
(a mismatch means our signatures don't verify against blst/py_ecc peers).
The sign/keygen pins are implementation KATs: computed once from this
module and frozen so any arithmetic regression (tower, Miller loop, final
exponentiation, serialization) fails loudly. Structural identities
(bilinearity, order-r torsion, subgroup membership) referee the parts no
vector reaches.
"""

import random

import pytest

from tendermint_tpu.crypto import bls_ref as B

# -- curve constants / groups ------------------------------------------------


def test_generators_and_orders():
    assert B.g1_on_curve(B.G1_GEN) and B.g1_in_subgroup(B.G1_GEN)
    assert B.g2_on_curve(B.G2_GEN) and B.g2_in_subgroup(B.G2_GEN)
    assert B._jac_is_identity(B._jac_mul(B.G1_GEN, B.R))
    assert B._jac_is_identity(B._jac_mul(B.G2_GEN, B.R))
    # p and r really are the BLS12-381 parameters: r = x^4 - x^2 + 1,
    # p = (x-1)^2/3 * r + x for the stated x
    x = B.X_PARAM
    assert B.R == x**4 - x**2 + 1
    assert B.P == (x - 1) ** 2 * B.R // 3 + x


# -- RFC 9380 known answers --------------------------------------------------

XMD_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"


def test_expand_message_xmd_rfc_vectors():
    # RFC 9380 K.1 (SHA-256, len_in_bytes = 0x20)
    assert (
        B.expand_message_xmd(b"", XMD_DST, 32).hex()
        == "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"
    )
    assert (
        B.expand_message_xmd(b"abc", XMD_DST, 32).hex()
        == "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"
    )


H2C_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"


def test_hash_to_g2_rfc_vector_empty_msg():
    # RFC 9380 J.10.1, msg = ""
    p = B.hash_to_g2(b"", H2C_DST)
    x, y = B._jac_to_affine(p)
    assert x.c0 == 0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A
    assert x.c1 == 0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D
    assert y.c0 == 0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92
    assert y.c1 == 0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6


def test_hash_to_g2_always_in_subgroup():
    for msg in (b"abc", b"tendermint-tpu", b"\x00" * 64):
        p = B.hash_to_g2(msg)
        assert B.g2_on_curve(p) and B.g2_in_subgroup(p)
        assert not B._jac_is_identity(p)


def test_sswu_and_iso_land_on_their_curves():
    u = B.Fp2(3, 7)
    x, y = B._sswu(u)
    assert y.square() == x.square() * x + B.SSWU_A * x + B.SSWU_B
    xi, yi = B._iso3_map(x, y)
    assert yi.square() == xi.square() * xi + B.B2


# -- serialization -----------------------------------------------------------


def test_compressed_round_trips_and_rejects():
    for k in (1, 2, 12345, B.R - 1):
        p1 = B._jac_mul(B.G1_GEN, k)
        assert B._jac_eq(B.g1_from_bytes(B.g1_to_bytes(p1)), p1)
        p2 = B._jac_mul(B.G2_GEN, k)
        assert B._jac_eq(B.g2_from_bytes(B.g2_to_bytes(p2)), p2)
    # identity encodings
    assert B._jac_is_identity(B.g1_from_bytes(bytes([0xC0]) + b"\x00" * 47))
    assert B._jac_is_identity(B.g2_from_bytes(bytes([0xC0]) + b"\x00" * 95))
    # uncompressed flag, bad length, x >= p, off-curve x all rejected
    assert B.g1_from_bytes(b"\x00" * 48) is None
    assert B.g1_from_bytes(b"\x80" + b"\x00" * 46) is None
    assert B.g1_from_bytes(bytes([0x9F]) + b"\xff" * 47) is None
    bad = bytearray(B.g1_to_bytes(B.G1_GEN))
    bad[47] ^= 1  # x+1: not on curve (or wrong subgroup) with high prob
    assert B.g1_from_bytes(bytes(bad)) is None


def test_g1_subgroup_check_rejects_low_order_component():
    # A curve point OUTSIDE the r-subgroup: h1 * P lies in G1, but a point
    # with a cofactor component must be rejected by g1_from_bytes.
    # Construct one by hashing x candidates until on-curve, then checking
    # it is NOT order r (overwhelmingly likely since h1 > 1).
    x = 2
    while True:
        y = B._fp_sqrt((x * x * x + B.B_G1) % B.P)
        if y is not None:
            pt = (B._G1Field(x), B._G1Field(y), B._G1Field(1))
            if not B._jac_is_identity(B._jac_mul(pt, B.R)):
                break
        x += 1
    enc = B.g1_to_bytes(pt)
    assert B.g1_from_bytes(enc) is None
    assert B.g1_from_bytes(enc, subgroup_check=False) is not None


# -- pairing -----------------------------------------------------------------


def test_pairing_bilinearity_and_torsion():
    e = B.pairing(B.G1_GEN, B.G2_GEN)
    assert not e.is_one()
    assert e.pow(B.R).is_one()
    a, b = 127, 993
    assert B.pairing(B._jac_mul(B.G1_GEN, a), B._jac_mul(B.G2_GEN, b)) == e.pow(a * b)


# -- signature scheme KATs ---------------------------------------------------

IKM = b"\x11" * 32


def test_keygen_kat():
    # spec KeyGen (HKDF-SHA256) pinned for a fixed IKM; nonzero and < r
    sk = B.keygen(IKM)
    assert 0 < sk < B.R
    assert sk == B.keygen(IKM)  # deterministic
    with pytest.raises(ValueError):
        B.keygen(b"short")


def test_sign_verify_and_tamper():
    sk = B.keygen(IKM)
    pk = B.sk_to_pk(sk)
    assert len(pk) == 48
    sig = B.sign(sk, b"msg")
    assert len(sig) == 96
    assert B.verify(pk, b"msg", sig)
    assert not B.verify(pk, b"msg2", sig)
    bad = bytearray(sig)
    bad[5] ^= 1
    assert not B.verify(pk, b"msg", bytes(bad))
    # identity pubkey must never verify
    assert not B.verify(bytes([0xC0]) + b"\x00" * 47, b"msg", sig)


def test_aggregate_over_0_1_n_keys():
    rng = random.Random(9)
    sks = [B.keygen(bytes([i]) * 32) for i in range(1, 6)]
    pks = [B.sk_to_pk(s) for s in sks]
    msg = b"same message"
    sigs = [B.sign(s, msg) for s in sks]
    # 0 keys: rejected
    assert B.aggregate_signatures([]) is None
    assert not B.fast_aggregate_verify([], msg, sigs[0])
    # 1 key: aggregate == plain signature
    assert B.aggregate_signatures(sigs[:1]) == sigs[0]
    assert B.fast_aggregate_verify(pks[:1], msg, sigs[0])
    # N keys
    agg = B.aggregate_signatures(sigs)
    assert B.fast_aggregate_verify(pks, msg, agg)
    # wrong subset / superset fail
    assert not B.fast_aggregate_verify(pks[:4], msg, agg)
    # distinct messages via aggregate_verify
    msgs = [bytes([i]) + b"-distinct" for i in range(3)]
    agg3 = B.aggregate_signatures([B.sign(s, m) for s, m in zip(sks[:3], msgs)])
    assert B.aggregate_verify(pks[:3], msgs, agg3)
    assert not B.aggregate_verify(pks[:3], msgs[::-1], agg3)
    del rng


def test_pop_prove_verify():
    sk1 = B.keygen(b"\x21" * 32)
    sk2 = B.keygen(b"\x22" * 32)
    pop = B.pop_prove(sk1)
    assert B.pop_verify(B.sk_to_pk(sk1), pop)
    assert not B.pop_verify(B.sk_to_pk(sk2), pop)
    # a PLAIN signature over the pubkey bytes is NOT a valid PoP (domain
    # separation: different DST)
    fake = B.sign(sk1, B.sk_to_pk(sk1))
    assert not B.pop_verify(B.sk_to_pk(sk1), fake)


def test_rogue_key_attack_defeated_by_pop():
    """The classic rogue-key forgery: attacker publishes pk_r = pk_a - pk_h
    (for honest pk_h) and 'aggregates' so the sum collapses to a key they
    control. The aggregate EQUATION verifies — PoP is what stops it,
    because the attacker cannot sign under pk_r's (unknown) secret key."""
    sk_h = B.keygen(b"\x31" * 32)  # honest
    sk_a = B.keygen(b"\x32" * 32)  # attacker-known
    pk_h_pt = B.g1_from_bytes(B.sk_to_pk(sk_h))
    rogue_pt = B._jac_add(B._jac_mul(B.G1_GEN, sk_a), B._jac_neg(pk_h_pt))
    rogue = B.g1_to_bytes(rogue_pt)
    msg = b"forged commit"
    forged = B.sign(sk_a, msg)  # signs for pk_h + rogue = sk_a * G1
    # the naive aggregate equation ACCEPTS the forgery...
    assert B.fast_aggregate_verify([B.sk_to_pk(sk_h), rogue], msg, forged)
    # ...but the attacker cannot produce a PoP for the rogue key: a PoP is
    # a signature under the rogue key's secret, which nobody knows. Any
    # PoP they can mint (e.g. under sk_a) fails pop_verify for rogue.
    assert not B.pop_verify(rogue, B.sign(sk_a, rogue, B.DST_POP))
