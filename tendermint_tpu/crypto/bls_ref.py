"""Pure-Python BLS12-381: the aggregate-signature referee AND wheel-less host path.

Mirrors the role crypto/ed25519_ref.py plays for the ed25519 pipeline: a
dependency-free (hashlib-only) implementation that is simultaneously

- the CORRECTNESS REFEREE every device kernel is differentially pinned
  against (tests/test_bls_kernels.py compares ops/fp381 + ops/bls12_msm
  limb outputs bit-for-bit against the ints produced here), and
- the host fast path on containers without an accelerator wheel (the
  aggregate-commit verify in types/validator_set.py routes here whenever
  the device MSM/pairing path is unavailable or the breaker is OPEN).

Scheme: the draft-irtf-cfrg-bls-signature "minimal-pubkey-size"
proof-of-possession ciphersuite, eth2-compatible:

    BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_

Public keys live in G1 (48-byte compressed), signatures in G2 (96-byte
compressed); an n-validator commit carries ONE 96-byte signature + a signer
bitmap instead of n*64 signature bytes (docs/BLS.md). Rogue-key defense is
proof-of-possession (pop_prove / pop_verify); aggregate verification MUST
only accept keys whose PoP has been checked (crypto/keys.py PopRegistry).

Design notes:

- Fp is raw Python ints mod P (fastest); Fp2/Fp6/Fp12 are slotted classes
  over the standard tower  Fp2 = Fp[u]/(u^2+1),  Fp6 = Fp2[v]/(v^3 - XI),
  Fp12 = Fp6[w]/(w^2 - v)  with XI = 1 + u.
- Every derivable constant IS derived at import (Frobenius coefficients,
  the psi untwist-Frobenius-twist endomorphism, the hard-part base-p
  digits) instead of hardcoded, so the only trusted-from-the-spec tables
  are the curve constants, the SSWU (A', B', Z) parameters and the
  RFC 9380 3-isogeny coefficients — each of which is pinned structurally
  (on-curve checks) and against RFC vectors in tests/test_bls_ref.py.
- hash_to_G2 follows RFC 9380 (hash_to_field via expand_message_xmd,
  simplified SSWU on the isogenous curve E', the 3-isogeny to E2, and the
  Budroni-Pintore psi-based clear_cofactor of appendix G.4, which equals
  multiplication by the suite's h_eff).
- The pairing is the optimal ate pairing: Miller loop over |x| (the BLS
  parameter, negative -> one conjugation), line evaluations in affine
  E(Fp12) coordinates (py_ecc-style: slow but transparently correct; the
  device path fuses these into Pallas kernels, ops/pallas_bls.py), easy
  final exponentiation via conjugate/inverse + Frobenius, hard part as
  four base-p digit exponentiations recombined through Frobenius.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Iterable, List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------
# Curve constants (BLS12-381; the spec-trusted table)

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000  # the BLS parameter x (negative)
H1 = 0x396C8C005555E1568C00AAAB0000AAAB  # G1 cofactor

B_G1 = 4  # E1: y^2 = x^3 + 4

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_X_C0 = 0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8
G2_X_C1 = 0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E
G2_Y_C0 = 0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801
G2_Y_C1 = 0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE

DST_SIG = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

PUBKEY_SIZE = 48  # compressed G1
SIGNATURE_SIZE = 96  # compressed G2


# --------------------------------------------------------------------------
# Fp: raw ints mod P


def _fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def _fp_sqrt(a: int) -> Optional[int]:
    """sqrt in Fp (P ≡ 3 mod 4): a^((P+1)/4); None if a is not a QR."""
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a % P else None


class Fp2:
    """c0 + c1*u, u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    def __add__(self, o: "Fp2") -> "Fp2":
        return Fp2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fp2") -> "Fp2":
        return Fp2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fp2":
        return Fp2(-self.c0, -self.c1)

    def __mul__(self, o: "Fp2") -> "Fp2":
        # Karatsuba: (a0+a1 u)(b0+b1 u) = a0b0 - a1b1 + ((a0+a1)(b0+b1) - a0b0 - a1b1) u
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fp2(t0 - t1, t2 - t0 - t1)

    def mul_int(self, k: int) -> "Fp2":
        return Fp2(self.c0 * k, self.c1 * k)

    def square(self) -> "Fp2":
        # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
        return Fp2((self.c0 + self.c1) * (self.c0 - self.c1), 2 * self.c0 * self.c1)

    def conj(self) -> "Fp2":
        return Fp2(self.c0, -self.c1)

    def inv(self) -> "Fp2":
        n = _fp_inv((self.c0 * self.c0 + self.c1 * self.c1) % P)
        return Fp2(self.c0 * n, -self.c1 * n)

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, o) -> bool:
        return isinstance(o, Fp2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self) -> int:
        return hash((self.c0, self.c1))

    def __repr__(self) -> str:
        return f"Fp2({hex(self.c0)}, {hex(self.c1)})"

    def pow(self, e: int) -> "Fp2":
        out, base = FP2_ONE, self
        while e:
            if e & 1:
                out = out * base
            base = base.square()
            e >>= 1
        return out

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for m=2 (sign of the 'lexically first' nonzero limb)."""
        sign_0 = self.c0 & 1
        zero_0 = self.c0 == 0
        return sign_0 | (zero_0 & (self.c1 & 1))

    def is_square(self) -> bool:
        # Euler over Fp via the norm: a is a square in Fp2 iff
        # N(a) = a^(p+1) = c0^2 + c1^2 is a square in Fp.
        n = (self.c0 * self.c0 + self.c1 * self.c1) % P
        return n == 0 or pow(n, (P - 1) // 2, P) == 1

    def sqrt(self) -> Optional["Fp2"]:
        """Complex-method square root for u^2 = -1; None if not a square."""
        if self.is_zero():
            return FP2_ZERO
        if self.c1 == 0:
            s = _fp_sqrt(self.c0)
            if s is not None:
                return Fp2(s, 0)
            # c0 is a nonresidue: sqrt(c0) = sqrt(-c0) * u since u^2 = -1
            s = _fp_sqrt(-self.c0 % P)
            return Fp2(0, s) if s is not None else None
        alpha = _fp_sqrt((self.c0 * self.c0 + self.c1 * self.c1) % P)
        if alpha is None:
            return None
        delta = (self.c0 + alpha) * _fp_inv(2) % P
        x0 = _fp_sqrt(delta)
        if x0 is None:
            delta = (self.c0 - alpha) * _fp_inv(2) % P
            x0 = _fp_sqrt(delta)
            if x0 is None:
                return None
        if x0 == 0:
            return None  # would divide by zero; c1 != 0 makes this unreachable
        y0 = self.c1 * _fp_inv(2 * x0 % P) % P
        cand = Fp2(x0, y0)
        return cand if cand.square() == self else None


FP2_ZERO = Fp2(0, 0)
FP2_ONE = Fp2(1, 0)
XI = Fp2(1, 1)  # the Fp6 nonresidue v^3 = 1 + u


class Fp6:
    """c0 + c1*v + c2*v^2, v^3 = XI."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fp2, c1: Fp2, c2: Fp2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    def __add__(self, o: "Fp6") -> "Fp6":
        return Fp6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fp6") -> "Fp6":
        return Fp6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fp6":
        return Fp6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fp6") -> "Fp6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2) * XI + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2 * XI
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fp6(c0, c1, c2)

    def square(self) -> "Fp6":
        return self * self

    def mul_by_v(self) -> "Fp6":
        """v * (c0 + c1 v + c2 v^2) = c2*XI + c0 v + c1 v^2."""
        return Fp6(self.c2 * XI, self.c0, self.c1)

    def inv(self) -> "Fp6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - (a1 * a2) * XI
        t1 = a2.square() * XI - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = a0 * t0 + (a2 * t1 + a1 * t2) * XI
        dinv = denom.inv()
        return Fp6(t0 * dinv, t1 * dinv, t2 * dinv)

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, o) -> bool:
        return (
            isinstance(o, Fp6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __hash__(self) -> int:
        return hash((self.c0, self.c1, self.c2))


FP6_ZERO = Fp6(FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = Fp6(FP2_ONE, FP2_ZERO, FP2_ZERO)


class Fp12:
    """c0 + c1*w, w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fp6, c1: Fp6):
        self.c0, self.c1 = c0, c1

    def __add__(self, o: "Fp12") -> "Fp12":
        return Fp12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fp12") -> "Fp12":
        return Fp12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fp12":
        return Fp12(-self.c0, -self.c1)

    def __mul__(self, o: "Fp12") -> "Fp12":
        t0 = self.c0 * o.c0
        t1 = self.c1 * o.c1
        t2 = (self.c0 + self.c1) * (o.c0 + o.c1)
        return Fp12(t0 + t1.mul_by_v(), t2 - t0 - t1)

    def square(self) -> "Fp12":
        return self * self

    def conj(self) -> "Fp12":
        """The p^6-Frobenius: w -> -w (conjugation over Fp6)."""
        return Fp12(self.c0, -self.c1)

    def inv(self) -> "Fp12":
        denom = (self.c0 * self.c0 - (self.c1 * self.c1).mul_by_v()).inv()
        return Fp12(self.c0 * denom, -(self.c1 * denom))

    def pow(self, e: int) -> "Fp12":
        if e < 0:
            return self.inv().pow(-e)
        out, base = FP12_ONE, self
        while e:
            if e & 1:
                out = out * base
            base = base.square()
            e >>= 1
        return out

    def is_one(self) -> bool:
        return self == FP12_ONE

    def __eq__(self, o) -> bool:
        return isinstance(o, Fp12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self) -> int:
        return hash((self.c0, self.c1))

    # -- w-power basis view (for Frobenius) --------------------------------

    def wcoeffs(self) -> List[Fp2]:
        """Coefficients over the basis {1, w, w^2=v, w^3=vw, w^4=v^2, w^5=v^2 w}."""
        a, b = self.c0, self.c1
        return [a.c0, b.c0, a.c1, b.c1, a.c2, b.c2]

    @staticmethod
    def from_wcoeffs(c: Sequence[Fp2]) -> "Fp12":
        return Fp12(Fp6(c[0], c[2], c[4]), Fp6(c[1], c[3], c[5]))

    def frobenius(self) -> "Fp12":
        """x -> x^p via conj on Fp2 coefficients + the derived gamma table."""
        return Fp12.from_wcoeffs(
            [c.conj() * _FROB_GAMMA[m] for m, c in enumerate(self.wcoeffs())]
        )


FP12_ZERO = Fp12(FP6_ZERO, FP6_ZERO)
FP12_ONE = Fp12(FP6_ONE, FP6_ZERO)

# Frobenius coefficients, DERIVED at import: pi(w^m) = XI^(m*(p-1)/6) * w^m
# (w^6 = v^3 = XI, and (p-1)/6 is an integer for this p).
_FROB_GAMMA: List[Fp2] = [XI.pow(m * (P - 1) // 6) for m in range(6)]


def fp2_embed(x: Fp2) -> Fp12:
    return Fp12(Fp6(x, FP2_ZERO, FP2_ZERO), FP6_ZERO)


def fp_embed(x: int) -> Fp12:
    return fp2_embed(Fp2(x, 0))


# w as an Fp12 element, and the untwist scale factors 1/w^2, 1/w^3.
_W = Fp12(FP6_ZERO, FP6_ONE)
_W_INV2 = (_W * _W).inv()
_W_INV3 = (_W * _W * _W).inv()


# --------------------------------------------------------------------------
# Jacobian point arithmetic (a = 0 short Weierstrass), generic over the
# coordinate field: ints for G1, Fp2 for G2 — every op used (+, -, *,
# square) exists on both. Points are (X, Y, Z) with Z == zero => identity.


class _G1Field:
    """Shim giving raw ints the operator surface the generic Jacobian
    formulas use; kept trivial so G1 stays close to raw-int speed."""

    __slots__ = ("v",)

    def __init__(self, v: int):
        self.v = v % P

    def __add__(self, o):
        return _G1Field(self.v + o.v)

    def __sub__(self, o):
        return _G1Field(self.v - o.v)

    def __neg__(self):
        return _G1Field(-self.v)

    def __mul__(self, o):
        return _G1Field(self.v * o.v)

    def mul_int(self, k: int):
        return _G1Field(self.v * k)

    def square(self):
        return _G1Field(self.v * self.v)

    def inv(self):
        return _G1Field(_fp_inv(self.v))

    def is_zero(self) -> bool:
        return self.v == 0

    def __eq__(self, o) -> bool:
        return isinstance(o, _G1Field) and self.v == o.v

    def __hash__(self) -> int:
        return hash(self.v)


def _jac_is_identity(pt) -> bool:
    return pt[2].is_zero()


def _jac_double(pt):
    X, Y, Z = pt
    if Z.is_zero():
        return pt
    # Y == 0 (a point of order 2; not in either r-subgroup but reachable on
    # generic curve inputs) needs no branch: Z3 = 2YZ = 0 = identity.
    A = X.square()
    B = Y.square()
    C = B.square()
    D = ((X + B).square() - A - C).mul_int(2)
    E = A.mul_int(3)
    F = E.square()
    X3 = F - D.mul_int(2)
    Y3 = E * (D - X3) - C.mul_int(8)
    Z3 = (Y * Z).mul_int(2)
    return (X3, Y3, Z3)


def _jac_add(p1, p2):
    if _jac_is_identity(p1):
        return p2
    if _jac_is_identity(p2):
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = Z1.square()
    Z2Z2 = Z2.square()
    U1 = X1 * Z2Z2
    U2 = X2 * Z1Z1
    S1 = Y1 * Z2 * Z2Z2
    S2 = Y2 * Z1 * Z1Z1
    if U1 == U2:
        if S1 == S2:
            return _jac_double(p1)
        zero = Z1 - Z1
        return (X1, Y1, zero)  # same x, opposite y: the identity (Z = 0)
    H = U2 - U1
    I = H.mul_int(2).square()
    J = H * I
    r = (S2 - S1).mul_int(2)
    V = U1 * I
    X3 = r.square() - J - V.mul_int(2)
    Y3 = r * (V - X3) - (S1 * J).mul_int(2)
    Z3 = ((Z1 + Z2).square() - Z1Z1 - Z2Z2) * H
    return (X3, Y3, Z3)


def _jac_neg(pt):
    return (pt[0], -pt[1], pt[2])


def _jac_mul(pt, k: int):
    if k < 0:
        return _jac_mul(_jac_neg(pt), -k)
    zero = pt[2] - pt[2]
    one = FP2_ONE if isinstance(zero, Fp2) else _G1Field(1)
    acc = (one, one, zero)  # identity: any X/Y with Z = 0
    if k == 0:
        return acc
    for bit in bin(k)[2:]:
        acc = _jac_double(acc)
        if bit == "1":
            acc = _jac_add(acc, pt)
    return acc


def _jac_to_affine(pt):
    """-> (x, y) coordinate pair, or None for the identity."""
    X, Y, Z = pt
    if Z.is_zero():
        return None
    zinv = Z.inv()
    zinv2 = zinv.square()
    return (X * zinv2, Y * zinv2 * zinv)


def _jac_eq(p1, p2) -> bool:
    i1, i2 = _jac_is_identity(p1), _jac_is_identity(p2)
    if i1 or i2:
        return i1 and i2
    Z1Z1, Z2Z2 = p1[2].square(), p2[2].square()
    return (
        p1[0] * Z2Z2 == p2[0] * Z1Z1
        and p1[1] * Z2Z2 * p2[2] == p2[1] * Z1Z1 * p1[2]
    )


# G1 points: Jacobian triples of _G1Field. G2: Jacobian triples of Fp2.

G1_GEN = (_G1Field(G1_X), _G1Field(G1_Y), _G1Field(1))
G1_IDENTITY = (_G1Field(1), _G1Field(1), _G1Field(0))
G2_GEN = (Fp2(G2_X_C0, G2_X_C1), Fp2(G2_Y_C0, G2_Y_C1), FP2_ONE)
G2_IDENTITY = (FP2_ONE, FP2_ONE, FP2_ZERO)

B2 = XI.mul_int(4)  # E2: y^2 = x^3 + 4(1+u)


def g1_on_curve(pt) -> bool:
    aff = _jac_to_affine(pt)
    if aff is None:
        return True
    x, y = aff
    return (y.v * y.v - x.v * x.v * x.v - B_G1) % P == 0


def g2_on_curve(pt) -> bool:
    aff = _jac_to_affine(pt)
    if aff is None:
        return True
    x, y = aff
    return y.square() == x.square() * x + B2


def g1_in_subgroup(pt) -> bool:
    return g1_on_curve(pt) and _jac_is_identity(_jac_mul(pt, R))


def g2_in_subgroup(pt) -> bool:
    return g2_on_curve(pt) and _jac_is_identity(_jac_mul(pt, R))


# --------------------------------------------------------------------------
# Serialization (ZCash/eth2 compressed encodings)

_HALF_P = (P - 1) // 2


def g1_to_bytes(pt) -> bytes:
    if _jac_is_identity(pt):
        return bytes([0xC0]) + b"\x00" * 47
    x, y = _jac_to_affine(pt)
    flags = 0x80 | (0x20 if y.v > _HALF_P else 0)
    enc = bytearray(x.v.to_bytes(48, "big"))
    enc[0] |= flags
    return bytes(enc)


def g1_from_bytes(data: bytes, subgroup_check: bool = True):
    """48 compressed bytes -> G1 Jacobian point; None if invalid."""
    if len(data) != PUBKEY_SIZE:
        return None
    flags = data[0]
    if not flags & 0x80:
        return None  # only compressed encodings are admitted
    if flags & 0x40:
        if flags != 0xC0 or any(data[1:]) or data[0] & 0x3F:
            return None
        return G1_IDENTITY
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        return None
    y = _fp_sqrt((x * x * x + B_G1) % P)
    if y is None:
        return None
    if (y > _HALF_P) != bool(flags & 0x20):
        y = P - y
    pt = (_G1Field(x), _G1Field(y), _G1Field(1))
    if subgroup_check and not g1_in_subgroup(pt):
        return None
    return pt


def _fp2_lex_gt_half(y: Fp2) -> bool:
    """'y > -y' under the (c1, c0) lexicographic order the ZCash format uses."""
    if y.c1 != 0:
        return y.c1 > _HALF_P
    return y.c0 > _HALF_P


def g2_to_bytes(pt) -> bytes:
    if _jac_is_identity(pt):
        return bytes([0xC0]) + b"\x00" * 95
    x, y = _jac_to_affine(pt)
    flags = 0x80 | (0x20 if _fp2_lex_gt_half(y) else 0)
    enc = bytearray(x.c1.to_bytes(48, "big") + x.c0.to_bytes(48, "big"))
    enc[0] |= flags
    return bytes(enc)


def g2_from_bytes(data: bytes, subgroup_check: bool = True):
    """96 compressed bytes -> G2 Jacobian point; None if invalid."""
    if len(data) != SIGNATURE_SIZE:
        return None
    flags = data[0]
    if not flags & 0x80:
        return None
    if flags & 0x40:
        if flags != 0xC0 or any(data[1:]) or data[0] & 0x3F:
            return None
        return G2_IDENTITY
    x_c1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x_c0 = int.from_bytes(data[48:], "big")
    if x_c0 >= P or x_c1 >= P:
        return None
    x = Fp2(x_c0, x_c1)
    y = (x.square() * x + B2).sqrt()
    if y is None:
        return None
    if _fp2_lex_gt_half(y) != bool(flags & 0x20):
        y = -y
    pt = (x, y, FP2_ONE)
    if subgroup_check and not g2_in_subgroup(pt):
        return None
    return pt


# --------------------------------------------------------------------------
# RFC 9380 hash-to-G2


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 section 5.3.1, hash = SHA-256."""
    if len(dst) > 255:
        dst = b"H2C-OVERSIZE-DST-" + hashlib.sha256(dst).digest()
    h = hashlib.sha256
    b_in_bytes, s_in_bytes = 32, 64
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len_in_bytes > 65535:
        raise ValueError("expand_message_xmd: requested output too long")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * s_in_bytes
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = h(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = h(b0 + b"\x01" + dst_prime).digest()
    uniform = b1
    bi = b1
    for i in range(2, ell + 1):
        bi = h(bytes(a ^ b for a, b in zip(b0, bi)) + i.to_bytes(1, "big") + dst_prime).digest()
        uniform += bi
    return uniform[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int) -> List[Fp2]:
    """RFC 9380 section 5.2 with m=2, L=64."""
    L = 64
    uniform = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        off = i * 2 * L
        e0 = int.from_bytes(uniform[off : off + L], "big") % P
        e1 = int.from_bytes(uniform[off + L : off + 2 * L], "big") % P
        out.append(Fp2(e0, e1))
    return out


# Simplified SSWU on the isogenous curve E': y^2 = x^3 + A'x + B'
# (RFC 9380 section 8.8.2).
SSWU_A = Fp2(0, 240)
SSWU_B = Fp2(1012, 1012)
SSWU_Z = Fp2(-2 % P, -1 % P)  # -(2 + u)


def _sswu(u: Fp2) -> Tuple[Fp2, Fp2]:
    """map_to_curve_simple_swu (RFC 9380 F.2, straight-line version)."""
    Z, A, B = SSWU_Z, SSWU_A, SSWU_B
    u2 = u.square()
    tv1 = Z * u2
    tv2 = tv1.square() + tv1
    if tv2.is_zero():
        x1 = B * (Z * A).inv()  # exceptional case: x = B / (Z * A)
    else:
        x1 = (-B) * A.inv() * (tv2.inv() + FP2_ONE)
    gx1 = x1.square() * x1 + A * x1 + B
    if gx1.is_square():
        x, y = x1, gx1.sqrt()
    else:
        x2 = tv1 * x1
        gx2 = x2.square() * x2 + A * x2 + B
        x, y = x2, gx2.sqrt()
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


# The 3-isogeny E' -> E2 (RFC 9380 appendix E.3). Coefficient table is
# spec-trusted; tests pin (a) SSWU output on E', (b) iso output on E2, and
# (c) the full suite against RFC 9380 known-answer vectors.
_ISO3_X_NUM = [
    Fp2(
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    Fp2(
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    Fp2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    Fp2(
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
]
_ISO3_X_DEN = [
    Fp2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    Fp2(
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
    FP2_ONE,
]
_ISO3_Y_NUM = [
    Fp2(
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    Fp2(
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    Fp2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    Fp2(
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
]
_ISO3_Y_DEN = [
    Fp2(
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    Fp2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    Fp2(
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
    FP2_ONE,
]


def _horner(coeffs: Sequence[Fp2], x: Fp2) -> Fp2:
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = acc * x + c
    return acc


def _iso3_map(x: Fp2, y: Fp2) -> Tuple[Fp2, Fp2]:
    xn, xd = _horner(_ISO3_X_NUM, x), _horner(_ISO3_X_DEN, x)
    yn, yd = _horner(_ISO3_Y_NUM, x), _horner(_ISO3_Y_DEN, x)
    return xn * xd.inv(), y * yn * yd.inv()


# psi: the untwist-Frobenius-twist endomorphism on E2, with DERIVED
# constants: psi(x, y) = (c_x * conj(x), c_y * conj(y)),
# c_x = 1/XI^((p-1)/3), c_y = 1/XI^((p-1)/2).
_PSI_CX = XI.pow((P - 1) // 3).inv()
_PSI_CY = XI.pow((P - 1) // 2).inv()


def _psi(pt):
    """psi on an affine-normalized Jacobian point."""
    aff = _jac_to_affine(pt)
    if aff is None:
        return G2_IDENTITY
    x, y = aff
    return (_PSI_CX * x.conj(), _PSI_CY * y.conj(), FP2_ONE)


def _clear_cofactor_g2(pt):
    """Budroni-Pintore fast clearing (RFC 9380 appendix G.4) — equivalent
    to multiplying by the suite's h_eff, so KATs match the RFC vectors."""
    x = X_PARAM
    t1 = _jac_mul(pt, x)  # x * P  (x negative: mul handles the negate)
    t2 = _psi(pt)
    t3 = _psi(_psi(_jac_double(pt)))  # psi^2(2P)
    t3 = _jac_add(t3, _jac_neg(t2))
    t2 = _jac_mul(_jac_add(t1, t2), x)
    t3 = _jac_add(t3, t2)
    t3 = _jac_add(t3, _jac_neg(t1))
    return _jac_add(t3, _jac_neg(pt))


def hash_to_g2(msg: bytes, dst: bytes = DST_SIG):
    """RFC 9380 hash_to_curve for BLS12381G2_XMD:SHA-256_SSWU_RO_."""
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    x0, y0 = _iso3_map(*_sswu(u0))
    x1, y1 = _iso3_map(*_sswu(u1))
    q = _jac_add((x0, y0, FP2_ONE), (x1, y1, FP2_ONE))
    return _clear_cofactor_g2(q)


# --------------------------------------------------------------------------
# Optimal ate pairing


def _untwist(pt):
    """E2(Fp2) Jacobian -> E(Fp12) affine pair, or None for identity."""
    aff = _jac_to_affine(pt)
    if aff is None:
        return None
    x, y = aff
    return (fp2_embed(x) * _W_INV2, fp2_embed(y) * _W_INV3)


def _linefunc(p1, p2, t):
    """Line through p1, p2 (affine Fp12 pairs) evaluated at t; p1 == p2
    gives the tangent, a vertical line gives x_t - x_1."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        lam = (y2 - y1) * (x2 - x1).inv()
        return lam * (xt - x1) - (yt - y1)
    if y1 == y2:
        lam = (x1 * x1) * fp_embed(3) * (y1 * fp_embed(2)).inv()
        return lam * (xt - x1) - (yt - y1)
    return xt - x1


def _affine12_add(p1, p2):
    """Affine addition on E(Fp12) (None = identity)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            lam = (x1 * x1) * fp_embed(3) * (y1 * fp_embed(2)).inv()
        else:
            return None
    else:
        lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam * lam - x1 - x2
    y3 = lam * (x1 - x3) - y1
    return (x3, y3)


def miller_loop(q, p) -> Fp12:
    """f_{|x|, q}(p) conjugated for the negative BLS parameter.

    q: G2 Jacobian point; p: G1 Jacobian point. Returns the unreduced
    pairing value (caller applies final_exponentiation)."""
    if _jac_is_identity(q) or _jac_is_identity(p):
        return FP12_ONE
    q12 = _untwist(q)
    aff = _jac_to_affine(p)
    p12 = (fp_embed(aff[0].v), fp_embed(aff[1].v))
    f = FP12_ONE
    t = q12
    n = -X_PARAM  # positive loop count
    for bit in bin(n)[3:]:  # MSB already consumed by t = q12
        f = f * f * _linefunc(t, t, p12)
        t = _affine12_add(t, t)
        if bit == "1":
            f = f * _linefunc(t, q12, p12)
            t = _affine12_add(t, q12)
    return f.conj()  # x < 0: f_{-n} ~ conj(f_n) up to final exponentiation


# Hard-part digits of (p^4 - p^2 + 1) / r in base p, derived at import.
assert (P**4 - P**2 + 1) % R == 0
_HARD_EXP = (P**4 - P**2 + 1) // R
_HARD_DIGITS: List[int] = []
_tmp = _HARD_EXP
while _tmp:
    _HARD_DIGITS.append(_tmp % P)
    _tmp //= P
del _tmp


def final_exponentiation(f: Fp12) -> Fp12:
    """f^((p^12 - 1) / r)."""
    # easy part: f^((p^6 - 1)(p^2 + 1))
    g = f.conj() * f.inv()
    g = g.frobenius().frobenius() * g
    # hard part: digits d_i of (p^4 - p^2 + 1)/r in base p; the p^i factors
    # become Frobenius applications (pi(m^d) = frob(m)^d = frob(m^d)).
    out = FP12_ONE
    for i, d in enumerate(_HARD_DIGITS):
        md = g.pow(d)
        for _ in range(i):
            md = md.frobenius()
        out = out * md
    return out


def pairing(p, q) -> Fp12:
    """e(p, q) for p in G1, q in G2 (full reduced pairing)."""
    return final_exponentiation(miller_loop(q, p))


def pairings_are_one(pairs: Iterable[Tuple[object, object]]) -> bool:
    """prod e(p_i, q_i) == 1, with ONE shared final exponentiation."""
    f = FP12_ONE
    for p, q in pairs:
        f = f * miller_loop(q, p)
    return final_exponentiation(f).is_one()


# --------------------------------------------------------------------------
# The signature scheme (minimal-pubkey-size, proof-of-possession)


def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """draft-irtf-cfrg-bls-signature KeyGen (HKDF-SHA256)."""
    if len(ikm) < 32:
        raise ValueError("IKM must be at least 32 bytes")
    salt = b"BLS-SIG-KEYGEN-SALT-"
    L = 48
    while True:
        salt = hashlib.sha256(salt).digest()
        prk = hmac.new(salt, ikm + b"\x00", hashlib.sha256).digest()
        okm = b""
        t = b""
        i = 1
        info = key_info + L.to_bytes(2, "big")
        while len(okm) < L:
            t = hmac.new(prk, t + info + i.to_bytes(1, "big"), hashlib.sha256).digest()
            okm += t
            i += 1
        sk = int.from_bytes(okm[:L], "big") % R
        if sk != 0:
            return sk


def sk_to_pk(sk: int) -> bytes:
    return g1_to_bytes(_jac_mul(G1_GEN, sk % R))


def sign(sk: int, msg: bytes, dst: bytes = DST_SIG) -> bytes:
    return g2_to_bytes(_jac_mul(hash_to_g2(msg, dst), sk % R))


def verify(pk_bytes: bytes, msg: bytes, sig_bytes: bytes, dst: bytes = DST_SIG) -> bool:
    pk = g1_from_bytes(pk_bytes)
    sig = g2_from_bytes(sig_bytes)
    if pk is None or sig is None or _jac_is_identity(pk):
        return False
    return pairings_are_one(
        [(_jac_neg(G1_GEN), sig), (pk, hash_to_g2(msg, dst))]
    )


def aggregate_signatures(sigs: Sequence[bytes]):
    """Aggregate 1..N signatures -> 96 compressed bytes; None on invalid
    input or an empty list (the spec rejects aggregating nothing)."""
    if not sigs:
        return None
    acc = G2_IDENTITY
    for s in sigs:
        pt = g2_from_bytes(s)
        if pt is None:
            return None
        acc = _jac_add(acc, pt)
    return g2_to_bytes(acc)


def aggregate_pubkeys(pks: Sequence[bytes]):
    """Aggregate public keys -> G1 Jacobian point; None on invalid input."""
    acc = G1_IDENTITY
    for k in pks:
        pt = g1_from_bytes(k)
        if pt is None or _jac_is_identity(pt):
            return None
        acc = _jac_add(acc, pt)
    return acc


def fast_aggregate_verify(
    pks: Sequence[bytes], msg: bytes, sig_bytes: bytes, dst: bytes = DST_SIG
) -> bool:
    """All signers signed the SAME msg: one pairing check against the
    aggregate pubkey. Callers MUST have verified each key's PoP (rogue-key
    defense); crypto/keys.PopRegistry enforces that at the framework layer."""
    if not pks:
        return False
    apk = aggregate_pubkeys(pks)
    sig = g2_from_bytes(sig_bytes)
    if apk is None or sig is None:
        return False
    return pairings_are_one([(_jac_neg(G1_GEN), sig), (apk, hash_to_g2(msg, dst))])


def aggregate_verify(
    pks: Sequence[bytes], msgs: Sequence[bytes], sig_bytes: bytes, dst: bytes = DST_SIG
) -> bool:
    """Distinct messages: n+1 pairings, one shared final exponentiation.
    Messages must be DISTINCT per the core spec when PoP is not used; the
    framework only calls this on the PoP-registered path, so duplicate
    messages are allowed (AggregateVerify in the PoP ciphersuite)."""
    if not pks or len(pks) != len(msgs):
        return False
    sig = g2_from_bytes(sig_bytes)
    if sig is None:
        return False
    pairs = [(_jac_neg(G1_GEN), sig)]
    for pk_b, m in zip(pks, msgs):
        pk = g1_from_bytes(pk_b)
        if pk is None or _jac_is_identity(pk):
            return False
        pairs.append((pk, hash_to_g2(m, dst)))
    return pairings_are_one(pairs)


def pop_prove(sk: int) -> bytes:
    """Proof of possession: sign your own pubkey bytes under the POP DST."""
    return sign(sk, sk_to_pk(sk), DST_POP)


def pop_verify(pk_bytes: bytes, proof: bytes) -> bool:
    return verify(pk_bytes, pk_bytes, proof, DST_POP)


def gen_sk(seed: Optional[bytes] = None) -> int:
    return keygen(seed if seed is not None else os.urandom(32))
