"""Random-linear-combination (RLC) batch Ed25519 verification on TPU.

The fast path for large batches: instead of N independent double-scalar
ladders (ops/ed25519_jax.py, ~3.5k field muls per signature), check ONE
group equation over random 128-bit coefficients z_i:

    [sum z_i s_i mod L] B  ==  sum [z_i] R_i  +  sum [z_i h_i mod 8L] A_i

rearranged as  sum [w_i] A_i + [(L-u) mod L] B + sum [z_i] R_i == identity,
with w_i = z_i h_i mod 8L and u = sum z_i s_i mod L. Coefficients z_i are
random ~124-bit values FORCED to multiples of 8 and scalars are reduced mod
8L (the full curve-group order, so reduction is exact for points of ANY
order): the cofactor-8 torsion component of every lane is annihilated
deterministically, making the combined check exactly the COFACTORED batch
equation [8] sum z'_i (s_i B - h_i A_i - R_i) == identity. If every
per-signature cofactored equation holds the combination is the identity; if
any fails, it is the identity with probability <= ~2^-120 over the z_i. The
caller falls back to the per-signature kernel when the batch check fails to
recover the exact per-signature mask. COFACTORED (ZIP-215-style) is the
framework's single verification predicate on EVERY path — this batch check,
the per-sig kernel (ops/ed25519_jax.py), and the host wrapper
(crypto/keys.py via ed25519_ref.verify_cofactored) — so acceptance never
depends on which path a node runs. Honest keys and signatures are
torsion-free, where cofactored agrees exactly with the reference's
cofactorless check (types/validator_set.go:680-702); only crafted torsion
inputs ever see the (deliberate, documented) divergence from Go.

sr25519 (schnorrkel) shares the SAME equation shape (s B == R + k A over
ristretto255, which is this curve quotiented by its torsion): sr lanes join
the MSM with ristretto-decoded points (ops/ristretto_jax.py) and
transcript challenges k_i in place of h_i. Multiples-of-8 coefficients make
edwards-coordinate identity exactly equivalent to ristretto equality, so
the sr device path has NO semantic divergence from the host verifier.

The multiscalar multiplication is Pippenger reshaped for a vector machine
(no scatter, no data-dependent control flow on device):

  host   per 8-bit window: stable-sort lane indices by digit; compute
         per-bucket boundary positions; decompose each boundary prefix
         into its Fenwick (binary-representation) tree nodes.
  device 1. decompress points (invalid -> identity, flagged);
         2. gather lanes into sorted order per window;
         3. pair-tree up-sweep: node (l, k) = sum of sorted lanes
            [k*2^l, (k+1)*2^l)  — log2(N) unrolled vector adds, total
            work ~N lane-adds per window;
         4. gather <=16 tree nodes per bucket boundary and add them:
            prefix[v] = exact sum of all lanes with digit <= v;
         5. bucket_v = prefix[v] - prefix[v-1]; weighted bucket reduce
            via suffix sums (sum_v v*S_v = sum_j suffix_j);
         6. Horner combine across windows (8 doublings + 1 add each, on
            a single point).

Per signature this costs ~80 batched point additions + 2 point
decompressions, vs ~770 add-equivalents for the per-sig ladder — the
doubling chains (the per-lane ladder's fixed cost) are shared across the
whole batch, which is the entire idea of Pippenger.

Window size is fixed at 8 bits so digits are exactly the scalar bytes.

A-point caching: consensus verifies the SAME validator public keys every
height, so decompression of A (a ~250-mul sqrt chain per point) is cached
across calls keyed by pubkey bytes — see crypto/batch.py. The kernel
variant `_rlc_core_cached` accepts predecompressed A coordinates.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Sequence, Tuple

import os

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops import aot_cache
from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops.ed25519_jax import (
    FieldCtx,
    Point,
    decompress,
    identity,
    make_ctx,
)

WINDOW_BITS = 8
NWIN = 32  # 256 bits / 8
NBUCKETS = 1 << WINDOW_BITS
FENWICK_K = 17  # max tree levels: boundary prefixes reach N <= 2^16 lanes


# --------------------------------------------------------------------------
# Small-constant context: rank-agnostic (20,) buffers reshaped per use.
# The MSM kernel works at many intermediate shapes (per tree level, per
# bucket phase), so full-batch materialized constants (FieldCtx) are only
# used for the single decompress shape; everything else uses these.


class SmallCtx(NamedTuple):
    comp: jnp.ndarray  # (20,)
    corr: jnp.ndarray  # (20,)
    one: jnp.ndarray  # (20,)
    d2: jnp.ndarray  # (20,)


def make_small_ctx() -> SmallCtx:
    return SmallCtx(
        comp=jnp.asarray(np.asarray(fe.COMP)),
        corr=jnp.asarray(np.asarray(fe.CORR)),
        one=jnp.asarray(fe.from_int(1)),
        d2=jnp.asarray(fe.from_int(fe.D2)),
    )


def _rs(c: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Reshape a (20,) constant buffer for broadcasting against rank-ndim."""
    return c.reshape((fe.NLIMBS,) + (1,) * (ndim - 1))


def _sub(C: SmallCtx, a, b):
    return fe.sub(a, b, _rs(C.comp, a.ndim), _rs(C.corr, a.ndim))


def _neg(C: SmallCtx, a):
    return _sub(C, jnp.zeros_like(a), a)


def _use_pallas() -> bool:
    from tendermint_tpu.ops import pallas_fe

    return pallas_fe.enabled()


# ---------------------------------------------------------------------------
# Fused-pipeline selection (ops/pallas_msm.py). The fused schedule keeps the
# gather/up-sweep/prefix/bucket stages VMEM-resident in one packed layout;
# the unfused per-level schedule below stays as the differential reference
# and the fallback for lane counts no chunk size tiles.

# Sticky runtime kill switch: the first hardware failure of the fused path
# (e.g. a Mosaic lowering rejection on some TPU generation) flips this and
# every later submit builds the unfused graph — crypto/batch.py retries the
# failed flush unfused, so one bad compile costs one retry, not the RLC path.
_FUSED_DISABLED: list = [None]  # reason string once disabled

# Submit-path accounting is PER THREAD: the prewarm thread and the
# consensus event loop may submit concurrently, and thread-local state
# keeps one flush's byte/dispatch deltas and fused flag from being
# attributed to another's — without serializing the submit path (host
# prep plus a first-call kernel compile can take minutes) behind a lock.
class _FlushThreadState(__import__("threading").local):
    def __init__(self):
        self.counters = {"h2d_bytes": 0, "dispatches": 0}
        self.last_fused = False


_FLUSH_TLS = _FlushThreadState()


def flush_counters() -> dict:
    """This thread's cumulative submit-path device-traffic counters
    ("h2d_bytes", "dispatches"). Tests pin a per-flush budget on the deltas
    (tests/test_flush_budget.py) so a regression that reintroduces per-flush
    uploads or extra dispatches fails tier-1 instead of only showing up in a
    lost bench round."""
    return _FLUSH_TLS.counters


def last_submit_fused() -> bool:
    """Whether this thread's most recent rlc_check_*_submit built the fused
    graph (observability: crypto/batch.py copies it into the flush detail)."""
    return _FLUSH_TLS.last_fused


def _set_submit_fused(fused: bool) -> None:
    _FLUSH_TLS.last_fused = bool(fused)


def _dispatch(name: str, jit_fn, *args):
    """aot_cache.call with device-traffic accounting: every numpy leaf is a
    host->device upload on this call; jax-array leaves are device-resident."""
    c = _FLUSH_TLS.counters
    c["dispatches"] += 1
    for leaf in jax.tree_util.tree_leaves(args):
        if isinstance(leaf, np.ndarray):
            c["h2d_bytes"] += leaf.nbytes
    return aot_cache.call(name, jit_fn, *args)


def fused_for_lanes(n_lanes: int) -> bool:
    """Route this lane count through the fused pipeline? TMTPU_FUSED_MSM:
    "0" never, "1" always (CPU twins included — tests), "auto" (default)
    with the Pallas kernels only."""
    if _FUSED_DISABLED[0] is not None:
        return False
    mode = os.environ.get("TMTPU_FUSED_MSM", "auto")
    if mode == "0":
        return False
    from tendermint_tpu.ops import pallas_msm

    if pallas_msm.chunk_for_lanes(n_lanes) is None:
        return False
    return True if mode == "1" else _use_pallas()


def disable_fused(reason: str) -> None:
    """Sticky per-process disable after a fused-path failure (see
    crypto/batch.py's retry); re-enabled only by a fresh process."""
    if _FUSED_DISABLED[0] is None:
        _FUSED_DISABLED[0] = reason
        import logging

        logging.getLogger("tendermint_tpu.ops.msm").warning(
            "fused MSM pipeline disabled for this process: %s", reason
        )


def _padd(C: SmallCtx, p: Point, q: Point) -> Point:
    """Unified a=-1 extended add (same formula as ed25519_jax.point_add but
    with rank-agnostic constants). On TPU this routes through the fused
    Pallas kernel (ops/pallas_fe.py) — ~11x the XLA fusion's field-mul
    throughput (the XLA conv churns its accumulator through HBM) and one
    custom call instead of ~500 HLO ops per add."""
    if _use_pallas():
        from tendermint_tpu.ops import pallas_fe

        return pallas_fe.padd(p, q)
    a = fe.mul(_sub(C, p.y, p.x), _sub(C, q.y, q.x))
    b = fe.mul(fe.add(p.y, p.x), fe.add(q.y, q.x))
    c = fe.mul(fe.mul(p.t, q.t), _rs(C.d2, p.t.ndim))
    d = fe.mul_small(fe.mul(p.z, q.z), 2)
    e = _sub(C, b, a)
    f = _sub(C, d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _pdbl(C: SmallCtx, p: Point) -> Point:
    if _use_pallas():
        from tendermint_tpu.ops import pallas_fe

        return pallas_fe.pdbl(p)
    xx = fe.square(p.x)
    yy = fe.square(p.y)
    zz2 = fe.mul_small(fe.square(p.z), 2)
    xy2 = fe.square(fe.add(p.x, p.y))
    e = _sub(C, xy2, fe.add(xx, yy))
    g = _sub(C, yy, xx)
    f = _sub(C, g, zz2)
    h = _neg(C, fe.add(xx, yy))
    return Point(fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def _pdbl_n(C: SmallCtx, p: Point, n: int) -> Point:
    """[2^n] p. On TPU, doublings fuse into Pallas kernels in runs of 8
    (single-kernel chains longer than ~8 blow up Mosaic compile time for no
    runtime gain); elsewhere a plain unrolled loop."""
    if _use_pallas():
        from tendermint_tpu.ops import pallas_fe

        while n > 0:
            k = min(n, 8)
            p = pallas_fe.pdbl(p, times=k)
            n -= k
        return p
    for _ in range(n):
        p = _pdbl(C, p)
    return p


def _pneg(C: SmallCtx, p: Point) -> Point:
    return Point(_neg(C, p.x), p.y, p.z, _neg(C, p.t))


def _pidentity(C: SmallCtx, batch_shape) -> Point:
    z = jnp.zeros((fe.NLIMBS, *batch_shape), dtype=jnp.int32)
    one = jnp.broadcast_to(_rs(C.one, 1 + len(batch_shape)), z.shape)
    return Point(z, one, one, z)


def _pselect(cond, a: Point, b: Point) -> Point:
    return Point(
        fe.select(cond, a.x, b.x),
        fe.select(cond, a.y, b.y),
        fe.select(cond, a.z, b.z),
        fe.select(cond, a.t, b.t),
    )


# --------------------------------------------------------------------------
# Level geometry (shared host/device so Fenwick indices line up).


def level_widths(n_lanes: int) -> list:
    """Widths of the pair-tree levels: level 0 = n_lanes, each next level
    halves (odd widths padded up by one identity lane first)."""
    widths = [n_lanes]
    w = n_lanes
    while w > 1:
        w = (w + 1) // 2
        widths.append(w)
    return widths


def level_offsets(n_lanes: int) -> Tuple[list, int]:
    widths = level_widths(n_lanes)
    offs = []
    total = 0
    for w in widths:
        offs.append(total)
        total += w
    return offs, total


# --------------------------------------------------------------------------
# Host-side preparation.


def fenwick_node_indices(ends: np.ndarray, n_lanes: int) -> np.ndarray:
    """ends: (T, NBUCKETS) int32, ends[w, v] = number of lanes whose window-w
    digit is <= v. Returns (T, NBUCKETS, FENWICK_K) int32 of global indices
    into the concatenated tree-levels array; slot l holds the level-l node of
    the Fenwick decomposition of prefix [0, ends[w, v]) — or the identity
    lane (index = total width) when bit l of the boundary is clear.

    Derivation: writing e = sum over set bits 2^l, the prefix [0, e)
    decomposes into one aligned block per set bit: the level-l block starting
    at offset (e >> (l+1)) << (l+1), i.e. node index (e >> (l+1)) << 1."""
    offs, total = level_offsets(n_lanes)
    e = ends.astype(np.int64)
    out = np.full((*ends.shape, FENWICK_K), total, dtype=np.int32)  # identity pad
    for lvl in range(min(FENWICK_K, len(offs))):
        bit = (e >> lvl) & 1
        idx = offs[lvl] + ((e >> (lvl + 1)) << 1)
        out[..., lvl] = np.where(bit == 1, idx, total).astype(np.int32)
    return out


def sort_windows(digits: np.ndarray, zero16_from: int = 0):
    """digits: (n_lanes, T) uint8 — window w digit of lane i is byte w of
    its scalar. Returns (perm (T, N), ends (T, NBUCKETS) int32).

    Upload-lean by design (the device tunnel moves ~20-40 MB/s, measured, so
    warm-call argument bytes ARE latency): perm ships as uint16 whenever the
    lane count fits (every production bucket), and instead of the
    (T, 256, 17) Fenwick node table only the (T, 256) bucket-boundary `ends`
    go to the device — ~32 KB vs ~0.5 MB — with the node decomposition
    recomputed on-device (fenwick_nodes_device, pure elementwise int ops).

    Routed through the native C counting sort (tendermint_tpu/native) when
    available: ~20x the numpy stable argsort at 20k lanes."""
    n, t = digits.shape
    idt = np.uint16 if n < (1 << 16) else np.int32
    if t == NWIN:
        from tendermint_tpu import native

        if native.available():
            perm32, ends = native.sort_windows(digits, zero16_from)
            return np.ascontiguousarray(perm32.astype(idt)), ends
    # per-column stable argsort in ONE call (axis=0), then counts via a
    # single bincount over offset digits
    perm = np.ascontiguousarray(
        np.argsort(digits, axis=0, kind="stable").T.astype(idt)
    )  # (T, n)
    offs = (np.arange(t, dtype=np.int64) * NBUCKETS)[None, :]
    flat = digits.astype(np.int64) + offs  # (n, T)
    counts = np.bincount(flat.ravel(), minlength=t * NBUCKETS).reshape(t, NBUCKETS)
    ends = np.cumsum(counts, axis=1).astype(np.int32)
    return perm, ends


def fenwick_nodes_device(ends: jnp.ndarray, n_lanes: int) -> jnp.ndarray:
    """Device-side fenwick_node_indices: ends (T, NBUCKETS) int32 ->
    (T, NBUCKETS, FENWICK_K) int32. Same derivation, elementwise."""
    offs, total = level_offsets(n_lanes)
    lvls = min(FENWICK_K, len(offs))
    e = jnp.asarray(ends).astype(jnp.int32)[..., None]  # (T, 256, 1)
    lvl = jnp.arange(lvls, dtype=jnp.int32)
    bit = (e >> lvl) & 1
    idx = jnp.asarray(np.asarray(offs[:lvls], dtype=np.int32)) + (
        (e >> (lvl + 1)) << 1
    )
    out = jnp.where(bit == 1, idx, jnp.int32(total))
    if lvls < FENWICK_K:
        pad = jnp.full((*out.shape[:-1], FENWICK_K - lvls), total, jnp.int32)
        out = jnp.concatenate([out, pad], axis=-1)
    return out



def scalars_to_bytes(scalars, n_lanes: int) -> np.ndarray:
    """Little-endian (n_lanes, 32) uint8; rows past len(scalars) are zero.

    Accepts a ready (m, 32) uint8 digit array as-is (the native host-prep
    path stays in the bytes domain end to end — crypto/batch.py). For int
    lists: one join + one frombuffer instead of a frombuffer per row, ~20x
    faster at 20k lanes."""
    if isinstance(scalars, np.ndarray) and scalars.dtype == np.uint8:
        if scalars.shape[0] == n_lanes:
            return scalars
        padded = np.zeros((n_lanes, 32), dtype=np.uint8)
        padded[: scalars.shape[0]] = scalars
        return padded
    blob = b"".join(int(s).to_bytes(32, "little") for s in scalars)
    out = np.frombuffer(blob, dtype=np.uint8).reshape(len(scalars), 32)
    if len(scalars) == n_lanes:
        return out
    padded = np.zeros((n_lanes, 32), dtype=np.uint8)
    padded[: len(scalars)] = out
    return padded


# --------------------------------------------------------------------------
# Device kernel.


def _pad_lanes(C: SmallCtx, p: Point, to: int) -> Point:
    w = p.x.shape[-1]
    if w == to:
        return p
    pad = _pidentity(C, p.x.shape[1:-1] + (to - w,))
    return Point(*(jnp.concatenate([a, b], axis=-1) for a, b in zip(p, pad)))


def _halve(C: SmallCtx, p: Point) -> Point:
    """One tree level: pairwise add over the (even-width) last axis."""
    return _padd(
        C,
        Point(*(a[..., 0::2] for a in p)),
        Point(*(a[..., 1::2] for a in p)),
    )


_TREE_SCAN_WIDTH = 256  # levels at or below this width run in one scan body


def _scan_structures() -> bool:
    """XLA:CPU's LLVM codegen cannot hold the fully-unrolled point-op
    graphs (compile memory exhaustion), so the CPU backend keeps the
    compile-sized scan forms; on TPU the unrolled forms measured ~18%
    faster end-to-end (loop-iteration overhead on narrow tensors)."""
    return jax.default_backend() == "cpu"


def _tree_levels(C: SmallCtx, p: Point) -> Point:
    """Build the concatenated pair-tree over the last axis, appending one
    identity lane at the end (the Fenwick pad target). p: (20, T, N).

    Compile-time shaping: wide levels (width > 256) are unrolled (the work
    shrinks geometrically, so unrolling is also the work-efficient layout);
    the tail levels run as ONE lax.scan body over fixed (…, 256)-padded
    arrays, so the whole tail costs a single point-add in the compiled
    graph — a fully-unrolled tree blew past XLA:CPU's compile memory.
    Level geometry must match level_widths()/level_offsets()."""
    widths = level_widths(p.x.shape[-1])
    levels = [p]
    cur = p
    floor = _TREE_SCAN_WIDTH if _scan_structures() else 1
    while cur.x.shape[-1] > floor:
        w = cur.x.shape[-1]
        if w % 2 == 1:
            cur = _pad_lanes(C, cur, w + 1)
        cur = _halve(C, cur)
        levels.append(cur)

    n_tail = len(widths) - len(levels)
    if n_tail > 0:
        # Fixed-width tail: state is the current level padded to a power of
        # two; each iteration halves and re-pads. ys collects every produced
        # level; logical widths come from level_widths().
        w0 = 1 << (max(cur.x.shape[-1] - 1, 1)).bit_length()  # pow2 >= width
        w0 = max(w0, 2)
        state = tuple(_pad_lanes(C, cur, w0))

        def body(st, _):
            pt = Point(*st)
            nxt = _pad_lanes(C, _halve(C, pt), w0)
            return tuple(nxt), tuple(nxt)

        _, ys = jax.lax.scan(body, state, None, length=n_tail)
        base = len(levels)
        for i in range(n_tail):
            lw = widths[base + i]
            levels.append(Point(*(ys[c][i][..., :lw] for c in range(4))))

    pad = _pidentity(C, p.x.shape[1:-1] + (1,))
    return Point(
        *(
            jnp.concatenate(
                [lv[i][..., : widths[k]] for k, lv in enumerate(levels)] + [pad[i]],
                axis=-1,
            )
            for i in range(4)
        )
    )


def _gather_lanes(p: Point, perm: jnp.ndarray) -> Point:
    """p coords (20, N); perm (T, N) -> coords (20, T, N).

    Layout matters enormously here: gathering scalars along the MINOR axis
    (`c[:, perm]`) ran at ~21 GB/s on TPU (15 ns/element — 19.5 ms of the
    62 ms r4 kernel). Instead gather whole ROWS of an (N, 4*20) table — all
    four coordinates' limbs contiguous per lane (320 B) — and let XLA fuse
    the surrounding transposes (slope-measured r5: lane 8.2 -> 5.3 ms,
    fenwick 23.3 -> 4.6 ms on the same index sets)."""
    perm = jnp.asarray(perm).astype(jnp.int32)  # uint16 on the wire
    n = p.x.shape[-1]
    t_ = perm.shape[0]
    arr = jnp.stack([c.T for c in p], axis=1).reshape(n, 4 * fe.NLIMBS)
    g = arr[perm].reshape(t_, perm.shape[1], 4, fe.NLIMBS)  # (T, N, 4, 20)
    return Point(*(jnp.moveaxis(g[:, :, c, :], -1, 0) for c in range(4)))


def _gather_nodes(tree: Point, node_idx: jnp.ndarray) -> Point:
    """tree coords (20, T, Wtot+1); node_idx (T, NBUCKETS, K) ->
    (20, T, NBUCKETS, K). Row-gather layout — see _gather_lanes."""
    node_idx = jnp.asarray(node_idx).astype(jnp.int32)  # uint16 on the wire
    t_, nb, k_ = node_idx.shape
    w = tree.x.shape[-1]
    arr = jnp.stack([jnp.moveaxis(c, 0, -1) for c in tree], axis=-2)  # (T, W, 4, 20)
    arr = arr.reshape(t_, w, 4 * fe.NLIMBS)
    g = jnp.take_along_axis(arr, node_idx.reshape(t_, nb * k_)[..., None], axis=1)
    g = g.reshape(t_, nb, k_, 4, fe.NLIMBS)
    return Point(*(jnp.moveaxis(g[..., c, :], -1, 0) for c in range(4)))


def _reduce_last_axis(C: SmallCtx, p: Point) -> Point:
    """Pair-tree sum over the last axis (odd widths identity-padded)."""
    while p.x.shape[-1] > 1:
        w = p.x.shape[-1]
        if w % 2 == 1:
            p = _pad_lanes(C, p, w + 1)
        p = _halve(C, p)
    return Point(*(a[..., 0] for a in p))


def _sum_last_axis(C: SmallCtx, p: Point) -> Point:
    """Tree-sum over the last axis (any width) as ONE scan body: state stays
    at a fixed power-of-two width, each iteration halves and re-pads with
    identity (compile-size over the small extra work)."""
    w = p.x.shape[-1]
    if w == 1:
        return Point(*(a[..., 0] for a in p))
    if not _scan_structures():
        while p.x.shape[-1] > 1:
            wd = p.x.shape[-1]
            if wd % 2 == 1:
                p = _pad_lanes(C, p, wd + 1)
            p = _halve(C, p)
        return Point(*(a[..., 0] for a in p))
    w0 = max(1 << (w - 1).bit_length(), 2)
    state = tuple(_pad_lanes(C, p, w0))

    def body(st, _):
        nxt = tuple(_pad_lanes(C, _halve(C, Point(*st)), w0))
        return nxt, None

    steps = (w0 - 1).bit_length()
    st, _ = jax.lax.scan(body, state, None, length=steps)
    return Point(*(a[..., 0] for a in st))


def _weighted_bucket_sum(C: SmallCtx, prefix: Point) -> Point:
    """prefix: (20, T, NBUCKETS) — prefix[v] = exact sum of all sorted lanes
    with digit <= v. Returns per-window W = sum_{v>=1} v * bucket_v, (20, T).

    The bucket differences telescope: with bucket_v = P_v - P_{v-1},
        sum_{v=1}^{V} v (P_v - P_{v-1})  =  V*P_V  -  sum_{v=0}^{V-1} P_v
    (V = 255). No per-bucket subtraction or suffix scan is needed, and the
    bucket-0 contribution (zero-scalar / padding lanes) appears in every
    P_v, so it cancels exactly: V*P_V carries V copies, the sum carries V."""
    v_max = prefix.x.shape[-1] - 1  # 255
    p_last = Point(*(a[..., -1] for a in prefix))  # (20, T)
    rest = Point(*(a[..., :-1] for a in prefix))  # v = 0..254
    s = _sum_last_axis(C, rest)

    # [255] P_255 = [256] P_255 - P_255: 8 doublings + one add of the negation.
    if not _scan_structures():
        m = _pdbl_n(C, p_last, v_max.bit_length())
    else:
        def dbl_body(st, _):
            return tuple(_pdbl(C, Point(*st))), None

        st, _ = jax.lax.scan(dbl_body, tuple(p_last), None, length=v_max.bit_length())
        m = Point(*st)
    m = _padd(C, m, _pneg(C, p_last))  # [256]P - P = [255]P
    return _padd(C, m, _pneg(C, s))


def _combine_windows(C: SmallCtx, w_pts: Point) -> Point:
    """w_pts coords (20, T) with window w weight 256^w -> sum [256^w] W_w.

    The ~248-doubling sequential depth is inherent (it equals the scalar
    bit-width), but HOW it is scheduled matters enormously on TPU: the
    round-3 Horner (lax.scan over 31 window steps) measured ~64 ms at 10k —
    ~2 ms/iteration of while-loop overhead on width-1 tensors, a third of
    total kernel time. The Pallas form is an unrolled pairwise fold:
        level k: V_i = U_{2i} + [2^(8*2^k)] U_{2i+1}
    — same 248 sequential doublings, but zero loop machinery, shrinking
    widths (16, 8, 4, 2, 1), and each point-op ONE custom call so the graph
    stays ~300 HLO ops. The fold is PALLAS-ONLY: expressed in raw jnp its
    ~253 point-ops inline to >15k HLO and the XLA:TPU compile ran >30 min
    before being killed (XLA:CPU dies the same way) — scan stays the
    non-pallas form on both backends."""
    t_ = w_pts.x.shape[-1]
    if _use_pallas():
        return _fold_windows(C, w_pts)

    acc = Point(*(a[..., t_ - 1] for a in w_pts))  # (20,)
    xs = jnp.stack(
        [jnp.moveaxis(a[..., : t_ - 1], -1, 0) for a in w_pts], axis=1
    )  # (T-1, 4, 20)
    xs = xs[::-1]  # MSB-first over remaining windows

    unroll_dbl = not _scan_structures()  # TPU: unrolled dblings inside body

    def body(acc_coords, wp):
        if unroll_dbl:
            p = Point(*acc_coords)
            for _ in range(WINDOW_BITS):
                p = _pdbl(C, p)
            acc_coords = tuple(p)
        else:
            def dbl(_, st):
                return tuple(_pdbl(C, Point(*st)))

            acc_coords = jax.lax.fori_loop(0, WINDOW_BITS, dbl, acc_coords)
        acc = _padd(C, Point(*acc_coords), Point(wp[0], wp[1], wp[2], wp[3]))
        return tuple(acc), None

    acc_coords, _ = jax.lax.scan(body, tuple(acc), xs)
    return Point(*acc_coords)


def _fold_windows(C: SmallCtx, w_pts: Point) -> Point:
    """The pairwise window fold (see _combine_windows docstring): level k
    computes V_i = U_{2i} + [2^(8*2^k)] U_{2i+1}. On TPU every point op is
    a Pallas call; on CPU the same schedule runs through the jnp point ops,
    which is what the differential test exercises (the fold itself is
    Pallas-only in production, so without this split a pairing/shift bug
    would only surface as end-to-end verification failure on hardware)."""
    p = w_pts
    shift = WINDOW_BITS
    while p.x.shape[-1] > 1:
        w = p.x.shape[-1]
        if w % 2 == 1:
            p = _pad_lanes(C, p, w + 1)
        even = Point(*(a[..., 0::2] for a in p))
        odd = Point(*(a[..., 1::2] for a in p))
        odd = _pdbl_n(C, odd, shift)
        p = _padd(C, even, odd)
        shift *= 2
    return Point(*(a[..., 0] for a in p))


def _window_points(C: SmallCtx, pts: Point, perm, node_idx) -> Point:
    """One window group: gather lanes, pair-tree, Fenwick prefix extraction,
    weighted bucket sums. pts (20, N); perm (T, N); returns (20, T)."""
    gathered = _gather_lanes(pts, perm)  # (20, T, N)
    tree = _tree_levels(C, gathered)  # (20, T, Wtot+1)
    nodes = _gather_nodes(tree, node_idx)  # (20, T, 256, K)
    prefix = _reduce_last_axis(C, nodes)  # (20, T, 256)
    return _weighted_bucket_sum(C, prefix)  # (20, T)


def _msm_total(C: SmallCtx, pts: Point, perm, node_idx) -> Point:
    """pts: decompressed valid points (20, N); perm (T, N). Returns the full
    multiscalar sum as a single point (20,). (A window-split variant — high
    windows over the A block only, since R-lane coefficients are < 2^128 —
    was tried and measured 4x SLOWER on TPU: two half-width pipelines lose
    to one fused full-width one.)"""
    w_pts = _window_points(C, pts, perm, node_idx)  # (20, T)
    return _combine_windows(C, w_pts)  # (20,)


def point_is_identity(C: SmallCtx, total: Point) -> jnp.ndarray:
    """Projective identity check with the degenerate-output guard: an
    exceptional unified addition (possible only on crafted torsion inputs)
    yields (0,0,0,0), which must read as "check failed" (-> per-sig
    fallback), not as the identity."""
    return fe.is_zero(total.x) & fe.eq(total.y, total.z) & ~fe.is_zero(total.z)


def _msm_is_identity(C: SmallCtx, pts: Point, perm, node_idx) -> jnp.ndarray:
    return point_is_identity(C, _msm_total(C, pts, perm, node_idx))


# ---------------------------------------------------------------------------
# Fused pipeline (ops/pallas_msm.py): the same MSM with the tree/prefix/
# bucket stages as VMEM-resident fused kernels in ONE packed limb layout.
#
# Storage map (row indices into the concatenated gatherable row table):
#   [0, T*N)                       level-0 lanes, bit-reversed within chunks
#   [G1, G1 + T*ncw*rows_out*128)  chunk trees (levels 1..lc, chunk-major)
#   [G2, G2 + T*(Wtop+1))          top tree over chunk roots + identity lane
# A bucket boundary e decomposes as: full chunks [0, e>>lc) via the top
# tree's Fenwick nodes (the old aligned-block derivation over ncw chunk
# totals), plus the bits of e & (ch-1) via level-0/chunk-tree nodes of the
# partial chunk — at bit-reversed in-level positions (pallas_msm docstring).


def fused_node_indices_device(ends: jnp.ndarray, n_lanes: int, ch: int) -> jnp.ndarray:
    """ends (T, NBUCKETS) int32 -> (NBUCKETS, T, Kf) int32 global row
    indices, bucket-major (v-major) so the downstream reduce/bucket kernels
    see flat lane order v*T + t."""
    from tendermint_tpu.ops import pallas_msm as PM

    g = PM.chunk_geometry(ch)
    ncw = n_lanes // ch
    t_ = ends.shape[0]
    toffs, ttot = level_offsets(ncw)
    wtop1 = ttot + 1
    g1 = t_ * n_lanes
    g2 = g1 + t_ * ncw * g.rows_out * 128

    e = jnp.asarray(ends).astype(jnp.int32).T[..., None]  # (NB, T, 1)
    w = jnp.arange(t_, dtype=jnp.int32)[None, :, None]
    ce = e >> g.lc
    r = e & (ch - 1)
    idn = g2 + w * wtop1 + ttot  # per-window identity lane

    # partial-chunk part: levels 0..lc-1, present iff bit l of r
    lvl = jnp.arange(g.lc, dtype=jnp.int32)
    bit = (r >> lvl) & 1
    j = (r >> (lvl + 1)) << 1
    q = PM.brev_jnp(j, g.lc - lvl)  # in-level bit-reversed position
    roff = jnp.asarray(g.row_off, dtype=jnp.int32)
    idx0 = w * n_lanes + ce * ch + q
    idxl = (
        g1
        + (w * ncw + ce) * (g.rows_out * 128)
        + (roff[lvl] + (q >> 7)) * 128
        + (q & 127)
    )
    cidx = jnp.where(lvl == 0, idx0, idxl)
    cidx = jnp.where(bit == 1, cidx, idn)

    # full-chunks part: the old Fenwick derivation over ncw chunk totals
    lt = len(toffs)
    lvl2 = jnp.arange(lt, dtype=jnp.int32)
    bit2 = (ce >> lvl2) & 1
    jt = (ce >> (lvl2 + 1)) << 1
    tidx = g2 + w * wtop1 + jnp.asarray(toffs, dtype=jnp.int32)[lvl2] + jt
    tidx = jnp.where(bit2 == 1, tidx, idn)
    return jnp.concatenate([cidx, tidx], axis=-1)


def _msm_total_fused(C: SmallCtx, pts: Point, perm, ends) -> Point:
    """The fused-schedule twin of _msm_total: identical group element,
    different (VMEM-resident) evaluation order. pts (20, N); perm (T, N)
    natural sorted order (the bit-reversal is composed in here); ends
    (T, NBUCKETS)."""
    from tendermint_tpu.ops import pallas_msm as PM

    perm = jnp.asarray(perm).astype(jnp.int32)
    t_ = perm.shape[0]
    n = pts.x.shape[-1]
    ch = PM.chunk_for_lanes(n)
    g = PM.chunk_geometry(ch)
    ncw = n // ch

    # gather lanes directly into fused order: whole 320-byte point rows
    # (the r5 row-gather layout), chunk-wise bit-reversed via the composed
    # permutation — the only big gather the tree phase pays.
    perm_f = jnp.take(perm, jnp.asarray(PM.brev_positions(n, ch)), axis=1)
    rowtab = jnp.stack([c.T for c in pts], axis=1).reshape(n, 4 * fe.NLIMBS)
    g_rows = rowtab[perm_f.reshape(-1)]  # (T*N, 80)

    # chunk trees: ONE kernel computes levels 1..lc per chunk in VMEM
    ctree = PM.uptree(PM.rows_to_packed(g_rows), ch)
    ctree_rows = PM.packed_to_rows(ctree)

    # top tree over the T*ncw chunk roots (tiny; existing limb-major path)
    root_row = g.row_off[g.lc]
    roots = ctree.reshape(4, fe.NLIMBS, t_ * ncw, g.rows_out, 128)[
        :, :, :, root_row, 0
    ]
    roots_pt = Point(*(roots[c].reshape(fe.NLIMBS, t_, ncw) for c in range(4)))
    top = _tree_levels(C, roots_pt)  # (20, T, Wtop+1) incl. identity lane
    wtop1 = top.x.shape[-1]
    top_rows = jnp.stack(
        [jnp.moveaxis(c, 0, -1) for c in top], axis=-2
    ).reshape(t_ * wtop1, 4 * fe.NLIMBS)

    # Fenwick prefix extraction: row-gather the decomposition nodes, reduce
    # them in ONE accumulating kernel (no materialized (T,256,K) tensor)
    all_rows = jnp.concatenate([g_rows, ctree_rows, top_rows], axis=0)
    node_idx = fused_node_indices_device(ends, n, ch)  # (NB, T, Kf)
    kf = node_idx.shape[-1]
    gathered = all_rows[node_idx.reshape(-1)]  # (NB*T*Kf, 80)
    gk = jnp.moveaxis(gathered.reshape(NBUCKETS * t_, kf, 4 * fe.NLIMBS), 1, 0)
    gk = jnp.moveaxis(gk, -1, 1).reshape(
        kf, 4, fe.NLIMBS, NBUCKETS * t_ // 128, 128
    )
    prefix = PM.fenwick_reduce(gk)  # packed, v-major

    # weighted bucket sum: one fused fold kernel + the tiny (20, T) tail
    s_coords, p255_coords = PM.bucket_fold(prefix, t_)
    s_pt = Point(*s_coords)
    p_last = Point(*p255_coords)
    m = _pdbl_n(C, p_last, WINDOW_BITS)  # [256] P_255
    m = _padd(C, m, _pneg(C, p_last))  # [255] P_255
    w_pts = _padd(C, m, _pneg(C, s_pt))  # (20, T) per-window sums
    return _combine_windows(C, w_pts)


def _msm_check(C: SmallCtx, pts: Point, perm, ends, fused: bool) -> jnp.ndarray:
    """Batch-identity check routing: fused (VMEM-resident schedule) vs the
    unfused per-level reference. `fused` is trace-static — the two variants
    are distinct jit programs (and distinct AOT artifacts)."""
    if fused:
        return point_is_identity(C, _msm_total_fused(C, pts, perm, ends))
    node_idx = fenwick_nodes_device(ends, pts.x.shape[-1])
    return _msm_is_identity(C, pts, perm, node_idx)


def _rlc_core(
    pts_bytes: jnp.ndarray,  # (32, N) uint8 — A lanes, B lane, R lanes, pads
    perm: jnp.ndarray,  # (T, N) int/uint
    ends: jnp.ndarray,  # (T, NBUCKETS) int32 bucket boundaries
    fctx: FieldCtx,  # materialized at batch shape (N,) for decompress
    C: SmallCtx,
    fused: bool = False,
) -> jnp.ndarray:
    """Returns bool (1+N,): [batch_ok, lane_ok...] packed into ONE array so
    the caller syncs in a single D2H round trip."""
    p, ok = decompress(fctx, pts_bytes)
    p = _pselect(ok, p, identity(fctx))
    bok = _msm_check(C, p, perm, ends, fused)
    return jnp.concatenate([bok[None], ok])


def _rlc_partial_core(
    pts_bytes: jnp.ndarray,  # (32, N) uint8 — chunk lanes [A | B | R | pads]
    perm: jnp.ndarray,  # (T, N)
    ends: jnp.ndarray,  # (T, NBUCKETS) int32
    fctx: FieldCtx,  # at shape (N,)
    C: SmallCtx,
    fused: bool = False,
):
    """One streamed-planner chunk (crypto/batch.py): the full Pippenger
    pipeline over this chunk's lanes, WITHOUT the identity check — the MSM
    is a sum over lanes, so an arbitrarily large flush decomposes into
    fixed-bucket partial sums accumulated on device (_partial_fold_core)
    with one identity check at the end (_partial_identity_core).

    Returns (coords (4, 20) int32 — the chunk's partial point in extended
    limbs, ok (N,) bool — per-lane decompress validity)."""
    p, ok = decompress(fctx, pts_bytes)
    p = _pselect(ok, p, identity(fctx))
    if fused:
        part = _msm_total_fused(C, p, perm, ends)
    else:
        node_idx = fenwick_nodes_device(ends, pts_bytes.shape[-1])
        part = _msm_total(C, p, perm, node_idx)
    return jnp.stack(part), ok


def _partial_fold_core(a: jnp.ndarray, b: jnp.ndarray, C: SmallCtx) -> jnp.ndarray:
    """Fold two (4, 20) partial points: ONE unified add — the tiny combine
    kernel the streamed planner dispatches per chunk (device-resident
    accumulation; nothing but the two points ever lives in HBM)."""
    s = _padd(C, Point(a[0], a[1], a[2], a[3]), Point(b[0], b[1], b[2], b[3]))
    return jnp.stack(s)


def _partial_identity_core(a: jnp.ndarray, C: SmallCtx) -> jnp.ndarray:
    """Identity check on an accumulated (4, 20) partial point — the streamed
    flush's combined-check verdict."""
    return point_is_identity(C, Point(a[0], a[1], a[2], a[3]))


def _rlc_core_cached(
    ax, ay, az, at,  # (20, Na) predecompressed A block (incl. B lane)
    r_bytes,  # (32, Nr) uint8
    perm,
    ends,  # (T, NBUCKETS) int32
    fctx: FieldCtx,  # at shape (Nr,)
    C: SmallCtx,
    fused: bool = False,
) -> jnp.ndarray:
    """Cached-A variant: lanes = [A block | R block]; only R is decompressed.
    Returns bool (1+Nr,): [batch_ok, r_ok...]."""
    r, r_ok = decompress(fctx, r_bytes)
    r = _pselect(r_ok, r, identity(fctx))
    pts = Point(
        *(
            jnp.concatenate([a, b], axis=-1)
            for a, b in zip(Point(ax, ay, az, at), r)
        )
    )
    bok = _msm_check(C, pts, perm, ends, fused)
    return jnp.concatenate([bok[None], r_ok])


def sort_windows_device(digits: jnp.ndarray):
    """In-graph per-window sort: digits (N, T) uint8 -> perm (T, N) int32,
    ends (T, NBUCKETS) int32 — the device-side twin of sort_windows.

    Why on device: the host counting sort is ~18 ms single-threaded at
    20k lanes AND the perm it produces is 2x the wire size of the digits
    it's derived from ((T,N) uint16 = 1.3 MB vs (N,T) uint8 = 655 KB at
    ~20-40 MB/s H2D). Sorting in-graph removes both. Stability is NOT
    required: bucket sums and Fenwick prefixes depend only on the SET of
    lanes at each digit value, never on intra-bucket order."""
    d_t = digits.T  # (T, N)
    perm = jnp.argsort(d_t, axis=1).astype(jnp.int32)
    sorted_d = jnp.take_along_axis(d_t, perm, axis=1)
    vals = jnp.arange(NBUCKETS, dtype=sorted_d.dtype)
    ends = jax.vmap(
        lambda row: jnp.searchsorted(row, vals, side="right")
    )(sorted_d).astype(jnp.int32)
    return perm, ends


def _rlc_core_cached_dsort(
    ax, ay, az, at,  # (20, Na) predecompressed A block (incl. B lane)
    r_bytes,  # (32, Nr) uint8
    digits,  # (Na+Nr, T) uint8 scalar digit rows (window w = byte w)
    fctx: FieldCtx,  # at shape (Nr,)
    C: SmallCtx,
    fused: bool = False,
) -> jnp.ndarray:
    """_rlc_core_cached with the window sort in-graph (sort_windows_device):
    the host sends raw scalar digit rows; perm/ends/Fenwick nodes are all
    derived on device."""
    perm, ends = sort_windows_device(digits)
    return _rlc_core_cached(ax, ay, az, at, r_bytes, perm, ends, fctx, C, fused)


def _rlc_core_cached_mixed(
    ax, ay, az, at,  # (20, Na) predecoded A block (incl. B lane, both key types)
    ed_r_bytes,  # (32, Ne) uint8 — ed25519 R encodings
    sr_r_bytes,  # (32, Ns) uint8 — ristretto255 R encodings
    perm,
    ends,  # (T, NBUCKETS) int32
    fctx_ed: FieldCtx,  # at shape (Ne,)
    fctx_sr: FieldCtx,  # at shape (Ns,)
    C: SmallCtx,
    fused: bool = False,
) -> jnp.ndarray:
    """Mixed-key-type cached-A variant: lanes = [A block | edR | srR].
    Returns bool (1+Ne+Ns,): [batch_ok, ed_r_ok..., sr_r_ok...]."""
    from tendermint_tpu.ops.ristretto_jax import ristretto_decode

    er, er_ok = decompress(fctx_ed, ed_r_bytes)
    er = _pselect(er_ok, er, identity(fctx_ed))
    sr, sr_ok = ristretto_decode(fctx_sr, sr_r_bytes)
    sr = _pselect(sr_ok, sr, identity(fctx_sr))
    pts = Point(
        *(
            jnp.concatenate([a, b, c], axis=-1)
            for a, b, c in zip(Point(ax, ay, az, at), er, sr)
        )
    )
    bok = _msm_check(C, pts, perm, ends, fused)
    return jnp.concatenate([bok[None], er_ok, sr_ok])


# The fused/unfused variants are separate jit objects (and carry distinct
# AOT-cache names below): `fused` changes the traced graph, so it must never
# share a compiled-program cache slot with the other variant.
_rlc_jit = jax.jit(_rlc_core)
_rlc_jit_fused = jax.jit(functools.partial(_rlc_core, fused=True))
_rlc_cached_jit = jax.jit(_rlc_core_cached)
_rlc_cached_jit_fused = jax.jit(functools.partial(_rlc_core_cached, fused=True))
_rlc_cached_dsort_jit = jax.jit(_rlc_core_cached_dsort)
_rlc_cached_dsort_jit_fused = jax.jit(
    functools.partial(_rlc_core_cached_dsort, fused=True)
)
_rlc_cached_mixed_jit = jax.jit(_rlc_core_cached_mixed)
_rlc_cached_mixed_jit_fused = jax.jit(
    functools.partial(_rlc_core_cached_mixed, fused=True)
)
_rlc_partial_jit = jax.jit(_rlc_partial_core)
_rlc_partial_jit_fused = jax.jit(functools.partial(_rlc_partial_core, fused=True))
_partial_fold_jit = jax.jit(_partial_fold_core)
_partial_identity_jit = jax.jit(_partial_identity_core)


def _device_sort_enabled() -> bool:
    # Default OFF: slope-measured 58.0 ms/commit at 10k vs 52.7 ms with the
    # host counting sort (TPU v5e through the tunnel) — the in-graph
    # argsort+searchsorted costs more than the 18 ms host sort + extra
    # 0.7 MB H2D it removes. Kept selectable for hosts where the tradeoff
    # flips (slow host CPU, faster interconnect). Scope: the pure-ed25519
    # cached path only — the mixed ed25519+sr25519 kernel always uses the
    # host sort.
    return os.environ.get("TMTPU_DEVICE_SORT", "0") != "0"


def basepoint_coords() -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host constants: the ed25519 basepoint in extended limbs (20,) int32."""
    from tendermint_tpu.crypto.ed25519_ref import BASE

    x, y, z, t = BASE
    return (fe.from_int(x), fe.from_int(y), fe.from_int(z), fe.from_int(t))


_decompress_jit = jax.jit(lambda b, fctx: decompress(fctx, b))


def decompress_rows(rows: np.ndarray) -> Tuple[Tuple[np.ndarray, ...], np.ndarray]:
    """rows (m, 32) uint8 -> ((x, y, z, t) each (20, m) int32, ok (m,) bool).
    Pads to a small shape-bucket internally; used to fill the pubkey cache."""
    m = rows.shape[0]
    pad = 1 << max(6, (m - 1).bit_length())
    buf = np.zeros((pad, 32), dtype=np.uint8)
    buf[:, 1] = 0x80  # y=2^255-ish: invalid, but masked by slicing below
    buf[:m] = rows
    p, ok = _decompress_jit(np.ascontiguousarray(buf.T), make_ctx((pad,)))
    coords = tuple(np.asarray(c)[:, :m] for c in p)
    return coords, np.asarray(ok)[:m]


def _trace_span(name: str, **attrs):
    """Flight-recorder span when tracing is on, else a no-op context
    (libs/trace.py); the submit spans cover host sort + async dispatch."""
    from tendermint_tpu.libs.trace import tracer

    if tracer.enabled:
        return tracer.span(name, **attrs)
    import contextlib

    return contextlib.nullcontext()


def rlc_check_submit(
    pts_bytes: np.ndarray, scalars: Sequence[int], zero16_from: int = 0,
    presorted=None,
):
    """Host prep + async device submit: pts_bytes (N, 32) uint8 encodings,
    [A block | R block] with scalars to match (0 = excluded lane; R-block
    scalars < 2^128). zero16_from: the A/R boundary when known (R-block
    scalars being < 2^128 lets the sort skip those rows in the high
    windows). `presorted=(perm, ends)` skips the digit expansion AND the
    window sort — the stage-overlapped submit (crypto/batch.py ISSUE 18)
    sorts on the prep side so this call dispatches immediately. Returns an
    unsynced device bool (1+N,): [batch_ok, lane_ok...] — np.asarray() it
    to sync."""
    n = pts_bytes.shape[0]
    with _trace_span("kernel.rlc_submit", variant="plain", lanes=n):
        if presorted is not None:
            perm, ends = presorted
        else:
            digits = scalars_to_bytes(scalars, n)
            perm, ends = sort_windows(digits, zero16_from=zero16_from)
        fctx = make_ctx((n,))
        fused = fused_for_lanes(n)
        _set_submit_fused(fused)
        return _dispatch(
            "rlc_plain_f" if fused else "rlc_plain",
            _rlc_jit_fused if fused else _rlc_jit,
            np.ascontiguousarray(pts_bytes.T), perm, ends, fctx, make_small_ctx(),
        )


def rlc_check(pts_bytes: np.ndarray, scalars: Sequence[int]) -> Tuple[bool, np.ndarray]:
    out = np.asarray(rlc_check_submit(pts_bytes, scalars))
    return bool(out[0]), out[1:]


def rlc_partial_submit(
    pts_bytes: np.ndarray, scalars, zero16_from: int = 0, presorted=None
):
    """Host prep + async submit of ONE streamed-flush chunk's partial MSM
    (crypto/batch.py's flush planner): same prep as rlc_check_submit, but
    the kernel returns the chunk's partial point instead of a verdict.
    `presorted=(perm, ends)` skips the window sort here — the planner's
    prep WORKER sorts chunk k+1 while chunk k's kernels execute, so the
    sort must not re-run on the submitting thread.
    Returns (coords (4, 20) int32 device array, ok (N,) bool device array)
    — both unsynced; np.asarray() to sync."""
    n = pts_bytes.shape[0]
    with _trace_span("kernel.rlc_partial_submit", variant="partial", lanes=n):
        if presorted is not None:
            perm, ends = presorted
        else:
            digits = scalars_to_bytes(scalars, n)
            perm, ends = sort_windows(digits, zero16_from=zero16_from)
        fctx = make_ctx((n,))
        fused = fused_for_lanes(n)
        _set_submit_fused(fused)
        return _dispatch(
            "rlc_partial_f" if fused else "rlc_partial",
            _rlc_partial_jit_fused if fused else _rlc_partial_jit,
            np.ascontiguousarray(pts_bytes.T), perm, ends, fctx, make_small_ctx(),
        )


def partial_fold_submit(acc, part):
    """Device-resident accumulation of streamed-chunk partials: one tiny
    padd kernel over two (4, 20) points (async; a no-sync dispatch)."""
    return _dispatch("partial_fold", _partial_fold_jit, acc, part, make_small_ctx())


def partial_identity_submit(acc):
    """The streamed flush's combined-check verdict on the accumulated
    partial point. Returns an unsynced device bool scalar."""
    return _dispatch(
        "partial_ident", _partial_identity_jit, acc, make_small_ctx()
    )


def rlc_check_cached_submit(
    a_coords: Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
    r_bytes: np.ndarray,  # (Nr, 32)
    scalars: Sequence[int],  # length Na + Nr, A block first
    presorted=None,
):
    """Cached-A variant of rlc_check_submit (A predecompressed, R by bytes).
    `presorted=(perm, ends)` is honored on the HOST-sort arm only (the
    device-sort arm derives perm/ends in-graph from raw digits and has no
    host sort to skip). Returns an unsynced device bool (1+Nr,):
    [batch_ok, r_ok...]."""
    na = a_coords[0].shape[-1]
    nr = r_bytes.shape[0]
    n = na + nr
    with _trace_span("kernel.rlc_submit", variant="cached", lanes=n):
        fctx = make_ctx((nr,))
        fused = fused_for_lanes(n)
        _set_submit_fused(fused)
        if _device_sort_enabled():
            # digits go down raw; perm/ends are derived in-graph
            # (sort_windows_device) — no host sort, half the wire bytes.
            digits = scalars_to_bytes(scalars, n)
            return _dispatch(
                "rlc_cached_ds_f" if fused else "rlc_cached_ds",
                _rlc_cached_dsort_jit_fused if fused else _rlc_cached_dsort_jit,
                *a_coords,
                np.ascontiguousarray(r_bytes.T),
                digits,
                fctx,
                make_small_ctx(),
            )
        # rows >= na are the z-lane (128-bit scalars) + padding: zero digits
        # in windows 16-31, so the sort skips their count pass
        if presorted is not None:
            perm, ends = presorted
        else:
            digits = scalars_to_bytes(scalars, n)
            perm, ends = sort_windows(digits, zero16_from=na)
        return _dispatch(
            "rlc_cached_f" if fused else "rlc_cached",
            _rlc_cached_jit_fused if fused else _rlc_cached_jit,
            *a_coords,
            np.ascontiguousarray(r_bytes.T),
            perm,
            ends,
            fctx,
            make_small_ctx(),
        )


def rlc_check_cached(
    a_coords: Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
    r_bytes: np.ndarray,
    scalars: Sequence[int],
) -> Tuple[bool, np.ndarray]:
    out = np.asarray(rlc_check_cached_submit(a_coords, r_bytes, scalars))
    return bool(out[0]), out[1:]


def rlc_check_cached_mixed_submit(
    a_coords: Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
    ed_r_bytes: np.ndarray,  # (Ne, 32)
    sr_r_bytes: np.ndarray,  # (Ns, 32)
    scalars: Sequence[int],  # length Na + Ne + Ns: A block, ed R, sr R
):
    """Mixed ed25519+sr25519 cached-A RLC submit (no sync). Returns an
    unsynced device bool (1+Ne+Ns,): [batch_ok, ed_r_ok..., sr_r_ok...]."""
    na = a_coords[0].shape[-1]
    ne = ed_r_bytes.shape[0]
    ns = sr_r_bytes.shape[0]
    n = na + ne + ns
    with _trace_span("kernel.rlc_submit", variant="mixed", lanes=n):
        digits = scalars_to_bytes(scalars, n)
        # rows >= na are the (128-bit) z-lane scalars of both R blocks
        perm, ends = sort_windows(digits, zero16_from=na)
        fused = fused_for_lanes(n)
        _set_submit_fused(fused)
        return _dispatch(
            "rlc_mixed_f" if fused else "rlc_mixed",
            _rlc_cached_mixed_jit_fused if fused else _rlc_cached_mixed_jit,
            *a_coords,
            np.ascontiguousarray(ed_r_bytes.T),
            np.ascontiguousarray(sr_r_bytes.T),
            perm,
            ends,
            make_ctx((ne,)),
            make_ctx((ns,)),
            make_small_ctx(),
        )
