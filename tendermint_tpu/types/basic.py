"""Core identifiers: BlockID, PartSetHeader, timestamps, enums.

Mirrors reference types/block.go (BlockID), types/part_set.go (PartSetHeader),
proto/tendermint/types/types.proto (SignedMsgType, BlockIDFlag).

Timestamps are integer nanoseconds since the Unix epoch throughout the
framework (the reference uses Go time.Time; canonical encodings split into
seconds/nanos exactly like protobuf Timestamp).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs import protowire as pw

NANOS = 1_000_000_000

# Block part size (reference: types/params.go BlockPartSizeBytes) and the hard
# block-size cap (reference: types/params.go MaxBlockSizeBytes = 100MB); the
# part-total bound derives from them. Decoded peer values above the bound are
# rejected before any allocation sized by them (PartSetHeader.validate_basic).
BLOCK_PART_SIZE_BYTES = 65536
MAX_BLOCK_SIZE_BYTES = 104_857_600
MAX_PART_SET_TOTAL = (MAX_BLOCK_SIZE_BYTES // BLOCK_PART_SIZE_BYTES) + 1


def ts_seconds_nanos(ts_ns: int) -> tuple[int, int]:
    return divmod(ts_ns, NANOS)


class SignedMsgType(enum.IntEnum):
    UNKNOWN = 0
    PREVOTE = 1
    PRECOMMIT = 2
    PROPOSAL = 32


class BlockIDFlag(enum.IntEnum):
    ABSENT = 1
    COMMIT = 2
    NIL = 3


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative Total")
        if self.total > MAX_PART_SET_TOTAL:
            raise ValueError(f"Total {self.total} exceeds maximum {MAX_PART_SET_TOTAL}")
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong Hash size")

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.total)
        w.bytes_field(2, self.hash)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "PartSetHeader":
        total, h = 0, b""
        for f, _, v in pw.Reader(data):
            if f == 1:
                total = v
            elif f == 2:
                h = v
        return cls(total=total, hash=h)


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        """True if this references a full block (reference: types/block.go IsComplete)."""
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong Hash size")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        # 8-byte width accommodates any varint-decodable total; callers are
        # expected to validate_basic() first, but key() itself must not raise
        # on hostile input (it sits on the VoteSet.add_vote path — hence the
        # per-instance memo: a vote storm calls key() several times per vote).
        k = self.__dict__.get("_key")
        if k is None:
            k = (
                self.hash
                + self.part_set_header.hash
                + (self.part_set_header.total & (2**64 - 1)).to_bytes(8, "big")
            )
            object.__setattr__(self, "_key", k)
        return k

    def encode(self) -> bytes:
        # per-instance memo (same idiom as key()): a BlockID is frozen and
        # every Vote/CommitSig wire encode embeds it — a vote storm shares
        # one instance across thousands of encodes
        cached = self.__dict__.get("_wire")
        if cached is not None:
            return cached
        w = pw.Writer()
        w.bytes_field(1, self.hash)
        psh = self.part_set_header.encode()
        w.message_field(2, psh, always=True)  # gogo non-nullable
        data = w.bytes()
        object.__setattr__(self, "_wire", data)
        return data

    @classmethod
    def decode(cls, data: bytes) -> "BlockID":
        h, psh = b"", PartSetHeader()
        for f, _, v in pw.Reader(data):
            if f == 1:
                h = v
            elif f == 2:
                psh = PartSetHeader.decode(v)
        return cls(hash=h, part_set_header=psh)


ZERO_BLOCK_ID = BlockID()
