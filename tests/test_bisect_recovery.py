"""Bisection recovery (ISSUE 20) — log-cost exact-mask recovery after a
failed combined check.

When the RLC combined check fails, the old recovery was one monolithic
per-signature flush over ALL n rows; the bisection ladder
(crypto/batch.py _bisect_recover / _bisect_recover_host) instead splits
the failed range at the largest power of two below its size, re-checks
halves with combined sub-checks over the SAME warm pow2 lane buckets,
and runs the per-sig kernel only at small leaves. These tests pin the
CONTRACT with the device kernels replaced by ed25519_ref host twins
(tests/test_flush_planner.py pattern — tier-1 pays no XLA compile):

- one bad row over C = ceil(n/leaf) chunks recovers in at most
  2*ceil(log2 C)+1 device flushes, counted TWO ways: the recovery
  ledger (LAST_FLUSH_DETAIL / trace counters) and an independent
  kernel-submission witness wrapped around the host twins;
- the recovered mask is byte-identical across every arm: single-chip
  bisect, streamed planner recovery, sharded-streamed recovery (fake
  mesh), host-RLC bisect, and the naive TMTPU_BISECT=0 fallback;
- the host arm (_bisect_recover_host) keeps the same log-cost bound;
- a dense flood trips the adaptive bail (TMTPU_BISECT_MAX_BAD) and the
  mask stays exact;
- TMTPU_BISECT=0 restores the straight-to-per-sig arm (one recovery
  flush, identical mask).
"""

import math
import os

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.crypto import batch
from tendermint_tpu.libs import trace as _trace
from tests.test_flush_planner import (
    _fake_mesh_env,
    _install_host_twins,
    _signed_rows,
)


@pytest.fixture
def bisect_env(monkeypatch):
    """Small-geometry bisection: RLC floor 8, leaf 8, planner out of the
    way, verified-row memo off (a memo hit would skip the flush whose
    count this file pins)."""
    monkeypatch.setattr(batch, "RLC_MIN", 8)
    monkeypatch.setenv("TMTPU_BISECT_LEAF", "8")
    prev = batch.planner_budget()
    batch.configure_planner(max_flush_lanes=1 << 16)
    batch.configure_verified_memo(0)
    yield
    batch.configure_planner(max_flush_lanes=prev)
    batch.configure_verified_memo(batch._memo_env_rows())


class _FlushWitness:
    """Independent device-flush counter: wraps the host-twin kernel entry
    points AFTER _install_host_twins, so the recovery ledger is checked
    against actual kernel submissions, not its own bookkeeping."""

    def __init__(self, monkeypatch):
        from tendermint_tpu.ops import ed25519_jax, msm_jax

        _install_host_twins(monkeypatch)
        self.combined = 0
        self.persig = 0
        real_full = msm_jax.rlc_check_submit
        real_persig = ed25519_jax.verify_prepared

        def counting_full(*a, **k):
            self.combined += 1
            return real_full(*a, **k)

        def counting_persig(*a, **k):
            self.persig += 1
            return real_persig(*a, **k)

        monkeypatch.setattr(msm_jax, "rlc_check_submit", counting_full)
        monkeypatch.setattr(ed25519_jax, "verify_prepared", counting_persig)


def _flip(sigs, i):
    """Valid encodings, wrong signature: only the curve check fails —
    precheck passes, so the row survives to the combined check (the
    poisoning shape; docs/ROBUSTNESS.md)."""
    sigs[i] = sigs[i][:32] + (1).to_bytes(32, "little")


# ---------------------------------------------------------------------------
# The flush bound.


@pytest.mark.parametrize("bad_row", [0, 27, 63], ids=["head", "mid", "tail"])
def test_one_bad_row_flush_bound_and_exact_mask(
    bisect_env, monkeypatch, bad_row
):
    """One poisoned row in 64 (8 chunks of leaf=8) recovers in at most
    2*ceil(log2 8)+1 = 7 device flushes — pinned by the recovery ledger,
    the trace counters AND the independent submission witness — and the
    mask is byte-identical to the CPU reference."""
    witness = _FlushWitness(monkeypatch)
    pks, msgs, sigs = _signed_rows(64)
    sigs = list(sigs)
    _flip(sigs, bad_row)

    counters0 = _trace.verify_stats()["counters"]["recovery_flushes"]
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    recovery = batch.LAST_FLUSH_DETAIL.get("recovery_flushes", 0)

    assert not mask[bad_row] and mask.sum() == 63
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    assert mask.tobytes() == cpu.tobytes()

    chunks = math.ceil(64 / 8)
    bound = 2 * math.ceil(math.log2(chunks)) + 1
    assert 1 <= recovery <= bound
    assert batch.LAST_JAX_PATH[0] == "rlc-bisect"
    # the witness: total kernel submissions = 1 initial (failed) combined
    # check + the recovery flushes the ledger claims
    assert witness.combined - 1 + witness.persig == recovery
    # and the cumulative trace counter grew by exactly that many
    assert (
        _trace.verify_stats()["counters"]["recovery_flushes"] - counters0
        == recovery
    )
    assert _trace.verify_stats()["last_flush"]["recovery_flushes"] == recovery


def test_two_bad_rows_cost_at_most_two_descents(bisect_env, monkeypatch):
    """k bad rows cost at most k independent descents: 2 poisoned rows in
    separate halves stay within 2 * (2*ceil(log2 C)+1) flushes."""
    witness = _FlushWitness(monkeypatch)
    pks, msgs, sigs = _signed_rows(64)
    sigs = list(sigs)
    _flip(sigs, 5)
    _flip(sigs, 60)

    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    recovery = batch.LAST_FLUSH_DETAIL.get("recovery_flushes", 0)

    assert not mask[5] and not mask[60] and mask.sum() == 62
    bound = 2 * math.ceil(math.log2(math.ceil(64 / 8))) + 1
    assert recovery <= 2 * bound
    assert witness.combined - 1 + witness.persig == recovery


def test_dense_flood_trips_adaptive_bail_mask_exact(bisect_env, monkeypatch):
    """A dense flood (half the rows poisoned) trips TMTPU_BISECT_MAX_BAD:
    remaining ranges skip their combined checks and go straight per-sig,
    so bisection never costs more than the naive arm by a growing factor
    — and the mask stays exact."""
    monkeypatch.setenv("TMTPU_BISECT_MAX_BAD", "2")
    _FlushWitness(monkeypatch)
    pks, msgs, sigs = _signed_rows(64)
    sigs = list(sigs)
    bad = set(range(0, 64, 2))
    for i in bad:
        _flip(sigs, i)

    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert all(bool(mask[i]) != (i in bad) for i in range(64))
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    assert mask.tobytes() == cpu.tobytes()


def test_bisect_disabled_restores_naive_arm(bisect_env, monkeypatch):
    """TMTPU_BISECT=0: straight-to-per-sig recovery — ONE whole-batch
    recovery flush, identical mask (the bench baseline arm)."""
    monkeypatch.setenv("TMTPU_BISECT", "0")
    witness = _FlushWitness(monkeypatch)
    pks, msgs, sigs = _signed_rows(64)
    sigs = list(sigs)
    _flip(sigs, 13)

    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")

    assert not mask[13] and mask.sum() == 63
    assert batch.LAST_FLUSH_DETAIL.get("recovery_flushes") == 1
    assert witness.persig == 1  # one monolithic per-sig flush, all 64 rows
    assert batch.LAST_JAX_PATH[0] == "persig"


# ---------------------------------------------------------------------------
# Host arm (_bisect_recover_host): same ladder on the striped host RLC.


def test_host_bisect_flush_bound_and_exact_mask(bisect_env, monkeypatch):
    """The CPU fallback's bisection keeps the log-cost shape: one bad row
    in 64 (host leaf 2, host-RLC floor lowered to 8 so the ladder actually
    splits) recovers with at most 2*ceil(log2 C)+1 host-RLC sub-checks."""
    monkeypatch.setattr(batch, "_HOST_RLC_MIN", 8)
    combined = [0]
    serial_rows = [0]
    real_rlc = batch._verify_batch_cpu_rlc
    real_serial = batch._verify_serial_host

    def counting_rlc(pks, msgs, sigs):
        combined[0] += 1
        return real_rlc(pks, msgs, sigs)

    def counting_serial(pks, msgs, sigs):
        serial_rows[0] += len(pks)
        return real_serial(pks, msgs, sigs)

    monkeypatch.setattr(batch, "_verify_batch_cpu_rlc", counting_rlc)
    monkeypatch.setattr(batch, "_verify_serial_host", counting_serial)

    pks, msgs, sigs = _signed_rows(64)
    sigs = list(sigs)
    _flip(sigs, 41)
    detail0 = batch.LAST_FLUSH_DETAIL.get("recovery_flushes", 0)

    mask = batch.verify_batch_cpu(pks, msgs, sigs)

    assert not mask[41] and mask.sum() == 63
    # host leaf = max(8 // 4, 1) = 2, but the _HOST_RLC_MIN guard stops
    # splitting at ranges under 16 rows: C = ceil(64 / 8) = 8 chunks
    bound = 2 * math.ceil(math.log2(8)) + 1
    recovery = batch.LAST_FLUSH_DETAIL.get("recovery_flushes", 0) - detail0
    assert 1 <= recovery <= bound
    # the serial loop ran on a small leaf, never the whole batch
    assert serial_rows[0] < 64
    # combined sub-checks: 1 initial (failed) + the ladder's re-checks
    assert combined[0] - 1 + (1 if serial_rows[0] else 0) <= bound + 1


def test_host_naive_arm_counts_its_recovery_flush(bisect_env, monkeypatch):
    """TMTPU_BISECT=0 on the host arm: the whole-batch serial pass is
    counted as one recovery flush (the ledger covers both arms)."""
    monkeypatch.setattr(batch, "_HOST_RLC_MIN", 8)
    monkeypatch.setenv("TMTPU_BISECT", "0")
    pks, msgs, sigs = _signed_rows(64)
    sigs = list(sigs)
    _flip(sigs, 7)
    detail0 = batch.LAST_FLUSH_DETAIL.get("recovery_flushes", 0)

    mask = batch.verify_batch_cpu(pks, msgs, sigs)

    assert not mask[7] and mask.sum() == 63
    assert batch.LAST_FLUSH_DETAIL.get("recovery_flushes", 0) - detail0 == 1


# ---------------------------------------------------------------------------
# Byte-identity across every recovery arm.


def test_mask_byte_identical_across_all_arms(bisect_env, monkeypatch):
    """The same poisoned 93-row set recovers the IDENTICAL mask through
    single-chip bisect, streamed planner recovery, sharded-streamed
    recovery, host-RLC bisect, and the naive fallback."""
    _FlushWitness(monkeypatch)
    pks, msgs, sigs = _signed_rows(93)
    sigs = list(sigs)
    for i in (0, 31, 62, 92):  # chunk boundaries of the 31-row planner
        _flip(sigs, i)

    reference = batch.verify_batch_cpu(pks, msgs, sigs)
    assert reference.sum() == 89

    arms = {}
    arms["bisect"] = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert batch.LAST_JAX_PATH[0] == "rlc-bisect"

    batch.configure_planner(max_flush_lanes=64)  # 31 rows per chunk
    arms["streamed"] = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert batch.LAST_JAX_PATH[0] == "rlc-streamed-recovery"

    env = _fake_mesh_env(4)
    monkeypatch.setattr(batch, "_sharded_env", lambda: env)
    arms["sharded-streamed"] = batch._verify_batch_streamed(pks, msgs, sigs)
    monkeypatch.setattr(batch, "_sharded_env", lambda: None)
    batch.configure_planner(max_flush_lanes=1 << 16)

    monkeypatch.setenv("TMTPU_BISECT", "0")
    arms["naive-persig"] = batch.verify_batch(pks, msgs, sigs, backend="jax")
    arms["naive-host"] = batch.verify_batch_cpu(pks, msgs, sigs)

    for name, mask in arms.items():
        assert mask.tobytes() == reference.tobytes(), name
