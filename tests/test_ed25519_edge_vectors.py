""" "Taming the many EdDSAs"-style edge vectors pinning cofactorless mode to
the reference's documented acceptance set (advisor r5 medium,
crypto/keys.py:150).

Cofactorless mode delegates ENTIRELY to OpenSSL, asserting (comment-only,
until now) that OpenSSL's ref10-lineage acceptance set matches the
reference's golang.org/x/crypto on edge inputs: non-canonical A ACCEPTED,
non-canonical R REJECTED (by the R-encoding comparison), small-order A
accepted iff the equation holds exactly, s < L ENFORCED. These vectors make
an OpenSSL/`cryptography`-wheel drift on any of those decisions fail CI
instead of silently reintroducing the consensus-fork vector the mode exists
to close (a mixed fleet forking at the 2/3 boundary).

Vectors are constructed from the pure-Python ground truth
(crypto/ed25519_ref.py); the assertions run the production host verifier
(crypto/keys.Ed25519PubKey.verify), i.e. OpenSSL itself.
"""

import pytest

pytest.importorskip(
    "cryptography", reason="edge suite pins OpenSSL's acceptance set"
)

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.crypto import keys

# (0, -1): the canonical order-2 point; enc = (p-1) little-endian, sign 0.
T2 = (0, ref.P - 1, 1, 0)
T2_ENC = ref.point_compress(T2)
# The identity encoded NON-canonically: y-field = p+1 ≡ 1 (mod p), sign 0.
# Decodes (mod p) to (0, 1) = identity for verifiers that skip the
# canonical-y check (ref10/x/crypto); ours rejects it in cofactored mode.
IDENTITY_NONCANONICAL = (ref.P + 1).to_bytes(32, "little")


@pytest.fixture
def cofactorless():
    keys.set_verify_mode("cofactorless")
    yield
    keys.set_verify_mode("cofactored")


def _honest(seed: bytes = b"\x15" * 32, msg: bytes = b"edge-honest"):
    priv = keys.gen_ed25519(seed)
    return priv.pub_key().bytes(), msg, priv.sign(msg)


def _small_order_a_sig(want_accept: bool, msg: bytes = b"edge-small-order"):
    """Forged signature under the order-2 pubkey A = T2: R = [r]B, s = r, so
    [s]B - [h]A - R = -[h]T2 — the identity iff h is EVEN. Grind r until the
    challenge h = SHA512(R||A||M) mod L has the wanted parity: even => exact
    (cofactorless) verifiers ACCEPT, odd => they REJECT (while the cofactored
    predicate accepts either way, the defect being pure torsion)."""
    for r in range(1, 1000):
        r_enc = ref.point_compress(ref.point_mul(r, ref.BASE))
        h = ref.sha512_mod_l(r_enc + T2_ENC + msg)
        if (h % 2 == 0) == want_accept:
            return T2_ENC, msg, r_enc + r.to_bytes(32, "little")
    raise AssertionError("no grind hit in 1000 tries (p=1/2 each)")


def test_sanity_honest_accept_both_modes(cofactorless):
    pk, msg, sig = _honest()
    assert keys.Ed25519PubKey(pk).verify(msg, sig)
    keys.set_verify_mode("cofactored")
    assert keys.Ed25519PubKey(pk).verify(msg, sig)


def test_s_boundary_rejected_both_modes(cofactorless):
    """s' = s + L satisfies the verification equation mod L, so ONLY the
    s < L canonicality check rejects it — the exact drift this vector
    watches for (signature malleability => consensus fork)."""
    pk, msg, sig = _honest()
    s = int.from_bytes(sig[32:], "little")
    assert s + ref.L < 2**256
    malleated = sig[:32] + (s + ref.L).to_bytes(32, "little")
    assert not keys.Ed25519PubKey(pk).verify(msg, malleated)
    keys.set_verify_mode("cofactored")
    assert not keys.Ed25519PubKey(pk).verify(msg, malleated)
    # just below the boundary stays accepted (the check is s < L, not < L-1)
    keys.set_verify_mode("cofactorless")
    assert keys.Ed25519PubKey(pk).verify(msg, sig)


def test_small_order_a_accepted_when_equation_exact(cofactorless):
    """ref10/x/crypto do NOT low-order-check A: the forged sig verifies
    exactly (h even), so cofactorless ACCEPTS. An OpenSSL build that starts
    rejecting small-order A would diverge from reference peers."""
    pk, msg, sig = _small_order_a_sig(want_accept=True)
    assert keys.Ed25519PubKey(pk).verify(msg, sig)


def test_small_order_a_rejected_when_torsion_remains(cofactorless):
    """h odd leaves a live torsion component: cofactorless REJECTS it —
    while cofactored (our default) accepts, the documented divergence."""
    pk, msg, sig = _small_order_a_sig(want_accept=False)
    assert not keys.Ed25519PubKey(pk).verify(msg, sig)
    keys.set_verify_mode("cofactored")
    assert keys.Ed25519PubKey(pk).verify(msg, sig)


def test_non_canonical_a_accepted_cofactorless_only(cofactorless):
    """A encoded non-canonically (y-field = p+1 => identity): x/crypto and
    ref10 reduce y mod p and ACCEPT; our cofactored mode REJECTS at the
    canonical-encoding precheck (the documented deliberate divergence —
    non-canonical VALIDATOR keys are blocked at ingestion in both modes)."""
    msg = b"edge-noncanonical-A"
    r = 7
    r_enc = ref.point_compress(ref.point_mul(r, ref.BASE))
    # A = identity => [h]A vanishes; s = r closes the equation for any h
    sig = r_enc + r.to_bytes(32, "little")
    assert keys.Ed25519PubKey(IDENTITY_NONCANONICAL).verify(msg, sig)
    keys.set_verify_mode("cofactored")
    assert not keys.Ed25519PubKey(IDENTITY_NONCANONICAL).verify(msg, sig)
    # and the validator-ingestion gate refuses the encoding in ANY mode
    with pytest.raises(ValueError):
        keys.pubkey_from_type_and_bytes("ed25519", IDENTITY_NONCANONICAL)


def test_non_canonical_r_rejected_both_modes(cofactorless):
    """R encoded non-canonically: the point equation holds, but x/crypto
    compares the CANONICAL encoding of [s]B - [h]A against sig[:32] bytes,
    so it REJECTS; cofactored requires canonical R outright."""
    priv = keys.gen_ed25519(b"\x16" * 32)
    pk = priv.pub_key().bytes()
    msg = b"edge-noncanonical-R"
    a_scalar, _prefix = ref.secret_expand(b"\x16" * 32)
    r_enc = IDENTITY_NONCANONICAL  # R = identity, encoded with y = p+1
    h = ref.sha512_mod_l(r_enc + pk + msg)
    s = h * a_scalar % ref.L  # [s]B = [h]A + identity: equation holds
    sig = r_enc + s.to_bytes(32, "little")
    assert not keys.Ed25519PubKey(pk).verify(msg, sig)
    keys.set_verify_mode("cofactored")
    assert not keys.Ed25519PubKey(pk).verify(msg, sig)
    # control: the SAME construction with canonical R encoding is accepted
    keys.set_verify_mode("cofactorless")
    r_canonical = ref.point_compress(ref.IDENTITY)
    h2 = ref.sha512_mod_l(r_canonical + pk + msg)
    sig2 = r_canonical + (h2 * a_scalar % ref.L).to_bytes(32, "little")
    assert keys.Ed25519PubKey(pk).verify(msg, sig2)


def test_torsion_defect_r_agrees_with_suite():
    """The existing torsion-defect vector (tests/sigutil.py) folded into the
    suite: cofactored accepts, cofactorless rejects."""
    from tests.sigutil import torsion_defect_sig

    pk, msg, sig = torsion_defect_sig()
    assert keys.Ed25519PubKey(pk).verify(msg, sig)
    keys.set_verify_mode("cofactorless")
    try:
        assert not keys.Ed25519PubKey(pk).verify(msg, sig)
    finally:
        keys.set_verify_mode("cofactored")
