"""State sync reactor: serves snapshots to peers + drives the local syncer.

reference: statesync/reactor.go — channels (:18-20), Receive (:98), Sync
(:248), recentSnapshots (:73).
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor
from tendermint_tpu.state.sm_state import State
from tendermint_tpu.statesync.chunks import Chunk
from tendermint_tpu.statesync.messages import (
    CHUNK_CHANNEL,
    CHUNK_MSG_SIZE,
    SNAPSHOT_CHANNEL,
    SNAPSHOT_MSG_SIZE,
    ChunkRequest,
    ChunkResponse,
    SnapshotsRequest,
    SnapshotsResponse,
    decode_message,
    encode_message,
)
from tendermint_tpu.statesync.snapshots import Snapshot
from tendermint_tpu.statesync.syncer import Syncer
from tendermint_tpu.types.block import Commit

logger = logging.getLogger("tendermint_tpu.statesync")

RECENT_SNAPSHOTS = 10  # reference: statesync/reactor.go:73


class StatesyncReactor(Reactor):
    # concurrent load_snapshot_chunk calls served to peers: a mass-rejoin
    # storm queues behind this bound in executor threads instead of
    # monopolizing the event loop the consensus reactor shares
    SERVE_CONCURRENCY = 2

    def __init__(self, conn_snapshot, conn_query, active: bool = False, metrics=None,
                 checkpoint_path: Optional[str] = None):
        super().__init__("STATESYNC")
        self.conn_snapshot = conn_snapshot
        self.conn_query = conn_query
        self.active = active  # True = we are syncing; False = serve only
        self.metrics = metrics  # StateSyncMetrics or None
        self.checkpoint_path = checkpoint_path  # crash-resume file (node path)
        self.syncer: Optional[Syncer] = None
        # chaos hook (chaos/catchup.ServeFaults): serve corrupted chunks on
        # schedule so rejoin soaks exercise the syncing side's punish paths
        self.serve_faults = None
        self._serve_sem: Optional[asyncio.Semaphore] = None

    def get_channels(self) -> List[ChannelDescriptor]:
        # both channels SHEDDABLE (ISSUE 12): snapshot/chunk serving rides
        # the PR 5 per-peer recv token buckets, so a thousand rejoining
        # nodes hammering one serving validator shed pre-dispatch instead
        # of starving its vote path (consensus channels have no bucket)
        return [
            ChannelDescriptor(
                SNAPSHOT_CHANNEL, priority=5,
                send_queue_capacity=10, recv_message_capacity=SNAPSHOT_MSG_SIZE,
                sheddable=True,
            ),
            ChannelDescriptor(
                CHUNK_CHANNEL, priority=3,
                send_queue_capacity=4, recv_message_capacity=CHUNK_MSG_SIZE,
                sheddable=True,
            ),
        ]

    # ----------------------------------------------------------------- peers

    async def add_peer(self, peer) -> None:
        """Ask every new peer for its snapshots while syncing
        (reference: reactor.go:221 AddPeer)."""
        if self.active:
            await peer.send(SNAPSHOT_CHANNEL, encode_message(SnapshotsRequest()))

    async def remove_peer(self, peer, reason) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    # --------------------------------------------------------------- receive

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg = decode_message(msg_bytes)
        except Exception as e:
            await self.switch.stop_peer_for_error(peer, e)
            return

        if isinstance(msg, SnapshotsRequest):
            # serve our app's recent snapshots (reference: reactor.go:110)
            for s in self._recent_snapshots(RECENT_SNAPSHOTS):
                await peer.send(
                    SNAPSHOT_CHANNEL,
                    encode_message(
                        SnapshotsResponse(s.height, s.format, s.chunks, s.hash, s.metadata)
                    ),
                )
        elif isinstance(msg, SnapshotsResponse):
            if self.syncer is not None:
                try:
                    msg.validate_basic()
                except ValueError as e:
                    await self.switch.stop_peer_for_error(peer, e)
                    return
                self.syncer.add_snapshot(
                    peer.id,
                    Snapshot(msg.height, msg.format, msg.chunks, msg.hash, msg.metadata),
                )
        elif isinstance(msg, ChunkRequest):
            # load from the app (reference: reactor.go:151) — in an executor
            # behind a small semaphore: chunk loads can be multi-MB reads,
            # and a rejoin storm must never block the consensus event loop
            if self._serve_sem is None:
                self._serve_sem = asyncio.Semaphore(self.SERVE_CONCURRENCY)
            async with self._serve_sem:
                resp = await asyncio.get_running_loop().run_in_executor(
                    None,
                    self.conn_snapshot.load_snapshot_chunk,
                    abci.RequestLoadSnapshotChunk(msg.height, msg.format, msg.index),
                )
            chunk = resp.chunk
            if chunk and self.serve_faults is not None and self.serve_faults.take_chunk_corrupt():
                chunk = self.serve_faults.corrupt_chunk(chunk)
            await peer.send(
                CHUNK_CHANNEL,
                encode_message(
                    ChunkResponse(
                        msg.height, msg.format, msg.index,
                        chunk, missing=not chunk,
                    )
                ),
            )
        elif isinstance(msg, ChunkResponse):
            if self.syncer is not None and not msg.missing:
                # torn-chunk guard: an empty non-missing payload is a wire
                # tear, not a chunk — treat as missing so the fetcher's
                # timeout/retry ladder re-sources it
                if not msg.chunk:
                    return
                self.syncer.add_chunk(
                    Chunk(msg.height, msg.format, msg.index, msg.chunk, peer.id)
                )

    def _recent_snapshots(self, n: int) -> List[Snapshot]:
        resp = self.conn_snapshot.list_snapshots()
        snaps = sorted(
            resp.snapshots, key=lambda s: (-s.height, -s.format)
        )[:n]
        return [
            Snapshot(s.height, s.format, s.chunks, s.hash, s.metadata) for s in snaps
        ]

    # ------------------------------------------------------------------ sync

    async def sync(self, state_provider, discovery_time: float,
                   chunk_fetchers: int = 4, chunk_timeout: float = 120.0,
                   chunk_retries: int = 8, chunk_backoff: float = 0.25,
                   ) -> Tuple[State, Commit]:
        """Run the full state sync (reference: reactor.go:248 Sync)."""
        if self.syncer is not None:
            raise RuntimeError("a state sync is already in progress")
        from tendermint_tpu.statesync.checkpoint import RestoreCheckpoint

        self.syncer = Syncer(
            state_provider,
            self.conn_snapshot,
            self.conn_query,
            self._request_chunk,
            chunk_fetchers=chunk_fetchers,
            chunk_timeout=chunk_timeout,
            metrics=self.metrics,
            chunk_retries=chunk_retries,
            chunk_backoff=chunk_backoff,
            punish_peer=self._punish_peer,
            checkpoint=RestoreCheckpoint(self.checkpoint_path),
        )
        if self.metrics is not None:
            self.metrics.syncing.set(1)
        try:
            # ask everyone already connected (late peers hit add_peer)
            await self.switch.broadcast(
                SNAPSHOT_CHANNEL, encode_message(SnapshotsRequest())
            )
            return await self.syncer.sync_any(discovery_time)
        finally:
            self.syncer = None
            if self.metrics is not None:
                self.metrics.syncing.set(0)

    async def _request_chunk(self, peer_id: str, height: int, fmt: int, index: int) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            await peer.send(CHUNK_CHANNEL, encode_message(ChunkRequest(height, fmt, index)))

    async def _punish_peer(self, peer_id: str, reason: str) -> None:
        """Syncer punish hook: route misconduct (corrupt chunks, app-
        rejected senders) into the trust scorer — repeated offenses
        disconnect via the reporter's threshold, one bad chunk does not."""
        if self.switch is None or getattr(self.switch, "reporter", None) is None:
            return
        from tendermint_tpu.p2p.behaviour import BAD_MESSAGE, PeerBehaviour

        await self.switch.reporter.report(
            PeerBehaviour(peer_id, BAD_MESSAGE, reason)
        )
