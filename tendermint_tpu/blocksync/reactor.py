"""Fast-sync reactor (v0-shaped): download blocks from peers, verify commits
BATCHED on the TPU, apply, then hand off to consensus
(reference: blockchain/v0/reactor.go:104,116,207; channel 0x40 :19).

TPU-first design (ISSUE 12): catch-up runs as a THREE-STAGE PIPELINE —

  fetch  : BlockPool keeps a window of heights in flight across scored
           peers (blocksync/pool.py);
  verify : a contiguous run of up to VERIFY_BATCH_BLOCKS downloaded blocks
           has ALL its commit signatures verified as ONE cross-height
           super-batch through the verification scheduler's catch-up lane
           (blocks x validators on the trailing batch axis — the reference
           runs VerifyCommitLight serially per block);
  apply  : verified blocks drain through a bounded queue into ABCI replay.

The verify stage runs in an executor thread, so super-batch i+1 is being
verified on the device while the event loop replays run i — catch-up
throughput is max(verify, apply) instead of verify+apply.

Crash safety: the verified-but-unapplied window is persisted in a
CatchupCheckpoint (blocksync/checkpoint.py); a killed node re-enters the
pipeline at its last applied height and applies the checkpointed window
without re-fetching or re-verifying it.

Degradation: when the verify circuit breaker is OPEN the super-batch
shrinks to single-block runs (per-commit CPU verify via the breaker's
cpu route) and the sync continues instead of coupling 16 heights into one
failure domain."""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import List, Optional

from tendermint_tpu.blocksync.checkpoint import CatchupCheckpoint
from tendermint_tpu.blocksync.messages import (
    BlockRequest,
    BlockResponse,
    NoBlockResponse,
    StatusRequest,
    StatusResponse,
    decode_message,
    encode_message,
)
from tendermint_tpu.blocksync.pool import BlockPool
from tendermint_tpu.crypto.batch import verify_batch
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor
from tendermint_tpu.types.basic import BlockID

logger = logging.getLogger("tendermint_tpu.blocksync")

BLOCKSYNC_CHANNEL = 0x40
STATUS_UPDATE_INTERVAL = 2.0
SWITCH_TO_CONSENSUS_INTERVAL = 0.5
# Super-batch run cap. 16 until ISSUE 13: the cap existed to bound the ONE
# device flush a run produced (16 blocks x 10k validators already brushed
# the lane-bucket ceiling). The flush planner now bounds device memory at
# its chunk budget regardless of flush size (crypto/batch.py
# max_flush_lanes — the scheduler's catch-up lane also splits oversized
# flushes into planner chunks with a vote-preemption point between them),
# so the run length is free to grow: longer runs amortize per-flush prep
# and give the cross-height batch more rows to collapse per signer.
VERIFY_BATCH_BLOCKS = 64
# verified-but-unapplied blocks the pipeline may hold (backpressure bound:
# verify never runs more than ~2 super-batches ahead of apply)
PIPELINE_WINDOW = 2 * VERIFY_BATCH_BLOCKS


class BlocksyncReactor(Reactor):
    def __init__(self, state, block_exec, block_store, consensus_reactor=None,
                 active: bool = True, metrics=None,
                 peer_timeout: float = None, retry_sleep: float = None,
                 scheduler=None, checkpoint_path: Optional[str] = None):
        super().__init__("BLOCKSYNC")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.consensus_reactor = consensus_reactor
        self.active = active  # False = serve blocks only (we're not syncing)
        self.metrics = metrics  # BlockSyncMetrics or None
        # global verification scheduler (crypto/scheduler.py): catch-up
        # verification rides the CATCHUP lane — it soaks idle device
        # capacity and yields to votes/light/admission (paused entirely at
        # overload pressure level 2)
        self.scheduler = scheduler
        self.checkpoint = CatchupCheckpoint(checkpoint_path)
        # chaos hook (chaos/catchup.ServeFaults): when installed, the SERVING
        # side of this reactor misbehaves on schedule — stalls block
        # requests or serves commit-tampered blocks — so catch-up soaks can
        # exercise the syncing side's peer scoring and redo paths
        self.serve_faults = None
        # [fastsync] peer_timeout / retry_sleep (None = pool defaults)
        from tendermint_tpu.blocksync.pool import PEER_TIMEOUT, RETRY_SLEEP

        self.peer_timeout = PEER_TIMEOUT if peer_timeout is None else peer_timeout
        self.retry_sleep = RETRY_SLEEP if retry_sleep is None else retry_sleep
        self.pool: Optional[BlockPool] = None
        self._tasks: List[asyncio.Task] = []
        self.synced = asyncio.Event()
        self._started_at = 0.0
        # -- pipeline state --------------------------------------------------
        # verified triples (first, parts, second) awaiting apply, in height
        # order; _verified_event wakes the apply stage
        self._verified: deque = deque()
        self._verified_event = asyncio.Event()
        self._verify_cursor = 0  # next height the verify stage examines

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKSYNC_CHANNEL, priority=5, send_queue_capacity=1000)]

    async def start(self) -> None:
        if not self.active:
            return
        self._started_at = time.monotonic()
        if self.metrics is not None:
            self.metrics.syncing.set(1)
        self._resume_from_checkpoint()
        self.pool = BlockPool(
            self.state.last_block_height + 1, self._send_request, self._punish_peer,
            metrics=self.metrics,
            peer_timeout=self.peer_timeout, retry_sleep=self.retry_sleep,
        )
        self._verify_cursor = self.pool.height
        self._verified.clear()
        self._verified_event.clear()
        self.pool.start()
        self._tasks = [
            asyncio.create_task(self._verify_routine(), name="bcverify"),
            asyncio.create_task(self._apply_routine(), name="bcapply"),
            asyncio.create_task(self._status_routine(), name="bcstatus"),
        ]

    async def stop(self) -> None:
        if self.pool:
            self.pool.stop()
        for t in self._tasks:
            t.cancel()

    # -- checkpoint resume ---------------------------------------------------

    def _resume_from_checkpoint(self) -> None:
        """Apply the persisted verified-but-unapplied window (crash-mid-
        blocksync resume): the commits were already super-batch verified
        before the crash, so the blocks re-enter at the APPLY stage."""
        blocks = self.checkpoint.load(self.state.last_block_height)
        if len(blocks) < 2:
            return
        # anchor proof: the first resumed block must extend OUR chain
        if (
            self.state.last_block_height > 0
            and blocks[0].header.last_block_id.hash != self.state.last_block_id.hash
        ):
            logger.warning("catch-up checkpoint does not extend our chain; discarding")
            self.checkpoint.clear()
            return
        from tendermint_tpu.types.part_set import PartSet

        n = 0
        try:
            for first, second in zip(blocks, blocks[1:]):
                parts = PartSet.from_data(first.encode())
                self._apply(first, parts, second)
                n += 1
        except Exception:
            # a failure the linkage proof can't cover (app lost its
            # post-crash state, validate failure, app blip) must not
            # crash-loop node startup: discard the checkpoint and fall
            # through to normal re-fetch from wherever state stands now
            logger.exception(
                "checkpoint replay failed after %d blocks; discarding "
                "checkpoint and re-fetching", n,
            )
            self.checkpoint.clear()
        if n and self.metrics is not None:
            self.metrics.resume_events_total.inc()
            self.metrics.blocks_applied_total.inc(n)
        if n:
            logger.info(
                "resumed catch-up from checkpoint: %d verified blocks applied "
                "without re-verification (now at height %d)",
                n, self.state.last_block_height,
            )

    def _write_checkpoint(self) -> None:
        """Persist the current verified-but-unapplied window. Called at
        verify-run boundaries and when the window drains — atomic writes,
        so a crash at any point leaves either the old or the new file.
        The window entries carry their already-encoded bytes (computed for
        PartSet.from_data at fetch-drain time), so a rewrite never
        re-encodes the whole window."""
        if not self.checkpoint.enabled:
            return
        blocks = [t[3] for t in self._verified]
        if self._verified:
            blocks.append(self._verified[-1][2].encode())  # trailing commit carrier
        self.checkpoint.save(self.state.last_block_height, blocks)

    async def _send_request(self, peer_id: str, height: int) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            await peer.send(BLOCKSYNC_CHANNEL, encode_message(BlockRequest(height)))

    async def _punish_peer(self, peer_id: str, reason: str) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            await self.switch.stop_peer_for_error(peer, reason)

    # -- peers -------------------------------------------------------------

    async def add_peer(self, peer) -> None:
        await peer.send(
            BLOCKSYNC_CHANNEL,
            encode_message(StatusResponse(self.block_store.height, self.block_store.base)),
        )
        if self.active:
            await peer.send(BLOCKSYNC_CHANNEL, encode_message(StatusRequest()))

    async def remove_peer(self, peer, reason) -> None:
        if self.pool:
            self.pool.remove_peer(peer.id)

    # -- receive -----------------------------------------------------------

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg = decode_message(msg_bytes)
        except Exception as e:
            await self.switch.stop_peer_for_error(peer, e)
            return
        sf = self.serve_faults
        if isinstance(msg, BlockRequest):
            if sf is not None and sf.block_stalled():
                return  # chaos: a stalling peer swallows the request
            block = self.block_store.load_block(msg.height)
            if block is not None:
                if sf is not None and sf.take_block_lie():
                    block = sf.corrupt_block(block)
                await peer.send(BLOCKSYNC_CHANNEL, encode_message(BlockResponse(block)))
            else:
                await peer.send(BLOCKSYNC_CHANNEL, encode_message(NoBlockResponse(msg.height)))
        elif isinstance(msg, StatusRequest):
            await peer.send(
                BLOCKSYNC_CHANNEL,
                encode_message(StatusResponse(self.block_store.height, self.block_store.base)),
            )
        elif isinstance(msg, StatusResponse):
            if self.pool:
                self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, BlockResponse):
            if self.pool:
                self.pool.add_block(peer.id, msg.block)
        elif isinstance(msg, NoBlockResponse):
            logger.debug("peer %s has no block %d", peer.id[:10], msg.height)

    async def switch_to_blocksync(self, state) -> None:
        """Post-state-sync handoff: start syncing blocks from the restored
        height (reference: blockchain/v0/reactor.go:116 SwitchToFastSync)."""
        self.state = state
        self.active = True
        self._started_at = time.monotonic()
        await self.start()
        await self.switch.broadcast(BLOCKSYNC_CHANNEL, encode_message(StatusRequest()))

    # -- sync --------------------------------------------------------------

    async def _status_routine(self) -> None:
        try:
            while True:
                await self.switch.broadcast(BLOCKSYNC_CHANNEL, encode_message(StatusRequest()))
                if self.metrics is not None and self.pool is not None:
                    # per-peer score gauges REPLACED each pass: departed
                    # peers' series drop instead of exposing stale scores
                    self.metrics.peer_score.replace_series({
                        (pid[:10],): st["score"]
                        for pid, st in self.pool.peer_stats().items()
                    })
                await asyncio.sleep(STATUS_UPDATE_INTERVAL)
        except asyncio.CancelledError:
            pass

    def _verify_run_batched(self, run: List[tuple], degraded: bool = False) -> Optional[int]:
        """One device batch over all (first, parts, second) triples: first's
        commit is second.last_commit, checked against the CURRENT validator
        set (reference: VerifyCommitLight per block, blockchain/v0/reactor.go).
        Returns the index of the first failing triple, or None.

        Validator sets can change mid-run (H+2 rule); the caller only
        *punishes* when index 0 fails at the exact head of the applied chain
        — later failures may just mean the set changed, and those heights
        are re-verified as the head of the next run against the then-correct
        set."""
        pubkeys, msgs, sigs, key_types = [], [], [], []
        spans = []  # (start, count, powers, total_power, ok_struct)
        vals = self.state.validators
        for first, parts, second, _enc in run:
            commit = second.last_commit
            first_id = BlockID(first.hash(), parts.header)
            start = len(sigs)
            powers = []
            if len(commit.signatures) != vals.size():
                spans.append((start, 0, [], 1, False))
                continue
            idxs = []
            for idx, cs_sig in enumerate(commit.signatures):
                if not cs_sig.for_block():
                    continue
                val = vals.validators[idx]
                pubkeys.append(val.pub_key.bytes())
                idxs.append(idx)
                sigs.append(cs_sig.signature)
                key_types.append(val.pub_key.type_name())
                powers.append(val.voting_power)
            msgs.extend(commit.vote_sign_bytes_many(self.state.chain_id, idxs))
            ok_struct = commit.block_id == first_id and commit.height == first.header.height
            spans.append((start, len(sigs) - start, powers, vals.total_voting_power(), ok_struct))
        if not sigs:
            return 0 if run else None
        if self.metrics is not None:
            self.metrics.super_batch_rows.observe(len(sigs))
        # key_types: sr25519 validators' sigs must verify under sr25519 rules
        # (mirrors validator_set.py batched Verify*; liveness in mixed sets).
        if not degraded and self.scheduler is not None and not self.scheduler.closed:
            # catch-up lane: idle-soak scheduling + exact-mask recovery —
            # verdicts byte-identical to the direct call below
            mask = self.scheduler.verify_rows(
                "catchup", pubkeys, msgs, sigs, key_types
            )
        else:
            # breaker-open degrade: verify_batch routes straight to the CPU
            # path while the breaker is OPEN (crypto/batch cpu-breaker)
            mask = verify_batch(pubkeys, msgs, sigs, key_types=key_types)
        for i, (start, count, powers, total, ok_struct) in enumerate(spans):
            if not ok_struct:
                return i
            tallied = sum(p for ok, p in zip(mask[start : start + count], powers) if ok)
            if tallied * 3 <= total * 2:
                return i
        return None

    def _breaker_open(self) -> bool:
        try:
            from tendermint_tpu.crypto.batch import BREAKER

            return not BREAKER.allow_device()
        except Exception:
            return False

    async def _verify_routine(self) -> None:
        """Stage 2: drain contiguous downloaded runs and super-batch verify
        them off-loop, feeding the apply stage's bounded window."""
        from tendermint_tpu.types.part_set import PartSet

        while True:
            try:
                await asyncio.sleep(0.02)
                if self.synced.is_set():
                    return
                # backpressure: never verify more than PIPELINE_WINDOW ahead
                # of the apply stage
                room = PIPELINE_WINDOW - len(self._verified)
                if room <= 0:
                    continue
                # breaker OPEN => single-block runs: one corrupt height must
                # not force a 16-block refetch while the device is sick, and
                # the per-commit CPU verify keeps the sync moving
                degraded = self._breaker_open()
                cap = 1 if degraded else min(VERIFY_BATCH_BLOCKS, room)

                run = []
                h = self._verify_cursor
                while len(run) < cap:
                    first = self.pool.get_block(h)
                    second = self.pool.get_block(h + 1)
                    if first is None or second is None:
                        break
                    enc = first.encode()
                    run.append((first, PartSet.from_data(enc), second, enc))
                    h += 1
                if not run:
                    continue
                if degraded and self.metrics is not None:
                    self.metrics.degraded_runs_total.inc()

                # batched verification across blocks x validators (the TPU
                # showcase: one kernel launch for the whole run). Off-loop:
                # the catch-up lane may hold these rows for its idle-soak
                # window (or pause them under overload), and that wait must
                # park an executor thread, never the shared event loop —
                # which is also what overlaps this verify with the apply
                # stage's ABCI replay of the previous run
                _tv0 = time.perf_counter()
                bad = await asyncio.get_running_loop().run_in_executor(
                    None, self._verify_run_batched, run, degraded
                )
                if self.metrics is not None:
                    self.metrics.verify_seconds.observe(time.perf_counter() - _tv0)
                n_ok = len(run) if bad is None else bad
                for triple in run[:n_ok]:
                    self._verified.append(triple)
                    self._verify_cursor += 1
                if n_ok:
                    self._verified_event.set()
                    self._write_checkpoint()
                if bad == 0:
                    if self._verify_cursor == self.state.last_block_height + 1:
                        # failed against the verified-CURRENT valset: bad
                        # data. Punish both providers of the offending pair
                        # and refetch
                        bad_height = self._verify_cursor
                        for h2 in (bad_height, bad_height + 1):
                            peer_id = self.pool.redo_request(h2)
                            if peer_id:
                                await self._punish_peer(peer_id, "invalid block/commit")
                    else:
                        # applies are still draining — the valset for this
                        # height may change once they land; re-verify then
                        # instead of punishing on a stale set
                        await asyncio.sleep(0.05)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("verify stage iteration failed; retrying")
                await asyncio.sleep(0.5)

    async def _apply_routine(self) -> None:
        """Stage 3: drain verified blocks into ABCI replay + the block store,
        and run the caught-up handoff check
        (reference: blockchain/v0/reactor.go:207 poolRoutine's apply half)."""
        last_switch_check = 0.0
        while True:
            try:
                now = time.monotonic()
                if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                    last_switch_check = now
                    if not self._verified and self._caught_up():
                        await self._switch_to_consensus()
                        return
                if not self._verified:
                    self._verified_event.clear()
                    try:
                        await asyncio.wait_for(
                            self._verified_event.wait(), SWITCH_TO_CONSENSUS_INTERVAL
                        )
                    except asyncio.TimeoutError:
                        pass
                    continue
                # peek-apply-pop: a transient apply failure (app blip) must
                # leave the triple in the window so the retry below re-applies
                # it — popping first would drop the block and wedge the sync
                first, parts, second, _enc = self._verified[0]
                self._apply(first, parts, second)
                self._verified.popleft()
                self.pool.pop_request()
                if self.metrics is not None:
                    self.metrics.blocks_applied_total.inc()
                if not self._verified:
                    # window drained: record the advanced applied height so a
                    # crash right now resumes without any re-verification
                    self._write_checkpoint()
                # yield so the verify stage / receive loop interleave with a
                # long replay drain
                await asyncio.sleep(0)
            except asyncio.CancelledError:
                return
            except Exception:
                # transient failures (app hiccough, connection blip) must not
                # kill the sync: consensus never starts if this task dies
                logger.exception("apply stage iteration failed; retrying")
                await asyncio.sleep(0.5)

    def _apply(self, block, parts, second) -> None:
        block_id = BlockID(block.hash(), parts.header)
        # the commit FOR this block travels in the next block's last_commit
        # (reference: reactor.go SaveBlock(first, firstParts, second.LastCommit))
        self.block_store.save_block(block, parts, second.last_commit)
        # trust_last_commit: the block's signatures were verified in the
        # super-batch (or the checkpoint proves a pre-crash batch did);
        # skip the per-block re-verification inside ApplyBlock — UNLESS the
        # validator set drifted between verify and apply (H+2 rule landing
        # mid-pipeline), in which case ApplyBlock re-verifies against the
        # now-correct set
        trust = block.header.validators_hash == self.state.validators.hash()
        self.state = self.block_exec.apply_block(
            self.state, block_id, block, trust_last_commit=trust
        )

    def _caught_up(self) -> bool:
        if self.pool.num_peers() == 0 and time.monotonic() - self._started_at < 5.0:
            return False  # give peers a moment to report
        max_h = self.pool.max_peer_height()
        # within one block of the best-known head counts as caught up: the
        # pool can never apply the head itself (it needs head+1's LastCommit),
        # and on a live chain the head keeps moving — consensus catchup gossip
        # closes the final gap after the handoff (reference: v0 pool
        # IsCaughtUp + consensus reactor catchup).
        return self.pool.num_peers() > 0 and self.pool.height + 1 >= max_h

    async def _switch_to_consensus(self) -> None:
        logger.info("fast sync complete at height %d; switching to consensus", self.state.last_block_height)
        if self.metrics is not None:
            self.metrics.syncing.set(0)
        self.pool.stop()
        self.checkpoint.clear()
        for t in self._tasks:
            if t is not asyncio.current_task():
                t.cancel()  # stop the verify stage + periodic StatusRequests
        self.synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.cs.state = None  # force update_to_state
            self.consensus_reactor.cs._update_to_state(self.state)
            if self.state.last_block_height > 0:
                self.consensus_reactor.cs._reconstruct_last_commit(self.state)
            await self.consensus_reactor.switch_to_consensus(self.state)
