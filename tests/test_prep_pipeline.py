"""ISSUE 18 — prep-pipeline tests: the staged single-flush submit (hashing
on the prep pool, A-block upload early, sort hoisted), the in-budget
2-chunk pipelined stream, and the striped host-RLC path.

The invariants pinned here:
  - byte identity: staged == serial == CPU verdicts, bit for bit, across
    geometries and with precheck-rejected rows at stage boundaries;
  - a prep-pool hashing failure latches in the future and fails the flush
    LOUDLY (and the pool is still usable afterwards);
  - hot-path hash budget: a clean flush challenge-hashes every row AT MOST
    once (batch.HASH_ROWS_HASHED);
  - the pipelined path engages only inside its geometry guard, labels
    itself "rlc-pipelined", and records 2-chunk overlap telemetry;
  - the striped host-RLC path returns verdicts identical to the unstriped
    path, including exact recovery around a tampered row.

Device kernels are replaced with ed25519_ref host twins (identical math,
real curve points) — see tests/test_flush_planner.py.
"""

import numpy as np
import pytest

from tendermint_tpu import native
from tendermint_tpu.crypto import batch
from tendermint_tpu.crypto.keys import gen_ed25519
from test_flush_planner import _install_host_twins, _signed_rows

needs_native = pytest.mark.skipif(
    not native.available(), reason="native helper module unavailable"
)


@pytest.fixture
def prep_cfg():
    """Snapshot/restore the process-global prep-pipeline config."""
    prev = dict(batch._PREP_CFG)
    yield batch._PREP_CFG
    batch._PREP_CFG.clear()
    batch._PREP_CFG.update(prev)


@pytest.fixture
def small_rlc(monkeypatch, prep_cfg):
    """RLC_MIN=8 + a 64-lane planner bucket (31 rows/chunk), restored after."""
    monkeypatch.setattr(batch, "RLC_MIN", 8)
    prev = batch.planner_budget()
    batch.configure_planner(max_flush_lanes=64)
    yield 31
    batch.configure_planner(max_flush_lanes=prev)
    batch.set_device_fault_hook(None)


def _rows_with_rejects(n, seed=b"\x21"):
    """n signed rows with stage-boundary rejects mixed in: a non-canonical
    s (>= L, rejected at precheck BEFORE hashing), an invalid pubkey
    encoding (rejected at the A-cache fill boundary), and a tampered
    message (valid encodings; only the combined check can catch it)."""
    pks, msgs, sigs = _signed_rows(n, seed)
    pks, msgs, sigs = list(pks), list(msgs), list(sigs)
    expect = np.ones(n, dtype=bool)
    # row 1: s >= L — precheck reject, stage-1 boundary
    sigs[1] = sigs[1][:32] + b"\xff" * 32
    expect[1] = False
    # row 3: y >= p — invalid point encoding, A-fill boundary
    pks[3] = b"\xff" * 32
    expect[3] = False
    # row n-2: bitflipped message — combined-check failure, recovery path
    msgs[n - 2] = msgs[n - 2][:-1] + bytes([msgs[n - 2][-1] ^ 1])
    expect[n - 2] = False
    return pks, msgs, sigs, expect


# ---------------------------------------------------------------------------
# staged single-flush submit


@needs_native
@pytest.mark.parametrize("n", [9, 16, 31], ids=["tiny", "pow2", "bucket-edge"])
def test_staged_vs_serial_vs_cpu_byte_identical(small_rlc, monkeypatch,
                                                prep_cfg, n):
    """Staged submit == serial submit == CPU host path, bit for bit."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(n, b"\x22")
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)

    prep_cfg["stream"] = False  # isolate the staged single flush
    prep_cfg["staged"] = True
    staged = batch.verify_batch(pks, msgs, sigs, backend="jax")
    prep_cfg["staged"] = False
    serial = batch.verify_batch(pks, msgs, sigs, backend="jax")

    assert staged.tobytes() == serial.tobytes() == cpu.tobytes()
    assert staged.all()


@needs_native
def test_staged_precheck_rejected_rows_at_stage_boundaries(small_rlc,
                                                           monkeypatch,
                                                           prep_cfg):
    """Rows rejected at each stage boundary (pre-hash precheck, A-fill
    exclusion, combined-check recovery) produce verdicts identical to the
    serial path and the CPU referee."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs, expect = _rows_with_rejects(20)
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)

    prep_cfg["stream"] = False
    prep_cfg["staged"] = True
    staged = batch.verify_batch(pks, msgs, sigs, backend="jax")
    prep_cfg["staged"] = False
    serial = batch.verify_batch(pks, msgs, sigs, backend="jax")

    assert staged.tobytes() == serial.tobytes() == cpu.tobytes()
    assert staged.tobytes() == expect.tobytes()


@needs_native
def test_prep_pool_exception_fails_flush_loudly(small_rlc, monkeypatch,
                                                prep_cfg):
    """A hashing failure on the prep pool latches in the future, re-raises
    at .result() on the dispatch thread, and leaves the pool usable."""
    _install_host_twins(monkeypatch)
    prep_cfg["stream"] = False
    prep_cfg["staged"] = True
    pks, msgs, sigs = _signed_rows(12, b"\x23")

    real = native.ed25519_h_batch

    def boom(*a, **kw):
        raise RuntimeError("injected prep-pool hash failure")

    monkeypatch.setattr(native, "ed25519_h_batch", boom)
    with pytest.raises(RuntimeError, match="injected prep-pool hash"):
        batch._rlc_submit(pks, msgs, sigs)

    # the pool is not wedged: the very next staged flush succeeds
    monkeypatch.setattr(native, "ed25519_h_batch", real)
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.all()


@needs_native
def test_hash_budget_at_most_once_per_row(small_rlc, monkeypatch, prep_cfg):
    """Hot-path guard: a clean flush challenge-hashes each row EXACTLY once
    — on the staged single flush and on the pipelined 2-chunk stream."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(24, b"\x24")

    prep_cfg["stream"] = False
    prep_cfg["staged"] = True
    batch.HASH_ROWS_HASHED[0] = 0
    assert batch.verify_batch(pks, msgs, sigs, backend="jax").all()
    assert batch.HASH_ROWS_HASHED[0] == 24

    prep_cfg["stream"] = True
    prep_cfg["stream_floor"] = 16
    batch.HASH_ROWS_HASHED[0] = 0
    assert batch.verify_batch(pks, msgs, sigs, backend="jax").all()
    assert batch.LAST_JAX_PATH[0] == "rlc-pipelined"
    assert batch.HASH_ROWS_HASHED[0] == 24


# ---------------------------------------------------------------------------
# pipelined in-budget 2-chunk stream


def test_pipelined_byte_identical_and_telemetry(small_rlc, monkeypatch,
                                                prep_cfg):
    """Above the stream floor (and inside the planner budget) a single
    flush rides TWO asymmetric chunks, labels itself rlc-pipelined, and
    records chunks/prep_overlap_s/prep_stages — verdicts byte-identical to
    the unstriped serial flush and the CPU path."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs = _signed_rows(24, b"\x25")
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)

    prep_cfg["stream"] = True
    prep_cfg["stream_floor"] = 16
    piped = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert batch.LAST_JAX_PATH[0] == "rlc-pipelined"
    det = dict(batch.LAST_FLUSH_DETAIL)

    prep_cfg["stream"] = False
    single = batch.verify_batch(pks, msgs, sigs, backend="jax")

    assert piped.tobytes() == single.tobytes() == cpu.tobytes()
    assert piped.all()
    assert det.get("chunks") == 2
    assert det.get("prep_overlap_s") is not None
    assert isinstance(det.get("prep_stages"), dict)


def test_pipelined_geometry_guard_declines(small_rlc, monkeypatch, prep_cfg):
    """A tail chunk past the planner bucket makes _verify_batch_pipelined
    decline (return None) instead of compiling a new shape."""
    _install_host_twins(monkeypatch)
    # n=40: head = max(8, 5) = 8, tail = 32 > 31-row chunk bucket
    pks, msgs, sigs = _signed_rows(40, b"\x26")
    assert batch._verify_batch_pipelined(pks[:40], msgs[:40], sigs[:40]) is None


def test_pipelined_bad_row_exact_recovery(small_rlc, monkeypatch, prep_cfg):
    """A tampered row in a pipelined flush still resolves to the exact
    per-row mask (combined check fails -> per-signature ladder)."""
    _install_host_twins(monkeypatch)
    pks, msgs, sigs, expect = _rows_with_rejects(24, b"\x27")
    prep_cfg["stream"] = True
    prep_cfg["stream_floor"] = 16
    mask = batch.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.tobytes() == expect.tobytes()
    cpu = batch.verify_batch_cpu(pks, msgs, sigs)
    assert mask.tobytes() == cpu.tobytes()


# ---------------------------------------------------------------------------
# striped host RLC


def _tiled_rows(n, base, seed=b"\x28"):
    pks, msgs, sigs = _signed_rows(base, seed)
    reps = -(-n // base)
    return (
        (list(pks) * reps)[:n],
        (list(msgs) * reps)[:n],
        (list(sigs) * reps)[:n],
    )


def test_striped_host_rlc_parity_and_overlap(prep_cfg):
    """The striped host-RLC path (stream on, n >= floor) returns verdicts
    identical to the unstriped host path, and records the pipelined
    overlap telemetry (prep_overlap_s, prep_stages, chunks)."""
    n = 2100  # 1024-row stripe floor -> 3 stripes
    pks, msgs, sigs = _tiled_rows(n, 128)

    prep_cfg["stream"] = True
    prep_cfg["stream_floor"] = 512
    prep_cfg["host_stripe"] = True  # force: "auto" is off on 1-core hosts
    striped = batch.verify_batch_cpu(pks, msgs, sigs)
    det = dict(batch.LAST_FLUSH_DETAIL)

    prep_cfg["stream"] = False
    serial = batch.verify_batch_cpu(pks, msgs, sigs)

    assert striped.tobytes() == serial.tobytes()
    assert striped.all()
    assert det.get("chunks") == 3
    assert det.get("prep_overlap_s") is not None
    assert isinstance(det.get("prep_stages"), dict)
    assert det.get("prep_s") is not None


def test_striped_host_rlc_bad_row_exact(prep_cfg):
    """A tampered row inside one stripe recovers the exact serial mask."""
    n = 1100  # 2 stripes (1024 + 76)
    pks, msgs, sigs = _tiled_rows(n, 64, b"\x29")
    msgs[1050] = msgs[1050][:-1] + bytes([msgs[1050][-1] ^ 1])

    prep_cfg["stream"] = True
    prep_cfg["stream_floor"] = 512
    prep_cfg["host_stripe"] = True
    striped = batch.verify_batch_cpu(pks, msgs, sigs)
    prep_cfg["stream"] = False
    serial = batch.verify_batch_cpu(pks, msgs, sigs)

    assert striped.tobytes() == serial.tobytes()
    assert not striped[1050]
    assert striped.sum() == n - 1


# ---------------------------------------------------------------------------
# native prep pool config


@needs_native
def test_prep_pool_configure_roundtrip():
    """configure_prep(prep_threads=...) resizes the native worker pool;
    0/None restores the host default min(cores, 8)."""
    import os

    default = min(8, os.cpu_count() or 1)
    try:
        batch.configure_prep(prep_threads=2)
        assert native.prep_pool_size() == 2
        batch.configure_prep(prep_threads=3)
        assert native.prep_pool_size() == 3
    finally:
        batch.configure_prep(prep_threads=0)
    assert native.prep_pool_size() == default


def test_config_plumbing_defaults():
    """CryptoConfig carries the ISSUE 18 knobs with production defaults."""
    from tendermint_tpu.config.config import CryptoConfig

    c = CryptoConfig()
    assert c.prep_threads == 0
    assert c.prep_staged is True
    assert c.prep_stream is True
    assert c.prep_stream_floor == 2048
    assert c.prep_host_stripe == "auto"
    assert c.verified_memo_rows == 65536
