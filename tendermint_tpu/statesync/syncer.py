"""Syncer: restores state machine snapshots via ABCI + verifies via light client.

reference: statesync/syncer.go — syncer (:38), AddSnapshot (:78), SyncAny
(:130), Sync (:217), offerSnapshot (:276), applyChunks (:312), fetchChunks
(:369), requestChunk (:402), verifyApp (:423).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional, Tuple

from tendermint_tpu.abci import types as abci
from tendermint_tpu.state.sm_state import State
from tendermint_tpu.statesync.chunks import Chunk, ChunkQueue, ChunkQueueClosed
from tendermint_tpu.statesync.snapshots import Snapshot, SnapshotPool
from tendermint_tpu.statesync.stateprovider import StateProvider
from tendermint_tpu.types.block import Commit

logger = logging.getLogger("tendermint_tpu.statesync")

# reference: statesync/syncer.go:21-35. CHUNK_TIMEOUT is only the
# no-config default: the node path passes [statesync] chunk_request_timeout
# through StatesyncReactor.sync (node/node.py _run_state_sync).
CHUNK_TIMEOUT = 2 * 60.0
MIN_SNAPSHOT_PEERS = 1


class SyncError(Exception):
    pass


class ErrNoSnapshots(SyncError):
    """reference: statesync/syncer.go errNoSnapshots."""


class ErrAbort(SyncError):
    """App returned ABORT (reference: errAbort)."""


class ErrRejectSnapshot(SyncError):
    pass


class ErrRejectFormat(SyncError):
    pass


class ErrRejectSender(SyncError):
    pass


class ErrVerifyFailed(SyncError):
    """App hash or height mismatch after restore (reference: errVerifyFailed)."""


class Syncer:
    """reference: statesync/syncer.go:38.

    request_chunk(peer_id, height, format, index) is an async callback into
    the reactor; conn_snapshot/conn_query are ABCI clients (snapshot + query
    connections of the 4-conn proxy)."""

    def __init__(
        self,
        state_provider: StateProvider,
        conn_snapshot,
        conn_query,
        request_chunk: Callable,
        chunk_fetchers: int = 4,
        chunk_timeout: float = CHUNK_TIMEOUT,
        metrics=None,
    ):
        self.state_provider = state_provider
        self.conn_snapshot = conn_snapshot
        self.conn_query = conn_query
        self.request_chunk = request_chunk
        self.chunk_fetchers = chunk_fetchers
        self.chunk_timeout = chunk_timeout
        self.metrics = metrics  # StateSyncMetrics or None
        self.snapshots = SnapshotPool()
        self.chunk_queue: Optional[ChunkQueue] = None
        self._processing: Optional[Snapshot] = None

    # ---------------------------------------------------------------- intake

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        """reference: syncer.go:78 AddSnapshot."""
        added = self.snapshots.add(peer_id, snapshot)
        if added:
            if self.metrics is not None:
                self.metrics.snapshots_discovered_total.inc()
            logger.info(
                "discovered snapshot height=%d format=%d chunks=%d from %s",
                snapshot.height, snapshot.format, snapshot.chunks, peer_id[:10],
            )
        return added

    def add_chunk(self, chunk: Chunk) -> bool:
        """reference: syncer.go:110 AddChunk."""
        q = self.chunk_queue
        if q is None or self._processing is None:
            return False
        if chunk.height != self._processing.height or chunk.format != self._processing.format:
            return False
        return q.add(chunk)

    def remove_peer(self, peer_id: str) -> None:
        self.snapshots.remove_peer(peer_id)

    # ------------------------------------------------------------------ sync

    async def sync_any(self, discovery_time: float) -> Tuple[State, Commit]:
        """Try snapshots best-first until one restores
        (reference: syncer.go:130 SyncAny)."""
        if discovery_time > 0:
            logger.info("discovering snapshots for %.1fs", discovery_time)
            await asyncio.sleep(discovery_time)
        while True:
            snapshot = self.snapshots.best()
            if snapshot is None:
                raise ErrNoSnapshots("no viable snapshots found")
            try:
                return await self.sync(snapshot)
            except ErrRejectSnapshot:
                logger.info("snapshot height=%d rejected; trying next", snapshot.height)
                self.snapshots.reject(snapshot)
            except ErrRejectFormat:
                logger.info("snapshot format %d rejected; trying next", snapshot.format)
                self.snapshots.reject_format(snapshot.format)
            except ErrRejectSender:
                logger.info("snapshot senders rejected; trying next")
                for peer_id in self.snapshots.get_peers(snapshot):
                    self.snapshots.reject_peer(peer_id)
                self.snapshots.reject(snapshot)
            except ErrVerifyFailed:
                logger.warning("snapshot height=%d failed verification; trying next", snapshot.height)
                self.snapshots.reject(snapshot)
            finally:
                if self.chunk_queue is not None:
                    self.chunk_queue.close()
                self.chunk_queue = None
                self._processing = None

    async def sync(self, snapshot: Snapshot) -> Tuple[State, Commit]:
        """Restore one snapshot (reference: syncer.go:217 Sync)."""
        # fetch the trusted app hash BEFORE offering (reference: :226)
        app_hash = await self.state_provider.app_hash(snapshot.height)
        snapshot = Snapshot(
            snapshot.height, snapshot.format, snapshot.chunks,
            snapshot.hash, snapshot.metadata, trusted_app_hash=app_hash,
        )
        self._processing = snapshot
        self.chunk_queue = ChunkQueue(snapshot)
        if self.metrics is not None:
            self.metrics.snapshot_height.set(snapshot.height)
            self.metrics.snapshot_chunks_total.set(snapshot.chunks)

        await self._offer_snapshot(snapshot)

        fetchers = [
            asyncio.create_task(self._fetch_chunks(), name=f"ss-fetch-{i}")
            for i in range(self.chunk_fetchers)
        ]
        # concurrently: build verified state via light client + apply chunks;
        # gather surfaces the FIRST failure immediately so a dead light
        # client aborts the sync instead of waiting out slow chunk peers
        state_task = asyncio.create_task(self.state_provider.state(snapshot.height))
        commit_task = asyncio.create_task(self.state_provider.commit(snapshot.height))
        apply_task = asyncio.create_task(self._apply_chunks(self.chunk_queue))
        try:
            _, state, commit = await asyncio.gather(apply_task, state_task, commit_task)
        except BaseException:
            for t in (apply_task, state_task, commit_task):
                t.cancel()
            raise
        finally:
            for f in fetchers:
                f.cancel()

        await self._verify_app(snapshot, state)
        logger.info("snapshot restored at height %d", snapshot.height)
        return state, commit

    async def _offer_snapshot(self, snapshot: Snapshot) -> None:
        """reference: syncer.go:276 offerSnapshot."""
        resp = self.conn_snapshot.offer_snapshot(
            abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(
                    height=snapshot.height,
                    format=snapshot.format,
                    chunks=snapshot.chunks,
                    hash=snapshot.hash,
                    metadata=snapshot.metadata,
                ),
                app_hash=snapshot.trusted_app_hash,
            )
        )
        r = resp.result
        if r == abci.OFFER_SNAPSHOT_ACCEPT:
            logger.info("snapshot height=%d format=%d accepted", snapshot.height, snapshot.format)
        elif r == abci.OFFER_SNAPSHOT_ABORT:
            raise ErrAbort("app aborted state sync")
        elif r == abci.OFFER_SNAPSHOT_REJECT:
            raise ErrRejectSnapshot("app rejected snapshot")
        elif r == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            raise ErrRejectFormat("app rejected format")
        elif r == abci.OFFER_SNAPSHOT_REJECT_SENDER:
            raise ErrRejectSender("app rejected senders")
        else:
            raise SyncError(f"unknown OfferSnapshot result {r}")

    async def _fetch_chunks(self) -> None:
        """One fetcher worker (reference: syncer.go:369 fetchChunks)."""
        import random

        q = self.chunk_queue
        snapshot = self._processing
        try:
            while True:
                index = q.allocate()
                if index is None:
                    if q.done():
                        return
                    await asyncio.sleep(0.05)
                    continue
                peers = self.snapshots.get_peers(snapshot)
                if peers:
                    # random peer per request so a silent-but-connected peer
                    # can't pin a chunk forever (reference: syncer.go:402)
                    peer_id = random.choice(peers)
                    await self.request_chunk(peer_id, snapshot.height, snapshot.format, index)
                # wait for it to arrive; retry on timeout (reference: :390)
                deadline = asyncio.get_event_loop().time() + self.chunk_timeout
                while not q.has(index) and index not in q._returned:
                    if asyncio.get_event_loop().time() > deadline:
                        q.retry(index)
                        break
                    await asyncio.sleep(0.05)
        except (asyncio.CancelledError, ChunkQueueClosed):
            pass

    async def _apply_chunks(self, q: ChunkQueue) -> None:
        """reference: syncer.go:312 applyChunks."""
        while True:
            chunk = await q.next()
            resp = self.conn_snapshot.apply_snapshot_chunk(
                abci.RequestApplySnapshotChunk(
                    index=chunk.index, chunk=chunk.chunk, sender=chunk.sender
                )
            )
            # punishment lists apply regardless of result (reference: :330)
            for peer_id in resp.reject_senders:
                self.snapshots.reject_peer(peer_id)
                q.discard_sender(peer_id)
            for index in resp.refetch_chunks:
                q.retry(index)

            r = resp.result
            if r == abci.APPLY_SNAPSHOT_CHUNK_ACCEPT:
                if self.metrics is not None:
                    self.metrics.chunks_applied_total.inc()
                if q.done():
                    return
            elif r == abci.APPLY_SNAPSHOT_CHUNK_ABORT:
                raise ErrAbort("app aborted chunk apply")
            elif r == abci.APPLY_SNAPSHOT_CHUNK_RETRY:
                q.retry(chunk.index)
            elif r == abci.APPLY_SNAPSHOT_CHUNK_RETRY_SNAPSHOT:
                q.retry_all()
            elif r == abci.APPLY_SNAPSHOT_CHUNK_REJECT_SNAPSHOT:
                raise ErrRejectSnapshot("app rejected snapshot during chunk apply")
            else:
                raise SyncError(f"unknown ApplySnapshotChunk result {r}")

    async def _verify_app(self, snapshot: Snapshot, state: State) -> None:
        """The app must now report the trusted hash/height
        (reference: syncer.go:423 verifyApp)."""
        resp = self.conn_query.info(abci.RequestInfo())
        if resp.last_block_app_hash != snapshot.trusted_app_hash:
            raise ErrVerifyFailed(
                f"app hash mismatch: expected {snapshot.trusted_app_hash.hex()}, "
                f"got {resp.last_block_app_hash.hex()}"
            )
        if resp.last_block_height != snapshot.height:
            raise ErrVerifyFailed(
                f"app height mismatch: expected {snapshot.height}, "
                f"got {resp.last_block_height}"
            )
