"""Block sync wire messages (reference: blockchain/v0/reactor.go +
proto/tendermint/blockchain). Envelope: oneof field per variant."""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types.block import Block


@dataclass(frozen=True)
class BlockRequest:
    height: int

    FIELD = 1

    def encode_body(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "BlockRequest":
        height = 0
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
        return cls(height)


@dataclass(frozen=True)
class NoBlockResponse:
    height: int

    FIELD = 2

    def encode_body(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "NoBlockResponse":
        height = 0
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
        return cls(height)


@dataclass(frozen=True)
class BlockResponse:
    block: Block

    FIELD = 3

    def encode_body(self) -> bytes:
        return self.block.encode()

    @classmethod
    def decode_body(cls, data: bytes) -> "BlockResponse":
        return cls(Block.decode(data))


@dataclass(frozen=True)
class StatusRequest:
    FIELD = 4

    def encode_body(self) -> bytes:
        return b""

    @classmethod
    def decode_body(cls, data: bytes) -> "StatusRequest":
        return cls()


@dataclass(frozen=True)
class StatusResponse:
    height: int
    base: int

    FIELD = 5

    def encode_body(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, self.height)
        w.varint_field(2, self.base)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "StatusResponse":
        height = base = 0
        for f, _, v in pw.Reader(data):
            if f == 1:
                height = pw.int64_from_varint(v)
            elif f == 2:
                base = pw.int64_from_varint(v)
        return cls(height, base)


_TYPES = {c.FIELD: c for c in (BlockRequest, NoBlockResponse, BlockResponse, StatusRequest, StatusResponse)}


def encode_message(msg) -> bytes:
    w = pw.Writer()
    w.message_field(msg.FIELD, msg.encode_body(), always=True)
    return w.bytes()


def decode_message(data: bytes):
    for f, _, v in pw.Reader(data):
        cls = _TYPES.get(f)
        if cls is not None:
            return cls.decode_body(v)
    raise ValueError("unknown blocksync message")
