"""Light-client-backed state provider: conjures a trusted sm.State + commit
at the snapshot height without replay.

reference: statesync/stateprovider.go — StateProvider iface (:27),
lightClientStateProvider (:46), AppHash (:86), Commit (:102), State (:112).
"""

from __future__ import annotations

from typing import List, Optional

from tendermint_tpu.light import Client, HTTPProvider, LightStore, TrustOptions
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.state.sm_state import State
from tendermint_tpu.types.basic import NANOS
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.params import (
    BlockParams,
    ConsensusParams,
    EvidenceParams,
)


class StateProvider:
    """reference: statesync/stateprovider.go:27."""

    async def app_hash(self, height: int) -> bytes:
        raise NotImplementedError

    async def commit(self, height: int) -> Commit:
        raise NotImplementedError

    async def state(self, height: int) -> State:
        raise NotImplementedError


class LightClientStateProvider(StateProvider):
    """Verifies everything through a light client over 2+ RPC endpoints
    (reference: statesync/stateprovider.go:46 NewLightClientStateProvider).

    rpc_clients: objects with async commit/validators/consensus_params/genesis
    methods (HTTPClient or LocalClient); the first is the light primary, the
    rest are witnesses."""

    def __init__(
        self,
        chain_id: str,
        rpc_clients: List,
        trust_height: int,
        trust_hash: bytes,
        trust_period_ns: int,
    ):
        if not rpc_clients:
            raise ValueError("at least one RPC server is required")
        self.chain_id = chain_id
        self.rpc_clients = rpc_clients
        providers = [HTTPProvider(chain_id, c) for c in rpc_clients]
        self.light = Client(
            chain_id,
            TrustOptions(trust_period_ns, trust_height, trust_hash),
            providers[0],
            providers[1:],
            LightStore(MemDB()),
        )
        self._initialized = False

    async def _ensure(self) -> None:
        if not self._initialized:
            await self.light.initialize()
            self._initialized = True

    async def app_hash(self, height: int) -> bytes:
        """AppHash at height H lives in header H+1
        (reference: stateprovider.go:86)."""
        await self._ensure()
        lb = await self.light.verify_light_block_at_height(height + 1)
        return lb.header.app_hash

    async def commit(self, height: int) -> Commit:
        """reference: stateprovider.go:102."""
        await self._ensure()
        lb = await self.light.verify_light_block_at_height(height)
        return lb.signed_header.commit

    async def state(self, height: int) -> State:
        """Build the post-snapshot state from three consecutive verified
        light blocks (reference: stateprovider.go:112)."""
        await self._ensure()
        last = await self.light.verify_light_block_at_height(height)
        cur = await self.light.verify_light_block_at_height(height + 1)
        nxt = await self.light.verify_light_block_at_height(height + 2)

        params = await self._consensus_params(height + 1)
        return State(
            chain_id=self.chain_id,
            initial_height=await self._initial_height(),
            last_block_height=last.height,
            last_block_id=last.signed_header.commit.block_id,
            last_block_time_ns=last.time_ns,
            last_validators=last.validator_set,
            validators=cur.validator_set,
            next_validators=nxt.validator_set,
            last_height_validators_changed=nxt.height,
            consensus_params=params,
            last_height_consensus_params_changed=cur.height,
            last_results_hash=cur.header.last_results_hash,
            app_hash=cur.header.app_hash,
        )

    async def _initial_height(self) -> int:
        for client in self.rpc_clients:
            try:
                resp = await client.genesis()
                return int(resp["genesis"].get("initial_height", 1))
            except Exception:
                continue
        return 1

    async def _consensus_params(self, height: int) -> ConsensusParams:
        last_err: Optional[Exception] = None
        for client in self.rpc_clients:
            try:
                resp = await client.consensus_params(height=height)
                cp = resp["consensus_params"]
                return ConsensusParams(
                    block=BlockParams(
                        max_bytes=int(cp["block"]["max_bytes"]),
                        max_gas=int(cp["block"]["max_gas"]),
                    ),
                    evidence=EvidenceParams(
                        max_age_num_blocks=int(cp["evidence"]["max_age_num_blocks"]),
                        max_age_duration_ns=int(cp["evidence"]["max_age_duration"]),
                    ),
                )
            except Exception as e:  # try the next endpoint
                last_err = e
        raise RuntimeError(f"failed to fetch consensus params: {last_err}")
