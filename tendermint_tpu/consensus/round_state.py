"""RoundState, RoundStepType and HeightVoteSet
(reference: consensus/types/round_state.go:16-67, height_vote_set.go:41)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tendermint_tpu.types.basic import BlockID, SignedMsgType
from tendermint_tpu.types.block import Block, Commit
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import VoteSet


class RoundStepType(enum.IntEnum):
    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


class HeightVoteSet:
    """All rounds' prevotes+precommits for one height; tracks peer-claimed
    majorities to spawn catch-up vote sets
    (reference: consensus/types/height_vote_set.go:41,117,185)."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet, defer_verification: bool = False):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self.defer_verification = defer_verification
        self._round_vote_sets: Dict[int, Tuple[VoteSet, VoteSet]] = {}
        self._peer_catchup_rounds: Dict[str, List[int]] = {}
        self.round = 0
        self.set_round(0)

    def set_round(self, round_: int) -> None:
        """Track round and round+1 (to allow round-skipping)."""
        new_round = self.round - 1 if self.round > 0 else 0
        del new_round
        for r in range(self.round, round_ + 2):
            if r not in self._round_vote_sets:
                self._add_round(r)
        self.round = round_

    def _add_round(self, round_: int) -> None:
        prevotes = VoteSet(
            self.chain_id, self.height, round_, SignedMsgType.PREVOTE, self.val_set,
            defer_verification=self.defer_verification,
        )
        precommits = VoteSet(
            self.chain_id, self.height, round_, SignedMsgType.PRECOMMIT, self.val_set,
            defer_verification=self.defer_verification,
        )
        self._round_vote_sets[round_] = (prevotes, precommits)

    def _get_vote_set(self, round_: int, type_: SignedMsgType) -> Optional[VoteSet]:
        entry = self._round_vote_sets.get(round_)
        if entry is None:
            return None
        return entry[0] if type_ == SignedMsgType.PREVOTE else entry[1]

    def has_pending(self) -> bool:
        """True if any round's vote set has deferred (unverified) votes."""
        return any(
            vs.pending_count() > 0
            for pair in self._round_vote_sets.values()
            for vs in pair
        )

    def flush_all(self):
        """Flush every round vote set with deferred votes in one pass.

        Returns [(type, round, committed_votes, failed_indices)] for each
        set that had pending votes — the caller publishes the committed
        votes (they were NOT published at enqueue time), re-runs the 2/3
        progress checks for those (type, round) pairs, and drains conflicts
        via drain_conflicts().
        """
        out = []
        for round_, (prevotes, precommits) in sorted(self._round_vote_sets.items()):
            for vs in (prevotes, precommits):
                if vs.pending_count() > 0:
                    committed, failed = vs.flush()
                    out.append((vs.signed_msg_type, round_, committed, failed))
        return out

    def drain_conflicts(self):
        """Collect equivocation conflicts discovered by deferred flushes."""
        out = []
        for prevotes, precommits in self._round_vote_sets.values():
            out.extend(prevotes.pop_conflicts())
            out.extend(precommits.pop_conflicts())
        return out

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        return self._get_vote_set(round_, SignedMsgType.PREVOTE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        return self._get_vote_set(round_, SignedMsgType.PRECOMMIT)

    def add_vote(self, vote: Vote, peer_id: str = "") -> bool:
        """(reference: height_vote_set.go:117 AddVote)"""
        if vote.type not in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
            raise ValueError(f"unexpected vote type {vote.type}")
        vote_set = self._get_vote_set(vote.round, vote.type)
        if vote_set is None:
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rounds) < 2:
                self._add_round(vote.round)
                vote_set = self._get_vote_set(vote.round, vote.type)
                rounds.append(vote.round)
            else:
                raise ValueError("peer has sent a vote that does not match our round for more than one round")
        return vote_set.add_vote(vote, peer_id)

    def pol_info(self) -> Tuple[int, Optional[BlockID]]:
        """Highest round with a prevote 2/3 majority (reference:
        height_vote_set.go POLInfo)."""
        # Only rounds <= self.round: a majority recorded in a peer-catchup
        # round above ours must not be reported as the POL (reference:
        # height_vote_set.go POLInfo scans hvs.round down to 0).
        for r in sorted((r for r in self._round_vote_sets if r <= self.round), reverse=True):
            vs = self.prevotes(r)
            if vs is not None:
                bid = vs.two_thirds_majority()
                if bid is not None:
                    return r, bid
        return -1, None

    def set_peer_maj23(self, round_: int, type_: SignedMsgType, peer_id: str, block_id: BlockID) -> None:
        if round_ not in self._round_vote_sets:
            self._add_round(round_)
        vs = self._get_vote_set(round_, type_)
        vs.set_peer_maj23(peer_id, block_id)


@dataclass
class RoundState:
    """(reference: consensus/types/round_state.go:67)"""

    height: int = 0
    round: int = 0
    step: RoundStepType = RoundStepType.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0
    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None
    votes: Optional[HeightVoteSet] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def round_state_summary(self) -> dict:
        return {
            "height": self.height,
            "round": self.round,
            "step": self.step.name,
            "proposal": self.proposal is not None,
            "proposal_block": self.proposal_block.hash().hex() if self.proposal_block else None,
            "locked_round": self.locked_round,
            "locked_block": self.locked_block.hash().hex() if self.locked_block else None,
            "valid_round": self.valid_round,
        }
