from tendermint_tpu.types.basic import (  # noqa: F401
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
    ZERO_BLOCK_ID,
)
from tendermint_tpu.types.block import (  # noqa: F401
    Block,
    Commit,
    CommitSig,
    ConsensusVersion,
    EMPTY_COMMIT,
    Header,
    txs_hash,
)
from tendermint_tpu.types.evidence import DuplicateVoteEvidence, decode_evidence  # noqa: F401
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator  # noqa: F401
from tendermint_tpu.types.params import ConsensusParams, DEFAULT_CONSENSUS_PARAMS  # noqa: F401
from tendermint_tpu.types.part_set import Part, PartSet  # noqa: F401
from tendermint_tpu.types.proposal import Proposal  # noqa: F401
from tendermint_tpu.types.validator_set import (  # noqa: F401
    CommitVerifyError,
    NotEnoughVotingPowerError,
    Validator,
    ValidatorSet,
)
from tendermint_tpu.types.vote import Vote  # noqa: F401
from tendermint_tpu.types.vote_set import ConflictingVotesError, VoteSet, VoteSetError  # noqa: F401
