"""Vote encode/sign-bytes memoization (types/vote.py).

A Vote is immutable post-construction, so its protowire encoding and
canonical sign-bytes can be computed at most once per instance no matter how
many ingest layers serialize it (WAL frame, gossip re-send, verify). The
instrumented counters ENCODE_COMPUTES / SIGN_BYTES_COMPUTES count actual
cache misses; these tests pin (a) at-most-once per ingest path and (b) that
a derived ("mutated") Vote NEVER serves the original's stale cache.
"""

import dataclasses
import time

from tendermint_tpu.consensus.messages import VoteMessage, encode_message
from tendermint_tpu.consensus.wal import WAL, MsgInfo
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.types import canonical
from tendermint_tpu.types import vote as vote_mod
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.vote import Vote

BID = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))


def make_vote(**overrides) -> Vote:
    kw = dict(
        type=SignedMsgType.PREVOTE,
        height=7,
        round=0,
        block_id=BID,
        timestamp_ns=1_700_000_000_000_000_000,
        validator_address=b"\x0a" * 20,
        validator_index=3,
        signature=b"\x55" * 64,
    )
    kw.update(overrides)
    return Vote(**kw)


def test_encode_computed_at_most_once_per_ingest_path(tmp_path):
    """The live ingest path serializes one gossiped vote for the WAL frame
    and again for each gossip re-send: the protowire encoder must run ONCE."""
    vote = Vote.decode(make_vote().encode())  # arrives off the wire
    before = vote_mod.ENCODE_COMPUTES
    wal = WAL(str(tmp_path / "wal"), group_commit=True)
    wal.write(MsgInfo(VoteMessage(vote), "peer-1"))        # WAL frame
    gossip_1 = encode_message(VoteMessage(vote))           # re-send to peer A
    gossip_2 = encode_message(VoteMessage(vote))           # re-send to peer B
    wal.flush_buffered()
    wal.close()
    assert vote_mod.ENCODE_COMPUTES - before == 1
    assert gossip_1 == gossip_2
    # and the WAL replay round-trips the identical vote
    got = [m for m in WAL(str(tmp_path / "wal")).iter_messages() if isinstance(m, MsgInfo)]
    assert got[0].msg.vote == vote


def test_memoized_encode_is_byte_identical_to_fresh_instance():
    v = make_vote()
    first = v.encode()
    assert v.encode() is first  # cache hit returns the same object
    fresh = dataclasses.replace(v)  # new instance, empty cache
    assert fresh.encode() == first
    assert Vote.decode(first) == v


def test_derived_vote_never_serves_stale_cache():
    """'Mutating' a frozen Vote means dataclasses.replace/with_signature —
    the derived instance must re-encode, not inherit the original's bytes."""
    v = make_vote()
    _ = v.encode()
    _ = v.sign_bytes("chain-a")
    for changed in (
        v.with_signature(b"\x66" * 64),
        dataclasses.replace(v, round=5),
        dataclasses.replace(v, height=8),
        dataclasses.replace(v, timestamp_ns=v.timestamp_ns + 1),
        dataclasses.replace(v, block_id=BlockID()),
    ):
        assert changed.encode() != v.encode()
        assert Vote.decode(changed.encode()) == changed
        if changed.height == v.height and changed.round == v.round:
            # signature is not part of sign-bytes; the others must differ
            if changed.timestamp_ns == v.timestamp_ns and changed.block_id == v.block_id:
                assert changed.sign_bytes("chain-a") == v.sign_bytes("chain-a")
            else:
                assert changed.sign_bytes("chain-a") != v.sign_bytes("chain-a")


def test_sign_bytes_memo_respects_chain_id():
    v = make_vote()
    before = vote_mod.SIGN_BYTES_COMPUTES
    a1 = v.sign_bytes("chain-a")
    a2 = v.sign_bytes("chain-a")
    assert a2 is a1
    assert vote_mod.SIGN_BYTES_COMPUTES - before == 1
    b = v.sign_bytes("chain-b")  # different chain: recompute, not stale serve
    assert b != a1
    assert vote_mod.SIGN_BYTES_COMPUTES - before == 2
    # byte-identical to the unmemoized canonical builder
    assert a1 == canonical.vote_sign_bytes(
        "chain-a", v.type, v.height, v.round, v.block_id, v.timestamp_ns
    )


def test_seed_sign_bytes_primes_the_memo():
    """commit_to_vote_set seeds per-vote sign-bytes from the batched builder;
    the seeded value must be exactly what sign_bytes would compute."""
    v = make_vote()
    expected = canonical.vote_sign_bytes(
        "seed-chain", v.type, v.height, v.round, v.block_id, v.timestamp_ns
    )
    [row] = canonical.vote_sign_bytes_many(
        "seed-chain", v.type, v.height, v.round, [(v.block_id, v.timestamp_ns)]
    )
    assert row == expected
    before = vote_mod.SIGN_BYTES_COMPUTES
    v.seed_sign_bytes("seed-chain", row)
    assert v.sign_bytes("seed-chain") is row
    assert vote_mod.SIGN_BYTES_COMPUTES == before  # no compute happened


def test_serial_verify_uses_memo_once():
    priv = gen_ed25519(b"\x42" * 32)
    unsigned = make_vote(validator_address=priv.pub_key().address(), signature=b"")
    sig = priv.sign(unsigned.sign_bytes("memo-chain"))
    vote = unsigned.with_signature(sig)
    before = vote_mod.SIGN_BYTES_COMPUTES
    assert vote.verify("memo-chain", priv.pub_key())
    assert vote.verify("memo-chain", priv.pub_key())  # re-verify: cache hit
    assert vote_mod.SIGN_BYTES_COMPUTES - before == 1
