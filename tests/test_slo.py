"""SLO engine (libs/slo.py): budget classification, multi-window burn-rate
trips and re-arms, metrics wiring, and the process-global flush feed.

The guard proof the acceptance criteria require lives here (tier-1, no net
needed): injected over-budget propagation latency trips the burn-rate guard
in both windows, and the guard re-arms once the fast window drains. Clocks
are synthetic — observations and evaluation take explicit timestamps."""

import os

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

import pytest

from tendermint_tpu.config.config import SLOConfig
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.libs import slo as slo_mod
from tendermint_tpu.libs.slo import OBJECTIVES, SLOEngine


def make_engine(**overrides):
    cfg = SLOConfig(
        target=0.9,  # 10% error budget: burn math stays integral in tests
        window_fast=10.0,
        window_slow=100.0,
        burn_rate_trip=4.0,
        min_samples=5,
        proposal_propagation=0.1,
        prevote_quorum_delay=0.5,
        commit_interval=1.0,
        verify_flush_wall=0.2,
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    reg = M.Registry()
    return SLOEngine(cfg, metrics=M.SLOMetrics(reg)), reg


def test_observe_classifies_against_budget():
    eng, _ = make_engine()
    assert eng.observe("proposal_propagation", 0.05, ts=1.0) is True
    assert eng.observe("proposal_propagation", 0.5, ts=1.1) is False
    snap = eng.snapshot(now=2.0)
    obj = snap["objectives"]["proposal_propagation"]
    assert obj["observations"] == 2
    assert obj["breaches"] == 1
    assert obj["worst_s"] == 0.5
    assert obj["budget_s"] == 0.1
    # unknown objectives are ignored, never raise (feeder safety)
    assert eng.observe("no_such_objective", 99.0) is True


def test_burn_rate_trips_on_injected_latency_and_rearms():
    """THE guard proof: a healthy stream keeps burn at 0; injected
    over-budget latency pushes burn past the trip threshold in BOTH windows;
    once the bad samples age out of the fast window the guard re-arms."""
    eng, _ = make_engine()
    t = 1000.0
    # healthy phase: 20 good observations over 20s
    for i in range(20):
        eng.observe("proposal_propagation", 0.01, ts=t + i)
    ev = eng.evaluate(now=t + 20)
    obj = ev["proposal_propagation"]
    assert obj["verdict"] == "ok" and not obj["tripped"]
    assert obj["burn_rate"]["fast"]["burn"] == 0.0

    # injected latency: every proposal now blows the 100ms budget. The slow
    # window still holds the 20 goods, so the breach count must push
    # (bad/total)/0.1 past 4.0 there too: 15/(20+15) = 0.43 -> burn 4.3
    t2 = t + 20
    for i in range(15):
        eng.observe("proposal_propagation", 0.8, ts=t2 + i * 0.5)
    ev = eng.evaluate(now=t2 + 8)
    obj = ev["proposal_propagation"]
    # fast window (10s) holds almost only breaches: burn ~= 1.0/0.1 = 10 >= 4
    assert obj["burn_rate"]["fast"]["burn"] >= 4.0
    assert obj["burn_rate"]["slow"]["burn"] >= 4.0
    assert obj["tripped"] and obj["verdict"] == "tripped"
    assert obj["trips_total"] == 1
    assert eng.any_tripped()
    with pytest.raises(AssertionError, match="proposal_propagation"):
        eng.assert_budgets()

    # recovery: good traffic again; once the fast window no longer burns
    # past the threshold the guard re-arms (trips_total stays 1)
    t3 = t2 + 8
    for i in range(40):
        eng.observe("proposal_propagation", 0.01, ts=t3 + i * 0.5)
    ev = eng.evaluate(now=t3 + 25)
    obj = ev["proposal_propagation"]
    assert not obj["tripped"]
    assert obj["trips_total"] == 1
    assert not eng.any_tripped()


def test_min_samples_guards_idle_chains():
    """One slow block on an idle chain must not page: below min_samples in
    the fast window the guard cannot trip even at infinite burn."""
    eng, _ = make_engine(min_samples=5)
    for i in range(4):
        eng.observe("commit_interval", 5.0, ts=100.0 + i)
    obj = eng.evaluate(now=105.0)["commit_interval"]
    assert obj["burn_rate"]["fast"]["burn"] >= 4.0
    assert not obj["tripped"]
    # the fifth breach crosses min_samples: now it trips
    eng.observe("commit_interval", 5.0, ts=104.5)
    assert eng.evaluate(now=105.0)["commit_interval"]["tripped"]


def test_trip_requires_both_windows():
    """A burst that saturates the fast window but is diluted over the slow
    window must NOT trip (single-window flap protection): 6 breaches in the
    last 10s against 300 goods spread over 100s."""
    eng, _ = make_engine()
    t = 0.0
    for i in range(300):
        eng.observe("verify_flush_wall", 0.01, ts=t + i * 0.3)  # 90s of good
    t2 = 91.0
    for i in range(6):
        eng.observe("verify_flush_wall", 1.0, ts=t2 + i)
    # evaluate with the goods aged OUT of the fast window (they end at 89.7,
    # cutoff is 90): fast burn is pure breach, slow burn is diluted
    obj = eng.evaluate(now=t2 + 9)["verify_flush_wall"]
    assert obj["burn_rate"]["fast"]["burn"] >= 4.0
    assert obj["burn_rate"]["slow"]["burn"] < 4.0
    assert not obj["tripped"]


def test_metrics_written():
    eng, reg = make_engine()
    for i in range(6):
        eng.observe("prevote_quorum_delay", 2.0, ts=50.0 + i)
    eng.evaluate(now=56.0)
    text = reg.expose()
    assert 'tendermint_slo_observations_total{slo="prevote_quorum_delay", verdict="breach"} 6' in text
    assert 'tendermint_slo_tripped{slo="prevote_quorum_delay"} 1' in text
    assert 'tendermint_slo_trips_total{slo="prevote_quorum_delay"} 1' in text
    assert 'tendermint_slo_budget_seconds{slo="prevote_quorum_delay"} 0.5' in text
    assert 'tendermint_slo_burn_rate{slo="prevote_quorum_delay", window="fast"}' in text


def test_flush_feed_routes_to_default_engine():
    """libs/trace.record_flush feeds verify_flush_wall through the
    process-global default engine (last node wins, tracer model)."""
    from tendermint_tpu.libs import trace

    eng, _ = make_engine()
    old = slo_mod.default_engine()
    slo_mod.set_default(eng)
    try:
        trace.record_flush(backend="cpu", path="test-slo", n=4, total_s=0.9)
        trace.record_flush(backend="cpu", path="test-slo", n=4, total_s=0.01)
    finally:
        slo_mod.set_default(old)
    snap = eng.snapshot()
    obj = snap["objectives"]["verify_flush_wall"]
    assert obj["observations"] == 2
    assert obj["breaches"] == 1  # 0.9s > 0.2s budget


def test_snapshot_shape_and_objectives_catalog():
    eng, _ = make_engine()
    snap = eng.snapshot(now=1.0)
    assert snap["enabled"] is True
    assert set(snap["objectives"]) == set(OBJECTIVES)
    for obj in snap["objectives"].values():
        assert {"budget_s", "burn_rate", "tripped", "verdict"} <= set(obj)
        assert {"fast", "slow"} == set(obj["burn_rate"])


def test_node_wires_engine_and_debug_slo_route(tmp_path):
    """A Node constructs the engine from [slo], the RPC layer serves
    /debug/slo and the /debug index lists every endpoint."""
    import asyncio

    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import LocalClient
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def run():
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.root_dir = ""
        cfg.consensus.wal_path = str(tmp_path / "wal" / "wal")
        cfg.instrumentation.forensics_dir = str(tmp_path / "forensics")
        priv = FilePV(gen_ed25519(b"s" * 32), state_file=str(tmp_path / "pv.json"))
        gen = GenesisDoc(
            chain_id="slo-route",
            validators=[GenesisValidator(priv.get_pub_key(), 10)],
        )
        node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
        assert node.slo is not None
        assert node.consensus.slo is node.slo
        await node.start()
        try:
            await node.wait_for_height(2)
            client = LocalClient(node)
            snap = await client.call("debug_slo")
            assert snap["enabled"] is True
            ci = snap["objectives"]["commit_interval"]
            assert ci["observations"] >= 1
            # a healthy single-node test chain must hold its budgets
            assert not snap["any_tripped"]
            idx = await client.call("debug_index")
            paths = {e["path"] for e in idx["endpoints"]}
            assert {
                "/debug", "/debug/trace", "/debug/verify_stats",
                "/debug/consensus_timeline", "/debug/overload",
                "/debug/mesh", "/debug/slo", "/debug/device_profile",
                "/metrics",
            } <= paths
            assert all(e["description"] for e in idx["endpoints"])
        finally:
            await node.stop()

    asyncio.run(run())
