"""Embedded key-value store abstraction (the reference's tm-db role).

Backends: MemDB (tests, ephemeral) and SQLiteDB (durable, single-file).
Interface mirrors tm-db: get/set/delete/has, prefix iteration in key order,
and write batches (reference: tm-db, wired via config/config.go:164-182).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class KVDB:
    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def write_batch(self, sets: List[Tuple[bytes, bytes]], deletes: List[bytes] = ()) -> None:
        for k, v in sets:
            self.set(k, v)
        for k in deletes:
            self.delete(k)

    def close(self) -> None:
        pass


class MemDB(KVDB):
    def __init__(self) -> None:
        self._data: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(key, None)

    def iterate_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            keys = sorted(k for k in self._data if k.startswith(prefix))
            items = [(k, self._data[k]) for k in keys]
        yield from items


class SQLiteDB(KVDB):
    """Durable kv store. WAL journal mode for concurrent readers; synchronous
    writes so the consensus crash-recovery ordering holds."""

    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=FULL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, value),
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def iterate_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        hi = prefix + b"\xff" * 8
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k <= ? ORDER BY k", (prefix, hi)
            ).fetchall()
        for k, v in rows:
            if bytes(k).startswith(prefix):
                yield bytes(k), bytes(v)

    def write_batch(self, sets, deletes=()) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?) ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                [(k, v) for k, v in sets],
            )
            if deletes:
                self._conn.executemany("DELETE FROM kv WHERE k = ?", [(k,) for k in deletes])
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
