"""Merkle: RFC6962 golden vectors + proof round-trips."""

import hashlib

from tendermint_tpu.crypto import merkle


def test_empty_tree():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()


def test_single_leaf():
    item = b"hello"
    assert merkle.hash_from_byte_slices([item]) == hashlib.sha256(b"\x00" + item).digest()


def test_two_leaves():
    a, b = b"a", b"b"
    la = hashlib.sha256(b"\x00" + a).digest()
    lb = hashlib.sha256(b"\x00" + b).digest()
    expect = hashlib.sha256(b"\x01" + la + lb).digest()
    assert merkle.hash_from_byte_slices([a, b]) == expect


def test_split_point():
    assert merkle.split_point(2) == 1
    assert merkle.split_point(3) == 2
    assert merkle.split_point(4) == 2
    assert merkle.split_point(5) == 4
    assert merkle.split_point(8) == 4
    assert merkle.split_point(9) == 8


def test_rfc6962_structure_five_leaves():
    items = [bytes([i]) for i in range(5)]
    left = merkle.hash_from_byte_slices(items[:4])
    right = merkle.hash_from_byte_slices(items[4:])
    expect = hashlib.sha256(b"\x01" + left + right).digest()
    assert merkle.hash_from_byte_slices(items) == expect


def test_proofs_verify():
    for n in [1, 2, 3, 5, 8, 13, 64]:
        items = [b"item-%d" % i for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, proof in enumerate(proofs):
            assert proof.total == n and proof.index == i
            assert proof.verify(root, items[i])
            # wrong leaf / wrong root fail
            assert not proof.verify(root, b"bogus")
            assert not proof.verify(b"\x00" * 32, items[i])


def test_proof_wrong_index_fails():
    items = [b"a", b"b", b"c", b"d"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    p = proofs[0]
    p.index = 1
    assert not p.verify(root, items[0])
