"""Benchmark harness: BASELINE.md configs, CPU-serial vs TPU.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

The headline metric is the LARGEST config that completed within the time
budget (TMTPU_BENCH_BUDGET_S, default 1500s) — ideally the north star
(BASELINE.md): wall latency to verify a 10k-validator commit on TPU, with
vs_baseline = serial-CPU-time / TPU-time (the reference's serial loop
semantics, types/validator_set.go:680-702). The metric name carries the
config, e.g. "verify_commit_10k_latency".

Sub-benchmarks (in "extra", budget permitting):
  batch128            — 128-sig batch verify (BASELINE config 1)
  verify_commit_1k    — VerifyCommit, 1k validators (config 2)
  light_trusting_4k   — VerifyCommitLightTrusting, 4k validators (config 3)
  streaming_{n}_sigs_per_sec — sustained sigs/s over repeated headline batches

Run WITHOUT the test conftest (needs the real TPU): `python bench.py`.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def make_batch(n: int, msg_len: int = 110):
    """n real signed (pubkey, msg, sig) triples, distinct keys, vote-sized msgs."""
    from tendermint_tpu.crypto.keys import gen_ed25519

    rng = np.random.default_rng(1234)
    pubkeys, msgs, sigs = [], [], []
    for i in range(n):
        seed = rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
        priv = gen_ed25519(seed)
        msg = b"%06d|" % i + bytes(rng.integers(0, 256, msg_len - 7, dtype=np.uint8))
        pubkeys.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(priv.sign(msg))
    return pubkeys, msgs, sigs


def time_cpu_serial(pubkeys, msgs, sigs) -> float:
    """The reference-shaped baseline: one OpenSSL verify per signature."""
    from tendermint_tpu.crypto.batch import verify_batch_cpu

    t0 = time.perf_counter()
    mask = verify_batch_cpu(pubkeys, msgs, sigs)
    dt = time.perf_counter() - t0
    assert mask.all()
    return dt


def time_tpu(pubkeys, msgs, sigs, iters: int = 3):
    """TPU end-to-end (host prep + device) and device-only times, best of iters."""
    from tendermint_tpu.crypto.batch import prepare_batch
    from tendermint_tpu.ops.ed25519_jax import verify_prepared

    best_e2e = best_dev = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        a, r, s_bits, h_bits, precheck, n = prepare_batch(pubkeys, msgs, sigs)
        t1 = time.perf_counter()
        mask = np.asarray(verify_prepared(a, r, s_bits, h_bits))[:n]
        t2 = time.perf_counter()
        assert (mask & precheck).all()
        best_e2e = min(best_e2e, t2 - t0)
        best_dev = min(best_dev, t2 - t1)
    return best_e2e, best_dev


def bench_config(name: str, n: int, serial_n: int | None = None):
    """One config: serial CPU baseline vs TPU. serial_n: subsample for the CPU
    loop when n is large (extrapolate linearly — the loop is exactly linear)."""
    log(f"[{name}] building {n} signed triples...")
    pubkeys, msgs, sigs = make_batch(n)

    sn = serial_n or n
    cpu_s = time_cpu_serial(pubkeys[:sn], msgs[:sn], sigs[:sn]) * (n / sn)

    # warm up compile out of band
    log(f"[{name}] cpu-serial {cpu_s*1e3:.2f} ms; compiling+running TPU path...")
    e2e, dev = time_tpu(pubkeys, msgs, sigs)
    log(
        f"[{name}] tpu e2e {e2e*1e3:.2f} ms (device {dev*1e3:.2f} ms) — "
        f"{n/e2e:,.0f} sigs/s e2e, speedup {cpu_s/e2e:.1f}x"
    )
    return {
        "n": n,
        "cpu_serial_ms": round(cpu_s * 1e3, 3),
        "tpu_e2e_ms": round(e2e * 1e3, 3),
        "tpu_device_ms": round(dev * 1e3, 3),
        "sigs_per_sec_e2e": round(n / e2e),
        "speedup_e2e": round(cpu_s / e2e, 2),
        "speedup_device": round(cpu_s / dev, 2),
    }


def main():
    """Time-budgeted: each config runs only if enough budget remains (first
    compiles are minutes); the final JSON ALWAYS prints, with the largest
    completed config as the headline. Budget via TMTPU_BENCH_BUDGET_S."""
    import os

    import jax

    log("devices:", jax.devices())
    budget = float(os.environ.get("TMTPU_BENCH_BUDGET_S", "1500"))
    t_start = time.perf_counter()

    def remaining():
        return budget - (time.perf_counter() - t_start)

    extra = {}
    head = None
    plan = [
        ("batch128", 128, None),
        ("verify_commit_1k", 1000, None),
        ("light_trusting_4k", 4096, 1024),
        ("verify_commit_10k", 10000, 1024),
    ]
    # rough per-config cost: compile (~2-5 min for a fresh bucket) + run
    for i, (name, n, serial_n) in enumerate(plan):
        need = 420.0
        if i > 0 and remaining() < need:
            log(f"[{name}] skipped: {remaining():.0f}s left < {need:.0f}s budget")
            break
        try:
            res = bench_config(name, n, serial_n=serial_n)
        except Exception as e:  # a failed config must not lose the others
            log(f"[{name}] FAILED: {e}")
            break
        extra[name] = res
        head = (name, res)

    # streaming: sustained throughput over consecutive batches (compile warm)
    if head is not None and remaining() > 60:
        from tendermint_tpu.crypto.batch import prepare_batch
        from tendermint_tpu.ops.ed25519_jax import verify_prepared

        sn = head[1]["n"]
        pubkeys, msgs, sigs = make_batch(sn)
        # pipelined: submit every batch before syncing, the shape of a real
        # deployment where the verifier streams commits (and the only honest
        # measurement through a high-RTT device tunnel)
        prepped = [prepare_batch(pubkeys, msgs, sigs) for _ in range(5)]
        t0 = time.perf_counter()
        outs = [verify_prepared(a, r, s_b, h_b) for a, r, s_b, h_b, _, _ in prepped]
        masks = [np.asarray(o) for o in outs]
        stream = len(prepped) * sn / (time.perf_counter() - t0)
        for m, (_, _, _, _, precheck, n) in zip(masks, prepped):
            assert (m[:n] & precheck).all()
        extra[f"streaming_{sn}_sigs_per_sec"] = round(stream)
        log(f"[streaming] {stream:,.0f} sigs/s sustained (pipelined)")

    if head is None:
        print(json.dumps({"metric": "verify_commit_latency", "value": -1,
                          "unit": "ms", "vs_baseline": 0, "extra": {"error": "no config completed"}}))
        return
    name, res = head
    print(
        json.dumps(
            {
                "metric": f"{name}_latency",
                "value": res["tpu_e2e_ms"],
                "unit": "ms",
                "vs_baseline": res["speedup_e2e"],
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
