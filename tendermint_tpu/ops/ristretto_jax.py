"""Batched ristretto255 decode on TPU (JAX).

Device-side point decode for sr25519 validator keys and signature R values,
so mixed ed25519+sr25519 commits verify in ONE device batch (the host path
is crypto/sr25519.ristretto_decode; reference semantics:
crypto/sr25519/pubkey.go:34 via go-schnorrkel/ristretto255).

Decode (RFC 9496 §4.3.1), batched over the trailing axes like every other
kernel in ops/:

    s      <- field element; fail if non-canonical or negative (odd)
    ss     = s^2; u1 = 1 - ss; u2 = 1 + ss
    v      = -(d*u1^2) - u2^2
    I      = invsqrt(v * u2^2)        (SQRT_RATIO_M1 with numerator 1)
    x      = |2*s * I*u2|;  y = u1 * I^2 * u2 * v;  t = x*y
    fail if not was_square, y == 0, or t negative

Decoded points land in the SAME extended (X, Y, Z=1, T) coordinates the
ed25519 kernels use, so they feed the shared Pippenger MSM (ops/msm_jax.py)
directly. Ristretto's quotient-group equality (encode(P) == encode(Q) iff
P - Q is small torsion) is handled by the RLC layer: every lane coefficient
is a multiple of 8, which annihilates the torsion component exactly
(crypto/batch.py).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops.ed25519_jax import FieldCtx, Point, make_ctx


def _sqrt_ratio_1v(ctx: FieldCtx, v: jnp.ndarray):
    """SQRT_RATIO_M1(1, v): returns (was_square, r) with r = nonneg
    sqrt(1/v) when v is square, sqrt(sqrt_m1/v) otherwise; r = 0 for v = 0."""
    v3 = fe.mul(fe.square(v), v)
    v7 = fe.mul(fe.square(v3), v)
    r = fe.mul(v3, fe.pow_p58(v7))
    check = fe.mul(v, fe.square(r))
    one = ctx.one
    neg_one = ctx.neg(one)
    correct = fe.eq(check, one)
    flipped = fe.eq(check, neg_one)
    flipped_i = fe.eq(check, ctx.neg(ctx.sqrt_m1))
    r = fe.select(flipped | flipped_i, fe.mul(r, ctx.sqrt_m1), r)
    # nonnegative representative
    r = fe.freeze(r)
    r = fe.select(fe.bit(r, 0) == 1, ctx.neg(r), r)
    return correct | flipped, r


def ristretto_decode(ctx: FieldCtx, s_bytes: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """uint8[32, ...batch] -> (Point, ok mask). Invalid lanes return junk
    coordinates under ok=False (callers select the identity)."""
    s_bytes = jnp.asarray(s_bytes)
    high_bit = (s_bytes[31] >> 7) & 1
    s = fe.from_bytes(s_bytes, mask_high_bit=True)
    # canonical (< p), top bit clear, and nonnegative (even)
    ok = fe.is_canonical_bytes(s_bytes) & (high_bit == 0) & ((s_bytes[0] & 1) == 0)

    one = ctx.one
    ss = fe.square(s)
    u1 = ctx.sub(one, ss)
    u2 = fe.add(one, ss)
    u2_sqr = fe.square(u2)
    v = ctx.sub(ctx.neg(fe.mul(ctx.d, fe.square(u1))), u2_sqr)
    was_square, invsqrt = _sqrt_ratio_1v(ctx, fe.mul(v, u2_sqr))
    den_x = fe.mul(invsqrt, u2)
    den_y = fe.mul(fe.mul(invsqrt, den_x), v)
    x = fe.freeze(fe.mul(fe.mul_small(s, 2), den_x))
    x = fe.select(fe.bit(x, 0) == 1, ctx.neg(x), x)  # CT_ABS
    y = fe.mul(u1, den_y)
    t = fe.mul(x, y)
    t_frozen = fe.freeze(t)
    ok = ok & was_square & ~fe.is_zero(y) & (fe.bit(t_frozen, 0) == 0)
    return Point(x, y, one, t), ok


_decode_jit = jax.jit(ristretto_decode)


def decode_rows(rows) -> Tuple[Tuple, "jnp.ndarray"]:
    """rows (m, 32) uint8 -> ((x, y, z, t) each (20, m) int32, ok (m,) bool).
    Host helper mirroring msm_jax.decompress_rows, used to fill the pubkey
    cache with predecoded sr25519 validator keys."""
    import numpy as np

    m = rows.shape[0]
    pad = 1 << max(6, (m - 1).bit_length())
    buf = np.zeros((pad, 32), dtype=np.uint8)
    buf[:, 0] = 1  # odd -> invalid, but masked by slicing below
    buf[:m] = rows
    p, ok = _decode_jit(make_ctx((pad,)), np.ascontiguousarray(buf.T))
    coords = tuple(np.asarray(c)[:, :m] for c in p)
    return coords, np.asarray(ok)[:m]
