"""Authenticated encryption channel upgrade (reference:
p2p/conn/secret_connection.go:92 MakeSecretConnection).

Same construction as the reference's STS protocol: ephemeral X25519 ECDH →
HKDF-SHA256 → two ChaCha20-Poly1305 AEADs (one per direction, chosen by
ephemeral-key sort order) → challenge signed with the node's long-term ed25519
key. Framing: 1024-byte sealed chunks with incrementing 96-bit little-endian
nonces (reference: secret_connection.go:453).

Divergence (documented): the reference binds the handshake with a merlin
(STROBE) transcript; we bind with SHA-256 over a domain-separated transcript
of the same values. Wire compatibility with Go peers is not a goal — the
security properties (key confirmation, MITM binding of the challenge to both
ephemerals and the shared secret) are preserved.
"""

from __future__ import annotations

import asyncio
import hashlib
import struct
from dataclasses import dataclass

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives import serialization
    from cryptography.exceptions import InvalidTag

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # minimal containers: plaintext transport only
    # The module stays importable so the transport/switch layer (which only
    # needs SecretConnection for isinstance checks and the opt-in encrypted
    # upgrade) works in plaintext mode (`use_secret_conn=False`) without the
    # wheel — the in-process multinode/chaos harness runs everywhere.
    HAVE_CRYPTOGRAPHY = False
    X25519PrivateKey = X25519PublicKey = ChaCha20Poly1305 = serialization = None

    class InvalidTag(Exception):  # type: ignore[no-redef]
        pass


from tendermint_tpu.crypto.keys import Ed25519PubKey, PrivKey, PubKey

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
AEAD_TAG_SIZE = 16
TOTAL_FRAME_SIZE = DATA_LEN_SIZE + DATA_MAX_SIZE
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE


class HandshakeError(Exception):
    pass


def _hkdf(secret: bytes) -> tuple[bytes, bytes, bytes]:
    """HKDF-SHA256 -> (recv_secret, send_secret, challenge) for the low party;
    mirrored for the high party (reference: secret_connection.go:343)."""
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives import hashes

    okm = HKDF(
        algorithm=hashes.SHA256(),
        length=96,
        salt=None,
        info=b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN",
    ).derive(secret)
    return okm[0:32], okm[32:64], okm[64:96]


@dataclass
class _Nonce:
    """96-bit little-endian counter nonce, incremented per frame."""

    counter: int = 0

    def use(self) -> bytes:
        n = struct.pack("<Q", self.counter) + b"\x00\x00\x00\x00"
        self.counter += 1
        if self.counter >= 1 << 64:
            raise OverflowError("nonce exhausted")
        return n


class SecretConnection:
    """Wraps an asyncio (reader, writer) pair after the handshake."""

    def __init__(self, reader, writer, send_aead, recv_aead, remote_pubkey: PubKey):
        self._reader = reader
        self._writer = writer
        self._send = send_aead
        self._recv = recv_aead
        self._send_nonce = _Nonce()
        self._recv_nonce = _Nonce()
        self.remote_pubkey = remote_pubkey
        self._recv_buf = b""

    # -- handshake ---------------------------------------------------------

    @classmethod
    async def upgrade(cls, reader, writer, priv_key: PrivKey) -> "SecretConnection":
        """(reference: secret_connection.go:92 MakeSecretConnection)"""
        if not HAVE_CRYPTOGRAPHY:
            raise ImportError(
                "secret connection requires the `cryptography` wheel "
                "(use plaintext transport for in-process tests)"
            )
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )

        writer.write(struct.pack(">I", len(eph_pub)) + eph_pub)
        await writer.drain()
        hdr = await reader.readexactly(4)
        (ln,) = struct.unpack(">I", hdr)
        if ln != 32:
            raise HandshakeError("bad ephemeral key length")
        remote_eph = await reader.readexactly(32)

        try:
            # cryptography raises on an all-zero shared secret (low-order /
            # small-subgroup ephemeral — an evil peer forcing a known key)
            shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        except ValueError as e:
            raise HandshakeError(f"bad ephemeral point: {e}") from e

        low_is_us = eph_pub < remote_eph
        lo, hi = (eph_pub, remote_eph) if low_is_us else (remote_eph, eph_pub)
        recv_secret, send_secret, challenge_lo = _hkdf(shared + lo + hi)
        if low_is_us:
            send_key, recv_key = send_secret, recv_secret
        else:
            send_key, recv_key = recv_secret, send_secret

        # Transcript binding: challenge covers both ephemerals + shared secret.
        transcript = hashlib.sha256(
            b"TMTPU_SECRET_CONNECTION_TRANSCRIPT" + lo + hi + challenge_lo
        ).digest()

        conn = cls(
            reader, writer, ChaCha20Poly1305(send_key), ChaCha20Poly1305(recv_key), None
        )

        # Exchange authenticated (pubkey, sig-over-transcript) over the
        # now-encrypted channel (reference: secret_connection.go shareAuthSignature).
        local_pub = priv_key.pub_key()
        sig = priv_key.sign(transcript)
        await conn.write_msg(local_pub.bytes() + sig)
        auth = await conn.read_msg()
        if len(auth) != 32 + 64:
            raise HandshakeError("bad auth message size")
        remote_pub = Ed25519PubKey(auth[:32])
        if not remote_pub.verify(transcript, auth[32:]):
            raise HandshakeError("challenge signature verification failed")
        conn.remote_pubkey = remote_pub
        return conn

    # -- framed encrypted I/O ----------------------------------------------

    async def write(self, data: bytes) -> None:
        """Split into <=1024B frames, seal each (reference: :453 Write)."""
        off = 0
        out = bytearray()
        while True:
            chunk = data[off : off + DATA_MAX_SIZE]
            frame = struct.pack("<I", len(chunk)) + chunk
            frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
            out += self._send.encrypt(self._send_nonce.use(), bytes(frame), None)
            off += DATA_MAX_SIZE
            if off >= len(data):
                break
        self._writer.write(bytes(out))
        await self._writer.drain()

    async def _read_frame(self) -> bytes:
        sealed = await self._reader.readexactly(SEALED_FRAME_SIZE)
        try:
            frame = self._recv.decrypt(self._recv_nonce.use(), sealed, None)
        except InvalidTag as e:
            raise HandshakeError("frame decryption failed") from e
        (ln,) = struct.unpack("<I", frame[:DATA_LEN_SIZE])
        if ln > DATA_MAX_SIZE:
            raise HandshakeError("frame length too large")
        return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + ln]

    async def read(self, n: int) -> bytes:
        """Read exactly n plaintext bytes."""
        while len(self._recv_buf) < n:
            self._recv_buf += await self._read_frame()
        out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
        return out

    # -- length-prefixed messages over the frames --------------------------

    async def write_msg(self, msg: bytes) -> None:
        await self.write(struct.pack(">I", len(msg)) + msg)

    async def read_msg(self, max_size: int = 1 << 22) -> bytes:
        hdr = await self.read(4)
        (ln,) = struct.unpack(">I", hdr)
        if ln > max_size:
            raise HandshakeError("message too large")
        return await self.read(ln)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass


class SyncSecretConnection:
    """Blocking-socket variant of SecretConnection — same STS construction,
    same framing — for threaded endpoints (the privval remote signer). One
    instance is NOT thread-safe; serialize calls externally."""

    def __init__(self, sock, send_aead, recv_aead, remote_pubkey):
        self._sock = sock
        self._send = send_aead
        self._recv = recv_aead
        self._send_nonce = _Nonce()
        self._recv_nonce = _Nonce()
        self.remote_pubkey = remote_pubkey
        self._recv_buf = b""

    @classmethod
    def upgrade(cls, sock, priv_key: PrivKey) -> "SyncSecretConnection":
        if not HAVE_CRYPTOGRAPHY:
            raise ImportError(
                "secret connection requires the `cryptography` wheel"
            )
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        sock.sendall(struct.pack(">I", len(eph_pub)) + eph_pub)
        (ln,) = struct.unpack(">I", _recv_exact(sock, 4))
        if ln != 32:
            raise HandshakeError("bad ephemeral key length")
        remote_eph = _recv_exact(sock, 32)

        try:
            # see async upgrade: low-order ephemeral -> all-zero shared secret
            shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        except ValueError as e:
            raise HandshakeError(f"bad ephemeral point: {e}") from e
        low_is_us = eph_pub < remote_eph
        lo, hi = (eph_pub, remote_eph) if low_is_us else (remote_eph, eph_pub)
        recv_secret, send_secret, challenge_lo = _hkdf(shared + lo + hi)
        if low_is_us:
            send_key, recv_key = send_secret, recv_secret
        else:
            send_key, recv_key = recv_secret, send_secret
        transcript = hashlib.sha256(
            b"TMTPU_SECRET_CONNECTION_TRANSCRIPT" + lo + hi + challenge_lo
        ).digest()

        conn = cls(sock, ChaCha20Poly1305(send_key), ChaCha20Poly1305(recv_key), None)
        local_pub = priv_key.pub_key()
        conn.write_msg(local_pub.bytes() + priv_key.sign(transcript))
        auth = conn.read_msg()
        if len(auth) != 32 + 64:
            raise HandshakeError("bad auth message size")
        remote_pub = Ed25519PubKey(auth[:32])
        if not remote_pub.verify(transcript, auth[32:]):
            raise HandshakeError("challenge signature verification failed")
        conn.remote_pubkey = remote_pub
        return conn

    def write(self, data: bytes) -> None:
        off = 0
        out = bytearray()
        while True:
            chunk = data[off : off + DATA_MAX_SIZE]
            frame = struct.pack("<I", len(chunk)) + chunk
            frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
            out += self._send.encrypt(self._send_nonce.use(), bytes(frame), None)
            off += DATA_MAX_SIZE
            if off >= len(data):
                break
        self._sock.sendall(bytes(out))

    def read(self, n: int) -> bytes:
        while len(self._recv_buf) < n:
            sealed = _recv_exact(self._sock, SEALED_FRAME_SIZE)
            try:
                frame = self._recv.decrypt(self._recv_nonce.use(), sealed, None)
            except InvalidTag as e:
                raise HandshakeError("frame decryption failed") from e
            (ln,) = struct.unpack("<I", frame[:DATA_LEN_SIZE])
            if ln > DATA_MAX_SIZE:
                raise HandshakeError("frame length too large")
            self._recv_buf += frame[DATA_LEN_SIZE : DATA_LEN_SIZE + ln]
        out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
        return out

    def write_msg(self, msg: bytes) -> None:
        self.write(struct.pack(">I", len(msg)) + msg)

    def read_msg(self, max_size: int = 1 << 22) -> bytes:
        (ln,) = struct.unpack(">I", self.read(4))
        if ln > max_size:
            raise HandshakeError("message too large")
        return self.read(ln)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise HandshakeError("connection closed during secret handshake")
        buf += chunk
    return buf
