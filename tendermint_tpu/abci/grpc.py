"""ABCI over gRPC: the reference's third transport
(reference: abci/client/grpc_client.go:1, abci/server/grpc_server.go:1,
service `tendermint.abci.ABCIApplication` in proto/tendermint/abci/types.proto).

No generated stubs: grpc-python's generic handlers take per-method
serializers, and the bare RequestX/ResponseX messages are exactly what
abci/wire.py already encodes for the socket transport (same v0.34 field
numbers) — so the wire format matches the reference's gRPC service without a
protoc step.

The reference runs one gRPC call per request with per-call goroutines but
documents that the socket client is the performant one
(abci/client/grpc_client.go:24); matching that, this transport is correct
and simple rather than the hot path — consensus deployments use the local
or socket client.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Optional

import grpc

from tendermint_tpu.abci import types as a
from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.abci.wire import REQUEST_TYPES, RESPONSE_TYPES, decode_msg, encode_msg

_SERVICE = "tendermint.abci.ABCIApplication"

# gRPC method name -> (snake name, request cls or None, response cls or None).
# None request/response = empty proto message (Flush/Commit/ListSnapshots…).
_METHODS = {
    "Echo": ("echo", None, None),  # special-cased string codec below
    "Flush": ("flush", None, None),
    "Info": ("info", a.RequestInfo, a.ResponseInfo),
    "SetOption": ("set_option", a.RequestSetOption, a.ResponseSetOption),
    "DeliverTx": ("deliver_tx", a.RequestDeliverTx, a.ResponseDeliverTx),
    "CheckTx": ("check_tx", a.RequestCheckTx, a.ResponseCheckTx),
    "Query": ("query", a.RequestQuery, a.ResponseQuery),
    "Commit": ("commit", None, a.ResponseCommit),
    "InitChain": ("init_chain", a.RequestInitChain, a.ResponseInitChain),
    "BeginBlock": ("begin_block", a.RequestBeginBlock, a.ResponseBeginBlock),
    "EndBlock": ("end_block", a.RequestEndBlock, a.ResponseEndBlock),
    "ListSnapshots": ("list_snapshots", None, a.ResponseListSnapshots),
    "OfferSnapshot": ("offer_snapshot", a.RequestOfferSnapshot, a.ResponseOfferSnapshot),
    "LoadSnapshotChunk": (
        "load_snapshot_chunk", a.RequestLoadSnapshotChunk, a.ResponseLoadSnapshotChunk,
    ),
    "ApplySnapshotChunk": (
        "apply_snapshot_chunk", a.RequestApplySnapshotChunk, a.ResponseApplySnapshotChunk,
    ),
}


def _enc_echo(message: str) -> bytes:
    from tendermint_tpu.libs import protowire as pw

    w = pw.Writer()
    w.string_field(1, message)
    return w.bytes()


def _dec_echo(data: bytes) -> str:
    from tendermint_tpu.libs import protowire as pw

    for f, _, v in pw.Reader(data):
        if f == 1:
            return v.decode()
    return ""


# grpc-python rejects None from (de)serializers, so empty proto messages
# (RequestFlush, RequestCommit, ResponseFlush, …) travel as b"".
def _req_serializer(cls):
    if cls is None:
        return lambda _msg: b""
    return encode_msg


def _req_deserializer(cls):
    if cls is None:
        return lambda _data: b""
    return lambda data: decode_msg(cls, data)


def _resp_serializer(cls):
    if cls is None:
        return lambda _msg: b""
    return encode_msg


def _resp_deserializer(cls):
    if cls is None:
        return lambda _data: b""
    return lambda data: decode_msg(cls, data)


class GrpcServer:
    """Serves one Application over gRPC
    (reference: abci/server/grpc_server.go:30)."""

    def __init__(self, addr: str, app: a.Application, max_workers: int = 8):
        self.app = app
        self._app_lock = threading.Lock()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
        handlers = {}
        for grpc_name, (snake, req_cls, resp_cls) in _METHODS.items():
            handlers[grpc_name] = grpc.unary_unary_rpc_method_handler(
                self._make_handler(grpc_name, snake),
                request_deserializer=(
                    _dec_echo if grpc_name == "Echo" else _req_deserializer(req_cls)
                ),
                response_serializer=(
                    _enc_echo if grpc_name == "Echo" else _resp_serializer(resp_cls)
                ),
            )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        host_port = addr.replace("tcp://", "")
        self.port = self._server.add_insecure_port(host_port)
        self.bound_addr = (host_port.rsplit(":", 1)[0], self.port)

    def _make_handler(self, grpc_name: str, snake: str):
        def handle(request, context):
            with self._app_lock:
                if grpc_name == "Echo":
                    return request  # ResponseEcho.message = RequestEcho.message
                if grpc_name == "Flush":
                    return b""
                method = getattr(self.app, snake)
                # commit / list_snapshots take no request message (b"" sentinel)
                return method() if request == b"" else method(request)

        return handle

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)


class GrpcClient(ABCIClient):
    """Synchronous gRPC client, one unary call per ABCI request
    (reference: abci/client/grpc_client.go — kept FIFO-equivalent by the
    caller's request ordering; errors surface as exceptions)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        target = addr.replace("tcp://", "").replace("grpc://", "")
        self._channel = grpc.insecure_channel(target)
        self._timeout = timeout
        self._calls = {}
        for grpc_name, (snake, req_cls, resp_cls) in _METHODS.items():
            self._calls[snake] = self._channel.unary_unary(
                f"/{_SERVICE}/{grpc_name}",
                request_serializer=(
                    _enc_echo if grpc_name == "Echo" else _req_serializer(req_cls)
                ),
                response_deserializer=(
                    _dec_echo if grpc_name == "Echo" else _resp_deserializer(resp_cls)
                ),
            )

    def _call(self, name: str, req=None):
        return self._calls[name](req, timeout=self._timeout)

    # -- the 17-method surface ------------------------------------------

    def echo(self, msg: str) -> str:
        return self._call("echo", msg)

    def flush(self) -> None:
        self._call("flush", None)

    def info(self, req):
        return self._call("info", req)

    def set_option(self, req):
        return self._call("set_option", req)

    def query(self, req):
        return self._call("query", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def begin_block(self, req):
        return self._call("begin_block", req)

    def deliver_tx(self, req):
        return self._call("deliver_tx", req)

    def end_block(self, req):
        return self._call("end_block", req)

    def commit(self):
        return self._call("commit", None)

    def list_snapshots(self):
        return self._call("list_snapshots", None)

    def offer_snapshot(self, req):
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("apply_snapshot_chunk", req)

    def close(self) -> None:
        self._channel.close()
