"""Evidence of Byzantine behaviour (reference: types/evidence.go).

v0.34 ships DuplicateVoteEvidence (two conflicting votes by one validator at
the same H/R/type). Verification checks the two conflicting signatures
(reference: types/evidence.go:189) — batched through crypto.batch alongside
everything else when pools flush.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.crypto.keys import PubKey
from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types.vote import Vote


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int
    validator_power: int
    timestamp_ns: int

    TYPE_URL = 1  # field number inside the Evidence oneof

    @classmethod
    def from_votes(
        cls, vote1: Vote, vote2: Vote, block_time_ns: int, total_power: int, val_power: int
    ) -> "DuplicateVoteEvidence":
        """Votes are ordered lexically by block ID key (reference:
        types/evidence.go NewDuplicateVoteEvidence)."""
        if vote1.block_id.key() < vote2.block_id.key():
            a, b = vote1, vote2
        else:
            a, b = vote2, vote1
        return cls(a, b, total_power, val_power, block_time_ns)

    @property
    def height(self) -> int:
        return self.vote_a.height

    def address(self) -> bytes:
        return self.vote_a.validator_address

    def hash(self) -> bytes:
        return tmhash.sum256(self.encode())

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("empty duplicate vote")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise ValueError("duplicate votes in invalid order")

    def verify(self, chain_id: str, pubkey: PubKey, batch_verifier=None) -> None:
        """(reference: evidence/verify.go VerifyDuplicateVote + types/evidence.go:189)

        batch_verifier: optional callable(pubkeys, msgs, sigs, key_types)
        -> bool mask — the evidence pool passes the global scheduler's
        catch-up lane here (crypto/scheduler.py) so gossiped evidence's two
        signature checks ride a combined device flush instead of two
        serial host verifies; None keeps the serial reference path."""
        a, b = self.vote_a, self.vote_b
        if a.height != b.height or a.round != b.round or a.type != b.type:
            raise ValueError("duplicate votes must have same H/R/S")
        if a.validator_address != b.validator_address:
            raise ValueError("duplicate votes must be from the same validator")
        if a.block_id == b.block_id:
            raise ValueError("duplicate votes must vote for different blocks")
        if pubkey.address() != a.validator_address:
            raise ValueError("address does not match pubkey")
        if batch_verifier is not None:
            pk = pubkey.bytes()
            kt = pubkey.type_name()
            mask = batch_verifier(
                [pk, pk],
                [a.sign_bytes(chain_id), b.sign_bytes(chain_id)],
                [a.signature, b.signature],
                [kt, kt],
            )
            if not mask[0]:
                raise ValueError("verifying VoteA: invalid signature")
            if not mask[1]:
                raise ValueError("verifying VoteB: invalid signature")
            return
        if not pubkey.verify(a.sign_bytes(chain_id), a.signature):
            raise ValueError("verifying VoteA: invalid signature")
        if not pubkey.verify(b.sign_bytes(chain_id), b.signature):
            raise ValueError("verifying VoteB: invalid signature")

    def encode(self) -> bytes:
        body = pw.Writer()
        body.message_field(1, self.vote_a.encode(), always=True)
        body.message_field(2, self.vote_b.encode(), always=True)
        body.varint_field(3, self.total_voting_power)
        body.varint_field(4, self.validator_power)
        sec, nanos = divmod(self.timestamp_ns, 1_000_000_000)
        body.message_field(5, pw.encode_timestamp(sec, nanos), always=True)
        # wrap in the Evidence oneof envelope
        w = pw.Writer()
        w.message_field(self.TYPE_URL, body.bytes(), always=True)
        return w.bytes()

    @classmethod
    def decode_body(cls, data: bytes) -> "DuplicateVoteEvidence":
        vote_a = vote_b = None
        total = valp = ts = 0
        for f, _, v in pw.Reader(data):
            if f == 1:
                vote_a = Vote.decode(v)
            elif f == 2:
                vote_b = Vote.decode(v)
            elif f == 3:
                total = pw.int64_from_varint(v)
            elif f == 4:
                valp = pw.int64_from_varint(v)
            elif f == 5:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                ts = sec * 1_000_000_000 + nanos
        if vote_a is None or vote_b is None:
            raise ValueError("malformed DuplicateVoteEvidence")
        return cls(vote_a, vote_b, total, valp, ts)


def decode_evidence(data: bytes):
    for f, _, v in pw.Reader(data):
        if f == DuplicateVoteEvidence.TYPE_URL:
            return DuplicateVoteEvidence.decode_body(v)
    raise ValueError("unknown evidence type")
