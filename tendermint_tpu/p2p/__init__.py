"""P2P fabric: authenticated multiplexed connections, switch/reactor routing,
peer exchange (reference: p2p/)."""

from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.key import NodeKey, pubkey_to_id
from tendermint_tpu.p2p.node_info import NodeInfo, parse_addr
from tendermint_tpu.p2p.peer import Peer, PeerSet

try:
    # SecretConnection's `cryptography` import is itself gated now (the
    # plaintext transport runs in minimal containers — that's how the chaos
    # smoke/soak nets exist everywhere), so this import normally succeeds;
    # the guard stays for any transitive import the wheel still owns.
    from tendermint_tpu.p2p.switch import Switch
    from tendermint_tpu.p2p.transport import MultiplexTransport
except ImportError:  # pragma: no cover - exercised in minimal containers
    Switch = None  # type: ignore[assignment]
    MultiplexTransport = None  # type: ignore[assignment]

__all__ = [
    "ChannelDescriptor",
    "MConnection",
    "MultiplexTransport",
    "NodeInfo",
    "NodeKey",
    "Peer",
    "PeerSet",
    "Reactor",
    "Switch",
    "parse_addr",
    "pubkey_to_id",
]
