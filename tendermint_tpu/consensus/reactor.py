"""Consensus reactor: gossips round state, proposals/parts and votes over 4
p2p channels (reference: consensus/reactor.go:27-30,41).

Channels: 0x20 State, 0x21 Data, 0x22 Vote, 0x23 VoteSetBits. Per peer, three
gossip tasks mirror the reference's goroutines (gossipDataRoutine :490,
gossipVotesRoutine :629, queryMaj23Routine :761). Internal consensus events
(NewRoundStep/ValidBlock/Vote) are broadcast via event-bus subscriptions
(reference: :398-470 broadcast routines).

All mutation of ConsensusState happens by enqueueing onto its receive loop
(add_peer_message); PeerState updates run inline on the shared asyncio loop —
a callback with no awaits is atomic, which is the same discipline the
reference achieves with the PeerState mutex."""

from __future__ import annotations

import asyncio
import logging
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from tendermint_tpu.consensus.cs_state import ConsensusState
from tendermint_tpu.consensus.messages import (
    BlockPartMessage,
    HasVoteMessage,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalMessage,
    ProposalPOLMessage,
    TraceContext,
    VoteMessage,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
    decode_message_traced,
    encode_message,
)
from tendermint_tpu.consensus.round_state import RoundStepType
from tendermint_tpu.libs import hotstats as _hotstats
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor
from tendermint_tpu.types.basic import BlockID, SignedMsgType
from tendermint_tpu.types.event_bus import (
    EVENT_NEW_ROUND_STEP,
    EVENT_VALID_BLOCK,
    EVENT_VOTE,
    query_for_event,
)

logger = logging.getLogger("tendermint_tpu.consensus.reactor")

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

GOSSIP_SLEEP = 0.02  # reference: config PeerGossipSleepDuration 100ms; tests are faster

# a trace stamp older than this measures catch-up/retransmission (the
# receiver's lag), not gossip propagation: count the message, drop the latency
STALE_TRACE_S = 30.0
QUERY_MAJ23_SLEEP = 0.5


def propagation_latency(recv_ts: float, origin_ts: float, skew) -> float:
    """Skew-corrected per-hop propagation latency in seconds.

    `origin_ts` lives in the ORIGIN node's wall-clock domain; `skew` is the
    origin's remote-minus-local offset estimated from timestamped ping/pong
    (p2p/conn/connection.py), so the origin's local send time is
    origin_ts - skew and latency = recv_ts - origin_ts + skew. Clamped at
    zero: residual skew error (±RTT/2) must never fabricate negative
    latency — honesty over precision."""
    lat = recv_ts - origin_ts
    if skew is not None:
        lat += skew
    return max(0.0, lat)


class PeerState:
    """What we know the peer knows (reference: consensus/reactor.go:928)."""

    def __init__(self, peer_id: str):
        self.peer_id = peer_id
        self.height = 0
        self.round = -1
        self.step = RoundStepType.NEW_HEIGHT
        self.start_time_ns = 0
        self.proposal = False
        self.proposal_block_psh = None
        self.proposal_block_parts: Optional[BitArray] = None
        self.proposal_pol_round = -1
        self.proposal_pol: Optional[BitArray] = None
        self.prevotes: Dict[int, BitArray] = {}
        self.precommits: Dict[int, BitArray] = {}
        self.last_commit_round = -1
        self.last_commit: Optional[BitArray] = None
        self.catchup_commit_round = -1
        self.catchup_commit: Optional[BitArray] = None

    # -- updates from messages (reference: reactor.go ApplyNewRoundStep...) --

    def apply_new_round_step(self, msg: NewRoundStepMessage) -> None:
        ps_height, ps_round = self.height, self.round
        if msg.height < self.height or (
            msg.height == self.height and msg.round < self.round
        ):
            return
        self.height = msg.height
        self.round = msg.round
        self.step = RoundStepType(msg.step) if msg.step else RoundStepType.NEW_HEIGHT
        self.start_time_ns = time.time_ns() - msg.seconds_since_start_time * 10**9
        if ps_height != msg.height or ps_round != msg.round:
            self.proposal = False
            self.proposal_block_psh = None
            self.proposal_block_parts = None
            self.proposal_pol_round = -1
            self.proposal_pol = None
        if ps_height != msg.height:
            if ps_height + 1 == msg.height and ps_round == msg.last_commit_round:
                self.last_commit_round = msg.last_commit_round
                self.last_commit = self.precommits.get(ps_round)
            else:
                self.last_commit_round = msg.last_commit_round
                self.last_commit = None
            self.prevotes.clear()
            self.precommits.clear()
            self.catchup_commit_round = -1
            self.catchup_commit = None

    def apply_new_valid_block(self, msg: NewValidBlockMessage) -> None:
        if msg.height != self.height:
            return
        if msg.round != self.round and not msg.is_commit:
            return
        self.proposal_block_psh = msg.block_part_set_header
        self.proposal_block_parts = BitArray.from_bools(msg.block_parts)

    def apply_proposal_pol(self, msg: ProposalPOLMessage) -> None:
        if msg.height != self.height or msg.proposal_pol_round != self.proposal_pol_round:
            return
        self.proposal_pol = BitArray.from_bools(msg.proposal_pol)

    def apply_has_vote(self, msg: HasVoteMessage) -> None:
        if msg.height != self.height:
            return
        self.set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def set_has_proposal(self, proposal) -> None:
        if self.height != proposal.height or self.round != proposal.round:
            return
        if self.proposal:
            return
        self.proposal = True
        if self.proposal_block_parts is None:
            self.proposal_block_psh = proposal.block_id.part_set_header
            self.proposal_block_parts = BitArray(proposal.block_id.part_set_header.total)
        self.proposal_pol_round = proposal.pol_round
        self.proposal_pol = None

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        if self.height != height or self.round != round_:
            return
        if self.proposal_block_parts is not None:
            self.proposal_block_parts.set_index(index, True)

    def _votes_bits(self, height: int, round_: int, type_: SignedMsgType, num_validators: int) -> Optional[BitArray]:
        if self.height != height:
            # votes for height-1 land in last_commit
            if self.height == height + 1 and type_ == SignedMsgType.PRECOMMIT and round_ == self.last_commit_round:
                if self.last_commit is None:
                    self.last_commit = BitArray(num_validators)
                return self.last_commit
            return None
        table = self.prevotes if type_ == SignedMsgType.PREVOTE else self.precommits
        if round_ not in table:
            table[round_] = BitArray(num_validators)
        return table[round_]

    # Hard cap on any peer-supplied validator index: bounds every BitArray
    # allocation a remote can trigger (the reference's PeerRoundState arrays
    # are implicitly sized by the known validator set).
    MAX_VOTE_INDEX = 1 << 16

    def set_has_vote(self, height: int, round_: int, type_: SignedMsgType, index: int, num_validators: int = 0) -> None:
        if index < 0 or index >= self.MAX_VOTE_INDEX:
            return
        bits = self._votes_bits(height, round_, type_, max(num_validators, index + 1))
        if bits is not None:
            if index >= bits.size():
                # grow (peer table created before we knew the valset size)
                grown = BitArray(index + 1)
                grown.update(bits)
                bits = grown
                table = self.prevotes if type_ == SignedMsgType.PREVOTE else self.precommits
                if self.height == height:
                    table[round_] = bits
                elif self.height == height + 1:
                    self.last_commit = bits
            bits.set_index(index, True)

    def apply_vote_set_bits(self, msg: VoteSetBitsMessage, our_votes: Optional[List[bool]] = None) -> None:
        bits = self._votes_bits(msg.height, msg.round, msg.type, len(msg.votes))
        if bits is None:
            return
        update = BitArray.from_bools(msg.votes)
        if our_votes is not None:
            # peer claims maj23: they have everything in (claimed OR ours)
            update = update.or_(BitArray.from_bools(our_votes))
        bits.update(update.or_(bits))

    def pick_vote_to_send(self, votes) -> Optional[object]:
        """votes: a VoteSet-like with bit_array()/get_by_index(); returns a
        Vote the peer lacks (reference: PeerState.PickSendVote :1049)."""
        picked = self.pick_votes_to_send(votes, limit=1)
        return picked[0] if picked else None

    def pick_votes_to_send(self, votes, limit: int = 64) -> List[object]:
        """Up to `limit` votes the peer lacks, in index order — ONE pass over
        the bit arrays per gossip wakeup instead of one full rescan per vote
        (the per-vote rescan made vote gossip O(validators) per vote)."""
        if votes is None or votes.size() == 0:
            return []
        ours = votes.bit_array()
        height = getattr(votes, "height", self.height)
        round_ = getattr(votes, "round", 0)
        type_ = getattr(votes, "signed_msg_type", SignedMsgType.PREVOTE)
        theirs = self._votes_bits(height, round_, type_, len(ours))
        out: List[object] = []
        for idx, have in enumerate(ours):
            if have and (theirs is None or not theirs.get_index(idx)):
                vote = votes.get_by_index(idx)
                if vote is not None:
                    out.append(vote)
                    if len(out) >= limit:
                        break
        return out


class ConsensusReactor(Reactor):
    def __init__(self, cs: ConsensusState, wait_sync: bool = False):
        super().__init__("CONSENSUS")
        self.cs = cs
        self.wait_sync = wait_sync  # True while fast-sync is running
        self._tasks: List[asyncio.Task] = []
        self._peer_tasks: Dict[str, List[asyncio.Task]] = {}
        # (height, round) proposals already seen once — bounds the first-
        # receipt dedupe behind the propagation SLO (chain observatory)
        self._prop_seen: "OrderedDict[tuple, None]" = OrderedDict()

    def get_channels(self) -> List[ChannelDescriptor]:
        # NEVER sheddable: the overload shed order is txs -> non-critical
        # gossip -> never votes (per-channel caps follow the reference's
        # consensus maxMsgSize of 1MB; block parts are 64KB chunks)
        cap = 1_048_576
        return [
            ChannelDescriptor(STATE_CHANNEL, priority=6, send_queue_capacity=100,
                              recv_message_capacity=cap),
            ChannelDescriptor(DATA_CHANNEL, priority=10, send_queue_capacity=100,
                              recv_message_capacity=cap),
            ChannelDescriptor(VOTE_CHANNEL, priority=7, send_queue_capacity=100,
                              recv_message_capacity=cap),
            ChannelDescriptor(VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=2,
                              recv_message_capacity=cap),
        ]

    async def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._broadcast_routine(), name="consr-broadcast"),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for tasks in self._peer_tasks.values():
            for t in tasks:
                t.cancel()
        self._peer_tasks.clear()

    async def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Fast-sync -> consensus handoff (reference: consensus/reactor.go:106)."""
        self.wait_sync = False
        await self.cs.start()
        # spin up gossip for peers added while syncing
        for peer in (self.switch.peers.list() if self.switch else []):
            if peer.id not in self._peer_tasks:
                ps = peer.get("cs_peer_state") or PeerState(peer.id)
                peer.set("cs_peer_state", ps)
                self._peer_tasks[peer.id] = [
                    asyncio.create_task(self._gossip_data_routine(peer, ps)),
                    asyncio.create_task(self._gossip_votes_routine(peer, ps)),
                    asyncio.create_task(self._query_maj23_routine(peer, ps)),
                ]

    # -- peers -------------------------------------------------------------

    async def add_peer(self, peer) -> None:
        ps = PeerState(peer.id)
        peer.set("cs_peer_state", ps)
        # announce our current state
        await peer.send(
            STATE_CHANNEL,
            encode_message(self._our_round_step(), trace=self._fresh_trace()),
        )
        if not self.wait_sync:
            self._peer_tasks[peer.id] = [
                asyncio.create_task(self._gossip_data_routine(peer, ps)),
                asyncio.create_task(self._gossip_votes_routine(peer, ps)),
                asyncio.create_task(self._query_maj23_routine(peer, ps)),
            ]

    async def remove_peer(self, peer, reason) -> None:
        for t in self._peer_tasks.pop(peer.id, []):
            t.cancel()

    # -- trace propagation (chain observatory, ISSUE 8) ---------------------

    def _self_id(self) -> str:
        sw = self.switch
        if sw is None:
            return ""
        try:
            return sw.node_info.node_id
        except Exception:
            return ""

    def _fresh_trace(self) -> TraceContext:
        """Origin stamp for a message WE generate right now (NewRoundStep,
        HasVote): hops 0, wall clock now."""
        return TraceContext(self._self_id(), time.time(), 0)

    def _otrace(self, payload) -> TraceContext:
        """Outbound trace for a gossiped payload (vote/proposal/part):
        self-originated objects get ONE origin stamp at first send (memoized
        — every peer sees the same origin time), relayed objects forward the
        received context with the hop count bumped."""
        rx = getattr(payload, "_rx_trace", None)
        if rx is not None:
            fwd = payload.__dict__.get("_fwd_trace")
            if fwd is None:
                fwd = rx.forwarded()
                object.__setattr__(payload, "_fwd_trace", fwd)
            return fwd
        mine = payload.__dict__.get("_origin_trace")
        if mine is None:
            mine = self._fresh_trace()
            object.__setattr__(payload, "_origin_trace", mine)
        return mine

    def _note_trace(self, msg, tctx: TraceContext, peer) -> None:
        """A traced message arrived: stash the context on the payload (so a
        re-gossip forwards it hop-bumped) and record per-hop propagation
        latency — skew-corrected against the origin's ping/pong clock-skew
        estimate when the origin is a direct peer, else against the relaying
        peer's (the best available proxy on a multi-hop path).

        The stamp is remote-supplied and arrives BEFORE consensus
        validation, so recording is defensive: per-height timeline entries
        only for heights adjacent to our own (a peer must not flush the
        ring with invented heights), and stamps older than STALE_TRACE_S
        record counts but never latency — catch-up/retransmitted gossip
        measures the RECEIVER's lag, and must not poison the origin's."""
        recv_ts = time.time()
        payload = kind = None
        if isinstance(msg, VoteMessage):
            payload, kind = msg.vote, "vote"
        elif isinstance(msg, BlockPartMessage):
            payload, kind = msg.part, "block_part"
        elif isinstance(msg, ProposalMessage):
            payload, kind = msg.proposal, "proposal"
        elif isinstance(msg, HasVoteMessage):
            kind = "has_vote"
        elif isinstance(msg, NewRoundStepMessage):
            kind = "round_step"
        else:
            kind = "other"
        if payload is not None:
            try:
                object.__setattr__(payload, "_rx_trace", tctx)
            except Exception:
                pass
        tl = self.cs._tl()
        slo = self.cs.slo
        m = self.cs._live_metrics()
        if tl is None and slo is None and m is None:
            return
        skew = None
        sw = self.switch
        if sw is not None:
            try:
                skew = sw.clock_skew(tctx.origin)
            except Exception:
                skew = None
        if skew is None:
            mc = getattr(peer, "mconn", None)
            if mc is not None:
                try:
                    skew = mc.clock_skew()
                except Exception:
                    skew = None
        lat = propagation_latency(recv_ts, tctx.origin_ts, skew)
        stale = lat > STALE_TRACE_S
        if tl is not None and not stale:
            tl.record_hop(tctx.origin, kind, lat, skew_corrected=skew is not None)

        def _height_ok(h: int) -> bool:
            ours = self.cs.rs.height
            return ours - 1 <= h <= ours + 1

        if kind == "proposal":
            p = msg.proposal
            if not _height_ok(p.height):
                return
            first = self._mark_first_receipt(p.height, p.round)
            if tl is not None:
                # the timeline dedupes first-seen itself and counts the
                # duplicate receipts
                tl.record_proposal_propagation(
                    p.height, p.round, tctx.origin, lat, tctx.hops, ts=recv_ts
                )
            if first and not stale:
                # budget/histogram semantics are FIRST local receipt: each
                # peer gossips the proposal independently, and a duplicate
                # arriving late from a lagging peer is not propagation
                if m is not None:
                    m.proposal_propagation_seconds.observe(lat)
                if slo is not None:
                    slo.observe("proposal_propagation", lat)
        elif kind == "block_part":
            if not _height_ok(msg.height):
                return
            if tl is not None:
                tl.record_block_part(
                    msg.height, msg.round, None if stale else lat, ts=recv_ts
                )
        elif kind == "vote":
            v = msg.vote
            if not _height_ok(v.height):
                return
            if tl is not None:
                tl.record_vote_origin(
                    v.height, v.round, v.type.name, tctx.origin,
                    None if stale else lat,
                )
            if m is not None and not stale:
                m.vote_propagation_seconds.observe(lat)

    def _mark_first_receipt(self, height: int, round_: int) -> bool:
        """True exactly once per (height, round) proposal receipt; the seen
        set is bounded (FIFO) so remote-supplied keys cannot grow it."""
        key = (height, round_)
        seen = self._prop_seen
        if key in seen:
            return False
        seen[key] = None
        while len(seen) > 256:
            seen.popitem(last=False)
        return True

    # -- receive -----------------------------------------------------------

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg, tctx = decode_message_traced(msg_bytes)
        except Exception as e:
            logger.error("bad consensus msg from %s: %s", peer.id[:10], e)
            await self.switch.stop_peer_for_error(peer, e)
            return
        ps: PeerState = peer.get("cs_peer_state")
        if ps is None:
            return
        if tctx is not None:
            try:
                self._note_trace(msg, tctx, peer)
            except Exception:
                logger.exception("trace propagation recording failed")
        rs = self.cs.rs

        if chan_id == STATE_CHANNEL:
            if isinstance(msg, NewRoundStepMessage):
                ps.apply_new_round_step(msg)
            elif isinstance(msg, NewValidBlockMessage):
                ps.apply_new_valid_block(msg)
            elif isinstance(msg, HasVoteMessage):
                ps.apply_has_vote(msg)
            elif isinstance(msg, VoteSetMaj23Message):
                if rs.height == msg.height and rs.votes is not None:
                    try:
                        rs.votes.set_peer_maj23(msg.round, msg.type, peer.id, msg.block_id)
                    except Exception as e:
                        logger.debug("set_peer_maj23: %s", e)
                    votes = (
                        rs.votes.prevotes(msg.round)
                        if msg.type == SignedMsgType.PREVOTE
                        else rs.votes.precommits(msg.round)
                    )
                    our = votes.bit_array_by_block_id(msg.block_id) if votes else None
                    if our is not None:
                        await peer.send(
                            VOTE_SET_BITS_CHANNEL,
                            encode_message(
                                VoteSetBitsMessage(msg.height, msg.round, msg.type, msg.block_id, our)
                            ),
                        )
        elif chan_id == DATA_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, ProposalMessage):
                ps.set_has_proposal(msg.proposal)
                await self.cs.add_peer_message(msg, peer.id)
            elif isinstance(msg, ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
            elif isinstance(msg, BlockPartMessage):
                ps.set_has_proposal_block_part(msg.height, msg.round, msg.part.index)
                m = self.cs.metrics
                if m is not None:
                    # block-part gossip timing (reference: CometBFT
                    # consensus/metrics.go BlockGossipPartsReceived /
                    # BlockGossipReceiveLatency)
                    matches = msg.height == rs.height and msg.round == rs.round
                    m.block_parts.labels("true" if matches else "false").inc()
                    if matches:
                        # origin: the round's proposal; before it arrives,
                        # fall back to the height start — valid for round 0
                        # only (start_time_ns is per-height, and counting a
                        # failed earlier round as gossip latency would
                        # pollute the tail)
                        origin_ns = 0
                        if rs.proposal is not None:
                            origin_ns = rs.proposal.timestamp_ns
                        elif rs.round == 0:
                            origin_ns = rs.start_time_ns
                        if origin_ns:
                            m.block_gossip_receive_latency.observe(
                                max(0.0, (time.time_ns() - origin_ns) / 1e9)
                            )
                await self.cs.add_peer_message(msg, peer.id)
        elif chan_id == VOTE_CHANNEL:
            if self.wait_sync:
                return
            if isinstance(msg, VoteMessage):
                n_vals = rs.validators.size() if rs.validators else 0
                ps.set_has_vote(
                    msg.vote.height, msg.vote.round, msg.vote.type, msg.vote.validator_index, n_vals
                )
                await self.cs.add_peer_message(msg, peer.id)
        elif chan_id == VOTE_SET_BITS_CHANNEL:
            if isinstance(msg, VoteSetBitsMessage):
                if rs.height == msg.height and rs.votes is not None:
                    votes = (
                        rs.votes.prevotes(msg.round)
                        if msg.type == SignedMsgType.PREVOTE
                        else rs.votes.precommits(msg.round)
                    )
                    our = votes.bit_array_by_block_id(msg.block_id) if votes else None
                    ps.apply_vote_set_bits(msg, our)
                else:
                    ps.apply_vote_set_bits(msg, None)

    # -- broadcasts (reference: reactor.go:398-470) -------------------------

    def _our_round_step(self) -> NewRoundStepMessage:
        rs = self.cs.rs
        return NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=int(rs.step),
            seconds_since_start_time=max(0, int((time.time_ns() - rs.start_time_ns) / 1e9)),
            last_commit_round=rs.last_commit.round if rs.last_commit is not None else -1,
        )

    async def _broadcast_routine(self) -> None:
        """Event-bus → p2p broadcasts, COALESCED per wakeup: each consume
        drains everything already queued on its subscription and handles the
        batch in one call. Under a vote storm that turns N per-vote wakeups
        (each a full per-peer broadcast round) into one batched
        `broadcast_many`; for round-step/valid-block events only the LATEST
        state is broadcast (a NewRoundStepMessage carries full state, so
        intermediate ones are strictly stale)."""
        bus = self.cs.event_bus
        sub_step = bus.subscribe("cs-reactor", query_for_event(EVENT_NEW_ROUND_STEP), 200)
        sub_valid = bus.subscribe("cs-reactor", query_for_event(EVENT_VALID_BLOCK), 200)
        sub_vote = bus.subscribe("cs-reactor", query_for_event(EVENT_VOTE), 500)

        async def consume(sub, handler):
            while True:
                try:
                    msg = await sub.next()
                except Exception:
                    return
                batch = [msg]
                done = False
                while True:
                    try:
                        m = sub.queue.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if m is None:  # cancellation sentinel (unsubscribed)
                        done = True
                        break
                    batch.append(m)
                try:
                    await handler(batch)
                except Exception:
                    logger.exception("broadcast handler failed")
                if done:
                    return

        async def on_steps(_msgs):
            # coalesced: broadcast our CURRENT round state once per drain
            if self.switch is not None:
                await self.switch.broadcast(
                    STATE_CHANNEL,
                    encode_message(self._our_round_step(), trace=self._fresh_trace()),
                )

        async def on_valid(_msgs):
            rs = self.cs.rs
            if self.switch is not None and rs.proposal_block_parts is not None:
                m = NewValidBlockMessage(
                    rs.height, rs.round, rs.proposal_block_parts.header,
                    rs.proposal_block_parts.bit_array(), rs.step == RoundStepType.COMMIT,
                )
                await self.switch.broadcast(STATE_CHANNEL, encode_message(m))

        async def on_votes(msgs):
            if self.switch is None:
                return
            hs = _hotstats.stats if _hotstats.stats.enabled else None
            if hs is not None:
                t0 = _hotstats.perf_counter()
            payloads = []
            trace = self._fresh_trace()  # one stamp for the whole drain batch
            for msg in msgs:
                vote = msg.data.vote
                payloads.append(
                    encode_message(
                        HasVoteMessage(vote.height, vote.round, vote.type, vote.validator_index),
                        trace=trace,
                    )
                )
            await self.switch.broadcast_many(STATE_CHANNEL, payloads)
            if hs is not None:
                hs.add("gossip", _hotstats.perf_counter() - t0, n=len(msgs))

        await asyncio.gather(
            consume(sub_step, on_steps), consume(sub_valid, on_valid), consume(sub_vote, on_votes)
        )

    # -- gossip routines ----------------------------------------------------

    async def _gossip_data_routine(self, peer, ps: PeerState) -> None:
        """(reference: consensus/reactor.go:490 gossipDataRoutine)"""
        try:
            while True:
                # Always yield once per iteration: peer.send() can return
                # False synchronously (dead connection) and a no-await loop
                # would freeze the event loop and resist cancellation.
                await asyncio.sleep(0)
                rs = self.cs.rs
                # 1. peer needs a part of the current proposal block
                if (
                    rs.proposal_block_parts is not None
                    and rs.height == ps.height
                    and ps.proposal_block_parts is not None
                    and rs.proposal_block_parts.header == ps.proposal_block_psh
                ):
                    ours = BitArray.from_bools(rs.proposal_block_parts.bit_array())
                    needed = ours.sub(ps.proposal_block_parts)
                    idx = needed.pick_random()
                    if idx is not None:
                        part = rs.proposal_block_parts.get_part(idx)
                        if part is not None:
                            ok = await peer.send(
                                DATA_CHANNEL,
                                encode_message(
                                    BlockPartMessage(rs.height, rs.round, part),
                                    trace=self._otrace(part),
                                ),
                            )
                            if ok:
                                ps.set_has_proposal_block_part(rs.height, rs.round, idx)
                            else:
                                await asyncio.sleep(GOSSIP_SLEEP)
                            continue
                # 2. peer is at an earlier height: catch them up from the store
                if ps.height != 0 and ps.height < rs.height and ps.height >= self.cs.block_store.base:
                    if await self._gossip_catchup(peer, ps):
                        continue
                # 3. peer needs our proposal
                if rs.proposal is not None and rs.height == ps.height and rs.round == ps.round and not ps.proposal:
                    await peer.send(
                        DATA_CHANNEL,
                        encode_message(
                            ProposalMessage(rs.proposal), trace=self._otrace(rs.proposal)
                        ),
                    )
                    ps.set_has_proposal(rs.proposal)
                    if 0 <= rs.proposal.pol_round:
                        pol = rs.votes.prevotes(rs.proposal.pol_round)
                        if pol is not None:
                            await peer.send(
                                DATA_CHANNEL,
                                encode_message(
                                    ProposalPOLMessage(rs.height, rs.proposal.pol_round, pol.bit_array())
                                ),
                            )
                    continue
                await asyncio.sleep(GOSSIP_SLEEP)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("gossip data routine died for %s", peer.id[:10])

    async def _gossip_catchup(self, peer, ps: PeerState) -> bool:
        """Send one block part for the peer's height from the store
        (reference: reactor.go:583 gossipDataForCatchup)."""
        if ps.proposal_block_parts is None:
            meta = self.cs.block_store.load_block_meta(ps.height)
            if meta is None:
                return False
            block_id = meta[0] if isinstance(meta, tuple) else meta.block_id
            ps.proposal_block_psh = block_id.part_set_header
            ps.proposal_block_parts = BitArray(block_id.part_set_header.total)
        needed = ps.proposal_block_parts.not_()
        idx = needed.pick_random()
        if idx is None:
            return False
        part = self.cs.block_store.load_block_part(ps.height, idx)
        if part is None:
            return False
        ok = await peer.send(
            DATA_CHANNEL,
            encode_message(
                BlockPartMessage(ps.height, ps.round, part), trace=self._otrace(part)
            ),
        )
        if ok:
            ps.proposal_block_parts.set_index(idx, True)
        return ok

    # max votes sent to one peer per gossip wakeup: one bit-array scan
    # amortizes over the whole run instead of one rescan per vote, while the
    # bound keeps a single peer from monopolizing the send queue
    VOTE_GOSSIP_BATCH = 64

    async def _gossip_votes_routine(self, peer, ps: PeerState) -> None:
        """(reference: consensus/reactor.go:629 gossipVotesRoutine; vote
        picking is batched — see PeerState.pick_votes_to_send)."""
        try:
            while True:
                await asyncio.sleep(0)  # guaranteed yield (see data routine)
                rs = self.cs.rs
                picked: List[object] = []
                if rs.height == ps.height and rs.votes is not None:
                    # current height: prevotes/precommits for peer's round,
                    # POL prevotes, our round's votes. The OUR-round sets are
                    # the round-catchup path (reference: reactor.go
                    # gossipVotesForHeight's final rs.Round clause): a peer
                    # that restarted or healed from a partition sits rounds
                    # behind and can only skip forward on +2/3 ANY at a later
                    # round — which it can never assemble unless same-height
                    # peers send votes from rounds ABOVE its own (the
                    # receiver files them under its peer-catchup rounds,
                    # round_state.py:116). Without this, a lagging validator
                    # crawls one timeout-stretched round at a time while the
                    # quorum needs it — the chaos soak's restart wedge.
                    candidates = [
                        rs.votes.prevotes(ps.round) if ps.round >= 0 else None,
                        rs.votes.precommits(ps.round) if ps.round >= 0 else None,
                        rs.votes.prevotes(ps.proposal_pol_round) if ps.proposal_pol_round >= 0 else None,
                    ]
                    if 0 <= ps.round < rs.round:
                        candidates.append(rs.votes.prevotes(rs.round))
                        candidates.append(rs.votes.precommits(rs.round))
                    for votes in candidates:
                        picked = (
                            ps.pick_votes_to_send(votes, self.VOTE_GOSSIP_BATCH)
                            if votes else []
                        )
                        if picked:
                            break
                elif (
                    rs.height == ps.height + 1 and rs.last_commit is not None
                ):
                    # peer is finishing the previous height: send last commit
                    picked = ps.pick_votes_to_send(rs.last_commit, self.VOTE_GOSSIP_BATCH)
                elif (
                    ps.height != 0
                    and rs.height > ps.height + 1
                    and ps.height >= self.cs.block_store.base
                ):
                    # catchup: precommits from the stored commit
                    commit = self.cs.block_store.load_block_commit(ps.height)
                    if commit is not None:
                        vote = self._pick_commit_vote(ps, commit)
                        if vote is not None:
                            picked = [vote]
                if picked:
                    sent_any = False
                    for vote in picked:
                        ok = await peer.send(
                            VOTE_CHANNEL,
                            encode_message(VoteMessage(vote), trace=self._otrace(vote)),
                        )
                        if not ok:
                            break
                        sent_any = True
                        # peer-state update coalesces naturally: bits flip as
                        # sends succeed, so the next scan skips them all
                        ps.set_has_vote(vote.height, vote.round, vote.type, vote.validator_index)
                    if sent_any:
                        continue
                await asyncio.sleep(GOSSIP_SLEEP)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("gossip votes routine died for %s", peer.id[:10])

    def _pick_commit_vote(self, ps: PeerState, commit):
        theirs = ps._votes_bits(
            commit.height, commit.round, SignedMsgType.PRECOMMIT, len(commit.signatures)
        )
        for idx, cs_sig in enumerate(commit.signatures):
            if cs_sig.absent():
                continue
            if theirs is None or not theirs.get_index(idx):
                return commit.get_vote(idx)
        return None

    async def _query_maj23_routine(self, peer, ps: PeerState) -> None:
        """(reference: consensus/reactor.go:761 queryMaj23Routine)"""
        try:
            while True:
                await asyncio.sleep(QUERY_MAJ23_SLEEP)
                rs = self.cs.rs
                if rs.votes is None or rs.height != ps.height:
                    continue
                for type_, votes in (
                    (SignedMsgType.PREVOTE, rs.votes.prevotes(rs.round)),
                    (SignedMsgType.PRECOMMIT, rs.votes.precommits(rs.round)),
                ):
                    if votes is None:
                        continue
                    maj = votes.two_thirds_majority()
                    if maj is not None:
                        await peer.send(
                            STATE_CHANNEL,
                            encode_message(VoteSetMaj23Message(rs.height, rs.round, type_, maj)),
                        )
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("query maj23 routine died for %s", peer.id[:10])
