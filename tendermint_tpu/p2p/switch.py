"""Switch: owns reactors and peers, routes channels, handles the peer
lifecycle (reference: p2p/switch.go:68).

accept_routine takes upgraded connections from the transport; add_peer wires
an MConnection whose on_receive dispatches to the reactor registered for the
channel (reference: p2p/switch.go:157 AddReactor, :788 addPeer). Persistent
peers are re-dialed with exponential backoff (reference: :379 reconnectToPeer).
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Dict, List, Optional

from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor, MConnection
from tendermint_tpu.p2p.node_info import parse_addr
from tendermint_tpu.p2p.peer import Peer, PeerSet
from tendermint_tpu.p2p.transport import Connection, MultiplexTransport

logger = logging.getLogger("tendermint_tpu.p2p")

RECONNECT_ATTEMPTS = 20
RECONNECT_BASE_DELAY = 0.5


class Switch:
    def __init__(
        self,
        transport: MultiplexTransport,
        max_peers: int = 50,
        metrics=None,
        trust_store_path: str | None = None,
        recv_limit=None,
    ):
        from tendermint_tpu.p2p.behaviour import Reporter, TrustStore

        self.metrics = metrics
        # trust metrics survive restarts when a store path is configured
        # (reference: p2p/trust/store.go; saved periodically + on stop)
        self.reporter = Reporter(
            self, store=TrustStore(trust_store_path) if trust_store_path else None
        )
        self.transport = transport
        self.peers = PeerSet()
        self.reactors: Dict[str, Reactor] = {}
        self._chan_to_reactor: Dict[int, Reactor] = {}
        self._channel_descs: List[ChannelDescriptor] = []
        self.max_peers = max_peers
        self.persistent_addrs: Dict[str, str] = {}  # peer id -> addr
        self._tasks: List[asyncio.Task] = []
        # Reconnect routines tracked SEPARATELY (peer id -> task): they sleep
        # up to 0.5*2^6 s between attempts, so stop() must cancel AND await
        # them (a bare fire-and-forget task would outlive the switch and dial
        # from a stopped node). One task per peer id — a flapping peer must
        # not accumulate concurrent reconnect loops.
        self._reconnect_tasks: Dict[str, asyncio.Task] = {}
        self._running = False
        self._dialing: set[str] = set()
        # Chaos/partition hook: when set, a peer id this predicate rejects
        # can neither be dialed nor accepted (tendermint_tpu/chaos/harness.py
        # partitions an in-process net by installing group filters).
        self._conn_filter = None
        # Inbound admission control (p2p/conn/connection.py RecvRateLimit):
        # applied to every peer MConnection's sheddable channels. None
        # disables per-channel rate limiting.
        self.recv_limit = recv_limit

    @property
    def node_info(self):
        return self.transport.node_info

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        """(reference: p2p/switch.go:157 AddReactor)"""
        for desc in reactor.get_channels():
            if desc.id in self._chan_to_reactor:
                raise ValueError(f"channel {desc.id:#x} already registered")
            self._chan_to_reactor[desc.id] = reactor
            self._channel_descs.append(desc)
        self.reactors[name] = reactor
        reactor.set_switch(self)
        # advertise channels in NodeInfo
        self.transport.node_info.channels = bytes(
            sorted(self._chan_to_reactor.keys())
        )
        return reactor

    TRUST_SAVE_INTERVAL = 60.0  # reference: p2p/trust/store.go saves each minute
    FLOWRATE_SAMPLE_INTERVAL = 2.0  # p2p gauge refresh (EWMA window is 1s)

    async def start(self) -> None:
        self._running = True
        for reactor in self.reactors.values():
            await reactor.start()
        self._tasks.append(asyncio.create_task(self._accept_routine(), name="sw-accept"))
        if self.reporter.store is not None:
            self._tasks.append(
                asyncio.create_task(self._trust_save_routine(), name="sw-trust-save")
            )
        if self.metrics is not None:
            self._tasks.append(
                asyncio.create_task(self._flowrate_routine(), name="sw-flowrate")
            )

    async def _trust_save_routine(self) -> None:
        while self._running:
            await asyncio.sleep(self.TRUST_SAVE_INTERVAL)
            self.reporter.save()

    async def _flowrate_routine(self) -> None:
        """Periodically fold every peer MConnection's flowrate Monitors and
        send-queue depths into the p2p gauges — the Monitors existed for
        rate limiting but were never read for observability."""
        while self._running:
            self.update_flow_metrics()
            await asyncio.sleep(self.FLOWRATE_SAMPLE_INTERVAL)

    def update_flow_metrics(self) -> None:
        if self.metrics is None:
            return
        send_rate = recv_rate = 0.0
        pending = 0
        skews = {}
        for peer in self.peers.list():
            try:
                st = peer.status()
            except Exception:
                continue
            send_rate += st["send_rate_bytes"]
            recv_rate += st["recv_rate_bytes"]
            pending += sum(c["pending_messages"] for c in st["channels"])
            if st.get("clock_skew_s") is not None:
                skews[(peer.id[:10],)] = st["clock_skew_s"]
        # replace, don't accumulate: a departed peer's series must drop out
        # (peer ids are remote-controlled label cardinality)
        self.metrics.clock_skew_seconds.replace_series(skews)
        self.metrics.send_rate_bytes.set(send_rate)
        self.metrics.recv_rate_bytes.set(recv_rate)
        self.metrics.pending_send_messages.set(pending)

    def clock_skew(self, node_id: str):
        """Remote-minus-local clock-skew estimate for a DIRECTLY connected
        peer (seconds), or None when the peer is unknown or unsampled. The
        chain observatory's propagation latencies subtract this before they
        are recorded, so cross-node deltas are honest."""
        peer = self.peers.get(node_id)
        if peer is None:
            return None
        try:
            return peer.clock_skew()
        except Exception:
            return None

    def set_conn_filter(self, fn) -> None:
        """Install (or clear, with None) a peer-id connection filter. Applies
        to dials, inbound upgrades, and reconnect attempts."""
        self._conn_filter = fn

    def _conn_allowed(self, peer_id: str) -> bool:
        return self._conn_filter is None or not peer_id or self._conn_filter(peer_id)

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        reconnects = list(self._reconnect_tasks.values())
        for t in reconnects:
            t.cancel()
        if reconnects:
            await asyncio.gather(*reconnects, return_exceptions=True)
        self._reconnect_tasks.clear()
        for peer in self.peers.list():
            await self._stop_and_remove_peer(peer, None)
        for reactor in self.reactors.values():
            await reactor.stop()
        self.reporter.save()
        await self.transport.close()

    # -- peer lifecycle ----------------------------------------------------

    async def _accept_routine(self) -> None:
        while self._running:
            try:
                conn = await self.transport.accept()
            except asyncio.CancelledError:
                return
            except Exception as e:
                logger.error("accept error: %s", e)
                continue
            if self.peers.size() >= self.max_peers:
                conn.transport.close()
                continue
            try:
                await self._add_peer(conn)
            except Exception as e:
                logger.info("failed to add inbound peer: %s", e)

    async def dial_peer(self, addr: str, persistent: bool = False) -> Optional[Peer]:
        """Dial 'id@host:port' and add the peer."""
        peer_id, _, _ = parse_addr(addr)
        if not self._conn_allowed(peer_id):
            raise ConnectionError(f"dial to {peer_id[:10]} blocked by conn filter")
        if peer_id and (self.peers.has(peer_id) or peer_id in self._dialing):
            return self.peers.get(peer_id)
        self._dialing.add(peer_id)
        try:
            conn = await self.transport.dial(addr)
            if persistent:
                self.persistent_addrs[conn.node_info.node_id] = addr
            return await self._add_peer(conn, persistent=persistent)
        finally:
            self._dialing.discard(peer_id)

    async def dial_peers_async(self, addrs: List[str], persistent: bool = False) -> None:
        async def _one(a):
            try:
                await self.dial_peer(a, persistent=persistent)
            except Exception as e:
                logger.info("dial %s failed: %s", a, e)
                if persistent:
                    pid, _, _ = parse_addr(a)
                    self._spawn_reconnect(a, pid)

        await asyncio.gather(*(_one(a) for a in addrs))

    async def _add_peer(self, conn: Connection, persistent: bool = False) -> Peer:
        ni = conn.node_info
        if not self._conn_allowed(ni.node_id):
            conn.transport.close()
            raise ConnectionError(f"peer {ni.node_id[:10]} blocked by conn filter")
        if self.peers.has(ni.node_id):
            conn.transport.close()
            raise ValueError(f"duplicate peer {ni.node_id}")
        persistent = persistent or ni.node_id in self.persistent_addrs

        peer_holder: List[Peer] = []

        async def on_receive(chan_id: int, msg: bytes) -> None:
            reactor = self._chan_to_reactor.get(chan_id)
            if reactor is None:
                raise ValueError(f"no reactor for channel {chan_id:#x}")
            if self.metrics is not None:
                self.metrics.peer_receive_bytes_total.labels(f"{chan_id:#x}").inc(len(msg))
            try:
                await reactor.receive(chan_id, peer_holder[0], msg)
            except Exception as e:
                # full report: records bad conduct AND applies the trust
                # threshold (the peer is usually also stopped by on_error)
                from tendermint_tpu.p2p.behaviour import BAD_MESSAGE, PeerBehaviour

                await self.reporter.report(
                    PeerBehaviour(peer_holder[0].id, BAD_MESSAGE, str(e))
                )
                raise
            self.reporter.metric(peer_holder[0].id).record_good(0.05)

        async def on_error(e: Exception) -> None:
            await self.stop_peer_for_error(peer_holder[0], e)

        async def on_rate_limit_exceeded() -> None:
            # persistent flooding past the per-channel budgets: record bad
            # conduct; repeated reports push the trust score under the
            # threshold and the Reporter disconnects the peer
            from tendermint_tpu.p2p.behaviour import RATE_LIMIT, PeerBehaviour

            if self.metrics is not None:
                self.metrics.rate_limit_disconnects.inc()
            await self.reporter.report(
                PeerBehaviour(
                    peer_holder[0].id, RATE_LIMIT, "inbound recv budget exceeded"
                )
            )

        mconn = MConnection(
            conn.transport, self._channel_descs, on_receive, on_error,
            recv_limit=self.recv_limit, metrics=self.metrics,
            on_rate_limit_exceeded=on_rate_limit_exceeded,
        )
        peer = Peer(ni, mconn, conn.outbound, persistent, conn.socket_addr,
                    metrics=self.metrics)
        peer_holder.append(peer)
        self.peers.add(peer)
        mconn.start()
        for reactor in self.reactors.values():
            await reactor.add_peer(peer)
        logger.info("added peer %s (%s)", ni.node_id[:10], ni.moniker)
        if self.metrics is not None:
            self.metrics.peers.set(self.peers.size())
        return peer

    async def stop_peer_for_error(self, peer: Peer, reason) -> None:
        """(reference: p2p/switch.go:324 StopPeerForError)"""
        if not self.peers.has(peer.id):
            return
        logger.info("stopping peer %s: %s", peer.id[:10], reason)
        await self._stop_and_remove_peer(peer, reason)
        if peer.persistent and self._running:
            addr = self.persistent_addrs.get(peer.id) or (
                f"{peer.id}@{peer.socket_addr}" if peer.outbound else None
            )
            if addr:
                self._spawn_reconnect(addr, peer.id)

    async def _stop_and_remove_peer(self, peer: Peer, reason) -> None:
        self.peers.remove(peer.id)
        # keep bad reputations (reconnecting with the same id stays scored),
        # drop good ones so the metrics map doesn't grow with peer churn
        if self.reporter.score(peer.id) > 0.8:
            self.reporter.metrics.pop(peer.id, None)
        if self.metrics is not None:
            self.metrics.peers.set(self.peers.size())
        await peer.stop()
        for reactor in self.reactors.values():
            try:
                await reactor.remove_peer(peer, reason)
            except Exception:
                logger.exception("reactor remove_peer failed")

    def _spawn_reconnect(self, addr: str, peer_id: str) -> None:
        """Track one reconnect routine per peer id; done tasks self-evict so
        the map doesn't grow with peer churn (the old bare create_task +
        append-to-_tasks leaked a completed task per flap and left sleepers
        alive across stop())."""
        existing = self._reconnect_tasks.get(peer_id)
        if existing is not None and not existing.done():
            return
        task = asyncio.create_task(
            self._reconnect_routine(addr, peer_id), name=f"sw-reconnect-{peer_id[:8]}"
        )
        self._reconnect_tasks[peer_id] = task

        def _evict(t, pid=peer_id):
            if self._reconnect_tasks.get(pid) is t:
                del self._reconnect_tasks[pid]

        task.add_done_callback(_evict)

    async def _reconnect_routine(self, addr: str, peer_id: str) -> None:
        """Exponential backoff reconnect (reference: p2p/switch.go:379)."""
        for attempt in range(RECONNECT_ATTEMPTS):
            if not self._running or self.peers.has(peer_id):
                return
            delay = RECONNECT_BASE_DELAY * (2 ** min(attempt, 6)) * (0.5 + random.random())
            await asyncio.sleep(delay)
            if not self._running or self.peers.has(peer_id):
                return
            if self.metrics is not None:
                self.metrics.reconnect_attempts.inc()
            try:
                await self.dial_peer(addr, persistent=True)
                return
            except Exception as e:
                logger.debug("reconnect %s attempt %d failed: %s", addr, attempt, e)

    async def disconnect_peer(self, peer_id: str, reason: str = "disconnect") -> None:
        """Drop a live peer connection WITHOUT spawning a reconnect routine
        (chaos partitions cut links; healing re-dials explicitly)."""
        peer = self.peers.get(peer_id)
        if peer is not None:
            logger.info("disconnecting peer %s: %s", peer_id[:10], reason)
            await self._stop_and_remove_peer(peer, reason)

    # -- broadcast ---------------------------------------------------------

    async def broadcast(self, chan_id: int, msg: bytes) -> None:
        """Async send to every peer (reference: p2p/switch.go:263)."""
        await asyncio.gather(
            *(p.send(chan_id, msg) for p in self.peers.list()),
            return_exceptions=True,
        )

    async def broadcast_many(self, chan_id: int, msgs: List[bytes]) -> None:
        """Coalesced broadcast: each peer receives the whole batch in order
        with ONE task per peer, instead of one gather round per message.
        Used by the consensus reactor's per-drain HasVote batches."""
        if not msgs:
            return
        if len(msgs) == 1:
            await self.broadcast(chan_id, msgs[0])
            return

        async def _send_all(p: Peer) -> None:
            for m in msgs:
                await p.send(chan_id, m)

        await asyncio.gather(
            *(_send_all(p) for p in self.peers.list()),
            return_exceptions=True,
        )

    def num_peers(self) -> int:
        return self.peers.size()
