"""CPU differential test for the Pallas-only pairwise window fold.

`_fold_windows` (the production schedule behind `_combine_windows` on TPU)
was previously exercised only via end-to-end verification on hardware — a
regression in its pairing/shift arithmetic would not surface in the CPU
suite (advisor r4). Here the SAME code path runs on CPU through the jnp
point ops and is checked against both the lax.scan Horner form and a host
bigint reference, over odd/even/one-window widths."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # window-fold kernel compiles; excluded
# from the tier-1 budget lane (-m 'not slow')

from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.ops import fe25519 as fe
from tendermint_tpu.ops.msm_jax import (
    Point,
    _combine_windows,
    _fold_windows,
    make_small_ctx,
)


def _w_pts(ks):
    """Point coords (20, T) for W_w = [k_w] B."""
    cols = []
    for k in ks:
        x, y, z, t = ref.point_mul(k, ref.BASE)
        cols.append([fe.from_int(x), fe.from_int(y), fe.from_int(z), fe.from_int(t)])
    return Point(
        *(np.stack([c[i] for c in cols], axis=-1).astype(np.int32) for i in range(4))
    )


def _compress(p: Point) -> bytes:
    x = fe.to_int(np.asarray(p.x)) % ref.P
    y = fe.to_int(np.asarray(p.y)) % ref.P
    z = fe.to_int(np.asarray(p.z)) % ref.P
    t = fe.to_int(np.asarray(p.t)) % ref.P
    return ref.point_compress((x, y, z, t))


@pytest.mark.parametrize("t_windows", [1, 2, 3, 5, 8])
def test_fold_matches_scan_and_reference(t_windows):
    rng = np.random.default_rng(41 + t_windows)
    ks = [int.from_bytes(rng.bytes(16), "little") | 1 for _ in range(t_windows)]
    w = _w_pts(ks)
    C = make_small_ctx()
    folded = _fold_windows(C, w)
    scanned = _combine_windows(C, w)  # CPU backend -> the scan/Horner form
    assert _compress(folded) == _compress(scanned)
    total = sum(k * (1 << (8 * i)) for i, k in enumerate(ks)) % ref.L
    expected = ref.point_compress(ref.point_mul(total, ref.BASE))
    assert _compress(folded) == expected
