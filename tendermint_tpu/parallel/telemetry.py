"""Per-shard mesh telemetry for the multi-chip paths (parallel/sharded.py).

The sharded 8-chip path had ZERO instrumentation while every MULTICHIP round
died opaquely. This module is the aggregation half: `parallel/sharded.py`
(and `ops/aot_cache.py` for artifact hits/misses) record into a
process-global store + the `tendermint_mesh_*` Prometheus series
(libs/metrics.py MeshMetrics, process-global registry), and two read
surfaces serve it: the `mesh` block of `GET /debug/verify_stats` and the
dedicated `GET /debug/mesh` route (rpc/server.py). The multichip dryrun
(__graft_entry__) prints the same snapshot so even an rc-124 round leaves
per-shard evidence in its captured tail.

Deliberately jax-free: importable by the RPC layer / verify_stats on
CPU-only nodes without dragging in the sharded machinery.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

_LOCK = threading.Lock()


def _fresh() -> Dict[str, Any]:
    return {
        "mesh": None,  # {"devices": [...], "shape": {...}, "platform"}
        "flushes": {},  # kind -> count
        "totals": {
            "submit_seconds": 0.0,
            "finish_seconds": 0.0,
            "all_gathers": 0,
            "all_gather_bytes": 0,
            "prep_seconds": 0.0,
            "prep_calls": 0,
        },
        "last_flush": None,
        "last_prep": None,
        "aot_cache": {},  # result -> count (hit / miss / corrupt)
        # Elastic mesh (ISSUE 19): per-device health + degrade ladder
        "health": None,  # parallel/health.MeshHealthManager.snapshot()
        "ladder": None,  # "full" | "survivor" | "single" | "host"
        "rebuilds": 0,
        "last_rebuild": None,
    }


_STATS: Dict[str, Any] = _fresh()


def _metrics():
    from tendermint_tpu.libs import metrics as _m

    return _m.mesh_metrics()


def record_mesh(axis_names, shape, devices, platform: str) -> None:
    """The mesh a sharded runner was built over (sharded_verify /
    sharded_rlc_check / sharded_commit_step construction time)."""
    info = {
        "axes": dict(zip(list(axis_names), [int(s) for s in shape])),
        "devices": [str(d) for d in devices],
        "n_devices": len(devices),
        "platform": platform,
    }
    with _LOCK:
        _STATS["mesh"] = info
    try:
        _metrics().devices.set(len(devices))
    except Exception:  # telemetry must never fail the verify path
        pass


def record_prepare(ndev: int, lanes_per_shard: int, seconds: float) -> None:
    """Host-side shard prep (prepare_rlc_shards): per-shard window sort +
    bucket boundaries."""
    with _LOCK:
        t = _STATS["totals"]
        t["prep_seconds"] += seconds
        t["prep_calls"] += 1
        _STATS["last_prep"] = {
            "shards": ndev,
            "lanes_per_shard": lanes_per_shard,
            "seconds": round(seconds, 6),
            "ts": time.time(),
        }
    try:
        _metrics().prep_seconds.inc(seconds)
    except Exception:
        pass


def record_pad(requested_lanes: int, padded_lanes: int) -> None:
    """Lane padding chosen by the routing layer (crypto/batch
    _verify_batch_rlc_sharded knows the real batch size; sharded.py only
    ever sees the padded arrays)."""
    waste = (
        (padded_lanes - requested_lanes) / padded_lanes if padded_lanes else 0.0
    )
    with _LOCK:
        last = _STATS.setdefault("last_pad", {})
        last.update(
            requested_lanes=requested_lanes,
            padded_lanes=padded_lanes,
            pad_waste_fraction=round(waste, 4),
        )
    try:
        _metrics().pad_waste_fraction.set(waste)
    except Exception:
        pass


def record_flush(
    kind: str,
    *,
    ndev: int,
    shard_lanes: int,
    submit_s: float,
    finish_s: float,
    all_gather_bytes: int = 0,
    devices: Optional[List[str]] = None,
    ok: Optional[bool] = None,
) -> None:
    """One sharded flush completed: `submit_s` = wall blocked dispatching
    the shard_map program, `finish_s` = wall blocked syncing its result
    (through a tunnel the finish dominates; per-shard skew hides inside it)."""
    with _LOCK:
        _STATS["flushes"][kind] = _STATS["flushes"].get(kind, 0) + 1
        t = _STATS["totals"]
        t["submit_seconds"] += submit_s
        t["finish_seconds"] += finish_s
        if all_gather_bytes:
            t["all_gathers"] += 1
            t["all_gather_bytes"] += all_gather_bytes
        _STATS["last_flush"] = {
            "kind": kind,
            "shards": ndev,
            "lanes_per_shard": shard_lanes,
            "lanes_total": shard_lanes * ndev,
            "submit_ms": round(submit_s * 1e3, 3),
            "finish_ms": round(finish_s * 1e3, 3),
            "all_gather_bytes": all_gather_bytes,
            "ok": ok,
            "ts": time.time(),
        }
    try:
        m = _metrics()
        m.flushes.labels(kind).inc()
        m.submit_seconds.inc(submit_s)
        m.finish_seconds.inc(finish_s)
        if all_gather_bytes:
            m.all_gathers.inc()
            m.all_gather_bytes.inc(all_gather_bytes)
        for i in range(ndev):
            dev = devices[i] if devices and i < len(devices) else str(i)
            m.shard_lanes.labels(dev).set(shard_lanes)
    except Exception:
        pass
    try:
        from tendermint_tpu.libs.trace import tracer

        if tracer.enabled:
            tracer.event(
                "mesh.flush",
                kind=kind,
                shards=ndev,
                lanes_per_shard=shard_lanes,
                submit_ms=round(submit_s * 1e3, 3),
                finish_ms=round(finish_s * 1e3, 3),
            )
    except Exception:
        pass


def record_rebuild(from_devices: int, to_devices: int, seconds: float) -> None:
    """One mesh rebuild (crypto/batch._sharded_env): the topology changed
    size — a device died (shrink) or re-joined after clean probes (grow)."""
    with _LOCK:
        _STATS["rebuilds"] += 1
        _STATS["last_rebuild"] = {
            "from_devices": int(from_devices),
            "to_devices": int(to_devices),
            "seconds": round(seconds, 6),
            "ts": time.time(),
        }
    try:
        _metrics().rebuilds.inc()
    except Exception:
        pass
    try:
        from tendermint_tpu.libs.trace import tracer

        if tracer.enabled:
            tracer.event(
                "mesh.rebuild",
                from_devices=int(from_devices),
                to_devices=int(to_devices),
                seconds=round(seconds, 6),
            )
    except Exception:
        pass


# Encoded ladder rungs for the tendermint_tpu_mesh_ladder_state gauge; keep
# in sync with parallel/health.LADDER_GAUGE.
_LADDER_GAUGE = {"full": 0, "survivor": 1, "single": 2, "host": 3}


def record_mesh_health(snapshot: dict, ladder: str) -> None:
    """Per-device health + ladder rung (crypto/batch._publish_mesh_health).
    The device-health gauge is replace_series'd: a departed device's series
    DROPS from /metrics instead of freezing at its last value."""
    with _LOCK:
        _STATS["health"] = snapshot
        _STATS["ladder"] = ladder
    try:
        m = _metrics()
        values = {}
        for dev, st in (snapshot.get("devices") or {}).items():
            if st.get("state") == "healthy":
                v = 1.0
            elif st.get("clean_probes", 0) > 0:
                v = 0.5  # dead but probing clean: mid-rejoin
            else:
                v = 0.0
            values[(dev,)] = v
        m.device_health.replace_series(values)
        m.ladder_state.set(_LADDER_GAUGE.get(ladder, 2))
    except Exception:
        pass


def record_aot(result: str) -> None:
    """AOT artifact-cache outcome (ops/aot_cache.py): `hit` (deserialized),
    `miss` (fresh export), `corrupt` (deleted + re-exported). Machine-scoped
    keys mean a foreign host's artifacts show up here as misses — the
    observable that distinguishes a healthy cold start from the
    cpu_aot_loader mismatch that killed MULTICHIP r04/r05."""
    with _LOCK:
        _STATS["aot_cache"][result] = _STATS["aot_cache"].get(result, 0) + 1
    try:
        _metrics().aot_cache.labels(result).inc()
    except Exception:
        pass


def mesh_stats() -> dict:
    """Snapshot for /debug/mesh, the verify_stats `mesh` block, and the
    multichip dryrun tail."""
    with _LOCK:
        out = {
            "mesh": dict(_STATS["mesh"]) if _STATS["mesh"] else None,
            "flushes": dict(_STATS["flushes"]),
            "totals": {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in _STATS["totals"].items()
            },
            "last_flush": dict(_STATS["last_flush"]) if _STATS["last_flush"] else None,
            "last_prep": dict(_STATS["last_prep"]) if _STATS["last_prep"] else None,
            "last_pad": dict(_STATS.get("last_pad") or {}) or None,
            "aot_cache": dict(_STATS["aot_cache"]),
            "ladder": _STATS.get("ladder"),
            "rebuilds": _STATS.get("rebuilds", 0),
            "last_rebuild": (
                dict(_STATS["last_rebuild"]) if _STATS.get("last_rebuild") else None
            ),
        }
    # Health reads LIVE from the manager (jax-free) so /debug/mesh shows
    # probe streaks as they advance, not the last pushed snapshot.
    try:
        from tendermint_tpu.parallel.health import MESH_HEALTH

        out["health"] = MESH_HEALTH.snapshot()
    except Exception:
        out["health"] = _STATS.get("health")
    return out


def reset() -> None:
    """Test hook: zero the aggregated mesh telemetry (not the metrics)."""
    global _STATS
    with _LOCK:
        _STATS = _fresh()
