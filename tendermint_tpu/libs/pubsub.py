"""Query-indexed pub/sub (reference: libs/pubsub/pubsub.go:91 + query DSL).

Events are (type, attributes) maps; subscriptions carry a Query that matches
composite key=value conditions. The query language covers the reference
grammar (reference: libs/pubsub/query/query.go): `key = 'value'`, numeric
comparisons =, <, <=, >, >=, CONTAINS, EXISTS, conjunctions with AND, and
chronological comparisons against `TIME <RFC3339>` / `DATE <YYYY-MM-DD>`
operands (e.g. `block.timestamp >= TIME 2013-05-03T14:45:00Z`)."""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from datetime import date, datetime, timezone
from typing import Dict, List, Optional, Tuple

_CONDITION_RE = re.compile(
    r"\s*([\w.]+)\s*(=|<=|>=|<|>|CONTAINS|EXISTS)\s*"
    r"((?:TIME|DATE)\s+[\w.:+\-]+|'(?:[^']*)'|\"(?:[^\"]*)\"|[\w.\-+]+)?\s*"
)


def _parse_rfc3339(raw: str) -> datetime:
    """RFC3339 timestamp or bare date -> aware datetime (UTC default)."""
    s = raw.strip()
    if s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt


@dataclass(frozen=True)
class Condition:
    key: str
    op: str
    value: str = ""
    # chronological operand: datetime parsed from TIME/DATE literals
    # (reference: libs/pubsub/query/query.go time/date conditions)
    time_value: Optional[datetime] = None


class Query:
    """Parsed conjunction of conditions."""

    def __init__(self, query_str: str):
        self.query_str = query_str.strip()
        self.conditions: List[Condition] = []
        if self.query_str:
            for clause in self.query_str.split(" AND "):
                m = _CONDITION_RE.fullmatch(clause)
                if not m:
                    raise ValueError(f"invalid query clause: {clause!r}")
                key, op, raw = m.group(1), m.group(2), m.group(3)
                if op == "EXISTS":
                    self.conditions.append(Condition(key, op))
                    continue
                if raw is None:
                    raise ValueError(f"missing value in clause: {clause!r}")
                if raw.startswith(("TIME ", "TIME\t", "DATE ", "DATE\t")):
                    kind, _, lit = raw.partition(raw[4])
                    try:
                        if kind == "DATE":
                            d = date.fromisoformat(lit.strip())
                            tv = datetime(d.year, d.month, d.day, tzinfo=timezone.utc)
                        else:
                            tv = _parse_rfc3339(lit)
                    except ValueError as e:
                        raise ValueError(f"invalid {kind} literal in {clause!r}: {e}")
                    self.conditions.append(Condition(key, op, lit.strip(), tv))
                    continue
                if raw[0] in "'\"":
                    raw = raw[1:-1]
                self.conditions.append(Condition(key, op, raw))

    def matches(self, events: Dict[str, List[str]]) -> bool:
        for cond in self.conditions:
            values = events.get(cond.key)
            if values is None:
                return False
            if cond.op == "EXISTS":
                continue
            if cond.time_value is not None:
                ok = False
                for v in values:
                    try:
                        ev = _parse_rfc3339(v)
                    except ValueError:
                        continue
                    if (
                        (cond.op == "=" and ev == cond.time_value)
                        or (cond.op == "<" and ev < cond.time_value)
                        or (cond.op == "<=" and ev <= cond.time_value)
                        or (cond.op == ">" and ev > cond.time_value)
                        or (cond.op == ">=" and ev >= cond.time_value)
                    ):
                        ok = True
                        break
                if not ok:
                    return False
                continue
            if cond.op == "=":
                if cond.value not in values:
                    return False
            elif cond.op == "CONTAINS":
                if not any(cond.value in v for v in values):
                    return False
            else:
                ok = False
                for v in values:
                    try:
                        fv, cv = float(v), float(cond.value)
                    except ValueError:
                        continue
                    if (
                        (cond.op == "<" and fv < cv)
                        or (cond.op == "<=" and fv <= cv)
                        or (cond.op == ">" and fv > cv)
                        or (cond.op == ">=" and fv >= cv)
                    ):
                        ok = True
                        break
                if not ok:
                    return False
        return True

    def __str__(self) -> str:
        return self.query_str

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self.query_str == other.query_str

    def __hash__(self) -> int:
        return hash(self.query_str)


@dataclass
class Message:
    data: object
    events: Dict[str, List[str]]


class Subscription:
    def __init__(self, out_capacity: int = 100):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=out_capacity)
        self.cancelled = False
        self.cancel_reason = ""

    async def next(self) -> Message:
        msg = await self.queue.get()
        if msg is None:
            raise RuntimeError(f"subscription cancelled: {self.cancel_reason}")
        return msg


class PubSubServer:
    """In-process server. publish() is non-blocking: a subscriber whose buffer
    is full is cancelled (same policy as the reference's non-buffered
    subscriptions)."""

    def __init__(self):
        self._subs: Dict[Tuple[str, str], Tuple[Query, Subscription]] = {}

    def subscribe(self, subscriber: str, query: Query, out_capacity: int = 100) -> Subscription:
        key = (subscriber, query.query_str)
        if key in self._subs:
            raise ValueError("already subscribed")
        sub = Subscription(out_capacity)
        self._subs[key] = (query, sub)
        return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        key = (subscriber, query.query_str)
        entry = self._subs.pop(key, None)
        if entry is None:
            raise ValueError("subscription not found")
        _, sub = entry
        sub.cancelled = True
        sub.cancel_reason = "unsubscribed"
        try:
            sub.queue.put_nowait(None)
        except asyncio.QueueFull:
            pass

    def unsubscribe_all(self, subscriber: str) -> None:
        for key in [k for k in self._subs if k[0] == subscriber]:
            _, sub = self._subs.pop(key)
            sub.cancelled = True
            sub.cancel_reason = "unsubscribed"
            try:
                sub.queue.put_nowait(None)
            except asyncio.QueueFull:
                pass

    def publish(self, data: object, events: Dict[str, List[str]]) -> None:
        for key in list(self._subs.keys()):
            query, sub = self._subs[key]
            if not query.matches(events):
                continue
            try:
                sub.queue.put_nowait(Message(data, events))
            except asyncio.QueueFull:
                # Slow subscriber: cancel it (reference: pubsub.go send on full)
                sub.cancelled = True
                sub.cancel_reason = "client is not pulling messages fast enough"
                del self._subs[key]

    def num_clients(self) -> int:
        return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        return sum(1 for k in self._subs if k[0] == subscriber)
