"""Elastic mesh health model (ISSUE 19).

One process-global MeshHealthManager scores every mesh device from the
sharded submit/finish accounting (parallel/sharded.py feeds every runner
outcome here) plus a cheap per-device probe kernel, and drives the degrade
LADDER the verify stack walks when chips disappear:

    full      every visible device healthy, full power-of-two mesh
    survivor  >= 1 device dead, mesh rebuilt on the next power-of-two of
              the healthy survivors (crypto/batch._sharded_env re-keys on
              `generation`)
    single    fewer than 2 healthy devices (or the breaker's "mesh"
              backend is open): single-chip fused RLC
    host      the device backend itself is open (crypto/circuit_breaker):
              chunked host-RLC / CPU verify

Scoring is deliberately simple and monotone: `fail_threshold` consecutive
failures (or stall strikes) mark a device DEAD; a dead device re-joins
only after `rejoin_probes` CONSECUTIVE clean probes — the hysteresis that
keeps the ladder from flapping between full and survivor mesh when a chip
is marginal. Every healthy-set change bumps `generation`, which is the
mesh cache key in crypto/batch.py.

Attribution: a chaos-injected ShardFaultError names the sick device
directly; a real jit failure usually does not, so `record_failure` probes
each device of the failed mesh individually to find it. A failure no probe
can attribute counts as a strike against the breaker's "mesh" BACKEND
(crypto/circuit_breaker.py per-backend states) — three of those open the
mesh rung while the single-chip device path stays closed.

Deliberately jax-free at import time (the default probe imports jax lazily)
so the host-twin tier-1 tests drive the whole ladder without XLA.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

HEALTHY = "healthy"
DEAD = "dead"

LADDER_FULL = "full"
LADDER_SURVIVOR = "survivor"
LADDER_SINGLE = "single"
LADDER_HOST = "host"

# Gauge encoding for tendermint_tpu_mesh_ladder_state (libs/metrics.py).
LADDER_GAUGE = {
    LADDER_FULL: 0,
    LADDER_SURVIVOR: 1,
    LADDER_SINGLE: 2,
    LADDER_HOST: 3,
}


def _default_probe(device) -> None:
    """One tiny round trip pinned to THIS device — compile-free, same
    rationale as the breaker probe: 'is the chip/tunnel alive' is the
    question, not 'does the kernel compile'."""
    import jax
    import numpy as np

    np.asarray(jax.device_put(np.arange(8, dtype=np.int32), device))


class DeviceHealth:
    """Per-device score card. `key` is str(device) — stable across the
    rebuilds that discard the jax Device objects themselves."""

    __slots__ = (
        "key", "state", "consec_failures", "stall_strikes",
        "clean_probes", "failures_total", "last_error", "died_at",
    )

    def __init__(self, key: str) -> None:
        self.key = key
        self.state = HEALTHY
        self.consec_failures = 0
        self.stall_strikes = 0
        self.clean_probes = 0
        self.failures_total = 0
        self.last_error = ""
        self.died_at = 0.0

    def as_dict(self) -> dict:
        return {
            "state": self.state,
            "consec_failures": self.consec_failures,
            "stall_strikes": self.stall_strikes,
            "clean_probes": self.clean_probes,
            "failures_total": self.failures_total,
            "last_error": self.last_error,
        }


class MeshHealthManager:
    """Process-global health ranking + rejoin prober for the device mesh."""

    def __init__(self, probe: Callable = _default_probe) -> None:
        self._lock = threading.RLock()
        self._devices: Dict[str, DeviceHealth] = {}
        self._probe = probe
        self._intercept: Optional[Callable] = None  # chaos hook, runs first
        self._cfg = {
            "enabled": True,
            "fail_threshold": 2,
            "stall_threshold_s": 0.0,  # 0 disables stall scoring
            "rejoin_probes": 3,
            "probe_interval_s": 2.0,
        }
        self.generation = 0  # bumped on every healthy-set change
        self._probe_thread: Optional[threading.Thread] = None
        self._spawn_probe_thread = True
        self._on_rejoin: List[Callable] = []

    # -- configuration ----------------------------------------------------

    def configure(
        self,
        enabled: Optional[bool] = None,
        fail_threshold: Optional[int] = None,
        stall_threshold_s: Optional[float] = None,
        rejoin_probes: Optional[int] = None,
        probe_interval_s: Optional[float] = None,
    ) -> None:
        """Apply `[crypto] mesh_health_*` config (node/node.py). Process-
        global, last node wins — same model as the breaker."""
        with self._lock:
            if enabled is not None:
                self._cfg["enabled"] = bool(enabled)
            if fail_threshold is not None:
                self._cfg["fail_threshold"] = max(1, int(fail_threshold))
            if stall_threshold_s is not None:
                self._cfg["stall_threshold_s"] = max(0.0, float(stall_threshold_s))
            if rejoin_probes is not None:
                self._cfg["rejoin_probes"] = max(1, int(rejoin_probes))
            if probe_interval_s is not None:
                self._cfg["probe_interval_s"] = max(0.05, float(probe_interval_s))

    def set_probe(self, fn: Optional[Callable]) -> None:
        """Replace the per-device probe (tests; None restores the default)."""
        with self._lock:
            self._probe = fn or _default_probe

    def set_probe_intercept(self, fn: Optional[Callable]) -> None:
        """Chaos hook: runs BEFORE the real probe so an injected device loss
        also fails probes (chaos/device.DeviceFaultInjector installs this)."""
        with self._lock:
            self._intercept = fn

    def add_rejoin_listener(self, fn: Callable) -> None:
        """Called (no args, outside the lock) whenever a device re-joins —
        crypto/batch uses this to drop the stale mesh runner eagerly."""
        with self._lock:
            if fn not in self._on_rejoin:
                self._on_rejoin.append(fn)

    def reset(self) -> None:
        """Forget all device history (tests / fresh topologies)."""
        with self._lock:
            self._devices.clear()
            self.generation += 1

    # -- scoring ----------------------------------------------------------

    def _entry(self, key: str) -> DeviceHealth:
        dh = self._devices.get(key)
        if dh is None:
            dh = self._devices[key] = DeviceHealth(key)
        return dh

    def record_success(self, devices: Sequence, elapsed_s: float = 0.0) -> None:
        """A sharded call over `devices` returned cleanly. Clears consecutive
        failure counts; scores a stall strike instead when the call's wall
        exceeded the stall threshold (a wedged-but-not-dead chip drags every
        shard, so the strike lands on all participants)."""
        if not self._cfg["enabled"]:
            return
        thr = self._cfg["stall_threshold_s"]
        stalled = thr > 0.0 and elapsed_s > thr
        with self._lock:
            changed = False
            for d in devices:
                dh = self._entry(str(d))
                if dh.state != HEALTHY:
                    continue
                dh.consec_failures = 0
                if stalled:
                    dh.stall_strikes += 1
                    if dh.stall_strikes >= self._cfg["fail_threshold"]:
                        changed |= self._mark_dead_locked(dh, "stall")
                else:
                    dh.stall_strikes = 0
            if changed:
                self.generation += 1
        if stalled:
            self._ensure_probe_thread()

    def record_failure(self, devices: Sequence, error: BaseException) -> bool:
        """A sharded call over `devices` raised. Attribute the failure to a
        device (ShardFaultError names it; otherwise probe each participant)
        and score it. Returns True when the healthy set changed (the caller
        must invalidate its mesh cache); False means the failure could not
        be pinned on any device — the caller should strike the breaker's
        "mesh" backend instead."""
        if not self._cfg["enabled"]:
            return False
        keys = [str(d) for d in devices]
        sick = self._attribute(keys, error)
        try:
            # stamp the exception so layered handlers (sharded._guarded,
            # crypto/batch's replay loop) never double-score one failure,
            # and so the caller can tell "attributed to a device" from
            # "mesh-collective failure" (-> breaker backend strike)
            error._mesh_scored = True
            error._mesh_attributed = bool(sick)
        except Exception:
            pass
        if not sick:
            return False
        changed = False
        with self._lock:
            for key in sick:
                dh = self._entry(key)
                dh.consec_failures += 1
                dh.failures_total += 1
                dh.last_error = repr(error)[:200]
                if (
                    dh.state == HEALTHY
                    and dh.consec_failures >= self._cfg["fail_threshold"]
                ):
                    changed |= self._mark_dead_locked(dh, repr(error)[:200])
            if changed:
                self.generation += 1
        self._ensure_probe_thread()
        return changed

    def mark_device_lost(self, device) -> bool:
        """Administrative / chaos kill: the device is gone NOW, no threshold
        accounting. Returns True when the healthy set changed."""
        with self._lock:
            dh = self._entry(str(device))
            dh.failures_total += 1
            dh.last_error = "device_lost"
            if dh.state == HEALTHY:
                self._mark_dead_locked(dh, "device_lost")
                self.generation += 1
                changed = True
            else:
                changed = False
        self._ensure_probe_thread()
        return changed

    def _mark_dead_locked(self, dh: DeviceHealth, reason: str) -> bool:
        dh.state = DEAD
        dh.clean_probes = 0
        dh.died_at = time.monotonic()
        dh.last_error = reason
        return True

    def _attribute(self, keys: List[str], error: BaseException) -> List[str]:
        """Which of `keys` is sick? ShardFaultError carries the answer; any
        other failure is localized by probing each participant."""
        dev = getattr(error, "device", None)
        if dev is not None:
            key = str(dev)
            return [key] if key in keys or not keys else [key]
        shard = getattr(error, "shard", None)
        if shard is not None and 0 <= int(shard) < len(keys):
            return [keys[int(shard)]]
        sick = []
        for key in keys:
            if not self._probe_one(key):
                sick.append(key)
        return sick

    # -- probing / rejoin -------------------------------------------------

    def _probe_one(self, key: str) -> bool:
        """Probe the device whose str() is `key`. The intercept (chaos) sees
        the key first; the real probe needs the live Device object, resolved
        from jax.devices() — a departed chip simply fails resolution."""
        intercept = self._intercept
        probe = self._probe
        try:
            if intercept is not None:
                intercept(key)
            if probe is _default_probe:
                import jax

                for d in jax.devices():
                    if str(d) == key:
                        probe(d)
                        return True
                return False
            probe(key)
            return True
        except Exception:
            return False

    def probe_round(self) -> bool:
        """One rejoin pass over the dead devices: a clean probe increments
        the device's streak, a failed probe resets it; `rejoin_probes`
        consecutive clean probes re-admit the device (generation bump, so
        the next _sharded_env call rebuilds toward the full mesh). Callable
        directly from tests; the background thread just loops it. Returns
        True when any device re-joined."""
        with self._lock:
            dead = [dh.key for dh in self._devices.values() if dh.state == DEAD]
            need = self._cfg["rejoin_probes"]
        rejoined = []
        for key in dead:
            ok = self._probe_one(key)
            with self._lock:
                dh = self._devices.get(key)
                if dh is None or dh.state != DEAD:
                    continue
                if ok:
                    dh.clean_probes += 1
                    if dh.clean_probes >= need:
                        dh.state = HEALTHY
                        dh.consec_failures = 0
                        dh.stall_strikes = 0
                        dh.last_error = ""
                        self.generation += 1
                        rejoined.append(key)
                else:
                    dh.clean_probes = 0
        if rejoined:
            for fn in list(self._on_rejoin):
                try:
                    fn()
                except Exception:
                    pass
        return bool(rejoined)

    def _ensure_probe_thread(self) -> None:
        if not self._spawn_probe_thread:
            return
        with self._lock:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                return
            if not any(dh.state == DEAD for dh in self._devices.values()):
                return
            t = threading.Thread(
                target=self._probe_loop, name="mesh-health-probe", daemon=True
            )
            self._probe_thread = t
        t.start()

    def _probe_loop(self) -> None:
        while True:
            with self._lock:
                interval = self._cfg["probe_interval_s"]
                alive = any(dh.state == DEAD for dh in self._devices.values())
            if not alive:
                return  # nothing left to nurse; thread respawns on next death
            time.sleep(interval)
            try:
                self.probe_round()
            except Exception:
                pass

    # -- queries ----------------------------------------------------------

    def healthy_devices(self, devices: Sequence) -> list:
        """Filter a jax.devices() list down to the healthy members, in mesh
        order. Unknown devices are healthy by default (no history = no
        penalty)."""
        if not self._cfg["enabled"]:
            return list(devices)
        with self._lock:
            out = []
            for d in devices:
                dh = self._devices.get(str(d))
                if dh is None or dh.state == HEALTHY:
                    out.append(d)
            return out

    def dead_count(self) -> int:
        with self._lock:
            return sum(1 for dh in self._devices.values() if dh.state == DEAD)

    def ladder_state(
        self, n_visible: int, mesh_devices: int, device_open: bool, mesh_open: bool
    ) -> str:
        """Name the active rung. Inputs come from the caller (crypto/batch)
        because only it knows the live topology: visible device count, the
        mesh size actually in use, and the two breaker gates."""
        if device_open:
            return LADDER_HOST
        if mesh_open or mesh_devices < 2:
            return LADDER_SINGLE
        if self.dead_count() > 0 or (n_visible and mesh_devices < n_visible):
            return LADDER_SURVIVOR
        return LADDER_FULL

    def snapshot(self) -> dict:
        """Per-device health for /debug/mesh, /debug/verify_stats and the
        MULTICHIP dryrun tail."""
        with self._lock:
            return {
                "enabled": self._cfg["enabled"],
                "generation": self.generation,
                "fail_threshold": self._cfg["fail_threshold"],
                "rejoin_probes": self._cfg["rejoin_probes"],
                "dead": self.dead_count(),
                "devices": {
                    key: dh.as_dict() for key, dh in sorted(self._devices.items())
                },
            }


MESH_HEALTH = MeshHealthManager()


def configure_mesh_health(**kwargs) -> None:
    """Apply `[crypto] mesh_health_*` config (node/node.py)."""
    MESH_HEALTH.configure(**kwargs)
