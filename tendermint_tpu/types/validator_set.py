"""Validator, ValidatorSet: proposer selection and BATCHED commit verification.

Re-implements the reference's types/validator.go + types/validator_set.go:
- weighted-round-robin proposer selection with priority centering/rescaling
  (reference: types/validator_set.go:113-247)
- validator-set updates with the H+2 semantics handled by the state layer
  (reference: types/validator_set.go:474-637)
- VerifyCommit / VerifyCommitLight / VerifyCommitLightTrusting
  (reference: types/validator_set.go:662,719,772)

THE key TPU-native departure: the reference verifies commit signatures in a
serial for-loop, one scalar ed25519 verify per validator
(reference: types/validator_set.go:680-702). Here every Verify* call gathers
all (pubkey, sign-bytes, signature) triples and flushes them through
crypto.batch.verify_batch — one vmap'd kernel launch over the validator axis.

Documented divergence: the Light/LightTrusting variants verify all relevant
signatures in one batch and tally only the valid ones, instead of the
reference's sequential early-exit at 2/3 — acceptance requires the same
+2/3 (or trust-level) threshold of *valid* signatures, but a commit whose
early signature is bad and later ones are good is accepted here if the valid
tally clears the threshold (the reference fails fast). This is strictly a
liveness-friendly relaxation; safety is unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.crypto.batch import verify_batch
from tendermint_tpu.crypto.keys import PubKey
from tendermint_tpu.crypto.merkle import hash_from_byte_slices
from tendermint_tpu.libs import protowire as pw

INT64_MAX = 2**63 - 1
MAX_TOTAL_VOTING_POWER = INT64_MAX // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2


class CommitVerifyError(Exception):
    pass


class NotEnoughVotingPowerError(CommitVerifyError):
    def __init__(self, got: int, needed: int):
        super().__init__(f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}")
        self.got = got
        self.needed = needed


def _clip64(x: int) -> int:
    return max(-(2**63), min(INT64_MAX, x))


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int
    address: bytes = b""
    proposer_priority: int = 0

    def __post_init__(self):
        if not self.address:
            self.address = self.pub_key.address()

    def copy(self) -> "Validator":
        return Validator(self.pub_key, self.voting_power, self.address, self.proposer_priority)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != tmhash.TRUNCATED_SIZE:
            raise ValueError("validator address is the wrong size")

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; tie broken by ascending address
        (reference: types/validator.go:64-84)."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("cannot compare identical validators")

    def simple_bytes(self) -> bytes:
        """SimpleValidator proto encoding used in ValidatorSet.Hash
        (reference: types/validator.go ToProto + types/validator_set.go Hash)."""
        pk = pw.Writer()
        if self.pub_key.type_name() == "ed25519":
            pk.bytes_field(1, self.pub_key.bytes())
        elif self.pub_key.type_name() == "sr25519":
            pk.bytes_field(3, self.pub_key.bytes())
        elif self.pub_key.type_name() == "bls12_381":
            pk.bytes_field(4, self.pub_key.bytes())
        else:
            raise ValueError(f"unsupported key type {self.pub_key.type_name()}")
        w = pw.Writer()
        w.message_field(1, pk.bytes(), always=True)
        w.varint_field(2, self.voting_power)
        return w.bytes()


class ValidatorSet:
    """Sorted validator set + proposer. Sorting: descending voting power,
    ties by ascending address (reference: types/validator_set.go ValidatorsByVotingPower)."""

    def __init__(self, validators: Sequence[Validator], proposer: Optional[Validator] = None):
        self.validators: List[Validator] = sorted(
            (v.copy() for v in validators),
            key=lambda v: (-v.voting_power, v.address),
        )
        self._total_voting_power: Optional[int] = None
        self._by_address: Dict[bytes, int] = {
            v.address: i for i, v in enumerate(self.validators)
        }
        if len(self._by_address) != len(self.validators):
            raise ValueError("duplicate validator address")
        self.proposer: Optional[Validator] = proposer
        if self.proposer is None and self.validators:
            self.proposer = self._compute_proposer()

    # -- basic accessors ----------------------------------------------------

    def __len__(self) -> int:
        return len(self.validators)

    def size(self) -> int:
        return len(self.validators)

    def is_nil_or_empty(self) -> bool:
        return len(self.validators) == 0

    def has_address(self, address: bytes) -> bool:
        return address in self._by_address

    def get_by_address(self, address: bytes) -> Tuple[int, Optional[Validator]]:
        idx = self._by_address.get(address)
        if idx is None:
            return -1, None
        return idx, self.validators[idx]

    def get_by_index(self, index: int) -> Tuple[bytes, Optional[Validator]]:
        if index < 0 or index >= len(self.validators):
            return b"", None
        v = self.validators[index]
        return v.address, v

    def total_voting_power(self) -> int:
        if self._total_voting_power is None:
            tot = 0
            for v in self.validators:
                tot = _clip64(tot + v.voting_power)
            self._total_voting_power = tot
        return self._total_voting_power

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet.__new__(ValidatorSet)
        vs.validators = [v.copy() for v in self.validators]
        vs._total_voting_power = self._total_voting_power
        vs._by_address = dict(self._by_address)
        vs.proposer = self.proposer.copy() if self.proposer else None
        return vs

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValueError("validator set is nil or empty")
        for v in self.validators:
            v.validate_basic()
        if self.proposer is None:
            raise ValueError("proposer failed validate basic, error: nil validator")
        self.proposer.validate_basic()

    def hash(self) -> bytes:
        """Merkle root of SimpleValidator encodings (reference:
        types/validator_set.go Hash)."""
        return hash_from_byte_slices([v.simple_bytes() for v in self.validators])

    # -- proposer selection -------------------------------------------------

    def _compute_proposer(self) -> Validator:
        res = self.validators[0]
        for v in self.validators[1:]:
            res = res.compare_proposer_priority(v)
        return res

    def get_proposer(self) -> Validator:
        if not self.validators:
            raise ValueError("empty validator set")
        if self.proposer is None:
            self.proposer = self._compute_proposer()
        return self.proposer

    def _compute_avg_proposer_priority(self) -> int:
        n = len(self.validators)
        s = sum(v.proposer_priority for v in self.validators)
        # Go big.Int.Div is Euclidean (non-negative remainder), which for a
        # positive divisor equals Python floor division.
        return s // n

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = self._compute_avg_proposer_priority()
        for v in self.validators:
            v.proposer_priority = _clip64(v.proposer_priority - avg)

    def rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        if diff < 0:
            diff = -diff
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                # Go integer division truncates toward zero
                p = v.proposer_priority
                v.proposer_priority = -((-p) // ratio) if p < 0 else p // ratio

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = _clip64(v.proposer_priority + v.voting_power)
        mostest = self._compute_proposer()
        mostest.proposer_priority = _clip64(
            mostest.proposer_priority - self.total_voting_power()
        )
        return mostest

    def increment_proposer_priority(self, times: int) -> None:
        """(reference: types/validator_set.go:116-138)"""
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("cannot call IncrementProposerPriority with non-positive times")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    # -- updates ------------------------------------------------------------

    def update_with_change_set(self, changes: Sequence[Validator]) -> None:
        """Apply validator updates/removals (power 0 = removal).
        (reference: types/validator_set.go:577-652 updateWithChangeSet)"""
        if not changes:
            return
        # split and sanity-check
        seen = set()
        updates: List[Validator] = []
        deletes: List[Validator] = []
        # Copy first: priorities are assigned to update entries below and must
        # not leak into the caller's objects.
        for c in sorted((c.copy() for c in changes), key=lambda v: v.address):
            if c.address in seen:
                raise ValueError(f"duplicate entry {c.address.hex()} in changes")
            seen.add(c.address)
            if c.voting_power < 0:
                raise ValueError("voting power can't be negative")
            if c.voting_power > MAX_TOTAL_VOTING_POWER:
                raise ValueError("to prevent clipping/overflow, voting power can't be higher than max")
            if c.voting_power == 0:
                deletes.append(c)
            else:
                updates.append(c)
        # verify deletes exist
        for d in deletes:
            if d.address not in self._by_address:
                raise ValueError(f"failed to find validator {d.address.hex()} to remove")
        # compute the new total voting power (before removals, like the reference)
        new_total = self.total_voting_power()
        for u in updates:
            _, old = self.get_by_address(u.address)
            new_total += u.voting_power - (old.voting_power if old else 0)
            if new_total > MAX_TOTAL_VOTING_POWER:
                raise ValueError("total voting power of resulting valset exceeds max")
        # new validators join with priority -1.125 * newTotal
        # (reference: types/validator_set.go:474-493)
        for u in updates:
            _, old = self.get_by_address(u.address)
            if old is None:
                u.proposer_priority = -(new_total + (new_total >> 3))
            else:
                u.proposer_priority = old.proposer_priority
        # apply
        by_addr = {v.address: v for v in self.validators}
        for u in updates:
            by_addr[u.address] = u.copy()
        for d in deletes:
            by_addr.pop(d.address, None)
        if not by_addr:
            raise ValueError("applying the validator changes would result in empty set")
        self.validators = sorted(
            by_addr.values(), key=lambda v: (-v.voting_power, v.address)
        )
        self._by_address = {v.address: i for i, v in enumerate(self.validators)}
        self._total_voting_power = None
        # scale and center
        self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
        self._shift_by_avg_proposer_priority()
        # keep proposer reference coherent
        if self.proposer is not None and self.proposer.address in self._by_address:
            self.proposer = self.validators[self._by_address[self.proposer.address]]
        elif self.validators:
            self.proposer = self._compute_proposer()

    # -- batched commit verification ---------------------------------------

    def verify_commit(self, chain_id: str, block_id, height: int, commit) -> None:
        """All signatures checked; +2/3 must be for the block.
        (reference: types/validator_set.go:662-714, serial loop replaced by one
        batched device verify)."""
        if self.size() != len(commit.signatures):
            raise CommitVerifyError(
                f"invalid commit -- wrong set size: {self.size()} vs {len(commit.signatures)}"
            )
        if height != commit.height:
            raise CommitVerifyError(f"invalid commit -- wrong height: {height} vs {commit.height}")
        if block_id != commit.block_id:
            raise CommitVerifyError(
                f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
            )
        pubkeys, sigs, meta, key_types, idxs = [], [], [], [], []
        for idx, cs in enumerate(commit.signatures):
            if cs.absent():
                continue
            val = self.validators[idx]
            pubkeys.append(val.pub_key.bytes())
            idxs.append(idx)
            sigs.append(cs.signature)
            meta.append((idx, val.voting_power, cs.for_block()))
            key_types.append(val.pub_key.type_name())
        msgs = commit.vote_sign_bytes_many(chain_id, idxs)
        mask = verify_batch(pubkeys, msgs, sigs, key_types=key_types)
        tallied = 0
        for ok, (idx, power, for_block) in zip(mask, meta):
            if not ok:
                raise CommitVerifyError(f"wrong signature (#{idx})")
            if for_block:
                tallied += power
        needed = self.total_voting_power() * 2 // 3
        if tallied <= needed:
            raise NotEnoughVotingPowerError(tallied, needed)

    def begin_verify_commit_light(self, chain_id: str, block_id, height: int, commit):
        """Submit-phase of verify_commit_light: structural checks + device
        submit; returns a finish() callable that syncs, tallies, and raises
        on failure. Lets callers overlap several independent commit
        verifications' device round trips (light/verifier.py pipelines the
        trusting+light pair this way)."""
        from tendermint_tpu.crypto.batch import verify_batch_finish, verify_batch_submit

        if self.size() != len(commit.signatures):
            raise CommitVerifyError(
                f"invalid commit -- wrong set size: {self.size()} vs {len(commit.signatures)}"
            )
        if height != commit.height:
            raise CommitVerifyError(f"invalid commit -- wrong height: {height} vs {commit.height}")
        if block_id != commit.block_id:
            raise CommitVerifyError(
                f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
            )
        pubkeys, sigs, powers, idxs = [], [], [], []
        key_types = []
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            val = self.validators[idx]
            pubkeys.append(val.pub_key.bytes())
            idxs.append(idx)
            sigs.append(cs.signature)
            powers.append(val.voting_power)
            key_types.append(val.pub_key.type_name())
        msgs = commit.vote_sign_bytes_many(chain_id, idxs)
        handle = verify_batch_submit(pubkeys, msgs, sigs, key_types=key_types)

        def finish() -> None:
            mask = verify_batch_finish(handle)
            tallied = sum(p for ok, p in zip(mask, powers) if ok)
            needed = self.total_voting_power() * 2 // 3
            if tallied <= needed:
                raise NotEnoughVotingPowerError(tallied, needed)

        return finish

    def verify_commit_light(self, chain_id: str, block_id, height: int, commit) -> None:
        """Only for-block signatures verified, batched; valid tally must exceed
        2/3 (reference: types/validator_set.go:719-763)."""
        self.begin_verify_commit_light(chain_id, block_id, height, commit)()

    def begin_verify_commit_light_trusting(
        self, chain_id: str, commit, trust_level: Fraction
    ):
        """Submit-phase of verify_commit_light_trusting; see
        begin_verify_commit_light."""
        from tendermint_tpu.crypto.batch import verify_batch_finish, verify_batch_submit

        if trust_level.denominator == 0:
            raise CommitVerifyError("trustLevel has zero Denominator")
        total_mul = self.total_voting_power() * trust_level.numerator
        needed = total_mul // trust_level.denominator
        seen: Dict[int, int] = {}
        pubkeys, sigs, powers, idxs = [], [], [], []
        key_types = []
        for idx, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            val_idx, val = self.get_by_address(cs.validator_address)
            if val is None:
                continue
            if val_idx in seen:
                raise CommitVerifyError(
                    f"double vote from {val.address.hex()} ({seen[val_idx]} and {idx})"
                )
            seen[val_idx] = idx
            pubkeys.append(val.pub_key.bytes())
            idxs.append(idx)
            sigs.append(cs.signature)
            powers.append(val.voting_power)
            key_types.append(val.pub_key.type_name())
        msgs = commit.vote_sign_bytes_many(chain_id, idxs)
        handle = verify_batch_submit(pubkeys, msgs, sigs, key_types=key_types)

        def finish() -> None:
            mask = verify_batch_finish(handle)
            tallied = sum(p for ok, p in zip(mask, powers) if ok)
            if tallied <= needed:
                raise NotEnoughVotingPowerError(tallied, needed)

        return finish

    def verify_commit_light_trusting(
        self, chain_id: str, commit, trust_level: Fraction
    ) -> None:
        """Trust-level verification against a possibly different validator set
        (reference: types/validator_set.go:772-830)."""
        self.begin_verify_commit_light_trusting(chain_id, commit, trust_level)()

    # -- BLS aggregate-commit verification (ISSUE 14) -----------------------

    def verify_aggregate_commit(self, chain_id: str, block_id, height: int, commit) -> None:
        """VerifyAggregateCommit: ONE pairing check + ONE bitmap-weighted
        aggregate-pubkey MSM, against the single canonical message every
        signer signed (types/block.AggregateCommit). No reference
        counterpart — the reference has no aggregate signatures at all.

        Routing: a plain Commit routes through verify_commit (the existing
        verify_batch ladder — device RLC, breaker, QoS lanes), so callers
        can pass whatever the wire delivered. The aggregate path:

        1. every bitmap validator must hold a bls12_381 key WITH a
           verified proof of possession (crypto/keys.register_pop) — the
           rogue-key defense; an unregistered key fails the commit, it is
           never silently skipped;
        2. apk = sum of signer pubkeys via the device-schedule MSM twin
           (ops/bls12_msm.g1_aggregate_bitmap; decompressed coordinates
           cached across heights like the ed25519 A cache);
        3. e(-g1, sigma) * e(apk, H(msg)) == 1 (bls_ref pairing);
        4. signer voting power must exceed 2/3 of the total.

        Raises CommitVerifyError / NotEnoughVotingPowerError like the
        other Verify* entries."""
        from tendermint_tpu.crypto import bls_ref
        from tendermint_tpu.crypto.batch import record_backend_rows
        from tendermint_tpu.crypto.keys import pop_verified
        from tendermint_tpu.libs import metrics as _metrics
        from tendermint_tpu.ops import bls12_msm
        from tendermint_tpu.types.block import AggregateCommit

        if not isinstance(commit, AggregateCommit):
            return self.verify_commit(chain_id, block_id, height, commit)
        commit.validate_basic()
        if height != commit.height:
            raise CommitVerifyError(
                f"invalid commit -- wrong height: {height} vs {commit.height}"
            )
        if block_id != commit.block_id:
            raise CommitVerifyError(
                f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
            )
        idxs = commit.signer_indices()
        if idxs and idxs[-1] >= self.size():
            raise CommitVerifyError(
                f"invalid commit -- signer index {idxs[-1]} out of range ({self.size()} validators)"
            )
        coords, powers = [], []
        for i in idxs:
            val = self.validators[i]
            if val.pub_key.type_name() != "bls12_381":
                raise CommitVerifyError(
                    f"invalid commit -- validator #{i} is {val.pub_key.type_name()}, "
                    "cannot join a BLS aggregate"
                )
            if not pop_verified(val.pub_key.bytes()):
                raise CommitVerifyError(
                    f"invalid commit -- validator #{i} has no verified proof of "
                    "possession (rogue-key defense)"
                )
            coords.append(_bls_pubkey_coords(val.pub_key.bytes()))
            powers.append(val.voting_power)
        record_backend_rows("bls12_381", len(idxs))
        m = _metrics.batch_metrics()
        m.aggregate_size.set(len(idxs))
        apk = bls12_msm.g1_aggregate_bitmap(coords, [True] * len(coords))
        if apk is None:
            raise CommitVerifyError("invalid commit -- empty aggregate pubkey")
        sig = bls_ref.g2_from_bytes(commit.agg_signature)
        if sig is None:
            raise CommitVerifyError("invalid commit -- malformed aggregate signature")
        apk_jac = (
            bls_ref._G1Field(apk[0]),
            bls_ref._G1Field(apk[1]),
            bls_ref._G1Field(1),
        )
        msg = commit.sign_bytes(chain_id)
        ok = bls_ref.pairings_are_one(
            [
                (bls_ref._jac_neg(bls_ref.G1_GEN), sig),
                (apk_jac, bls_ref.hash_to_g2(msg)),
            ]
        )
        if not ok:
            raise CommitVerifyError("invalid commit -- aggregate signature mismatch")
        tallied = sum(powers)
        needed = self.total_voting_power() * 2 // 3
        if tallied <= needed:
            raise NotEnoughVotingPowerError(tallied, needed)


# Decompressed BLS pubkey coordinate cache: consensus re-verifies the same
# validator set every height, and the 48-byte -> affine decompression (one
# field sqrt + subgroup check) is the per-key host cost worth amortizing —
# the exact shape of crypto/batch.py's ed25519 A cache.
_BLS_COORD_CACHE: Dict[bytes, Tuple[int, int]] = {}


def _bls_pubkey_coords(pk_bytes: bytes) -> Tuple[int, int]:
    got = _BLS_COORD_CACHE.get(pk_bytes)
    if got is not None:
        return got
    from tendermint_tpu.crypto import bls_ref

    pt = bls_ref.g1_from_bytes(pk_bytes)
    if pt is None:
        raise CommitVerifyError("invalid bls12_381 pubkey in validator set")
    aff = bls_ref._jac_to_affine(pt)
    got = (aff[0].v, aff[1].v)
    if len(_BLS_COORD_CACHE) < 1 << 20:
        _BLS_COORD_CACHE[bytes(pk_bytes)] = got
    return got
