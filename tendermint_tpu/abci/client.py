"""ABCI clients (reference: abci/client/client.go:22).

LocalClient: direct in-process calls under one lock (reference:
abci/client/local_client.go:15) — the default for in-proc apps. The socket
client/server for out-of-process apps lives in abci.socket.

ReconnectingClient: resilience wrapper for the NON-consensus connections
(mempool/query/snapshot): on a broken pipe / dead socket / per-call timeout
it rebuilds the underlying client with exponential backoff and retries, so
an app restart costs rechecks a few retries instead of crashing the node.
The consensus connection is never wrapped — a consensus-conn failure stays
fatal-loud, matching the reference (proxy/multi_app_conn.go kills the node
when the consensus client dies)."""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time
from typing import Callable, Optional

from tendermint_tpu.abci import types as abci

logger = logging.getLogger("tendermint_tpu.abci")


class ABCIClient:
    """Synchronous 17-method client interface. Async pipelining is layered on
    top by callers that need it (the executor batches DeliverTx itself)."""

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def set_option(self, req: abci.RequestSetOption) -> abci.ResponseSetOption:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    def list_snapshots(self) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req: abci.RequestOfferSnapshot) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError

    def echo(self, msg: str) -> str:
        return msg

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class ReconnectingClient(ABCIClient):
    """Delegates every ABCI method to a lazily (re)created inner client;
    transport failures tear the inner client down and retry on a fresh one
    with exponential backoff ([base] abci_reconnect_*). Only transport
    errors are retried — an app-level exception response passes through."""

    RETRIABLE = (
        ConnectionError,
        BrokenPipeError,
        OSError,
        TimeoutError,
        concurrent.futures.TimeoutError,  # distinct from TimeoutError on py<=3.10
    )

    def __init__(
        self,
        creator: Callable[[], "ABCIClient"],
        attempts: int = 5,
        base_delay: float = 0.2,
        max_delay: float = 5.0,
        name: str = "abci",
    ):
        self._creator = creator
        self.attempts = attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.name = name
        self.reconnects = 0  # successful inner-client rebuilds after a failure
        self._client: Optional[ABCIClient] = None
        self._had_failure = False
        self._lock = threading.Lock()

    def _get(self) -> ABCIClient:
        with self._lock:
            c = self._client
            if c is not None and not getattr(c, "is_dead", lambda: False)():
                return c
            if c is not None:
                self._had_failure = True
                try:
                    c.close()
                except Exception:
                    pass
            self._client = self._creator()
            if self._had_failure:
                self.reconnects += 1
                self._had_failure = False
            return self._client

    def _drop(self, client: ABCIClient) -> None:
        with self._lock:
            self._had_failure = True
            if self._client is client:
                self._client = None
        try:
            client.close()
        except Exception:
            pass

    def _call(self, method: str, *args):
        last: Optional[Exception] = None
        for attempt in range(self.attempts + 1):
            if attempt > 0:
                delay = min(self.base_delay * (2 ** (attempt - 1)), self.max_delay)
                logger.warning(
                    "ABCI %s conn %s failed (%s); reconnect attempt %d in %.2fs",
                    self.name, method, last, attempt, delay,
                )
                time.sleep(delay)
            try:
                client = self._get()
            except self.RETRIABLE as e:  # app still down: keep backing off
                last = e
                continue
            try:
                return getattr(client, method)(*args)
            except self.RETRIABLE as e:
                last = e
                self._drop(client)
        raise ConnectionError(
            f"ABCI {self.name} connection failed after "
            f"{self.attempts + 1} attempts: {last}"
        )

    def close(self) -> None:
        with self._lock:
            c, self._client = self._client, None
        if c is not None:
            try:
                c.close()
            except Exception:
                pass

    def flush(self) -> None:
        with self._lock:
            c = self._client
        if c is not None:
            try:
                c.flush()
            except self.RETRIABLE:
                self._drop(c)

    def info(self, req):
        return self._call("info", req)

    def set_option(self, req):
        return self._call("set_option", req)

    def query(self, req):
        return self._call("query", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def begin_block(self, req):
        return self._call("begin_block", req)

    def deliver_tx(self, req):
        return self._call("deliver_tx", req)

    def end_block(self, req):
        return self._call("end_block", req)

    def commit(self):
        return self._call("commit")

    def list_snapshots(self):
        return self._call("list_snapshots")

    def offer_snapshot(self, req):
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("apply_snapshot_chunk", req)


class LocalClient(ABCIClient):
    """Direct calls to an in-process Application under a shared mutex —
    mirrors the reference's local_client semantics where all connections to
    one app serialize on one lock (reference: abci/client/local_client.go:23)."""

    def __init__(self, app: abci.Application, lock: Optional[threading.RLock] = None):
        self.app = app
        self.lock = lock or threading.RLock()

    def info(self, req):
        with self.lock:
            return self.app.info(req)

    def set_option(self, req):
        with self.lock:
            return self.app.set_option(req)

    def query(self, req):
        with self.lock:
            return self.app.query(req)

    def check_tx(self, req):
        with self.lock:
            return self.app.check_tx(req)

    def init_chain(self, req):
        with self.lock:
            return self.app.init_chain(req)

    def begin_block(self, req):
        with self.lock:
            return self.app.begin_block(req)

    def deliver_tx(self, req):
        with self.lock:
            return self.app.deliver_tx(req)

    def end_block(self, req):
        with self.lock:
            return self.app.end_block(req)

    def commit(self):
        with self.lock:
            return self.app.commit()

    def list_snapshots(self):
        with self.lock:
            return self.app.list_snapshots()

    def offer_snapshot(self, req):
        with self.lock:
            return self.app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        with self.lock:
            return self.app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        with self.lock:
            return self.app.apply_snapshot_chunk(req)
