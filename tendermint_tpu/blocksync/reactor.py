"""Fast-sync reactor (v0-shaped): download blocks from peers, verify commits
BATCHED on the TPU, apply, then hand off to consensus
(reference: blockchain/v0/reactor.go:104,116,207; channel 0x40 :19).

TPU-first design: the reference verifies each block's commit serially
(VerifyCommitLight per block inside poolRoutine). Here the sync routine
drains a run of up to VERIFY_BATCH_BLOCKS contiguous downloaded blocks and
verifies ALL their commit signatures in one device batch (blocks x validators
on the trailing batch axis — BASELINE config 4), then applies sequentially."""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional

from tendermint_tpu.blocksync.messages import (
    BlockRequest,
    BlockResponse,
    NoBlockResponse,
    StatusRequest,
    StatusResponse,
    decode_message,
    encode_message,
)
from tendermint_tpu.blocksync.pool import BlockPool
from tendermint_tpu.crypto.batch import verify_batch
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor
from tendermint_tpu.types.basic import BlockID

logger = logging.getLogger("tendermint_tpu.blocksync")

BLOCKSYNC_CHANNEL = 0x40
STATUS_UPDATE_INTERVAL = 2.0
SWITCH_TO_CONSENSUS_INTERVAL = 0.5
VERIFY_BATCH_BLOCKS = 16


class BlocksyncReactor(Reactor):
    def __init__(self, state, block_exec, block_store, consensus_reactor=None,
                 active: bool = True, metrics=None,
                 peer_timeout: float = None, retry_sleep: float = None,
                 scheduler=None):
        super().__init__("BLOCKSYNC")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.consensus_reactor = consensus_reactor
        self.active = active  # False = serve blocks only (we're not syncing)
        self.metrics = metrics  # BlockSyncMetrics or None
        # global verification scheduler (crypto/scheduler.py): catch-up
        # verification rides the CATCHUP lane — it soaks idle device
        # capacity and yields to votes/light/admission (paused entirely at
        # overload pressure level 2)
        self.scheduler = scheduler
        # [fastsync] peer_timeout / retry_sleep (None = pool defaults)
        from tendermint_tpu.blocksync.pool import PEER_TIMEOUT, RETRY_SLEEP

        self.peer_timeout = PEER_TIMEOUT if peer_timeout is None else peer_timeout
        self.retry_sleep = RETRY_SLEEP if retry_sleep is None else retry_sleep
        self.pool: Optional[BlockPool] = None
        self._tasks: List[asyncio.Task] = []
        self.synced = asyncio.Event()
        self._started_at = 0.0

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(BLOCKSYNC_CHANNEL, priority=5, send_queue_capacity=1000)]

    async def start(self) -> None:
        if not self.active:
            return
        self._started_at = time.monotonic()
        if self.metrics is not None:
            self.metrics.syncing.set(1)
        self.pool = BlockPool(
            self.state.last_block_height + 1, self._send_request, self._punish_peer,
            metrics=self.metrics,
            peer_timeout=self.peer_timeout, retry_sleep=self.retry_sleep,
        )
        self.pool.start()
        self._tasks = [
            asyncio.create_task(self._sync_routine(), name="bcsync"),
            asyncio.create_task(self._status_routine(), name="bcstatus"),
        ]

    async def stop(self) -> None:
        if self.pool:
            self.pool.stop()
        for t in self._tasks:
            t.cancel()

    async def _send_request(self, peer_id: str, height: int) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            await peer.send(BLOCKSYNC_CHANNEL, encode_message(BlockRequest(height)))

    async def _punish_peer(self, peer_id: str, reason: str) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            await self.switch.stop_peer_for_error(peer, reason)

    # -- peers -------------------------------------------------------------

    async def add_peer(self, peer) -> None:
        await peer.send(
            BLOCKSYNC_CHANNEL,
            encode_message(StatusResponse(self.block_store.height, self.block_store.base)),
        )
        if self.active:
            await peer.send(BLOCKSYNC_CHANNEL, encode_message(StatusRequest()))

    async def remove_peer(self, peer, reason) -> None:
        if self.pool:
            self.pool.remove_peer(peer.id)

    # -- receive -----------------------------------------------------------

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        try:
            msg = decode_message(msg_bytes)
        except Exception as e:
            await self.switch.stop_peer_for_error(peer, e)
            return
        if isinstance(msg, BlockRequest):
            block = self.block_store.load_block(msg.height)
            if block is not None:
                await peer.send(BLOCKSYNC_CHANNEL, encode_message(BlockResponse(block)))
            else:
                await peer.send(BLOCKSYNC_CHANNEL, encode_message(NoBlockResponse(msg.height)))
        elif isinstance(msg, StatusRequest):
            await peer.send(
                BLOCKSYNC_CHANNEL,
                encode_message(StatusResponse(self.block_store.height, self.block_store.base)),
            )
        elif isinstance(msg, StatusResponse):
            if self.pool:
                self.pool.set_peer_range(peer.id, msg.base, msg.height)
        elif isinstance(msg, BlockResponse):
            if self.pool:
                self.pool.add_block(peer.id, msg.block)
        elif isinstance(msg, NoBlockResponse):
            logger.debug("peer %s has no block %d", peer.id[:10], msg.height)

    async def switch_to_blocksync(self, state) -> None:
        """Post-state-sync handoff: start syncing blocks from the restored
        height (reference: blockchain/v0/reactor.go:116 SwitchToFastSync)."""
        self.state = state
        self.active = True
        self._started_at = time.monotonic()
        await self.start()
        await self.switch.broadcast(BLOCKSYNC_CHANNEL, encode_message(StatusRequest()))

    # -- sync --------------------------------------------------------------

    async def _status_routine(self) -> None:
        try:
            while True:
                await self.switch.broadcast(BLOCKSYNC_CHANNEL, encode_message(StatusRequest()))
                await asyncio.sleep(STATUS_UPDATE_INTERVAL)
        except asyncio.CancelledError:
            pass

    def _verify_run_batched(self, run: List[tuple]) -> Optional[int]:
        """One device batch over all (first, parts, second) triples: first's
        commit is second.last_commit, checked against the CURRENT validator
        set (reference: VerifyCommitLight per block, blockchain/v0/reactor.go).
        Returns the index of the first failing triple, or None.

        Validator sets can change mid-run (H+2 rule); the caller only
        *punishes* when index 0 fails — later failures may just mean the set
        changed, and those heights are re-verified as the head of the next
        run against the then-correct set."""
        pubkeys, msgs, sigs, key_types = [], [], [], []
        spans = []  # (start, count, powers, total_power, ok_struct)
        vals = self.state.validators
        for first, parts, second in run:
            commit = second.last_commit
            first_id = BlockID(first.hash(), parts.header)
            start = len(sigs)
            powers = []
            if len(commit.signatures) != vals.size():
                spans.append((start, 0, [], 1, False))
                continue
            idxs = []
            for idx, cs_sig in enumerate(commit.signatures):
                if not cs_sig.for_block():
                    continue
                val = vals.validators[idx]
                pubkeys.append(val.pub_key.bytes())
                idxs.append(idx)
                sigs.append(cs_sig.signature)
                key_types.append(val.pub_key.type_name())
                powers.append(val.voting_power)
            msgs.extend(commit.vote_sign_bytes_many(self.state.chain_id, idxs))
            ok_struct = commit.block_id == first_id and commit.height == first.header.height
            spans.append((start, len(sigs) - start, powers, vals.total_voting_power(), ok_struct))
        if not sigs:
            return 0 if run else None
        # key_types: sr25519 validators' sigs must verify under sr25519 rules
        # (mirrors validator_set.py batched Verify*; liveness in mixed sets).
        if self.scheduler is not None and not self.scheduler.closed:
            # catch-up lane: idle-soak scheduling + exact-mask recovery —
            # verdicts byte-identical to the direct call below
            mask = self.scheduler.verify_rows(
                "catchup", pubkeys, msgs, sigs, key_types
            )
        else:
            mask = verify_batch(pubkeys, msgs, sigs, key_types=key_types)
        for i, (start, count, powers, total, ok_struct) in enumerate(spans):
            if not ok_struct:
                return i
            tallied = sum(p for ok, p in zip(mask[start : start + count], powers) if ok)
            if tallied * 3 <= total * 2:
                return i
        return None

    async def _sync_routine(self) -> None:
        """(reference: blockchain/v0/reactor.go:207 poolRoutine)"""
        last_switch_check = 0.0
        while True:
            try:
                await asyncio.sleep(0.02)
                now = time.monotonic()
                if now - last_switch_check > SWITCH_TO_CONSENSUS_INTERVAL:
                    last_switch_check = now
                    if self._caught_up():
                        await self._switch_to_consensus()
                        return

                # drain a contiguous run of downloaded (first, second) pairs
                from tendermint_tpu.types.part_set import PartSet

                run = []
                h = self.pool.height
                while len(run) < VERIFY_BATCH_BLOCKS:
                    first = self.pool.get_block(h)
                    second = self.pool.get_block(h + 1)
                    if first is None or second is None:
                        break
                    run.append((first, PartSet.from_data(first.encode()), second))
                    h += 1
                if not run:
                    continue

                # batched verification across blocks x validators (the TPU
                # showcase: one kernel launch for the whole run). Off-loop:
                # the catch-up lane may hold these rows for its idle-soak
                # window (or pause them under overload), and that wait must
                # park an executor thread, never the shared event loop
                _tv0 = time.perf_counter()
                bad = await asyncio.get_running_loop().run_in_executor(
                    None, self._verify_run_batched, run
                )
                if self.metrics is not None:
                    self.metrics.verify_seconds.observe(time.perf_counter() - _tv0)
                n_ok = len(run) if bad is None else bad
                for first, parts, second in run[:n_ok]:
                    self._apply(first, parts, second)
                    self.pool.pop_request()
                if n_ok and self.metrics is not None:
                    self.metrics.blocks_applied_total.inc(n_ok)
                if bad == 0:
                    # failed against the verified-current valset: bad data.
                    # punish both providers of the offending pair and refetch
                    bad_height = self.pool.height
                    for h2 in (bad_height, bad_height + 1):
                        peer_id = self.pool.redo_request(h2)
                        if peer_id:
                            await self._punish_peer(peer_id, "invalid block/commit")
            except asyncio.CancelledError:
                return
            except Exception:
                # transient failures (app hiccough, connection blip) must not
                # kill the sync: consensus never starts if this task dies
                logger.exception("sync iteration failed; retrying")
                await asyncio.sleep(0.5)

    def _apply(self, block, parts, second) -> None:
        block_id = BlockID(block.hash(), parts.header)
        # the commit FOR this block travels in the next block's last_commit
        # (reference: reactor.go SaveBlock(first, firstParts, second.LastCommit))
        self.block_store.save_block(block, parts, second.last_commit)
        # trust_last_commit: the run's signatures were just verified in the
        # device batch; skip the per-block re-verification inside ApplyBlock
        # (the reference double-verifies here — one place we beat it)
        self.state = self.block_exec.apply_block(
            self.state, block_id, block, trust_last_commit=True
        )

    def _caught_up(self) -> bool:
        if self.pool.num_peers() == 0 and time.monotonic() - self._started_at < 5.0:
            return False  # give peers a moment to report
        max_h = self.pool.max_peer_height()
        # within one block of the best-known head counts as caught up: the
        # pool can never apply the head itself (it needs head+1's LastCommit),
        # and on a live chain the head keeps moving — consensus catchup gossip
        # closes the final gap after the handoff (reference: v0 pool
        # IsCaughtUp + consensus reactor catchup).
        return self.pool.num_peers() > 0 and self.pool.height + 1 >= max_h

    async def _switch_to_consensus(self) -> None:
        logger.info("fast sync complete at height %d; switching to consensus", self.state.last_block_height)
        if self.metrics is not None:
            self.metrics.syncing.set(0)
        self.pool.stop()
        for t in self._tasks:
            if t is not asyncio.current_task():
                t.cancel()  # stop the periodic StatusRequest broadcasts
        self.synced.set()
        if self.consensus_reactor is not None:
            self.consensus_reactor.cs.state = None  # force update_to_state
            self.consensus_reactor.cs._update_to_state(self.state)
            if self.state.last_block_height > 0:
                self.consensus_reactor.cs._reconstruct_last_commit(self.state)
            await self.consensus_reactor.switch_to_consensus(self.state)
