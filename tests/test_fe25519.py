"""Differential tests: JAX GF(2^255-19) kernel vs Python big-int arithmetic."""

import pytest

pytestmark = pytest.mark.kernel  # heavy compiles; fast lane: -m 'not kernel'

import numpy as np

from tendermint_tpu.ops import fe25519 as fe

P = fe.P
rng = np.random.default_rng(1234)


def rand_ints(n, below=P):
    return [int.from_bytes(rng.bytes(40), "little") % below for _ in range(n)]


def batch_from_ints(xs):
    return np.stack([fe.from_int(x) for x in xs], axis=-1)  # (20, n)


def batch_to_ints(limbs):
    arr = np.asarray(limbs)
    return [fe.to_int(arr[:, i]) for i in range(arr.shape[1])]


def test_roundtrip_int():
    for x in rand_ints(20) + [0, 1, P - 1, P - 19, 2**255 - 20]:
        assert fe.to_int(fe.from_int(x)) == x % P


def test_add_sub_mul_random():
    n = 64
    a, b = rand_ints(n), rand_ints(n)
    A, B = batch_from_ints(a), batch_from_ints(b)
    assert batch_to_ints(fe.add(A, B)) == [(x + y) % P for x, y in zip(a, b)]
    assert batch_to_ints(fe.sub(A, B)) == [(x - y) % P for x, y in zip(a, b)]
    assert batch_to_ints(fe.mul(A, B)) == [(x * y) % P for x, y in zip(a, b)]
    assert batch_to_ints(fe.square(A)) == [(x * x) % P for x in a]


def test_mul_worst_case_limbs():
    # All limbs at the carried maximum: 2^13 (i >= 1), 2^13 + 607 on limb 0 —
    # the bound the int32 accumulation analysis relies on (see fe25519.carry).
    big = np.array([1 << fe.RADIX] * fe.NLIMBS, dtype=np.int32)
    big0 = big.copy()
    big0[0] += 607
    A = np.stack([big0, big], axis=-1)
    va = [fe.to_int(A[:, i]) for i in range(2)]
    got = batch_to_ints(fe.mul(A, A))
    assert got == [(x * x) % P for x in va]


def test_edge_values():
    xs = [0, 1, 2, 19, P - 1, P - 2, (P + 1) // 2, 2**255 - 20]
    ys = [P - 1, 1, P - 19, 0, P - 1, 2, 3, 2**254]
    A, B = batch_from_ints(xs), batch_from_ints(ys)
    assert batch_to_ints(fe.mul(A, B)) == [(x * y) % P for x, y in zip(xs, ys)]
    assert batch_to_ints(fe.sub(A, B)) == [(x - y) % P for x, y in zip(xs, ys)]


def test_chained_ops_stay_reduced():
    # Long chains of ops must not overflow or drift.
    n = 8
    a = rand_ints(n)
    A = batch_from_ints(a)
    ref = list(a)
    X = A
    for i in range(50):
        X = fe.mul(X, A) if i % 3 else fe.add(fe.sub(X, A), X)
        ref = [
            (r * x) % P if i % 3 else ((r - x) + r) % P for r, x in zip(ref, a)
        ]
    assert batch_to_ints(X) == ref


def test_freeze_and_eq():
    n = 16
    a = rand_ints(n)
    A = batch_from_ints(a)
    # a + (p) and a must compare equal
    App = fe.add(A, batch_from_ints([P - 19])[:, [0] * n])
    assert list(np.asarray(fe.eq(A, fe.add(A, fe.const_fe(0, (n,)))))) == [True] * n
    frozen = np.asarray(fe.freeze(App))
    assert batch_to_ints(frozen) == [(x + P - 19) % P for x in a]


def test_inv():
    n = 16
    a = rand_ints(n)
    A = batch_from_ints(a)
    got = batch_to_ints(fe.inv(A))
    assert got == [pow(x, P - 2, P) for x in a]
    # inv(0) == 0
    Z = batch_from_ints([0])
    assert batch_to_ints(fe.inv(Z)) == [0]


def test_pow_p58():
    n = 8
    a = rand_ints(n)
    A = batch_from_ints(a)
    got = batch_to_ints(fe.pow_p58(A))
    assert got == [pow(x, (P - 5) // 8, P) for x in a]


def test_bytes_roundtrip():
    n = 32
    xs = rand_ints(n) + [0, 1, P - 1]
    A = batch_from_ints(xs)
    enc = np.asarray(fe.to_bytes(A))  # (32, n)
    for i, x in enumerate(xs):
        assert enc[:, i].tobytes() == int.to_bytes(x, 32, "little")
    back = fe.from_bytes(np.asarray(enc))
    assert batch_to_ints(back) == [x % P for x in xs]


def test_from_bytes_masks_sign_bit():
    x = P - 5
    raw = bytearray(int.to_bytes(x, 32, "little"))
    raw[31] |= 0x80
    arr = np.frombuffer(bytes(raw), dtype=np.uint8).reshape(32, 1)
    assert batch_to_ints(fe.from_bytes(arr))[0] == x


def test_is_canonical_bytes():
    cases = {0: True, 1: True, P - 1: True, P: False, P + 5: False, 2**255 - 1: False}
    vals = list(cases)
    arr = np.stack(
        [np.frombuffer(int.to_bytes(v, 32, "little"), dtype=np.uint8) for v in vals],
        axis=-1,
    )
    got = list(np.asarray(fe.is_canonical_bytes(arr)))
    assert got == [cases[v] for v in vals]


def test_mul_small_and_neg():
    n = 8
    a = rand_ints(n)
    A = batch_from_ints(a)
    assert batch_to_ints(fe.mul_small(A, 121666)) == [x * 121666 % P for x in a]
    assert batch_to_ints(fe.neg(A)) == [(-x) % P for x in a]


def test_bit():
    xs = [1, 2, P - 1, 7]
    A = fe.freeze(batch_from_ints(xs))
    assert list(np.asarray(fe.bit(A, 0))) == [x & 1 for x in xs]
    assert list(np.asarray(fe.bit(A, 1))) == [(x >> 1) & 1 for x in xs]
    assert list(np.asarray(fe.bit(A, 254))) == [(x >> 254) & 1 for x in xs]
