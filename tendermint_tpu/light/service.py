"""Light-client-as-a-service: the server-side verification multiplexer.

The serving story for "millions of users" in committee-based chains is
light clients ("Practical Light Clients for Committee-Based Blockchains",
"A Tendermint Light Client" — PAPERS.md): clients ship
skipping-verification requests and a full node answers them. This module
turns ONE node into that verification server (ROADMAP item 3):

- concurrent `light_verify`/`light_block` requests (rpc/server.py routes)
  land here;
- repeat heights are answered from a bounded verified-header cache
  (LightStore) with SINGLE-FLIGHT semantics: K concurrent requests for the
  same uncached height await one verification, not K;
- distinct-height misses are COALESCED: same-tick misses group into one
  window body (light/coalescer.py), every miss submits its commit checks'
  (pubkey, msg, sig) rows through `begin_verify_commit_light_trusting` /
  `begin_verify_commit_light` under the global verification scheduler's
  LIGHT-lane accumulator (crypto/scheduler.py, ISSUE 11), and the lane
  holds the rows for the coalescing window — so window bodies fired ticks
  apart, and the node's other consumers, share ONE combined device flush;
- heights the trusted valset can't vouch for (+1/3 overlap missing after a
  valset rotation) fall back to the bisection client (light/client.py),
  whose interim headers warm the same cache;
- per-client admission rides the PR 5 load-shedding machinery: the RPC
  routes are LoadGate-sheddable (429 + Retry-After) and the service adds
  its own `max_pending` backstop so a light-client flood can never starve
  the live vote path's device access;
- a client-supplied expected hash that disagrees with the verified header
  is a structured conflicting-header error (possible attack on the
  client's other providers), counted and surfaced — never a 500.

Trust model: the service anchors on the EARLIEST header its provider can
serve and treats it as the root of trust. For the in-node wiring
(LocalNodeProvider) that root is the node's own executed chain — objective
for the node, subjective for its clients exactly as when they pick any
primary. The anchor commit is still verified against its own validator
set before use.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.libs.trace import tracer as _tracer
from tendermint_tpu.libs.txtrace import StageStats
from tendermint_tpu.light import verifier
from tendermint_tpu.light.client import Client, ErrConflictingHeaders, TrustOptions
from tendermint_tpu.light.coalescer import Coalescer
from tendermint_tpu.light.provider import (
    ErrLightBlockNotFound,
    Provider,
    ProviderError,
)
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    ErrInvalidHeader,
    LightError,
)
from tendermint_tpu.types.basic import NANOS
from tendermint_tpu.types.light import LightBlock
from tendermint_tpu.types.validator_set import (
    CommitVerifyError,
    Fraction,
    NotEnoughVotingPowerError,
)

__all__ = [
    "LightService",
    "LocalNodeProvider",
    "LightServiceError",
    "ErrLightOverloaded",
    "ErrConflictingHeader",
    "ErrHeightNotAvailable",
    "ErrVerificationFailed",
    "ErrLightDisabled",
    "ErrBadRequest",
]

# JSON-RPC error codes for the structured light errors (implementation-
# defined range; rpc/server.py translates LightServiceError transparently
# on every transport)
CODE_CONFLICT = -32010
CODE_NOT_AVAILABLE = -32011
CODE_INVALID = -32012
CODE_DISABLED = -32013
CODE_BAD_REQUEST = -32602  # JSON-RPC invalid params


class LightServiceError(Exception):
    """Structured service error: `code` + `data` ride the JSON-RPC error
    object so a client can dispatch on the failure, not parse a string."""

    code = CODE_INVALID

    def __init__(self, message: str, data: Optional[dict] = None):
        super().__init__(message)
        self.data = data or {}


class ErrLightOverloaded(LightServiceError):
    """Service-level admission refusal; the RPC layer translates this to
    HTTP 429 + Retry-After exactly like a LoadGate shed."""

    code = -32005  # same code as RPCShedError's translation


class ErrConflictingHeader(LightServiceError):
    """The verified header disagrees with what the client (or another
    cached verification) expected — possible light-client attack."""

    code = CODE_CONFLICT

    def __init__(self, height: int, verified_hash: bytes, other_hash: bytes):
        super().__init__(
            f"conflicting header at height {height}: verified "
            f"{verified_hash.hex()} vs {other_hash.hex()}",
            {
                "height": height,
                "verified_hash": verified_hash.hex().upper(),
                "conflicting_hash": other_hash.hex().upper(),
            },
        )


class ErrHeightNotAvailable(LightServiceError):
    code = CODE_NOT_AVAILABLE


class ErrVerificationFailed(LightServiceError):
    code = CODE_INVALID


class ErrLightDisabled(LightServiceError):
    """The node runs without a light service ([light_service] enabled =
    false) — a structured refusal, not an internal error + stack trace."""

    code = CODE_DISABLED


class ErrBadRequest(LightServiceError):
    """Unparseable client input (e.g. a non-hex hash parameter)."""

    code = CODE_BAD_REQUEST


class _NeedBisection(Exception):
    """Internal: the fast path can't vouch (trust-level miss / expired or
    missing trusted ancestor); retry through the bisection client."""


@dataclass
class _Job:
    """One coalesced miss: verify `target` from `trusted` (non-adjacent
    skipping check, or adjacent when the heights touch)."""

    height: int
    target: LightBlock
    trusted: LightBlock


class LocalNodeProvider(Provider):
    """Provider reading the serving node's OWN stores — no RPC round trip,
    no re-parse (the reference's light service proxies over HTTP even to
    localhost; here the service lives in the node)."""

    def __init__(self, node):
        self.node = node
        self.calls = 0

    def chain_id(self) -> str:
        return self.node.genesis.chain_id

    def earliest_height(self) -> int:
        return max(self.node.block_store.base, 1)

    async def light_block(self, height: Optional[int]) -> LightBlock:
        # the body is pure synchronous store-read + parse + hash work —
        # off the shared event loop so a burst of cache misses never
        # delays the consensus reactor (the bisection worker's private
        # loop just hops to that executor's thread pool, also fine)
        return await asyncio.get_running_loop().run_in_executor(
            None, self._light_block_sync, height
        )

    def _light_block_sync(self, height: Optional[int]) -> LightBlock:
        from tendermint_tpu.types.light import SignedHeader

        self.calls += 1
        store = self.node.block_store
        if height is None:
            height = store.height
        block = store.load_block(height)
        if block is None:
            raise ErrLightBlockNotFound(f"no block at height {height}")
        commit = None
        nxt = store.load_block(height + 1)
        if nxt is not None and nxt.last_commit.height == height:
            commit = nxt.last_commit
        else:
            commit = store.load_seen_commit(height)
        if commit is None:
            raise ErrLightBlockNotFound(f"no commit at height {height}")
        vals = self.node.state_store.load_validators(height)
        if vals is None:
            raise ErrLightBlockNotFound(f"no validator set at height {height}")
        lb = LightBlock(SignedHeader(block.header, commit), vals)
        lb.validate_basic(self.chain_id())
        return lb


class LightService:
    """The verification-serving subsystem. One instance per node (wired by
    node/node.py from `[light_service]` config); bench.py's `light_serve`
    scenario and the tests drive it standalone over a MockProvider."""

    def __init__(
        self,
        chain_id: str,
        provider: Provider,
        config,
        *,
        store: Optional[LightStore] = None,
        metrics=None,
        slo=None,
        trust_level: Optional[Fraction] = None,
        now_ns: Optional[Callable[[], int]] = None,
        scheduler=None,
        own_scheduler_if_missing: bool = True,
    ):
        self.chain_id = chain_id
        self.provider = provider
        self.config = config
        self.store = store or LightStore(MemDB())
        self.metrics = metrics  # libs/metrics.LightServiceMetrics or None
        self.slo = slo  # libs/slo.SLOEngine or None
        # Global verification scheduler (crypto/scheduler.py, ISSUE 11):
        # every window's commit-check rows ride the LIGHT lane, whose
        # max_wait is pinned below to this service's coalesce_window — the
        # PR 9 coalescing-window SLO now lives in ONE place, and light rows
        # share combined flushes with the node's other consumers. A
        # standalone service (tests, bench) owns a private scheduler; a
        # node with `[scheduler] enabled = false` passes
        # own_scheduler_if_missing=False and the service degrades to plain
        # per-window-body FlushAccumulator flushes (same-tick coalescing
        # only — the operator turned the lane engine off).
        self._owns_scheduler = scheduler is None and own_scheduler_if_missing
        if self._owns_scheduler:
            from tendermint_tpu.crypto.scheduler import VerifyScheduler

            scheduler = VerifyScheduler()
        self.scheduler = scheduler
        if scheduler is not None:
            scheduler.set_lane_wait("light", float(config.coalesce_window))
        self._seen_flush_seqs: set = set()  # device-flush dedupe (bounded)
        self.trust_level = trust_level or Fraction(
            getattr(config, "trust_level_numerator", 1),
            getattr(config, "trust_level_denominator", 3),
        )
        verifier.validate_trust_level(self.trust_level)
        self._now_ns = now_ns or time.time_ns
        self.trust_period_ns = int(float(config.trust_period) * NANOS)
        self.max_clock_drift_ns = int(
            float(getattr(config, "max_clock_drift", 10.0)) * NANOS
        )
        self.cache_blocks = int(config.cache_blocks)
        self.max_pending = int(config.max_pending)
        self.coalescer = Coalescer(
            self._run_jobs,
            max_jobs=int(config.max_heights_per_flush),
        )
        self._inflight: Dict[int, asyncio.Future] = {}  # single-flight map
        self._pending = 0
        self._anchor_lock = asyncio.Lock()
        self._counter_lock = threading.Lock()
        # hot-path LRU of DESERIALIZED light blocks: the Zipfian workload
        # hits a few heights constantly, and a store hit re-parses the whole
        # block (commit sigs + valset) from bytes per request
        self._hot: "OrderedDict[int, LightBlock]" = OrderedDict()
        self._hot_cap = max(8, min(self.cache_blocks, 256))
        # counters (mirrored to tendermint_light_* when metrics are wired)
        self.requests_total = 0
        self.cache_hits = 0
        self.singleflight_waits = 0
        self.flushes = 0
        self.lanes_total = 0
        self.bisections = 0
        self.sheds = 0
        self.conflicts = 0
        self.outcomes: Dict[str, int] = {}
        # per-request stage spans (ISSUE 10): a slow light_verify p99 is
        # attributable to a STAGE — admission backstop, cache probe,
        # single-flight wait, provider fetch, coalesce-window wait, the
        # shared device flush wall, or the bisection walk — instead of one
        # opaque number. Recording is gated on the tracer flag (the
        # hotstats contract: disabled costs one flag check per site);
        # percentiles surface in light_status / GET /debug/light.
        self.stage_stats = StageStats()

    # -- public API -----------------------------------------------------------

    async def verify_height(
        self, height: int, expected_hash: Optional[bytes] = None
    ) -> Tuple[LightBlock, str]:
        """Verify (or recall) the light block at `height`; returns
        (light_block, source) with source in cache|flush|bisection.
        Raises a structured LightServiceError on refusal/failure."""
        if height <= 0:
            raise ErrHeightNotAvailable(f"height must be positive, got {height}")
        t0 = time.perf_counter()
        self.requests_total += 1
        try:
            lb, source = await self._verify_height_inner(height)
        except ErrLightOverloaded:
            self._count_outcome("shed")
            raise
        except LightServiceError as e:
            self._count_outcome(
                "conflict" if isinstance(e, ErrConflictingHeader) else "error"
            )
            self._observe_latency(time.perf_counter() - t0)
            raise
        if expected_hash and lb.hash() != expected_hash:
            self._record_conflict()
            self._count_outcome("conflict")
            self._observe_latency(time.perf_counter() - t0)
            raise ErrConflictingHeader(height, lb.hash(), expected_hash)
        self._count_outcome(source)
        self._observe_latency(time.perf_counter() - t0)
        return lb, source

    def _hot_get(self, height: int) -> Optional[LightBlock]:
        with self._counter_lock:
            lb = self._hot.get(height)
            if lb is not None:
                self._hot.move_to_end(height)
            return lb

    def _hot_put(self, lb: LightBlock) -> None:
        with self._counter_lock:
            self._hot[lb.height] = lb
            self._hot.move_to_end(lb.height)
            while len(self._hot) > self._hot_cap:
                self._hot.popitem(last=False)

    def _span(self, stage: str, t0: float) -> None:
        """Record one per-request stage duration — one flag check when
        tracing is off (stage taxonomy: admission, cache_probe,
        singleflight_wait, provider_fetch, coalesce_wait, flush_wall,
        bisection)."""
        if _tracer.enabled:
            self.stage_stats.observe(stage, time.perf_counter() - t0)

    async def _verify_height_inner(self, height: int) -> Tuple[LightBlock, str]:
        t_probe = time.perf_counter()
        cached = self._hot_get(height)
        if cached is None:
            cached = self.store.light_block(height)
            if cached is not None:
                self._hot_put(cached)
        self._span("cache_probe", t_probe)
        if cached is not None:
            with self._counter_lock:
                self.cache_hits += 1
            if self.metrics is not None:
                self.metrics.cache_hits.inc()
            return cached, "cache"
        # single-flight: the FIRST requester for an uncached height leads;
        # everyone else awaits its future (one verification, not K)
        fut = self._inflight.get(height)
        if fut is not None:
            with self._counter_lock:
                self.singleflight_waits += 1
            t_wait = time.perf_counter()
            kind, value = await asyncio.shield(fut)
            self._span("singleflight_wait", t_wait)
            if kind == "err":
                raise value
            if kind == "retry":
                # the leader was CANCELLED (its client disconnected) — that
                # must not cascade to the whole cohort; race to lead a fresh
                # verification instead
                return await self._verify_height_inner(height)
            # the follower is answered from the leader's now-cached
            # verification — a cache hit, counted only on success
            with self._counter_lock:
                self.cache_hits += 1
            if self.metrics is not None:
                self.metrics.cache_hits.inc()
            return value, "cache"  # served from the leader's verification
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._inflight[height] = fut
        try:
            result = await self._verify_miss(height)
        except asyncio.CancelledError:
            if not fut.done():
                fut.set_result(("retry", None))
            raise
        except BaseException as e:
            if not fut.done():
                fut.set_result(("err", e))
            raise
        else:
            if not fut.done():
                fut.set_result(("ok", result[0]))
            return result
        finally:
            self._inflight.pop(height, None)

    async def _verify_miss(self, height: int) -> Tuple[LightBlock, str]:
        t_adm = time.perf_counter()
        if self.max_pending > 0 and self._pending >= self.max_pending:
            with self._counter_lock:
                self.sheds += 1
            if self.metrics is not None:
                self.metrics.shed.inc()
            raise ErrLightOverloaded(
                f"light service at max_pending={self.max_pending}"
            )
        self._pending += 1
        try:
            await self._ensure_anchor()
            # the admission span covers the backstop check + anchor wait —
            # on a cold service the first requests pay the anchor
            # verification here, and the span names that
            self._span("admission", t_adm)
            t_fetch = time.perf_counter()
            try:
                target = await self.provider.light_block(height)
            except ErrLightBlockNotFound as e:
                raise ErrHeightNotAvailable(str(e)) from e
            except ProviderError as e:
                raise ErrHeightNotAvailable(f"provider failed: {e}") from e
            finally:
                self._span("provider_fetch", t_fetch)
            try:
                # hashing-heavy for large valsets — off the shared loop
                await asyncio.get_running_loop().run_in_executor(
                    None, target.validate_basic, self.chain_id
                )
            except ValueError as e:
                raise ErrVerificationFailed(f"invalid light block: {e}") from e
            # a concurrent bisection may have verified this exact height
            # while we awaited the provider — serve it instead of verifying
            # against ourselves
            cached = self.store.light_block(height)
            if cached is not None:
                return cached, "cache"
            trusted = self.store.light_block_before(height)
            source = "flush"
            if trusted is None or verifier.header_expired(
                trusted.signed_header, self.trust_period_ns, self._now_ns()
            ):
                lb = await self._bisect_spanned(height)
                source = "bisection"
            else:
                try:
                    t_coal = time.perf_counter()
                    try:
                        lb = await self.coalescer.submit(
                            _Job(height=height, target=target, trusted=trusted)
                        )
                    finally:
                        # window-arm wait + the shared flush, as this request
                        # experienced it (the flush wall alone is recorded
                        # per-window by _run_jobs)
                        self._span("coalesce_wait", t_coal)
                except _NeedBisection:
                    lb = await self._bisect_spanned(height)
                    source = "bisection"
                except (CommitVerifyError, ErrInvalidHeader, LightError) as e:
                    raise ErrVerificationFailed(
                        f"verification failed at height {height}: {e}"
                    ) from e
            self._save_verified(lb)
            return lb, source
        finally:
            self._pending -= 1

    # -- anchoring / fallback -------------------------------------------------

    async def _ensure_anchor(self) -> None:
        """Pin the root of trust: the earliest header the provider serves,
        verified against its own validator set (+2/3), saved as the first
        cache entry. Runs once (or again if the cache was fully pruned)."""
        if self.store.size() > 0:
            return
        async with self._anchor_lock:
            if self.store.size() > 0:
                return
            anchor_h = None
            earliest = getattr(self.provider, "earliest_height", None)
            if callable(earliest):
                anchor_h = earliest()
            try:
                try:
                    lb = await self.provider.light_block(anchor_h or 1)
                except ProviderError:
                    lb = await self.provider.light_block(None)  # latest
            except ProviderError as e:
                # a fresh node with no committed blocks yet: "not ready",
                # never a -32603 internal error
                raise ErrHeightNotAvailable(
                    f"no anchor header available yet: {e}"
                ) from e
            def _check_anchor():
                lb.validate_basic(self.chain_id)
                # the anchor is self-vouching: +2/3 of its own valset
                # signed it
                lb.validator_set.verify_commit_light(
                    self.chain_id,
                    lb.signed_header.commit.block_id,
                    lb.height,
                    lb.signed_header.commit,
                )

            try:
                # signature verification off the shared event loop — the
                # consensus reactor must never wait behind a light anchor
                await asyncio.get_running_loop().run_in_executor(
                    None, _check_anchor
                )
            except (ValueError, CommitVerifyError) as e:
                raise ErrVerificationFailed(f"anchor rejected: {e}") from e
            self.store.save_light_block(lb)

    async def _bisect_spanned(self, height: int) -> LightBlock:
        t0 = time.perf_counter()
        try:
            return await self._bisect(height)
        finally:
            self._span("bisection", t0)

    async def _bisect(self, height: int) -> LightBlock:
        """Bisection fallback (light/client.py) for heights the direct
        skipping check can't vouch for; interim headers land in the shared
        cache and warm future windows. The whole walk — many serial commit
        verifications — runs in a worker thread with its own event loop so
        it never blocks the loop the consensus reactor shares; a FRESH
        Client per call keeps asyncio primitives loop-local (initialize is
        ~free: the anchor is already cached, so it short-circuits on the
        stored hash)."""
        with self._counter_lock:
            self.bisections += 1
        anchor = self.store.first_light_block()
        if anchor is None:
            raise ErrHeightNotAvailable("no trusted anchor")
        now_ns = self._now_ns()

        def _run() -> LightBlock:
            client = Client(
                self.chain_id,
                TrustOptions(self.trust_period_ns, anchor.height, anchor.hash()),
                self.provider,
                [],
                self.store,
                trust_level=self.trust_level,
                max_clock_drift_ns=self.max_clock_drift_ns,
                pruning_size=self.cache_blocks,
            )

            async def go():
                await client.initialize(now_ns)
                return await client.verify_light_block_at_height(height, now_ns)

            return asyncio.run(go())

        try:
            return await asyncio.get_running_loop().run_in_executor(None, _run)
        except ErrConflictingHeaders as e:
            self._record_conflict()
            blocks = getattr(e, "conflicting_blocks", [])
            other = blocks[0].hash() if blocks else b""
            raise ErrConflictingHeader(height, b"", other) from e
        except LightError as e:
            raise ErrVerificationFailed(
                f"bisection failed at height {height}: {e}"
            ) from e

    def _save_verified(self, lb: LightBlock) -> None:
        existing = self.store.light_block(lb.height)
        if existing is not None and existing.hash() != lb.hash():
            # two verification paths produced different headers for one
            # height — surface it, never silently overwrite trusted state
            self._record_conflict()
            raise ErrConflictingHeader(lb.height, existing.hash(), lb.hash())
        self.store.save_light_block(lb)
        self._hot_put(lb)
        self.store.prune(self.cache_blocks)

    # -- the coalesced window body (worker thread) ----------------------------

    def _run_jobs(self, jobs: List[_Job]):
        """One coalesced batch: submit every job's commit checks under the
        scheduler's light-lane accumulator, flush ONCE (the rows join the
        node-wide combined flush after at most the lane's coalescing
        window), then settle each job from its own mask slice. Runs in the
        coalescer's worker thread — the lane wait parks this thread, never
        the event loop."""
        from tendermint_tpu.crypto import batch as _batch

        now_ns = self._now_ns()
        prepared: List = []
        t_flush = time.perf_counter()
        acc = (
            self.scheduler.accumulate("light")
            if self.scheduler is not None
            else _batch.FlushAccumulator()
        )
        with _batch.accumulate_flushes(acc):
            for job in jobs:
                try:
                    prepared.append(self._submit_job(job, now_ns))
                except Exception as e:
                    prepared.append(e)
            lanes = acc.lanes
        acc.flush()  # rides the light lane's shared device flush
        # one sample per BATCH (submit phases + lane wait + the shared
        # device flush): the wall every rider of this batch shares
        self._span("flush_wall", t_flush)
        results = []
        for job, fins in zip(jobs, prepared):
            if isinstance(fins, Exception):
                results.append((False, fins))
                continue
            try:
                self._finish_job(fins)
                results.append((True, job.target))
            except Exception as e:
                results.append((False, e))
        with self._counter_lock:
            # `flushes` counts DEVICE flushes our rows rode: batches that
            # merged into one combined flush share a flush_seq and count
            # once. A SET of seen seqs (bounded), not a max-seen watermark:
            # concurrent window bodies riding different flushes can
            # complete out of order. Plain accumulators (no scheduler) and
            # inline fallbacks count their own flushes.
            seq = getattr(acc, "flush_seq", None)
            if seq is None:
                if lanes:
                    self.flushes += getattr(acc, "flush_count", 1)
            elif seq not in self._seen_flush_seqs:
                if len(self._seen_flush_seqs) > 4096:
                    self._seen_flush_seqs.clear()
                self._seen_flush_seqs.add(seq)
                self.flushes += 1
            self.lanes_total += lanes
        if self.metrics is not None:
            self.metrics.coalesced_lanes.observe(lanes)
        return results, {"lanes": lanes, "jobs": len(jobs)}

    def _submit_job(self, job: _Job, now_ns: int):
        """Header checks + SUBMIT phase of the commit verifications (the
        rows accumulate into the shared flush); finishes are deferred to
        after the flush. Mirrors light/verifier.verify_non_adjacent /
        verify_adjacent with the device sync factored out."""
        target, trusted = job.target, job.trusted
        verifier._verify_new_header_and_vals(
            target.signed_header,
            target.validator_set,
            trusted.signed_header,
            now_ns,
            self.max_clock_drift_ns,
        )
        commit = target.signed_header.commit
        if target.height == trusted.height + 1:
            # adjacent: the new valset is pinned by NextValidatorsHash —
            # checked BEFORE any signature rows join the shared flush
            # (verify_adjacent rejects before verifying too)
            if (
                target.header.validators_hash
                != trusted.header.next_validators_hash
            ):
                raise ErrInvalidHeader(
                    "new header's validators do not match the trusted "
                    "header's next validators"
                )
            fin_light = target.validator_set.begin_verify_commit_light(
                self.chain_id, commit.block_id, target.height, commit
            )
            return None, fin_light
        fin_trusting = trusted.validator_set.begin_verify_commit_light_trusting(
            self.chain_id, commit, self.trust_level
        )
        fin_light = target.validator_set.begin_verify_commit_light(
            self.chain_id, commit.block_id, target.height, commit
        )
        return fin_trusting, fin_light

    @staticmethod
    def _finish_job(fins) -> None:
        fin_trusting, fin_light = fins
        if fin_trusting is not None:
            try:
                fin_trusting()
            except NotEnoughVotingPowerError as e:
                # recoverable: the trusted valset can't vouch — bisect
                raise _NeedBisection(str(e)) from e
        fin_light()

    # -- bookkeeping / introspection ------------------------------------------

    def _count_outcome(self, outcome: str) -> None:
        with self._counter_lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics.requests.labels(outcome).inc()

    def _record_conflict(self) -> None:
        with self._counter_lock:
            self.conflicts += 1
        if self.metrics is not None:
            self.metrics.conflicting_headers.inc()

    def _observe_latency(self, seconds: float) -> None:
        if self.slo is not None:
            self.slo.observe("light_verify_p99", seconds)

    def status(self) -> dict:
        """The `light_status` RPC document: span + policy, no counters.
        Reads only the store's height index — a scrape must not pay two
        full light-block parses just to report the span."""
        heights = self.store.heights()
        return {
            "enabled": True,
            "chain_id": self.chain_id,
            "trusted_span": {
                "first": heights[0] if heights else 0,
                "last": heights[-1] if heights else 0,
            },
            "cache_size": len(heights),
            "cache_blocks": self.cache_blocks,
            # the coalescing window now lives in the scheduler's light lane
            # (this service pins it from [light_service] coalesce_window)
            "coalesce_window_s": float(self.config.coalesce_window),
            "max_heights_per_flush": self.coalescer.max_jobs,
            "max_pending": self.max_pending,
            "pending": self._pending,
            # per-request stage latency attribution (ISSUE 10): a slow p99
            # names its stage — cache_probe / singleflight_wait / admission /
            # provider_fetch / coalesce_wait / flush_wall / bisection
            "stage_percentiles": self.stage_stats.percentiles(),
        }

    def stats(self) -> dict:
        """The GET /debug/light document (also the `light` block of
        /debug/verify_stats): status + every counter + coalescer stats."""
        with self._counter_lock:
            counters = {
                "requests": self.requests_total,
                "cache_hits": self.cache_hits,
                "singleflight_waits": self.singleflight_waits,
                "flushes": self.flushes,
                "lanes_total": self.lanes_total,
                "bisections": self.bisections,
                "sheds": self.sheds,
                "conflicting_headers": self.conflicts,
                "outcomes": dict(self.outcomes),
            }
        out = self.status()
        out.update(counters)
        out["coalescer"] = self.coalescer.stats()
        return out

    def close(self) -> None:
        self.coalescer.close()
        if self._owns_scheduler and self.scheduler is not None:
            self.scheduler.close()
