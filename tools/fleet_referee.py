#!/usr/bin/env python
"""Standalone runner for the fleet referee (ISSUE 17 verdict engine).

Audits a fleet soak's observatory dumps offline — cross-node block-hash
safety, per-role SLO verdicts, waterfall coverage, terminal accounting —
and emits fleet_report.{json,md} plus a pinned exit code (0 pass, 2 safety
violation, 3 SLO tripped, 4 partial coverage, 1 no data). Implementation:
tendermint_tpu/tools/fleet_referee.py. Usage:

    python tools/fleet_referee.py --dumps ./observatory --check
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from tendermint_tpu.tools.fleet_referee import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
