"""On-demand profiler capture (libs/profiler.py) and the offline analyzer
(tools/profile_report.py): the start→stop round-trip on the CPU backend and
the per-stage attribution table — acceptance for the observatory's layer 1.
The CPU caveat (docs/OBSERVABILITY.md): the capture carries host/XLA:CPU
spans but no device plane; the PIPELINE is identical on real accelerators,
which is exactly what these tests pin."""

import gzip
import json
import os

import pytest

from tendermint_tpu.libs import profiler
from tendermint_tpu.tools import profile_report


def _flush_once():
    import jax
    import jax.numpy as jnp

    x = jnp.arange(1024.0)
    return jax.block_until_ready(jnp.dot(x, x))


def test_start_stop_roundtrip_on_cpu_backend(tmp_path):
    info = profiler.start(str(tmp_path))
    assert info["active"] and info["dir"].startswith(str(tmp_path))
    st = profiler.status()
    assert st["active"] and st["running_s"] >= 0
    with pytest.raises(profiler.ProfilerError):
        profiler.start(str(tmp_path))  # one session per process
    _flush_once()
    out = profiler.stop()
    assert out["active"] is False and out["duration_s"] >= 0
    assert out["artifacts"], "CPU-backend capture must still produce artifacts"
    st = profiler.status()
    assert not st["active"] and st["last_capture"]["dir"] == out["dir"]
    with pytest.raises(profiler.ProfilerError):
        profiler.stop()  # stop when idle is an error, not a no-op

    # the captured trace renders a per-stage table in one command
    rep = profile_report.report(out["dir"])
    assert rep["events"] > 0 and rep["stages"]
    md = profile_report.render_markdown(rep)
    assert "| stage |" in md and "## Top ops" in md


def test_trace_function_one_flush_capture(tmp_path):
    result, run_dir = profiler.trace_function(
        _flush_once, base_dir=str(tmp_path)
    )
    assert float(result) > 0  # the traced fn's result comes back
    assert profile_report.find_capture_files(run_dir)
    rep = profile_report.report(run_dir, top=5)
    assert len(rep["ops"]) <= 5


def _write_chrome_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_profile_report_stage_classification_and_self_times(tmp_path):
    """Parser unit test on a synthetic perfetto trace: fused-stage names
    classify into the PERF.md stages, and `self` excludes nested children."""
    _write_chrome_trace(tmp_path / "x.trace.json.gz", [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 2,
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "name": "fused_uptree_pass2", "pid": 1, "tid": 2,
         "ts": 0, "dur": 500},
        {"ph": "X", "name": "fenwick_reduce.3", "pid": 1, "tid": 2,
         "ts": 500, "dur": 300},
        {"ph": "X", "name": "bucket_fold_kernel", "pid": 1, "tid": 2,
         "ts": 800, "dur": 100},
        {"ph": "X", "name": "persig_ladder", "pid": 1, "tid": 2,
         "ts": 900, "dur": 50},
        # nesting on another thread: outer 1000us contains inner 400us
        {"ph": "X", "name": "outer_op", "pid": 1, "tid": 3, "ts": 0,
         "dur": 1000},
        {"ph": "X", "name": "inner_op", "pid": 1, "tid": 3, "ts": 100,
         "dur": 400},
    ])
    rep = profile_report.report(str(tmp_path))
    stages = {s["name"]: s for s in rep["stages"]}
    assert stages["uptree"]["total_us"] == 500
    assert stages["fenwick_reduce"]["total_us"] == 300
    assert stages["bucket_fold"]["total_us"] == 100
    assert stages["persig"]["total_us"] == 50
    ops = {o["name"]: o for o in rep["ops"]}
    assert ops["outer_op"]["total_us"] == 1000
    assert ops["outer_op"]["self_us"] == 600  # minus the nested inner
    assert ops["inner_op"]["self_us"] == 400
    # plane names resolved from the M metadata events
    assert any(p["plane"] == "/device:TPU:0" for p in rep["planes"])


def test_profile_report_parses_xplane_artifacts(tmp_path):
    """The xplane.pb protobuf walker parses a REAL capture's artifact (no
    tensorflow/tensorboard in this container — the walker is our only
    reader) and agrees with the capture's own artifact list."""
    _, run_dir = profiler.trace_function(_flush_once, base_dir=str(tmp_path))
    xplanes = [
        os.path.join(dp, fn)
        for dp, _, fns in os.walk(run_dir)
        for fn in fns if fn.endswith(".xplane.pb")
    ]
    if not xplanes:
        pytest.skip("jax build wrote no xplane artifact")
    events = profile_report.load_events(xplanes[0])
    assert events, "xplane walker must decode events from a real capture"
    assert all(e["dur_us"] >= 0 for e in events)


def test_report_errors_on_empty_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        profile_report.report(str(tmp_path))
    assert profile_report.main([str(tmp_path)]) == 2


def test_classify_first_match_wins():
    assert profile_report.classify("fused_uptree_x") == "uptree"
    assert profile_report.classify("jit_rlc_msm") == "msm_other"
    assert profile_report.classify("TransferToDevice") == "transfer"
    assert profile_report.classify("$SomePythonFrame") == "host_python"
    assert profile_report.classify("mystery") == "other"


def test_debug_device_profile_route(tmp_path):
    """GET /debug/device_profile?action=start|stop|status against a live
    RPCServer handler: the operator surface for profiling a running node."""
    import asyncio
    from types import SimpleNamespace

    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.rpc.server import RPCServer

    cfg = test_config()
    cfg.instrumentation.profile_dir = str(tmp_path)
    rpc = RPCServer(SimpleNamespace(config=cfg, metrics=None))

    async def run():
        st = await rpc._debug_device_profile({})
        assert st["active"] is False
        # start/stop are unsafe-gated (they mutate process-global profiler
        # state); status above served fine without it
        cfg.rpc.unsafe = False
        with pytest.raises(ValueError, match="unsafe"):
            await rpc._debug_device_profile({"action": "start"})
        cfg.rpc.unsafe = True
        out = await rpc._debug_device_profile({"action": "start"})
        assert out["active"] and out["dir"].startswith(str(tmp_path))
        _flush_once()
        out = await rpc._debug_device_profile({"action": "stop"})
        assert not out["active"] and out["artifacts"]
        with pytest.raises(ValueError):
            await rpc._debug_device_profile({"action": "bogus"})

    asyncio.run(run())


def test_profiler_actions_counted():
    from tendermint_tpu.libs import metrics as M

    text = M.global_registry().expose()
    # the round-trips above incremented start/stop at least once each
    assert "tendermint_profiler_actions_total" in text
