"""Reactor interface (reference: p2p/base_reactor.go:15).

A reactor owns a set of channels; the Switch routes each received message to
the reactor registered for its channel. Lifecycle: set_switch -> start ->
(add_peer/receive/remove_peer)* -> stop."""

from __future__ import annotations

from typing import List

from tendermint_tpu.p2p.conn.connection import ChannelDescriptor


class Reactor:
    def __init__(self, name: str):
        self.name = name
        self.switch = None

    def set_switch(self, switch) -> None:
        self.switch = switch

    def get_channels(self) -> List[ChannelDescriptor]:
        raise NotImplementedError

    async def start(self) -> None:
        pass

    async def stop(self) -> None:
        pass

    async def add_peer(self, peer) -> None:
        """Called after the peer is started and registered."""

    async def remove_peer(self, peer, reason) -> None:
        """Called when the peer is stopped (error or disconnect)."""

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        """One complete message from a peer on one of our channels."""
