"""Verify-path circuit breaker: persistent device failure trips TPU -> CPU.

The batch pipeline already has a *per-flush* degradation ladder (RLC -> per-sig
-> CPU inside crypto/batch.py), but before this module a persistently sick
device was re-tried on EVERY flush: each one paid the device submit, the
exception, and the CPU fallback — a retry storm that added the device timeout
to every consensus round. The breaker makes the degradation *sticky*:

    CLOSED ──(threshold consecutive device failures
              or flush-deadline overruns)──> OPEN
    OPEN ──(probe scheduled after exponential backoff)──> HALF_OPEN
    HALF_OPEN ──probe passes──> CLOSED   /  probe fails──> OPEN (backoff *= 2)

While OPEN (or HALF_OPEN), `allow_device()` is False and crypto/batch routes
default-"jax" verification straight to the host loop — no device work at all,
so a dead tunnel costs exactly one failed flush. A background daemon thread
probes the device with exponential backoff (base..max, configured via
`[crypto] breaker_probe_base/max`) and re-arms the TPU path when a probe
passes. State + trip counters ride /metrics (tendermint_batch_verify_breaker_*)
and /debug/verify_stats (the `breaker` block).

No reference counterpart — the reference's serial host loop
(types/validator_set.go:680) has no device to break away from. The pattern is
the standard Nygard circuit breaker, applied to an accelerator dependency.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger("tendermint_tpu.crypto.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class VerifyCircuitBreaker:
    """Thread-safe; shared by the consensus event loop, the prewarm thread,
    and its own probe thread."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        failure_threshold: int = 3,
        flush_deadline_s: float = 0.0,
        probe_interval_base: float = 1.0,
        probe_interval_max: float = 60.0,
        probe: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        spawn_probe_thread: bool = True,
    ):
        self._lock = threading.RLock()
        self.enabled = enabled
        self.failure_threshold = max(1, int(failure_threshold))
        self.flush_deadline_s = float(flush_deadline_s)
        self.probe_interval_base = float(probe_interval_base)
        self.probe_interval_max = float(probe_interval_max)
        self._probe = probe  # raises on an unhealthy device
        self._clock = clock
        self._spawn_probe_thread = spawn_probe_thread
        self.state = CLOSED
        self._consec_failures = 0
        self._consec_overruns = 0
        self._trips = {}  # reason -> count
        self._last_error: Optional[str] = None
        self._opened_at: Optional[float] = None
        self._probe_backoff = self.probe_interval_base
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_wakeup = threading.Event()
        # Per-backend rungs (ISSUE 19): named sub-breakers below the global
        # device gate — e.g. "mesh" covers the sharded multi-chip path, so a
        # sick MESH degrades to single-chip while allow_device() stays True.
        self._backends: dict = {}  # name -> state dict

    # -- config / lifecycle -------------------------------------------------

    def configure(
        self,
        *,
        enabled: Optional[bool] = None,
        failure_threshold: Optional[int] = None,
        flush_deadline_s: Optional[float] = None,
        probe_interval_base: Optional[float] = None,
        probe_interval_max: Optional[float] = None,
    ) -> None:
        """Apply `[crypto]` config (node/node.py). Keeps current state except
        that disabling re-closes an open breaker."""
        with self._lock:
            if failure_threshold is not None:
                self.failure_threshold = max(1, int(failure_threshold))
            if flush_deadline_s is not None:
                self.flush_deadline_s = float(flush_deadline_s)
            if probe_interval_base is not None:
                self.probe_interval_base = float(probe_interval_base)
            if probe_interval_max is not None:
                self.probe_interval_max = float(probe_interval_max)
            if enabled is not None:
                self.enabled = bool(enabled)
                if not self.enabled and self.state != CLOSED:
                    self._close_locked()
        self._probe_wakeup.set()  # wake a sleeping probe loop to re-check

    def set_probe(self, probe: Optional[Callable[[], None]]) -> None:
        self._probe = probe

    def reset(self) -> None:
        """Force-close and zero counters (tests, operator reset)."""
        with self._lock:
            self._close_locked()
            self._trips.clear()
            self._last_error = None
            self._backends.clear()
        self._probe_wakeup.set()  # let the probe loop notice and exit now

    # -- the hot-path gate --------------------------------------------------

    def allow_device(self) -> bool:
        """One cheap read on every flush: True routes to the device, False
        means the caller must use the CPU path without touching the device."""
        return (not self.enabled) or self.state == CLOSED

    # -- outcome recording --------------------------------------------------

    def record_success(self, duration_s: Optional[float] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            if (
                duration_s is not None
                and self.flush_deadline_s > 0
                and duration_s > self.flush_deadline_s
            ):
                self._consec_overruns += 1
                self._consec_failures = 0
                if (
                    self.state == CLOSED
                    and self._consec_overruns >= self.failure_threshold
                ):
                    # CLOSED guard: a straggler flush submitted before a trip
                    # must not re-trip an already-open breaker (double-counted
                    # trips + a probe-backoff reset mid-escalation)
                    self._trip_locked("flush_deadline", f"flush took {duration_s:.3f}s")
                return
            self._consec_failures = 0
            self._consec_overruns = 0

    def record_failure(self, error: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._last_error = error or "device call failed"
            self._consec_failures += 1
            if self.state == CLOSED and self._consec_failures >= self.failure_threshold:
                self._trip_locked("device_error", error)

    # -- per-backend states (ISSUE 19 elastic mesh) -------------------------
    #
    # The global CLOSED/OPEN pair above answers "may we touch the device AT
    # ALL"; these named rungs answer "may we use THIS path on the device".
    # Opening a backend never opens the global breaker: tripping "mesh"
    # routes sharded flushes to the single-chip fused path while
    # allow_device() stays True — the all-or-nothing trip becomes a ladder.

    def _backend_locked(self, name: str) -> dict:
        st = self._backends.get(name)
        if st is None:
            st = self._backends[name] = {
                "state": CLOSED,
                "consec_failures": 0,
                "trips": 0,
                "last_error": None,
                "opened_at": None,
                "backoff": self.probe_interval_base,
            }
        return st

    def allow_backend(self, name: str) -> bool:
        """Cheap per-flush gate for a named backend rung. False while that
        rung is open; after the rung's backoff elapses it half-opens, so
        exactly one trial flush re-tests the path (no dedicated prober:
        the trial IS the probe — its success/failure records below)."""
        if not self.enabled:
            return True
        with self._lock:
            st = self._backends.get(name)
            if st is None or st["state"] == CLOSED:
                return True
            if st["state"] == HALF_OPEN:
                return True
            if (
                st["opened_at"] is not None
                and self._clock() - st["opened_at"] >= st["backoff"]
            ):
                st["state"] = HALF_OPEN
                return True
            return False

    def record_backend_failure(self, name: str, error: str = "") -> bool:
        """One failure that is attributable to the BACKEND, not to a single
        device (e.g. an un-attributed mesh flush failure: every per-device
        probe passed, yet the collective call died). Trips the rung open at
        the same consecutive-failure threshold as the global breaker; a
        half-open trial failure re-opens immediately with doubled backoff.
        Returns True when this call tripped the rung."""
        if not self.enabled:
            return False
        with self._lock:
            st = self._backend_locked(name)
            st["last_error"] = error or "backend call failed"
            st["consec_failures"] += 1
            tripped = False
            if st["state"] == HALF_OPEN:
                st["state"] = OPEN
                st["opened_at"] = self._clock()
                st["backoff"] = min(st["backoff"] * 2, self.probe_interval_max)
                st["consec_failures"] = 0
            elif (
                st["state"] == CLOSED
                and st["consec_failures"] >= self.failure_threshold
            ):
                st["state"] = OPEN
                st["trips"] += 1
                st["consec_failures"] = 0
                st["opened_at"] = self._clock()
                st["backoff"] = self.probe_interval_base
                tripped = True
        if tripped:
            try:
                self._metrics().breaker_trips.labels(f"backend:{name}").inc()
            except Exception:
                pass
            logger.error(
                "verify backend %r tripped open: %s — degrading one rung "
                "(device path itself stays armed)", name, error or "n/a",
            )
        return tripped

    def record_backend_success(self, name: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            st = self._backends.get(name)
            if st is None:
                return
            st["consec_failures"] = 0
            if st["state"] == HALF_OPEN:
                st["state"] = CLOSED
                st["opened_at"] = None
                st["backoff"] = self.probe_interval_base
                logger.warning("verify backend %r trial passed — re-armed", name)

    def open_backend(self, name: str, error: str = "") -> None:
        """Force a rung open (the mesh health model uses this when the
        healthy device count can no longer form a >= 2-chip mesh)."""
        with self._lock:
            st = self._backend_locked(name)
            if st["state"] != OPEN:
                st["state"] = OPEN
                st["trips"] += 1
                st["opened_at"] = self._clock()
            st["last_error"] = error or st["last_error"]

    def close_backend(self, name: str) -> None:
        """Re-arm a rung (health prober, after clean probes)."""
        with self._lock:
            st = self._backends.get(name)
            if st is None:
                return
            if st["state"] != CLOSED:
                logger.warning("verify backend %r re-armed", name)
            st["state"] = CLOSED
            st["consec_failures"] = 0
            st["opened_at"] = None
            st["backoff"] = self.probe_interval_base

    # -- state transitions --------------------------------------------------

    def _metrics(self):
        from tendermint_tpu.libs import metrics as _metrics

        return _metrics.batch_metrics()

    def _set_state_locked(self, state: str) -> None:
        self.state = state
        try:
            self._metrics().breaker_state.set(_STATE_GAUGE[state])
        except Exception:  # metrics must never break the verify path
            pass

    def _trip_locked(self, reason: str, error: str) -> None:
        self._trips[reason] = self._trips.get(reason, 0) + 1
        self._opened_at = self._clock()
        self._probe_backoff = self.probe_interval_base
        self._consec_failures = 0
        self._consec_overruns = 0
        self._set_state_locked(OPEN)
        try:
            self._metrics().breaker_trips.labels(reason).inc()
        except Exception:
            pass
        logger.error(
            "verify-path circuit breaker TRIPPED (%s): %s — routing "
            "verification to the CPU host loop until a health probe passes",
            reason, error or "n/a",
        )
        try:
            from tendermint_tpu.libs.trace import tracer

            if tracer.enabled:
                tracer.event("breaker.trip", reason=reason, error=error or None)
        except Exception:
            pass
        if self._spawn_probe_thread:
            self._start_probe_thread_locked()

    def _close_locked(self) -> None:
        self._consec_failures = 0
        self._consec_overruns = 0
        self._opened_at = None
        self._probe_backoff = self.probe_interval_base
        self._set_state_locked(CLOSED)

    # -- probing ------------------------------------------------------------

    def probe_now(self) -> bool:
        """One synchronous probe attempt; True iff it passed (breaker closes).
        Used by tests/bench and the probe thread."""
        probe = self._probe
        with self._lock:
            if self.state == CLOSED:
                return True
            self._set_state_locked(HALF_OPEN)
        ok, err = True, ""
        if probe is not None:
            try:
                probe()
            except Exception as e:
                ok, err = False, repr(e)
        with self._lock:
            try:
                self._metrics().breaker_probes.labels("pass" if ok else "fail").inc()
            except Exception:
                pass
            open_for = (
                round(self._clock() - self._opened_at, 3)
                if self._opened_at is not None
                else None
            )
            if ok:
                logger.warning(
                    "verify-path circuit breaker: health probe passed — "
                    "re-arming the device path"
                )
                self._close_locked()
            else:
                self._last_error = err
                self._probe_backoff = min(
                    self._probe_backoff * 2, self.probe_interval_max
                )
                self._set_state_locked(OPEN)
            next_backoff = self._probe_backoff
        # Flight-recorder events (same ring as the flush spans they explain:
        # /debug/trace interleaves breaker history with the degraded flushes)
        try:
            from tendermint_tpu.libs.trace import tracer

            if tracer.enabled:
                if ok:
                    tracer.event("breaker.rearm", open_for_s=open_for)
                else:
                    tracer.event(
                        "breaker.probe_fail",
                        reason=err or None,
                        next_backoff_s=next_backoff,
                        open_for_s=open_for,
                    )
        except Exception:
            pass
        return ok

    def _start_probe_thread_locked(self) -> None:
        if self._probe_thread is not None and self._probe_thread.is_alive():
            # A live loop serves the new trip too; the nudge covers the
            # window where it is mid-backoff (it re-checks state on wake).
            # The loop can only decide to EXIT (and clear _probe_thread)
            # under this same lock, so a thread seen alive here either
            # already cleared the slot (and we spawn below) or will observe
            # the new OPEN state at its next top-of-loop check — no
            # open-forever-with-no-prober window.
            self._probe_wakeup.set()
            return
        self._probe_wakeup.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="verify-breaker-probe", daemon=True
        )
        self._probe_thread.start()

    def _probe_loop(self) -> None:
        while True:
            with self._lock:
                if self.state == CLOSED or not self.enabled:
                    # exit decision and slot clear are atomic with the
                    # trip path's is_alive check (same lock)
                    self._probe_thread = None
                    return
                wait = self._probe_backoff
            # Event.wait instead of sleep: reset()/configure()/a re-trip
            # wake the loop early to re-check state
            if self._probe_wakeup.wait(wait):
                self._probe_wakeup.clear()
            with self._lock:
                if self.state == CLOSED or not self.enabled:
                    self._probe_thread = None
                    return
            try:
                self.probe_now()
            except Exception:  # a broken probe fn must not kill the loop
                logger.exception("breaker probe raised unexpectedly")

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/verify_stats `breaker` block."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "state": self.state,
                "consecutive_failures": self._consec_failures,
                "consecutive_overruns": self._consec_overruns,
                "failure_threshold": self.failure_threshold,
                "flush_deadline_s": self.flush_deadline_s or None,
                "trips": dict(self._trips),
                "open_for_s": (
                    round(self._clock() - self._opened_at, 3)
                    if self._opened_at is not None and self.state != CLOSED
                    else None
                ),
                "probe_backoff_s": (
                    self._probe_backoff if self.state != CLOSED else None
                ),
                "last_error": self._last_error,
                "backends": {
                    name: {
                        "state": st["state"],
                        "consecutive_failures": st["consec_failures"],
                        "trips": st["trips"],
                        "open_for_s": (
                            round(self._clock() - st["opened_at"], 3)
                            if st["opened_at"] is not None and st["state"] != CLOSED
                            else None
                        ),
                        "last_error": st["last_error"],
                    }
                    for name, st in sorted(self._backends.items())
                },
            }
