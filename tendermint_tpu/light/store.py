"""Trusted light-block store.

reference: light/store/store.go (Store iface) + light/store/db/db.go
(DB-backed impl with ordered heights, size-bounded pruning).

Thread-safe: the light SERVICE (light/service.py) uses a LightStore as its
verified-header cache and hits it from many concurrent request tasks, the
coalescer's worker thread, and the pruner at once — `_heights` is guarded
by an RLock so a reader never sees a half-applied insert/remove (the
reference wraps its db in a mutex for the same reason,
light/store/db/db.go:25)."""

from __future__ import annotations

import bisect
import struct
import threading
from typing import List, Optional

from tendermint_tpu.libs.kvdb import KVDB
from tendermint_tpu.types.light import (
    LightBlock,
    light_block_from_bytes,
    light_block_to_bytes,
)

_LB_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _LB_PREFIX + struct.pack(">Q", height)


class LightStore:
    """Stores verified light blocks keyed by big-endian height so prefix
    iteration yields ascending order (reference: light/store/db/db.go:33)."""

    def __init__(self, db: KVDB):
        self.db = db
        self._lock = threading.RLock()
        self._heights: List[int] = [
            struct.unpack(">Q", k[len(_LB_PREFIX):])[0]
            for k, _ in db.iterate_prefix(_LB_PREFIX)
        ]
        self._heights.sort()

    def save_light_block(self, lb: LightBlock) -> None:
        """reference: light/store/db/db.go:52 SaveLightBlock."""
        if lb.height <= 0:
            raise ValueError("height <= 0")
        with self._lock:
            i = bisect.bisect_left(self._heights, lb.height)
            if i == len(self._heights) or self._heights[i] != lb.height:
                self._heights.insert(i, lb.height)
            self.db.set(_key(lb.height), light_block_to_bytes(lb))

    def light_block(self, height: int) -> Optional[LightBlock]:
        """reference: light/store/db/db.go:96 LightBlock."""
        raw = self.db.get(_key(height))
        return light_block_from_bytes(raw) if raw is not None else None

    def latest_light_block(self) -> Optional[LightBlock]:
        """reference: light/store/db/db.go:126 LightBlockBefore/latest."""
        with self._lock:
            h = self._heights[-1] if self._heights else None
        return self.light_block(h) if h is not None else None

    def first_light_block(self) -> Optional[LightBlock]:
        with self._lock:
            h = self._heights[0] if self._heights else None
        return self.light_block(h) if h is not None else None

    def light_block_before(self, height: int) -> Optional[LightBlock]:
        """Latest stored block strictly below height
        (reference: light/store/db/db.go:126)."""
        with self._lock:
            i = bisect.bisect_left(self._heights, height)
            if i == 0:
                return None
            h = self._heights[i - 1]
        return self.light_block(h)

    def delete_light_block(self, height: int) -> None:
        with self._lock:
            self.db.delete(_key(height))
            try:
                self._heights.remove(height)
            except ValueError:
                pass

    def prune(self, size: int) -> None:
        """Keep only the newest `size` blocks (reference: light/store/db/db.go:152)."""
        with self._lock:
            while len(self._heights) > size:
                self.delete_light_block(self._heights[0])

    def size(self) -> int:
        with self._lock:
            return len(self._heights)

    def heights(self) -> List[int]:
        with self._lock:
            return list(self._heights)
