"""Fleet referee end-to-end (ISSUE 17): the seeded heterogeneous soak.

Three lanes:

- tier-1 spec tests: `FleetSpec.generate` is a pure function of the seed —
  same seed, same fingerprint, bit-for-bit; role split, mixed keys, staged
  joiners, bounded-degree topology, and the fleet-aware chaos-composer
  invariants (partition groups span every index, crashes never target a
  staged joiner or node 0) all hold by construction;
- a tier-1 smoke (7 nodes — the issue caps it at 8): the full
  harness -> chaos -> workloads -> dumps -> referee -> release-gate story,
  small enough for the tier-1 budget;
- the slow acceptance soak: >= 50 nodes, all three roles, simultaneous
  chaos + signed-tx flood + Zipfian light traffic, >= 20 heights, zero
  safety violations, every surviving node on the report's waterfall, and
  the seed replays the same schedule fingerprint. BLS validators are 0 at
  this scale — the pure-python CPU pairing costs ~0.4 s per verify, so the
  mixed-key path is proven live by the small soak below instead.
"""

import asyncio
import json
import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.chaos.fleet import (
    ROLE_FULL,
    ROLE_LIGHT,
    ROLE_VALIDATOR,
    ROLES,
    FleetSpec,
    run_fleet_soak,
)
from tendermint_tpu.tools import fleet_referee as ref
from tendermint_tpu.tools import release_gate as gate

SEED = int(os.environ.get("TMTPU_FLEET_SEED", "20260807"))


# -- the seeded spec (tier-1) --------------------------------------------------


def test_fleet_spec_is_deterministic():
    a = FleetSpec.generate(SEED, 50)
    b = FleetSpec.generate(SEED, 50)
    assert a.to_json() == b.to_json()
    assert a.fingerprint() == b.fingerprint()
    assert a.schedule.fingerprint() == b.schedule.fingerprint()
    # a different seed is a different fleet
    c = FleetSpec.generate(SEED + 1, 50)
    assert c.fingerprint() != a.fingerprint()


def test_fleet_spec_round_trips_through_json():
    a = FleetSpec.generate(SEED, 50)
    b = FleetSpec.from_json(a.to_json())
    assert b.fingerprint() == a.fingerprint()
    assert b.nodes == a.nodes
    assert b.topology == a.topology


def test_fleet_spec_heterogeneity():
    spec = FleetSpec.generate(SEED, 50)
    assert spec.n_nodes == 50
    roles = {ns.role for ns in spec.nodes}
    assert roles == set(ROLES)
    # mixed validator keys: the default spec carries a real BLS validator
    key_types = {ns.key_type for ns in spec.validators}
    assert key_types == {"ed25519", "bls12_381"}
    # staged joiners exist and cover both catch-up paths
    modes = {ns.sync_mode for ns in spec.joiners}
    assert "blocksync" in modes and "statesync" in modes
    assert all(ns.join_at > 0 for ns in spec.joiners)
    assert all(ns.role == ROLE_FULL for ns in spec.joiners)
    # node 0 anchors statesync: always an initial ed25519 validator
    n0 = spec.nodes[0]
    assert (n0.role, n0.key_type, n0.join_at) == (ROLE_VALIDATOR, "ed25519", 0.0)


def test_fleet_topology_is_bounded_and_connected():
    spec = FleetSpec.generate(SEED, 50)
    n = spec.n_nodes
    # far below the O(n^2)/2 full mesh
    assert len(spec.topology) < n * 8
    assert all(0 <= a < b < n for a, b in spec.topology)
    # the initial nodes form one connected component (ring + chords)
    initial = {ns.index for ns in spec.initial()}
    adj = {i: set() for i in initial}
    for a, b in spec.topology:
        if a in initial and b in initial:
            adj[a].add(b)
            adj[b].add(a)
    seen, frontier = {0}, [0]
    while frontier:
        nxt = frontier.pop()
        for j in adj[nxt]:
            if j not in seen:
                seen.add(j)
                frontier.append(j)
    assert seen == initial
    # every staged joiner has edges into the initial set to dial at join_at
    for ns in spec.joiners:
        peers = {b for a, b in spec.topology if a == ns.index}
        peers |= {a for a, b in spec.topology if b == ns.index}
        assert peers & initial


def test_fleet_schedule_respects_the_lifecycle():
    spec = FleetSpec.generate(SEED, 50)
    n = spec.n_nodes
    initial = {ns.index for ns in spec.initial()}
    light = {ns.index for ns in spec.light_edges}
    assert len(spec.schedule) > 0
    for ev in spec.schedule.events:
        params = ev.param_dict()
        if ev.kind == "partition":
            covered = {i for g in params["groups"] for i in g}
            # LocalChaosNet blocks a node absent from ALL groups from
            # everything — a staged joiner must never boot into a void
            assert covered == set(range(n))
        elif ev.kind in ("crash", "restart"):
            t = params["target"]
            assert t in initial and t not in light and t != 0
        elif ev.kind in ("peer_stall", "peer_lie", "chunk_corrupt"):
            t = params["target"]
            assert spec.role_of(t) == ROLE_VALIDATOR and t != 0


def test_fleet_spec_rejects_sub_quorum_fleets():
    with pytest.raises(ValueError):
        FleetSpec.generate(SEED, 3)


def _smoke_spec(seed=SEED):
    """7 nodes (the issue caps the tier-1 smoke at 8): 4 ed25519
    validators (one seated as the signature poisoner), one resident full
    node, one blocksync joiner, one light edge; two short benign-ish chaos
    episodes plus at least one guaranteed sig_poison flood."""
    return FleetSpec.generate(
        seed,
        7,
        validator_frac=0.58,
        light_frac=0.15,
        joiner_frac=0.5,
        bls_validators=0,
        statesync_joiners=0,
        poisoners=1,
        peer_degree=3,
        episodes=2,
        min_gap=0.5,
        max_gap=1.0,
        min_episode=0.8,
        max_episode=1.5,
        start_delay=0.5,
        join_window=(2.0, 4.0),
        chaos_kinds=("partition", "peer_stall", "sig_poison"),
    )


# -- the tier-1 smoke: harness -> referee -> verdict ---------------------------


def test_fleet_smoke_end_to_end(tmp_path):
    spec = _smoke_spec()
    assert len(spec.validators) == 4
    assert len(spec.joiners) == 1
    assert len(spec.light_edges) == 1
    # the spec seats exactly one poisoner and schedules its flood
    poisoners = [ns for ns in spec.nodes if ns.poisoner]
    assert len(poisoners) == 1 and poisoners[0].role == ROLE_VALIDATOR
    assert any(ev.kind == "sig_poison" for ev in spec.schedule.events)
    # the composer protects the poisoner like the anchor: its flood (and
    # its quarantine) must stay observable for the whole soak
    assert all(
        ev.param_dict().get("target") != poisoners[0].index
        for ev in spec.schedule.events
        if ev.kind in ("crash", "restart")
    )

    # the suspicion scorer is process-global (like the verified-row memo):
    # start this soak from a clean slate so the quarantine assertions below
    # are about THIS seeded adversary, not an earlier test's leftovers
    from tendermint_tpu.crypto import provenance as _prov

    _prov.default_scorer().reset()

    res = asyncio.run(
        run_fleet_soak(spec, str(tmp_path), min_heights=6, deadline_s=240.0)
    )

    assert res["verdict"] == "pass"
    assert res["safety_violations"] == 0

    # adversarial flush defense: the poisoner's flood (precheck-passing,
    # verify-failing votes) was absorbed with ZERO safety violations, and
    # the scorer quarantined exactly the seeded adversary's peer tag
    poisoner_id = res["poisoners"][poisoners[0].index]
    assert poisoner_id
    suspicion = res["suspicion"]
    assert f"peer:{poisoner_id}" in suspicion["quarantined"]
    # repeat offenses while quarantined fed the punishment pipeline
    assert suspicion["punished"] >= 1
    assert res["heights"] >= 6
    assert res["live_nodes"] == 7
    assert res["chaos_applied"] >= len(spec.schedule)
    assert res["chaos_errors"] == []
    assert res["workload"]["tx_submitted"] > 0
    assert res["workload"]["light_ok"] > 0
    # the blocksync joiner came up mid-soak and caught up (the soak's
    # settle gate holds every live node within lag_tolerance=2 of head)
    (joiner,) = res["joiners"].values()
    assert joiner["sync_mode"] == "blocksync"
    assert joiner["height"] >= res["heights"] - 2

    # the report covers EVERY surviving node's waterfall
    report = res["report"]
    assert report["coverage"]["partial"] is False
    assert report["waterfall"]["uncovered"] == []
    assert len(report["waterfall"]["per_node"]) == 7
    assert set(report["roles"].values()) == set(ROLES)
    assert report["manifest"]["fingerprint"] == spec.fingerprint()

    # same seed, same fleet: the soak log's fingerprints replay
    again = _smoke_spec()
    assert again.fingerprint() == res["fingerprint"]
    assert again.schedule.fingerprint() == res["schedule_fingerprint"]

    # the referee CLI re-audits the evidence offline and agrees
    dumps_dir = res["dumps_dir"]
    assert ref.main(["--dumps", dumps_dir, "--check"]) == 0
    with open(os.path.join(dumps_dir, "fleet_report.json")) as f:
        on_disk = json.load(f)
    assert on_disk["verdict"] == "pass"

    # ... and the composed release gate hands down the same verdict
    result = gate.evaluate(fleet_dumps=dumps_dir, perf_root=str(tmp_path))
    assert result["exit_code"] == 0
    assert result["verdict"] == "pass"


# -- the slow acceptance soaks -------------------------------------------------


@pytest.mark.slow
def test_fleet_soak_50_nodes(tmp_path):
    """ISSUE 17 acceptance: >= 50 nodes, all three roles, chaos + tx flood
    + light traffic at once, >= 20 heights, zero safety violations, full
    waterfall coverage, reproducible schedule fingerprint."""
    spec = FleetSpec.generate(SEED, 50, bls_validators=0)
    # one starved core boots 44 nodes in ~6 min and then commits a height
    # every ~25-45 s under the chaos episodes — measured ~17.5 min end to
    # end, so the stall deadline sits well past that
    res = asyncio.run(
        run_fleet_soak(spec, str(tmp_path), min_heights=20, deadline_s=1800.0)
    )

    assert res["verdict"] == "pass"
    assert res["safety_violations"] == 0
    assert res["heights"] >= 20
    assert res["workload"]["tx_submitted"] > 0
    assert res["workload"]["light_ok"] > 0

    report = res["report"]
    # every surviving node is on the waterfall — nobody dropped silently
    assert report["coverage"]["partial"] is False
    assert report["waterfall"]["uncovered"] == []
    assert len(report["waterfall"]["per_node"]) == res["live_nodes"]
    assert set(report["roles"].values()) == set(ROLES)

    # both catch-up paths ran: the statesync joiner's store starts past
    # genesis (it trusted a snapshot), the blocksync joiners' at 1
    modes = {j["sync_mode"] for j in res["joiners"].values()}
    assert modes == {"blocksync", "statesync"}
    for j in res["joiners"].values():
        assert j["height"] is not None and j["height"] >= 20 - 2
        if j["sync_mode"] == "statesync":
            assert j["base"] > 1

    # the same seed replays the same fleet and the same chaos
    again = FleetSpec.generate(SEED, 50, bls_validators=0)
    assert again.fingerprint() == res["fingerprint"]
    assert again.schedule.fingerprint() == res["schedule_fingerprint"]


@pytest.mark.slow
def test_fleet_mixed_keys_live(tmp_path):
    """The mixed ed25519/BLS validator path, live at a scale the
    pure-python pairing backend can afford (~0.4 s per BLS verify)."""
    spec = FleetSpec.generate(
        SEED + 1,
        6,
        validator_frac=0.67,
        light_frac=0.17,
        joiner_frac=0.0,
        bls_validators=1,
        statesync_joiners=0,
        peer_degree=3,
        episodes=1,
        min_episode=0.5,
        max_episode=1.0,
        chaos_kinds=("device_error",),
    )
    assert {ns.key_type for ns in spec.validators} == {"ed25519", "bls12_381"}
    res = asyncio.run(
        run_fleet_soak(spec, str(tmp_path), min_heights=4, deadline_s=420.0)
    )
    assert res["verdict"] == "pass"
    assert res["safety_violations"] == 0
    assert res["heights"] >= 4
