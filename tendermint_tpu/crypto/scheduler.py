"""Global verification scheduler: one device, every consumer, QoS lanes.

The repo grew five ad-hoc batch-verification entry points — live votes
(types/vote_set.py), the light service (light/service.py), commit
verification (types/validator_set.py), blocksync catch-up
(blocksync/reactor.py), and evidence (evidence/pool.py) — all competing
for the one device uncoordinated, plus the single biggest serial loop
left: per-tx CheckTx signature verification on the admission path
(mempool/mempool.py). This module is the coordinator ROADMAP item 2 calls
for: every consumer submits its (pubkey, msg, sig) rows to a node-wide
`VerifyScheduler`, which owns the device and drains priority lanes into
combined flushes ("Efficient FPGA-based ECDSA Verification Engine for
Permissioned Blockchains", PAPERS.md, is exactly this shape: admission-path
batch verification as the throughput lever for permissioned chains).

Lanes, in priority order:

    votes      the live consensus path. PREEMPTS: queued vote rows flush
               immediately and ALONE — they never wait behind, or share a
               flush with, bulk work (a vote flush's wall must not inflate
               because 10k CheckTx rows were queued).
    light      light-client serving (light/service.py). Rows wait at most
               the PR 9 coalescing-window SLO (`light_max_wait`), so many
               clients x many heights still share one cross-height flush.
    admission  CheckTx signature prechecks (mempool/mempool.py). Bounded
               latency (`admission_max_wait`), bounded rows per flush.
    catchup    blocksync replay + evidence re-verification. Soaks IDLE
               device capacity only: scheduled when no higher lane has
               rows, with a starvation floor so a busy node still syncs.
    quarantine rows from sources the suspicion scorer has quarantined
               (crypto/provenance.py: peers/senders whose rows recently
               failed). Flushes ALONE, only when every other lane is
               empty (plus a starvation floor), so a poisoning flood can
               force recovery bisections only on its own flushes — never
               on a vote/light/admission flush again.

Budgets respond to the PR 5 overload controller (node/overload.py calls
`set_pressure`): level 1 shrinks the admission/catch-up budgets (fewer rows
per flush, longer waits); level 2 pauses catch-up entirely. Per-lane queue
waits feed the PR 8 SLO burn-rate engine (`verify_lane_wait_*` budgets) and
the `tendermint_verify_lane_*` metric series; `stats()` is served as the
`scheduler` block of GET /debug/verify_stats.

Under the hood one dispatch thread drains the lanes into combined
`crypto/batch.verify_batch` flushes. Verdict recovery is the
FlushAccumulator contract (PR 9): the combined RLC check only
short-circuits when EVERY row passes, and any failure recovers the exact
per-row mask, so each consumer's verdict slice is byte-identical to a
standalone verify_batch of its own rows. The flush itself rides the full
PR 4 ladder — circuit breaker, CPU degrade — so a breaker-OPEN routes
every lane to the host loop with zero device work.

Consumers integrate three ways:

    mask = sched.verify_rows("admission", pubkeys, msgs, sigs)   # blocking
    with sched.lane_scope("catchup"):                            # transparent
        ...        # any verify_batch / verify_commit* inside routes via the lane
    with crypto.batch.accumulate_flushes(sched.accumulate("light")) as acc:
        ...        # PR 9 submit/finish phases, flush() rides the lane

All three block the calling thread until the lane's flush lands (the same
contract as calling verify_batch directly — only the WHO-flushes moved).
A consumer is never wedged: a closed scheduler, or a verdict that misses
`wait_timeout`, falls back to an inline verify_batch on the caller's
thread.

No reference counterpart: the reference verifies every signature serially
at each call site; a device worth sharing is what makes scheduling it a
subsystem.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from tendermint_tpu.libs.txtrace import StageStats

logger = logging.getLogger("tendermint_tpu.crypto.scheduler")

__all__ = [
    "LANES",
    "VerifyScheduler",
    "LaneAccumulator",
    "Ticket",
    "set_default",
    "default_scheduler",
]

# priority order: index 0 preempts everything below it
LANES = ("votes", "light", "admission", "catchup", "quarantine")

# a starving catch-up lane flushes anyway after this many times its
# configured idle wait (unless pressure level 2 pauses it): "soaks idle
# capacity" must not become "a syncing node wedges whenever the chain is
# busy" — the floor trades a little bulk interference for liveness
CATCHUP_STARVATION_FACTOR = 10.0


class Ticket:
    """One submit's claim on a future combined flush: `wait()` blocks until
    the dispatch thread lands the flush and returns this submit's verdict
    slice (or re-raises the flush's error)."""

    __slots__ = ("lane", "rows", "enqueued_t", "flush_seq", "wait_s",
                 "_event", "_mask", "_error")

    def __init__(self, lane: str, rows: int):
        self.lane = lane
        self.rows = rows
        self.enqueued_t = time.monotonic()
        self.flush_seq: Optional[int] = None  # device flush this rode
        self.wait_s: Optional[float] = None   # queue wait (enqueue -> flush)
        self._event = threading.Event()
        self._mask: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"verify ticket ({self.lane}, {self.rows} rows) not flushed "
                f"within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._mask

    # dispatcher side
    def _resolve(self, mask: Optional[np.ndarray],
                 error: Optional[BaseException]) -> None:
        self._mask = mask
        self._error = error
        self._event.set()


class _LaneState:
    __slots__ = ("name", "queue", "rows", "flushes", "rows_total", "paused")

    def __init__(self, name: str):
        self.name = name
        self.queue: deque = deque()  # of (Ticket, pubkeys, msgs, sigs, key_types)
        self.rows = 0                # queued rows (depth)
        self.flushes = 0             # flushes that carried this lane's rows
        self.rows_total = 0          # rows flushed lifetime
        self.paused = False          # pressure level 2 (catch-up only)


class _Budgets:
    """Effective per-lane budgets under the current pressure level."""

    __slots__ = ("max_rows", "max_wait")

    def __init__(self, max_rows: int, max_wait: float):
        self.max_rows = max_rows
        self.max_wait = max_wait


class LaneAccumulator:
    """FlushAccumulator-compatible adapter (crypto/batch.accumulate_flushes
    installs it unchanged): rows accumulate locally during the submit
    phases, and `flush()` rides the scheduler lane instead of dispatching
    its own device call — so e.g. a whole light coalescing window joins the
    node-wide combined flush. Mirrors FlushAccumulator's latch semantics: a
    failed flush re-raises for every later finish."""

    __slots__ = ("scheduler", "lane", "pubkeys", "msgs", "sigs", "key_types",
                 "_mask", "_flushed", "_error", "flush_count", "flush_seq")

    def __init__(self, scheduler: "VerifyScheduler", lane: str):
        self.scheduler = scheduler
        self.lane = lane
        self.pubkeys: list = []
        self.msgs: list = []
        self.sigs: list = []
        self.key_types: list = []
        self._mask: Optional[np.ndarray] = None
        self._flushed = False
        self._error: Optional[BaseException] = None
        self.flush_count = 0
        self.flush_seq: Optional[int] = None  # the shared device flush id

    @property
    def lanes(self) -> int:
        return len(self.pubkeys)

    def add(self, pubkeys, msgs, sigs, key_types) -> tuple:
        if self._flushed:
            raise RuntimeError("LaneAccumulator already flushed")
        start = len(self.pubkeys)
        self.pubkeys.extend(pubkeys)
        self.msgs.extend(msgs)
        self.sigs.extend(sigs)
        self.key_types.extend(
            key_types if key_types is not None else ["ed25519"] * len(pubkeys)
        )
        return start, len(self.pubkeys)

    def flush(self) -> np.ndarray:
        if self._flushed:
            if self._error is not None:
                raise self._error
            return self._mask
        self._flushed = True
        if not self.pubkeys:
            self._mask = np.zeros(0, dtype=bool)
            return self._mask
        self.flush_count += 1
        try:
            kt = (
                self.key_types
                if any(t != "ed25519" for t in self.key_types)
                else None
            )
            ticket = self.scheduler.submit(
                self.lane, self.pubkeys, self.msgs, self.sigs, self.key_types
            )
            if ticket is None:  # closed/disabled: inline on this thread
                self._mask = self.scheduler._inline(
                    self.pubkeys, self.msgs, self.sigs, kt
                )
                return self._mask
            # rows passed through so a wait_timeout miss verifies inline
            # (the never-wedge contract) instead of failing every rider
            self._mask = self.scheduler._wait_or_fallback(
                ticket, (self.pubkeys, self.msgs, self.sigs, kt)
            )
            self.flush_seq = ticket.flush_seq
        except BaseException as e:
            self._error = e
            raise
        return self._mask


class VerifyScheduler:
    """The node-wide device coordinator (see module docstring)."""

    def __init__(self, config=None, backend: Optional[str] = None,
                 metrics=None, slo=None):
        """config: config.SchedulerConfig (None = defaults); backend: crypto
        backend for the combined flushes (None/"" = crypto default);
        metrics: libs/metrics.SchedulerMetrics or None; slo:
        libs/slo.SLOEngine or None (fed verify_lane_wait_* per flush)."""
        if config is None:
            from tendermint_tpu.config.config import SchedulerConfig

            config = SchedulerConfig()
        self.config = config
        self.backend = backend or (getattr(config, "backend", "") or None)
        self.metrics = metrics
        self.slo = slo
        self._lanes: Dict[str, _LaneState] = {n: _LaneState(n) for n in LANES}
        self._base: Dict[str, _Budgets] = {
            "votes": _Budgets(int(config.votes_max_rows),
                              float(config.votes_max_wait)),
            "light": _Budgets(int(config.light_max_rows),
                              float(config.light_max_wait)),
            "admission": _Budgets(int(config.admission_max_rows),
                                  float(config.admission_max_wait)),
            "catchup": _Budgets(int(config.catchup_max_rows),
                                float(config.catchup_max_wait)),
            "quarantine": _Budgets(
                int(getattr(config, "quarantine_max_rows", 4096)),
                float(getattr(config, "quarantine_max_wait", 0.05)),
            ),
        }
        self.pressure_level = 0
        self.wait_timeout = float(getattr(config, "wait_timeout", 30.0))
        self._cv = threading.Condition()
        self._closed = False
        self.flush_seq = 0          # device flushes issued
        self.preemptions = 0        # vote flushes that jumped queued bulk work
        self.fallbacks = 0          # consumer-side inline fallbacks
        self.wait_stats = StageStats()  # per-lane queue-wait percentiles
        self.flush_rows_last: Dict[str, int] = {}
        # bounded per-flush journal: {"seq", "t" (monotonic, flush start),
        # "wall_s", "rows": {lane: n}, "wait_s": {lane: oldest wait}} —
        # windowed analysis for the tx_admission bench (vote-path p99
        # before/during a flood) and the preemption tests
        self.flush_log: deque = deque(maxlen=4096)
        self._thread = threading.Thread(
            target=self._run, name="verify-scheduler", daemon=True
        )
        self._thread.start()
        # install the lane_scope router so verify_batch/verify_commit* calls
        # inside `with sched.lane_scope(...)` route here transparently
        _install_router()

    # -- budgets / pressure ---------------------------------------------------

    def effective_budget(self, lane: str) -> _Budgets:
        """The lane's budget under the current pressure level: level >= 1
        shrinks admission/catch-up rows by pressure_rows_factor and
        stretches their waits by pressure_wait_factor (votes and light are
        never squeezed); level 2 pauses catch-up (see _plan_locked)."""
        base = self._base[lane]
        if self.pressure_level < 1 or lane in ("votes", "light"):
            return base
        rf = float(getattr(self.config, "pressure_rows_factor", 0.5))
        wf = float(getattr(self.config, "pressure_wait_factor", 2.0))
        return _Budgets(
            max(1, int(base.max_rows * rf)) if base.max_rows > 0 else 0,
            base.max_wait * wf,
        )

    def set_pressure(self, level: int) -> None:
        """Overload-controller hook (node/overload.py): 0 normal, 1 shrink
        admission/catch-up budgets, 2 additionally pause catch-up."""
        with self._cv:
            if level == self.pressure_level:
                return
            self.pressure_level = int(level)
            self._lanes["catchup"].paused = level >= 2
            self._cv.notify_all()

    def set_lane_wait(self, lane: str, max_wait: float) -> None:
        """Re-pin one lane's coalescing window (light/service.py wires its
        [light_service] coalesce_window here so the PR 9 SLO survives the
        migration)."""
        with self._cv:
            self._base[lane].max_wait = max(0.0, float(max_wait))
            self._cv.notify_all()

    # -- submit side ----------------------------------------------------------

    def submit(self, lane: str, pubkeys: Sequence[bytes],
               msgs: Sequence[bytes], sigs: Sequence[bytes],
               key_types: Optional[Sequence[str]] = None,
               sources: Optional[Sequence[str]] = None) -> Optional[Ticket]:
        """Queue one consumer's rows on `lane`; returns a Ticket (None when
        the scheduler is closed — callers verify inline then). Thread-safe;
        never blocks beyond the lane mutex. `sources` is the rows' optional
        provenance (crypto/provenance.py tags); None tags them with the
        consumer lane at flush time."""
        if lane not in self._lanes:
            raise ValueError(f"unknown verify lane {lane!r}")
        n = len(pubkeys)
        if not (n == len(msgs) == len(sigs)):
            raise ValueError("pubkeys/msgs/sigs length mismatch")
        ticket = Ticket(lane, n)
        if n == 0:
            ticket._resolve(np.zeros(0, dtype=bool), None)
            return ticket
        kt = list(key_types) if key_types is not None else None
        src = list(sources) if sources is not None else None
        with self._cv:
            if self._closed:
                return None
            st = self._lanes[lane]
            st.queue.append(
                (ticket, list(pubkeys), list(msgs), list(sigs), kt, src)
            )
            st.rows += n
            if self.metrics is not None:
                self.metrics.lane_depth.labels(lane).set(st.rows)
            self._cv.notify_all()
        return ticket

    def verify_rows(self, lane: str, pubkeys, msgs, sigs,
                    key_types=None, sources=None) -> np.ndarray:
        """Submit + block for the verdict slice — the drop-in replacement
        for a consumer's own `verify_batch(...)` call. Falls back to an
        inline verify_batch when the scheduler is closed or the ticket
        misses wait_timeout (a consumer is never wedged on the lane).

        Rows whose source is QUARANTINED (crypto/provenance.py) split off
        first and ride the quarantine lane instead, so a poisoning flood
        can never drag a vote/light/admission flush into bisection
        recovery (_verify_rows_partitioned merges the verdicts back in
        row order — the caller sees one mask either way).

        The VOTES lane never queues here: vote rows would flush alone
        anyway (bulk rows never ride a vote flush), so queuing them behind
        the dispatch thread only ADDS a handoff — and, worse, parks them
        behind whatever bulk flush is already in flight. True preemption is
        not queuing at all: the vote flush runs immediately on the caller's
        thread, with full lane accounting (depth-0 wait, flush journal,
        preemption count when bulk work sat queued)."""
        if lane != "quarantine" and sources is not None:
            from tendermint_tpu.crypto import provenance as _prov

            q = _prov.default_scorer().quarantined_sources()
            if q and any(s in q for s in sources):
                return self._verify_rows_partitioned(
                    lane, pubkeys, msgs, sigs, key_types, sources, q
                )
        if lane == "votes":
            return self._verify_votes_inline(pubkeys, msgs, sigs, key_types,
                                             sources)
        ticket = self.submit(lane, pubkeys, msgs, sigs, key_types, sources)
        if ticket is None:
            return self._inline(pubkeys, msgs, sigs, key_types, sources)
        return self._wait_or_fallback(
            ticket, (pubkeys, msgs, sigs, key_types, sources)
        )

    def _verify_rows_partitioned(self, lane, pubkeys, msgs, sigs, key_types,
                                 sources, quarantined) -> np.ndarray:
        """Split a submit whose sources are partly quarantined: suspect rows
        queue on the quarantine lane FIRST (non-blocking), the clean rows
        flush through their own lane as usual, then this thread blocks for
        the quarantine verdict and merges the masks in row order."""
        idx_q = [i for i, s in enumerate(sources) if s in quarantined]
        idx_c = [i for i, s in enumerate(sources) if s not in quarantined]

        def _take(seq, idx):
            return [seq[i] for i in idx]

        out = np.zeros(len(pubkeys), dtype=bool)
        q_rows = (
            _take(pubkeys, idx_q), _take(msgs, idx_q), _take(sigs, idx_q),
            _take(key_types, idx_q) if key_types is not None else None,
            _take(sources, idx_q),
        )
        q_ticket = self.submit("quarantine", *q_rows)
        if idx_c:
            out[idx_c] = self.verify_rows(
                lane,
                _take(pubkeys, idx_c), _take(msgs, idx_c), _take(sigs, idx_c),
                _take(key_types, idx_c) if key_types is not None else None,
                _take(sources, idx_c),
            )
        if q_ticket is None:
            out[idx_q] = self._inline(*q_rows)
        else:
            out[idx_q] = self._wait_or_fallback(q_ticket, q_rows)
        return out

    def _verify_votes_inline(self, pubkeys, msgs, sigs, key_types,
                             sources=None) -> np.ndarray:
        n = len(pubkeys)
        if n == 0:
            return np.zeros(0, dtype=bool)
        t0 = time.monotonic()
        with self._cv:
            preempted = any(
                self._lanes[name].queue for name in LANES if name != "votes"
            )
            if preempted:
                self.preemptions += 1
                if self.metrics is not None:
                    self.metrics.preemptions.inc()
        mask = self._inline(pubkeys, msgs, sigs, key_types, sources)
        wall = time.monotonic() - t0
        with self._cv:
            self.flush_seq += 1
            st = self._lanes["votes"]
            st.flushes += 1
            st.rows_total += n
            self.flush_rows_last = {"votes": n}
            self.flush_log.append({
                "seq": self.flush_seq, "t": t0, "wall_s": wall,
                "rows": {"votes": n}, "wait_s": {"votes": 0.0},
                "error": None,
            })
        self.wait_stats.observe("votes", 0.0)
        if self.metrics is not None:
            self.metrics.lane_wait.labels("votes").observe(0.0)
            self.metrics.lane_flush_rows.labels("votes").observe(n)
        if self.slo is not None:
            self.slo.observe("verify_lane_wait_votes", 0.0)
        return mask

    def _wait_or_fallback(self, ticket: Ticket, rows=None) -> np.ndarray:
        try:
            return ticket.wait(self.wait_timeout)
        except TimeoutError:
            with self._cv:
                self.fallbacks += 1
                # dequeue the abandoned ticket: its consumer is about to
                # verify inline, so flushing these rows later would be pure
                # duplicate work nobody reads
                st = self._lanes[ticket.lane]
                for entry in list(st.queue):
                    if entry[0] is ticket:
                        st.queue.remove(entry)
                        st.rows -= ticket.rows
                        break
            logger.warning(
                "verify lane %s ticket (%d rows) missed the %.0fs wait "
                "timeout; verifying inline on the caller's thread",
                ticket.lane, ticket.rows, self.wait_timeout,
            )
            if rows is None:
                raise
            return self._inline(*rows)

    def _inline(self, pubkeys, msgs, sigs, key_types,
                sources=None) -> np.ndarray:
        from tendermint_tpu.crypto import batch as _batch

        if sources is None:
            # keep the untagged call shape: tests stub verify_batch with
            # 5-arg fakes, and an untagged flush has nothing to score
            return _batch.verify_batch(
                pubkeys, msgs, sigs, self.backend, key_types
            )
        return _batch.verify_batch(pubkeys, msgs, sigs, self.backend, key_types,
                                   sources=sources)

    def accumulate(self, lane: str) -> LaneAccumulator:
        """A FlushAccumulator-compatible adapter whose flush() rides `lane`
        (install via crypto/batch.accumulate_flushes(acc=...))."""
        return LaneAccumulator(self, lane)

    @contextlib.contextmanager
    def lane_scope(self, lane: str):
        """While active on this thread, verify_batch / verify_batch_submit
        calls (and everything built on them: verify_commit,
        begin_verify_commit_light*, blocksync runs) route their rows
        through `lane` instead of dispatching their own flush."""
        if lane not in self._lanes:
            raise ValueError(f"unknown verify lane {lane!r}")
        prev = getattr(_TLS, "scope", None)
        _TLS.scope = (self, lane)
        try:
            yield self
        finally:
            _TLS.scope = prev

    # -- dispatch thread ------------------------------------------------------

    def _plan_locked(self):
        """Decide the next combined flush under the lock. Returns
        (entries, lanes, preempted, timeout_s): `entries` is the popped
        work (empty = nothing ready; sleep `timeout_s`)."""
        now = time.monotonic()
        votes = self._lanes["votes"]
        if votes.queue:
            # PREEMPT: the whole votes backlog flushes now, alone — bulk
            # rows never ride a vote flush (its wall is the vote path's)
            preempted = any(
                self._lanes[n].queue for n in LANES if n != "votes"
            )
            entries = list(votes.queue)
            votes.queue.clear()
            votes.rows = 0
            return entries, {"votes"}, preempted, None

        ready: List[str] = []
        next_deadline: Optional[float] = None
        bulk_pending = any(
            self._lanes[n].queue for n in ("votes", "light", "admission")
        )
        for lane in ("light", "admission", "catchup"):
            st = self._lanes[lane]
            if not st.queue:
                continue
            eff = self.effective_budget(lane)
            oldest = st.queue[0][0].enqueued_t
            wait = now - oldest
            if lane == "catchup":
                # idle-soak: ready when nothing hotter is queued; the
                # starvation floor keeps a busy node syncing regardless —
                # and bounds the pressure-level-2 pause too (a parked
                # consumer must flush before its wait_timeout inline
                # fallback, or the pause converts into duplicate inline
                # work on a starved executor thread)
                floor = eff.max_wait * CATCHUP_STARVATION_FACTOR
                if st.paused:
                    if wait >= floor:
                        ready.append(lane)
                    else:
                        dl = oldest + floor
                        next_deadline = dl if next_deadline is None else min(next_deadline, dl)
                    continue
                if not bulk_pending and (
                    wait >= eff.max_wait
                    or (eff.max_rows > 0 and st.rows >= eff.max_rows)
                ):
                    ready.append(lane)
                elif wait >= floor:
                    ready.append(lane)
                else:
                    dl = oldest + (floor if bulk_pending else eff.max_wait)
                    next_deadline = dl if next_deadline is None else min(next_deadline, dl)
                continue
            if (eff.max_rows > 0 and st.rows >= eff.max_rows) or wait >= eff.max_wait:
                ready.append(lane)
            else:
                dl = oldest + eff.max_wait
                next_deadline = dl if next_deadline is None else min(next_deadline, dl)
        # Quarantine: suspect rows flush ALONE, and only when every other
        # lane is drained — a poisoned flood's bisection recoveries can
        # never ride, or be ridden by, clean work. The starvation floor
        # (same factor as catch-up) bounds how long a suspect consumer
        # blocks, so parole stays reachable and the wait_timeout inline
        # fallback stays the backstop, not the norm.
        qst = self._lanes["quarantine"]
        if qst.queue:
            eff = self.effective_budget("quarantine")
            oldest = qst.queue[0][0].enqueued_t
            wait = now - oldest
            floor = eff.max_wait * CATCHUP_STARVATION_FACTOR
            others = bulk_pending or bool(self._lanes["catchup"].queue)
            triggered = (not others and not ready) and (
                wait >= eff.max_wait
                or (eff.max_rows > 0 and qst.rows >= eff.max_rows)
            )
            if triggered or wait >= floor:
                entries = []
                taken_rows = 0
                while qst.queue:
                    if eff.max_rows > 0 and taken_rows >= eff.max_rows:
                        break
                    entry = qst.queue.popleft()
                    qst.rows -= entry[0].rows
                    taken_rows += entry[0].rows
                    entries.append(entry)
                return entries, {"quarantine"}, False, None
            dl = oldest + (floor if (others or ready) else eff.max_wait)
            next_deadline = dl if next_deadline is None else min(next_deadline, dl)
        if not ready:
            timeout = None if next_deadline is None else max(0.0, next_deadline - now)
            return [], set(), False, timeout

        # Combined flush: the trigger lane(s) plus a ride-along drain of the
        # other bulk lanes up to their row budgets — rows that would flush
        # within one window anyway share this one. Catch-up never rides a
        # busy flush (idle-soak only); it IS the flush only when it triggered.
        take = set(ready)
        for lane in ("light", "admission"):
            if self._lanes[lane].queue:
                take.add(lane)
        entries = []
        lanes_taken = set()
        for lane in ("light", "admission", "catchup"):
            if lane not in take:
                continue
            st = self._lanes[lane]
            eff = self.effective_budget(lane)
            taken_rows = 0
            while st.queue:
                if eff.max_rows > 0 and taken_rows >= eff.max_rows:
                    break
                entry = st.queue.popleft()
                st.rows -= entry[0].rows
                taken_rows += entry[0].rows
                entries.append(entry)
                lanes_taken.add(lane)
        return entries, lanes_taken, False, None

    def _run(self) -> None:
        while True:
            q_entries: list = []
            with self._cv:
                entries: list = []
                while not self._closed:
                    entries, lanes, preempted, timeout = self._plan_locked()
                    if entries:
                        break
                    self._cv.wait(timeout=timeout)
                if self._closed:
                    # drain everything still queued in one final pass so no
                    # consumer blocks into its fallback timeout on teardown
                    # (quarantined rows still flush separately: the
                    # isolation invariant holds through teardown too)
                    entries = []
                    lanes, preempted = set(), False
                    for lane in LANES:
                        st = self._lanes[lane]
                        if st.queue:
                            if lane == "quarantine":
                                q_entries = list(st.queue)
                            else:
                                lanes.add(lane)
                                entries.extend(st.queue)
                        st.queue.clear()
                        st.rows = 0
                if preempted:
                    self.preemptions += 1
                    if self.metrics is not None:
                        self.metrics.preemptions.inc()
                closed = self._closed
            if entries:
                self._flush(entries, lanes)
            if q_entries:
                self._flush(q_entries, {"quarantine"})
            if closed:
                return

    def _flush(self, entries: list, lanes: set) -> None:
        """One combined device flush for `entries` (dispatch-thread only).
        Slices the combined mask back per ticket — the FlushAccumulator
        recovery contract keeps each slice byte-identical to a standalone
        verify_batch of that submit's rows."""
        from tendermint_tpu.crypto import batch as _batch

        t_flush = time.monotonic()
        pubkeys: list = []
        msgs: list = []
        sigs: list = []
        key_types: list = []
        sources: list = []
        slices = []
        lane_rows: Dict[str, int] = {}
        lane_oldest: Dict[str, float] = {}
        for ticket, pk, ms, sg, kt, src in entries:
            start = len(pubkeys)
            pubkeys.extend(pk)
            msgs.extend(ms)
            sigs.extend(sg)
            key_types.extend(kt if kt is not None else ["ed25519"] * len(pk))
            # provenance: untagged rows sharing a flush with tagged ones
            # carry their consumer lane, so the suspicion scorer can always
            # attribute a failing row (crypto/provenance.py tag conventions)
            sources.extend(
                src if src is not None else [f"lane:{ticket.lane}"] * len(pk)
            )
            slices.append((ticket, start, len(pubkeys)))
            lane_rows[ticket.lane] = lane_rows.get(ticket.lane, 0) + ticket.rows
            prev = lane_oldest.get(ticket.lane)
            if prev is None or ticket.enqueued_t < prev:
                lane_oldest[ticket.lane] = ticket.enqueued_t
        kt_arg = key_types if any(t != "ed25519" for t in key_types) else None
        # an all-untagged flush passes sources=None: nothing to score, and
        # the untagged verify_batch call shape stays byte-for-byte the same
        src_arg = (
            sources if any(e[5] is not None for e in entries) else None
        )
        mask: Optional[np.ndarray] = None
        error: Optional[BaseException] = None
        try:
            mask = self._verify_chunked(pubkeys, msgs, sigs, kt_arg, src_arg)
        except BaseException as e:  # tickets re-raise; the thread survives
            error = e
            logger.exception(
                "scheduler flush failed (%d rows, lanes %s)",
                len(pubkeys), sorted(lanes),
            )
        wall_s = time.monotonic() - t_flush
        with self._cv:
            self.flush_seq += 1
            seq = self.flush_seq
            self.flush_rows_last = dict(lane_rows)
            self.flush_log.append({
                "seq": seq,
                "t": t_flush,
                "wall_s": wall_s,
                "rows": dict(lane_rows),
                "wait_s": {
                    lane: t_flush - t0 for lane, t0 in lane_oldest.items()
                },
                "error": repr(error) if error is not None else None,
            })
            for lane in lane_rows:
                st = self._lanes[lane]
                st.flushes += 1
                st.rows_total += lane_rows[lane]
                if self.metrics is not None:
                    self.metrics.lane_depth.labels(lane).set(st.rows)
        for lane, rows in lane_rows.items():
            wait = t_flush - lane_oldest[lane]
            self.wait_stats.observe(lane, wait)
            if self.metrics is not None:
                self.metrics.lane_wait.labels(lane).observe(wait)
                self.metrics.lane_flush_rows.labels(lane).observe(rows)
            if self.slo is not None:
                self.slo.observe(f"verify_lane_wait_{lane}", wait)
        for ticket, start, end in slices:
            ticket.flush_seq = seq
            ticket.wait_s = t_flush - ticket.enqueued_t
            ticket._resolve(mask[start:end] if mask is not None else None, error)

    def _verify_chunked(self, pubkeys, msgs, sigs, kt_arg,
                        sources=None) -> np.ndarray:
        """The dispatch thread's verify body: an oversized combined flush
        (catch-up super-batches, admission floods) splits into flush-planner
        chunks (crypto/batch.planner_chunk_rows) with a PREEMPTION POINT
        between chunks — vote rows that queued while a chunk ran flush next,
        alone, before the following chunk. A vote flush therefore waits at
        most ONE chunk, never a 200k-lane monolith; verdict slices stay
        byte-identical (chunk masks concatenate in row order, and each chunk
        rides the normal verify_batch ladder)."""
        from tendermint_tpu.crypto import batch as _batch

        chunk = _batch.planner_chunk_rows()
        n = len(pubkeys)
        if n <= chunk:
            if sources is None:
                return _batch.verify_batch(
                    pubkeys, msgs, sigs, self.backend, kt_arg
                )
            return _batch.verify_batch(pubkeys, msgs, sigs, self.backend,
                                       kt_arg, sources=sources)
        parts = []
        for lo in range(0, n, chunk):
            if lo:
                self._preempt_votes_between_chunks()
            hi = min(lo + chunk, n)
            kt_c = kt_arg[lo:hi] if kt_arg is not None else None
            if sources is None:
                parts.append(
                    _batch.verify_batch(
                        pubkeys[lo:hi], msgs[lo:hi], sigs[lo:hi],
                        self.backend, kt_c,
                    )
                )
            else:
                parts.append(
                    _batch.verify_batch(
                        pubkeys[lo:hi], msgs[lo:hi], sigs[lo:hi],
                        self.backend, kt_c, sources=sources[lo:hi],
                    )
                )
        return np.concatenate(parts)

    def _preempt_votes_between_chunks(self) -> None:
        """Between-chunk preemption point (dispatch thread only): drain any
        queued vote rows into their own flush before the next bulk chunk."""
        with self._cv:
            st = self._lanes["votes"]
            if not st.queue:
                return
            entries = list(st.queue)
            st.queue.clear()
            st.rows = 0
            self.preemptions += 1
            if self.metrics is not None:
                self.metrics.preemptions.inc()
        self._flush(entries, {"votes"})

    # -- introspection / lifecycle --------------------------------------------

    def stats(self) -> dict:
        """The `scheduler` block of GET /debug/verify_stats (see
        docs/SCHEDULER.md for the field list)."""
        with self._cv:
            lanes = {}
            for name in LANES:
                st = self._lanes[name]
                eff = self.effective_budget(name)
                base = self._base[name]
                lanes[name] = {
                    "depth_rows": st.rows,
                    "queued_submits": len(st.queue),
                    "flushes": st.flushes,
                    "rows_total": st.rows_total,
                    "paused": st.paused,
                    "budget": {
                        "max_rows": base.max_rows,
                        "max_wait_s": base.max_wait,
                        "effective_max_rows": eff.max_rows,
                        "effective_max_wait_s": eff.max_wait,
                    },
                }
            out = {
                "enabled": True,
                "closed": self._closed,
                "backend": self.backend or "auto",
                "pressure_level": self.pressure_level,
                "flushes": self.flush_seq,
                "preemptions": self.preemptions,
                "inline_fallbacks": self.fallbacks,
                "last_flush_rows": dict(self.flush_rows_last),
                "lanes": lanes,
            }
        out["lane_wait_percentiles"] = self.wait_stats.percentiles()
        # cross-flush verified-row memo (crypto/batch.py ISSUE 18): every
        # lane consults it before joining the combined flush, so light
        # serving and blocksync catch-up reuse each other's verdicts — the
        # hit/eviction counters belong on the same debug surface
        from tendermint_tpu.crypto import batch as _batch

        out["verified_memo"] = _batch.verified_memo_stats()
        # Elastic mesh (ISSUE 19): the ladder rung every queued flush will
        # route through — a scheduler serving from a survivor mesh (or
        # single-chip after a mesh trip) should say so on the same surface
        # its lane waits are judged on.
        try:
            out["mesh_ladder"] = _batch.mesh_ladder_state()
        except Exception:
            out["mesh_ladder"] = None
        # Adversarial flush defense (crypto/provenance.py): which sources
        # are quarantined / closest to it, on the same surface operators
        # already read lane health from.
        try:
            from tendermint_tpu.crypto import provenance as _prov

            out["suspicion"] = _prov.default_scorer().stats()
        except Exception:
            out["suspicion"] = None
        return out

    def close(self) -> None:
        """Stop the dispatch thread after one final drain; later submits
        return None and consumers verify inline."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)

    @property
    def closed(self) -> bool:
        return self._closed


# -- lane-scope routing (crypto/batch hook) ------------------------------------

_TLS = threading.local()


def _route_rows(pubkeys, msgs, sigs, backend, key_types, sources=None):
    """crypto/batch's lane router: verify_batch consults this at entry and,
    when the calling thread sits inside a lane_scope, routes the rows
    through that scheduler lane. Returns None (= route normally) outside a
    scope, for a closed scheduler, and for the scheduler's own dispatch
    flush (the scope is cleared around verify_rows)."""
    scope = getattr(_TLS, "scope", None)
    if scope is None:
        return None
    sched, lane = scope
    if sched.closed:
        return None
    _TLS.scope = None  # the inline fallback must not re-enter the router
    try:
        return sched.verify_rows(lane, pubkeys, msgs, sigs, key_types, sources)
    finally:
        _TLS.scope = scope


_ROUTER_INSTALLED = False


def _install_router() -> None:
    global _ROUTER_INSTALLED
    if _ROUTER_INSTALLED:
        return
    from tendermint_tpu.crypto import batch as _batch

    _batch.set_lane_router(_route_rows)
    _ROUTER_INSTALLED = True


# -- process-global default ----------------------------------------------------
#
# Deep consumers (types/vote_set.py, evidence/pool.py) have no wiring path
# from the Node; they read the process-global default — last node wins, the
# same model as the tracer, the SLO flush feed, and the breaker config.

_DEFAULT: Optional[VerifyScheduler] = None


def set_default(sched: Optional[VerifyScheduler]) -> None:
    global _DEFAULT
    _DEFAULT = sched


def default_scheduler() -> Optional[VerifyScheduler]:
    """The live process-global scheduler, or None (closed schedulers read
    as None so a stopped node never wedges a survivor's consumers)."""
    s = _DEFAULT
    if s is None or s.closed:
        return None
    return s
