"""Batched GF(p) arithmetic for BLS12-381 in int32 limbs (numpy OR jax).

Extends the fe25519 design (uniform small radix, leading limb axis, int32
discipline) to the 381-bit base field. Two things change because p_381 has
no Solinas structure (no cheap 2^k wrap like 2^260 ≡ 608 mod p_25519):

- RADIX drops 13 -> 12 and NLIMBS goes 20 -> 33 (396 bits of capacity):
  the Montgomery interleave below ADDS up to 33 more radix-width products
  per limb on top of the convolution's 33, and 2 * 33 * (2^12)^2 < 2^31
  is what keeps every accumulator a non-negative int32 (the fe25519 radix
  would overflow: 2 * 30 * (2^13)^2 > 2^31).
- Reduction is MONTGOMERY (R = 2^396), interleaved limb-serial like a CIOS
  pass but vectorized across the batch axis: after the 65-limb school book
  convolution, 33 steps each zero one low limb (m_i = T_i * (-p^-1) mod
  2^12; T += m_i * p << 12i; push T_i's carry up) and the top 33 limbs are
  the Montgomery product. Elements therefore live in the Montgomery domain
  (value * R mod p) on device; host boundaries convert with python ints.

The PACKED transfer/storage layout is 13 int32 words of radix 30 (390 bits
>= the canonical 381) — the pallas_msm packed layout extended from 10
words x radix 26 (ed25519) to 13 words for the wider field; pack/unpack are
host-side numpy.

Every op is written over PYTHON LISTS of per-limb rows (the pallas_fe
in-kernel idiom), so the SAME code runs on numpy arrays (the tier-1 CPU
twin, zero XLA work) and on jax arrays (the device path) — the two are
bit-for-bit identical by construction, and tests/test_bls_kernels.py pins
the numpy twin against crypto/bls_ref.py's python-int arithmetic.

Value-bound discipline (each op documents its part):
- "carried" limbs are <= 2^12 (one unit of slack above 2^12 - 1 is fine
  everywhere: the convolution bound uses 2 * 33 * 4096^2 = 1.108e9 < 2^31);
- mul/square require input VALUES < 2^388 (so a*b < R*p) and return < 2p;
- add returns the plain sum; sub adds the all-4096 complement (value
  ~2^384 ~ 13p) — so value magnitude grows by ~13p per sub and resets
  < 2p at the next mul. The longest mul-free add/sub chain in the point
  formulas (ops/bls12_msm.py) is 4 ops: worst case < 2p + 4*14p < 2^387.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001  # G1/G2 order r

RADIX = 12
NLIMBS = 33
MASK = (1 << RADIX) - 1
NBITS = RADIX * NLIMBS  # 396
R_MONT = (1 << NBITS) % P
R_INV = pow(1 << NBITS, P - 2, P)
PPRIME = (-pow(P, -1, 1 << RADIX)) % (1 << RADIX)  # -p^-1 mod 2^12

PACK_RADIX = 30
PACK_WORDS = 13  # 13 * 30 = 390 bits >= 381


def _limbs_of(x: int) -> List[int]:
    return [(x >> (RADIX * i)) & MASK for i in range(NLIMBS)]


P_LIMBS = _limbs_of(P)

# sub complement: limbs 0..31 hold 2^13 (dominating any carried limb and
# its <= 2 units of slack above MASK), the TOP limb holds only 8 — an
# all-2^13 complement would have value ~2^397, past the 33-limb capacity.
# The graded complement's value is ~2^388.2; sub and mul_small outputs
# are immediately re-folded at bit 384 (W384 = 2^384 mod p, below), which
# caps folded values at < 2^384 + top*p < 2^387.7 and their top limb at
# <= 13 < 16 — so every op output is a valid operand everywhere: COMP
# dominates the subtrahend limb-wise, and the Montgomery precondition
# a*b < R*p = 2^776.9 holds for the worst product of two unfolded sums
# (< 2^388.4 each; audited in ops/bls12_msm.py / ops/pallas_bls.py).
COMP_LIMBS = [1 << (RADIX + 1)] * (NLIMBS - 1) + [16]
_COMP_VAL = sum(c << (RADIX * i) for i, c in enumerate(COMP_LIMBS))
CORR_LIMBS = _limbs_of(-_COMP_VAL % P)
W384_LIMBS = _limbs_of((1 << (RADIX * (NLIMBS - 1))) % P)  # 2^384 mod p


# --------------------------------------------------------------------------
# host-side int conversions (python ints <-> limb vectors, Montgomery domain)


def from_int(x: int) -> np.ndarray:
    """python int -> canonical (NON-Montgomery) limbs, shape (33,)."""
    return np.array(_limbs_of(x % P), dtype=np.int32)


def to_int(limbs) -> int:
    """limbs (33, ...) -> python int of lane 0 (limbs need not be canonical)."""
    arr = np.asarray(limbs, dtype=np.int64).reshape(NLIMBS, -1)[:, 0]
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMBS)) % P


def mont_from_int(x: int) -> np.ndarray:
    """python int -> MONTGOMERY-domain limbs (x * R mod p)."""
    return from_int(x % P * R_MONT % P)


def mont_to_int(limbs) -> int:
    """Montgomery limbs -> python int (value * R^-1 mod p)."""
    return to_int(limbs) * R_INV % P


def mont_from_ints(xs: Sequence[int]) -> np.ndarray:
    """ints -> (33, n) int32 Montgomery limb block."""
    out = np.zeros((NLIMBS, len(xs)), dtype=np.int32)
    for j, x in enumerate(xs):
        out[:, j] = mont_from_int(x)
    return out


def mont_to_ints(limbs) -> List[int]:
    arr = np.asarray(limbs, dtype=np.int64).reshape(NLIMBS, -1)
    out = []
    for j in range(arr.shape[1]):
        v = sum(int(arr[i, j]) << (RADIX * i) for i in range(NLIMBS)) % P
        out.append(v * R_INV % P)
    return out


# --------------------------------------------------------------------------
# packed transfer layout: 13 int32 words of radix 30 (canonical values only)


def pack(values: Sequence[int]) -> np.ndarray:
    """canonical ints -> (13, n) int32 packed words (radix 2^30)."""
    out = np.zeros((PACK_WORDS, len(values)), dtype=np.int32)
    m = (1 << PACK_RADIX) - 1
    for j, v in enumerate(values):
        if not 0 <= v < P:
            raise ValueError("pack expects canonical field elements")
        for i in range(PACK_WORDS):
            out[i, j] = (v >> (PACK_RADIX * i)) & m
    return out


def unpack(words) -> List[int]:
    arr = np.asarray(words, dtype=np.int64).reshape(PACK_WORDS, -1)
    return [
        sum(int(arr[i, j]) << (PACK_RADIX * i) for i in range(PACK_WORDS))
        for j in range(arr.shape[1])
    ]


# --------------------------------------------------------------------------
# core ops over row lists (np or jnp arrays; xp picked off the rows)

Rows = List  # NLIMBS rows, each an array of identical batch shape


def rows_of(a) -> Rows:
    """(33, ...batch) array -> row list."""
    return [a[i] for i in range(NLIMBS)]


def stack(rows: Rows, xp=np):
    return xp.stack(rows)


def carry_rows(rows: Rows, passes: int = 2) -> Rows:
    """Parallel carry passes, NO top wrap: NBITS = 396 gives 15 bits of
    headroom above the < 2^388 value bound, so carry out of limb 32 is
    impossible for in-discipline values. Two passes bring any <= 1.11e9
    accumulation to limbs <= 2^12 + 1; a third (mul's output) to 2^12."""
    for _ in range(passes):
        out = []
        carry_in = None
        for r in rows:
            c = r >> RADIX
            masked = r & MASK
            out.append(masked if carry_in is None else masked + carry_in)
            carry_in = c
        # carry out of the top limb would mean value >= 2^396: out of
        # discipline by > 2^8; drop is deliberate (documented invariant).
        rows = out
    return rows


def add_rows(a: Rows, b: Rows) -> Rows:
    return carry_rows([x + y for x, y in zip(a, b)], passes=1)


def fold_top_rows(a: Rows) -> Rows:
    """Fold the top limb (bits 384..395) through W384 = 2^384 mod p:
    resets the value bound to < 2^384 + a_top * p and the top limb to
    <= 3 for any in-discipline input (a_top <= 12). One broadcast
    multiply-add + carries — cheap enough to run after every sub."""
    hi = a[NLIMBS - 1]
    out = [x + hi * w for x, w in zip(a, W384_LIMBS)]
    out[NLIMBS - 1] = hi * W384_LIMBS[NLIMBS - 1]
    return carry_rows(out, passes=2)


def sub_rows(a: Rows, b: Rows) -> Rows:
    """a - b mod p via the graded complement + top fold (see COMP_LIMBS)."""
    return fold_top_rows(
        carry_rows(
            [x + (k - y) + c for x, y, k, c in zip(a, b, COMP_LIMBS, CORR_LIMBS)],
            passes=2,
        )
    )


def mul_small_rows(a: Rows, k: int) -> Rows:
    """a * k for small k (carried limbs * k < 2^31 => k < 2^19 - safe for
    the b3 = 12 and 2/3/4/8 constants the point formulas use). The top
    fold keeps the scaled value a valid operand for every downstream op."""
    return fold_top_rows(carry_rows([x * k for x in a], passes=2))


_P_COL = np.array(P_LIMBS, dtype=np.int32)[:, None]


def _mul_np(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Vectorized numpy form of mul_rows on stacked (33, ...batch) arrays:
    33 shifted row multiply-adds for the convolution instead of 33*33
    scalar-loop ops, identical int32 partial sums (addition order is free
    within the proven < 2^31 bounds), so outputs are bit-for-bit equal to
    the row-list form the jax path traces."""
    batch = A.shape[1:]
    a = A.reshape(NLIMBS, -1)
    b = B.reshape(NLIMBS, -1)
    prod = np.zeros((2 * NLIMBS, a.shape[1]), dtype=np.int32)
    for i in range(NLIMBS):
        prod[i : i + NLIMBS] += a[i][None, :] * b
    for i in range(NLIMBS):
        m = (prod[i] & MASK) * PPRIME & MASK
        prod[i : i + NLIMBS] += m[None, :] * _P_COL
        prod[i + 1] += prod[i] >> RADIX
    out = prod[NLIMBS : 2 * NLIMBS]
    for _ in range(3):
        c = out >> RADIX
        out = out & MASK
        out[1:] += c[:-1]
    return out.reshape(NLIMBS, *batch)


def mul_rows(a: Rows, b: Rows) -> Rows:
    """Montgomery product aR * bR -> abR (inputs carried, values < 2^388;
    output carried, value < 2p).

    Bounds: conv limb <= 33 * 4096^2 = 5.54e8; each Montgomery step adds
    m_i * p_j <= 4095^2 per limb (33 steps but each limb index receives
    from at most 33 of them) and the pushed carry <= 2.71e5 — every
    accumulator < 1.11e9 < 2^31."""
    if isinstance(a[0], np.ndarray):
        return rows_of(_mul_np(np.stack(a), np.stack(b)))
    return _mul_rows_loop(a, b)


def _mul_rows_loop(a: Rows, b: Rows) -> Rows:
    """Row-list form (what the jax path traces; XLA fuses the shifted
    accumulations). tests pin it bit-for-bit against _mul_np."""
    # 65-limb schoolbook convolution (plus one slot for the final carry)
    n = NLIMBS
    prod = [None] * (2 * n)
    for i in range(n):
        ai = a[i]
        for j in range(n):
            t = ai * b[j]
            k = i + j
            prod[k] = t if prod[k] is None else prod[k] + t
    zero = a[0] - a[0]
    prod[2 * n - 1] = zero
    # interleaved Montgomery: zero limbs 0..32 one at a time
    for i in range(n):
        m = (prod[i] & MASK) * PPRIME & MASK
        for j in range(n):
            prod[i + j] = prod[i + j] + m * P_LIMBS[j]
        prod[i + 1] = prod[i + 1] + (prod[i] >> RADIX)
    out = prod[n : 2 * n]
    return carry_rows(out, passes=3)


def square_rows(a: Rows) -> Rows:
    """Symmetric convolution (half the MACs), then the same Montgomery
    interleave. Term-for-term equal partial sums to mul_rows(a, a)."""
    if isinstance(a[0], np.ndarray):
        return mul_rows(a, a)
    n = NLIMBS
    prod = [None] * (2 * n)
    for i in range(n):
        t = a[i] * a[i]
        k = 2 * i
        prod[k] = t if prod[k] is None else prod[k] + t
        for j in range(i + 1, n):
            t = a[i] * (a[j] + a[j])
            k = i + j
            prod[k] = t if prod[k] is None else prod[k] + t
    zero = a[0] - a[0]
    prod[2 * n - 1] = zero
    for i in range(n):
        m = (prod[i] & MASK) * PPRIME & MASK
        for j in range(n):
            prod[i + j] = prod[i + j] + m * P_LIMBS[j]
        prod[i + 1] = prod[i + 1] + (prod[i] >> RADIX)
    return carry_rows(prod[n : 2 * n], passes=3)


def select_rows(cond, a: Rows, b: Rows, xp=np) -> Rows:
    """cond ? a : b elementwise over the batch (cond: bool batch array)."""
    return [xp.where(cond, x, y) for x, y in zip(a, b)]


def is_zero_val(rows: Rows) -> np.ndarray:
    """Batch bool: value ≡ 0 mod p. HOST-side (numpy) only: used at the
    tiny result boundary (one point / a few lanes), not in kernels."""
    arr = np.asarray([np.asarray(r, dtype=np.int64) for r in rows])
    flat = arr.reshape(NLIMBS, -1)
    out = np.zeros(flat.shape[1], dtype=bool)
    for j in range(flat.shape[1]):
        v = sum(int(flat[i, j]) << (RADIX * i) for i in range(NLIMBS))
        out[j] = v % P == 0
    return out.reshape(arr.shape[1:])


# convenience wrappers on stacked (33, ...batch) arrays


def mul(a, b, xp=np):
    return stack(mul_rows(rows_of(a), rows_of(b)), xp)


def add(a, b, xp=np):
    return stack(add_rows(rows_of(a), rows_of(b)), xp)


def sub(a, b, xp=np):
    return stack(sub_rows(rows_of(a), rows_of(b)), xp)
