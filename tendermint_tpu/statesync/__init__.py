"""State sync: bootstrap a node from an app snapshot instead of replay.

reference: statesync/ — syncer.go, reactor.go, chunks.go, snapshots.go,
stateprovider.go.
"""

from tendermint_tpu.statesync.chunks import Chunk, ChunkQueue  # noqa: F401
from tendermint_tpu.statesync.reactor import StatesyncReactor  # noqa: F401
from tendermint_tpu.statesync.snapshots import Snapshot, SnapshotPool  # noqa: F401
from tendermint_tpu.statesync.stateprovider import (  # noqa: F401
    LightClientStateProvider,
    StateProvider,
)
from tendermint_tpu.statesync.syncer import (  # noqa: F401
    ErrNoSnapshots,
    SyncError,
    Syncer,
)
