"""Node identity: ed25519 node key, ID = hex(address(pubkey))
(reference: p2p/key.go)."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tendermint_tpu.crypto.keys import Ed25519PrivKey, PubKey, gen_ed25519


def pubkey_to_id(pub: PubKey) -> str:
    """ID is the hex of the 20-byte address (reference: p2p/key.go PubKeyToID)."""
    return pub.address().hex()


@dataclass
class NodeKey:
    priv_key: Ed25519PrivKey

    @property
    def id(self) -> str:
        return pubkey_to_id(self.priv_key.pub_key())

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(gen_ed25519())

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        """(reference: p2p/key.go LoadOrGenNodeKey)"""
        if os.path.exists(path):
            with open(path) as f:
                doc = json.load(f)
            return cls(Ed25519PrivKey(bytes.fromhex(doc["priv_key"])))
        nk = cls.generate()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"priv_key": nk.priv_key.bytes().hex()}, f)
        os.chmod(path, 0o600)
        return nk
