"""Multi-chip sharded verification on the virtual 8-device CPU mesh:
masks must match single-device results exactly
(the dryrun in __graft_entry__ covers sharded_commit_step; this covers
sharded_verify and the 2D mesh layout)."""

import pytest

pytestmark = [pytest.mark.kernel, pytest.mark.slow]  # heavy one-time
# compiles: excluded from the tier-1 budget lane (-m 'not slow'); run
# explicitly via -m kernel

import os

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

import jax

from tendermint_tpu.crypto.batch import prepare_batch
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.ops.ed25519_jax import verify_prepared
from tendermint_tpu.parallel.sharded import make_mesh, shard_batch_arrays, sharded_verify


@pytest.fixture(scope="module", autouse=True)
def _free_compile_memory():
    """XLA:CPU compilation of the 8-virtual-device sharded kernels peaks at
    tens of GB of compiler memory; after ~200 suite tests' accumulated
    executables it ABORTED inside backend_compile (observed r4). Dropping
    every previously-compiled executable first keeps the full-suite process
    under the ceiling (later modules reload from the persistent cache)."""
    from tests.conftest import free_compile_memory

    free_compile_memory()
    yield


def make_inputs(n):
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        priv = gen_ed25519(bytes([i % 250 + 1]) * 32)
        m = b"shard-%04d" % i
        pubs.append(priv.pub_key().bytes())
        msgs.append(m)
        sigs.append(priv.sign(m))
    # corrupt a few
    sigs[3] = sigs[3][:5] + bytes([sigs[3][5] ^ 1]) + sigs[3][6:]
    sigs[n - 1] = b"\x00" * 64
    return pubs, msgs, sigs


# one mesh layout only: each layout compiles the kernel afresh on 8 virtual
# devices (~2 min); the 2D blocks x vals layout is exercised every round by
# __graft_entry__.dryrun_multichip
@pytest.mark.heavy
@pytest.mark.parametrize(
    "mesh_shape,axes,batch_shape",
    [((8,), ("vals",), (32,))],
)
def test_sharded_verify_matches_single_device(mesh_shape, axes, batch_shape):
    devices = jax.devices("cpu")
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    n = 32
    pubs, msgs, sigs = make_inputs(n)
    a, r, s_bits, h_bits, precheck, _ = prepare_batch(pubs, msgs, sigs)
    a, r, s_bits, h_bits = (np.asarray(x)[:, :n] for x in (a, r, s_bits, h_bits))

    single = np.asarray(verify_prepared(a, r, s_bits, h_bits))

    mesh = make_mesh(devices[:8], shape=mesh_shape, axis_names=axes)
    reshaped = [x.reshape(x.shape[0], *batch_shape) for x in (a, r, s_bits, h_bits)]
    sharded_in = shard_batch_arrays(mesh, *reshaped)
    mask = np.asarray(sharded_verify(mesh)(*sharded_in)).reshape(-1)

    assert mask.tolist() == single.tolist()
    assert not mask[3] and not mask[n - 1]
    assert mask.sum() == n - 2


@pytest.mark.heavy
def test_verify_batch_routes_through_mesh(monkeypatch):
    """Production routing: with >1 device and TMTPU_SHARDED=1, verify_batch
    must execute the sharded kernel (crypto/batch._sharded_runner), making
    multi-chip the real path rather than a demo (r2 verdict item 4)."""
    from tendermint_tpu.crypto import batch as B

    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual devices")
    monkeypatch.setenv("TMTPU_SHARDED", "1")
    monkeypatch.setattr(B, "_SHARDED_RUNNER", None)
    n = 32
    pubs, msgs, sigs = make_inputs(n)
    mask = B.verify_batch_jax(pubs, msgs, sigs)
    assert B.LAST_JAX_PATH[0] == "sharded"
    assert mask.sum() == n - 2 and not mask[3] and not mask[n - 1]
    monkeypatch.setenv("TMTPU_SHARDED", "0")
    B._SHARDED_RUNNER = None


@pytest.mark.heavy
def test_sharded_rlc_check_all_valid_and_fallback(monkeypatch):
    """The RLC/Pippenger fast path sharded over the mesh (r3 verdict item 5):
    all-valid batches pass the combined check with lanes split across 8
    devices ("rlc-sharded" path, no fallback); a bad signature fails the
    combined check and recovers the exact mask via the sharded per-sig
    kernel. Cross-chip traffic is one all_gather of partial points."""
    from tendermint_tpu.crypto import batch as B

    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual devices")
    monkeypatch.setenv("TMTPU_SHARDED", "1")
    monkeypatch.setattr(B, "_SHARDED_RUNNER", None)
    monkeypatch.setattr(B, "RLC_MIN", 1)
    n = 24
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        priv = gen_ed25519(bytes([i % 250 + 1]) * 32)
        m = b"rlc-shard-%04d" % i
        pubs.append(priv.pub_key().bytes())
        msgs.append(m)
        sigs.append(priv.sign(m))
    mask = B.verify_batch_jax(pubs, msgs, sigs)
    assert B.LAST_JAX_PATH[0] == "rlc-sharded"
    assert mask.all() and len(mask) == n
    # one bad signature -> combined check fails -> exact sharded mask
    sigs[5] = sigs[5][:7] + bytes([sigs[5][7] ^ 1]) + sigs[5][8:]
    mask = B.verify_batch_jax(pubs, msgs, sigs)
    assert B.LAST_JAX_PATH[0] == "sharded"
    assert not mask[5] and mask.sum() == n - 1
    B._SHARDED_RUNNER = None
