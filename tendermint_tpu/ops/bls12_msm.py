"""General-base BLS12-381 G1 MSM on the fused Pippenger schedule.

Generalizes the ed25519 RLC engine (ops/msm_jax.py) to a general-base,
general-scalar 381-bit multiscalar multiplication — the aggregate-pubkey
workload of BLS aggregate commits (types/validator_set.py) and the opening
move for the ZK-prover serving scenario (ROADMAP item 4):

- HOST PREP IS SHARED: BLS scalars are < r < 2^255, so the existing 8-bit
  x 32-window digit schedule, `msm_jax.scalars_to_bytes`, and the native
  counting sort `msm_jax.sort_windows` are reused unchanged.
- POINT ARITHMETIC IS BRANCHLESS-COMPLETE: Renes-Costello-Batina 2015
  algorithm 7 (complete addition, a = 0, b3 = 12) over ops/fp381 Montgomery
  limbs — one formula covers add, double, identity and inverses, so bucket
  accumulation needs no exceptional-case lanes (the edwards engine gets the
  same property from the unified extended-coordinate add).
- BUCKET ACCUMULATION is a sorted-lane SEGMENTED SUFFIX SUM: lanes sorted
  by (window, digit) reduce in ceil(log2 n) distance-doubling rounds of
  one complete-add each (the same data movement the fused uptree kernel
  performs in VMEM; ops/pallas_bls.py carries the in-kernel form), then
  per-window weighted bucket sums via the standard 255-step suffix
  accumulation and a Horner window combine.

Like ops/fp381, every op runs identically on numpy (the tier-1 CPU twin —
and the production HOST path for aggregate-pubkey accumulation on
wheel-less containers: ~30x the pure-python Jacobian loop at 10k keys) and
on jax arrays. tests/test_bls_kernels.py pins both the point ops and full
MSMs bit-for-bit against crypto/bls_ref.py on real curve points.

Memory discipline: lanes are processed in WINDOW GROUPS of
`WINDOW_GROUP` x n rows (a 100k-key MSM peaks ~320 MB instead of 1.3 GB),
mirroring the crypto/batch.py flush planner's fixed-footprint chunking.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from tendermint_tpu.ops import fp381 as F
from tendermint_tpu.ops.msm_jax import NBUCKETS, NWIN, scalars_to_bytes, sort_windows

B3 = 12  # 3 * b, b = 4
WINDOW_GROUP = 8  # windows per segmented-sum block (memory bound)

# A point is (X, Y, Z) stacked (33, ...batch) int32 Montgomery limbs;
# the projective identity is (0 : 1 : 0).
Point = Tuple[np.ndarray, np.ndarray, np.ndarray]

_ONE_MONT = F.mont_from_int(1)


def identity(batch_shape=(), xp=np) -> Point:
    z = xp.zeros((F.NLIMBS, *batch_shape), dtype=np.int32)
    one = xp.broadcast_to(
        xp.asarray(_ONE_MONT).reshape((F.NLIMBS,) + (1,) * len(batch_shape)),
        (F.NLIMBS, *batch_shape),
    ).astype(np.int32)
    return (z, one, z)


def padd(p: Point, q: Point, xp=np) -> Point:
    """Complete addition (RCB15 algorithm 7, a = 0, b3 = 12): covers
    P+Q, P+P, P+(-P) and either operand the identity, branch-free.

    The b3 scaling of Y3 is applied to BOTH sub operands BEFORE the
    subtraction (sub(12*X3, 12*Y3) instead of 12*(X3 - Y3)) to respect the
    fp381 value-bound discipline (a scaled sub output would exceed the
    Montgomery mul precondition; see fp381.COMP_LIMBS)."""
    X1, Y1, Z1 = (F.rows_of(c) for c in p)
    X2, Y2, Z2 = (F.rows_of(c) for c in q)
    mul, add, sub, small = F.mul_rows, F.add_rows, F.sub_rows, F.mul_small_rows
    t0 = mul(X1, X2)
    t1 = mul(Y1, Y2)
    t2 = mul(Z1, Z2)
    t3 = sub(mul(add(X1, Y1), add(X2, Y2)), add(t0, t1))  # X1Y2 + X2Y1
    t4 = sub(mul(add(Y1, Z1), add(Y2, Z2)), add(t1, t2))  # Y1Z2 + Y2Z1
    t0_3 = add(add(t0, t0), t0)  # 3*t0
    t2b = small(t2, B3)
    z3 = add(t1, t2b)
    t1s = sub(t1, t2b)
    # y3 = b3 * (X1Z2 + X2Z1), with b3 distributed into both sub operands
    # so the subtrahend stays a mul_small output (fp381 bound discipline)
    y3 = sub(
        small(mul(add(X1, Z1), add(X2, Z2)), B3), small(add(t0, t2), B3)
    )
    X3 = sub(mul(t3, t1s), mul(t4, y3))
    Y3 = add(mul(t1s, z3), mul(y3, t0_3))
    Z3 = add(mul(z3, t4), mul(t0_3, t3))
    return (F.stack(X3, xp), F.stack(Y3, xp), F.stack(Z3, xp))


def pselect(cond, a: Point, b: Point, xp=np) -> Point:
    """cond ? a : b with cond shaped like the batch."""
    c = cond[None] if hasattr(cond, "shape") else cond
    return tuple(xp.where(c, x, y) for x, y in zip(a, b))


# --------------------------------------------------------------------------
# host conversions


def points_from_affine_ints(coords: Sequence[Tuple[int, int]]) -> Point:
    """[(x, y), ...] affine ints -> batched Montgomery point block (Z = 1)."""
    n = len(coords)
    X = np.zeros((F.NLIMBS, n), dtype=np.int32)
    Y = np.zeros((F.NLIMBS, n), dtype=np.int32)
    Z = np.zeros((F.NLIMBS, n), dtype=np.int32)
    for j, (x, y) in enumerate(coords):
        X[:, j] = F.mont_from_int(x)
        Y[:, j] = F.mont_from_int(y)
        Z[:, j] = _ONE_MONT
    return (X, Y, Z)


def point_to_affine_int(pt: Point, lane: int = 0) -> Optional[Tuple[int, int]]:
    """One lane -> affine (x, y) python ints, or None for the identity.
    Host-side (python-int inversion); results are tiny (one point)."""
    xs = F.mont_to_ints(np.asarray(pt[0]).reshape(F.NLIMBS, -1)[:, lane : lane + 1])
    ys = F.mont_to_ints(np.asarray(pt[1]).reshape(F.NLIMBS, -1)[:, lane : lane + 1])
    zs = F.mont_to_ints(np.asarray(pt[2]).reshape(F.NLIMBS, -1)[:, lane : lane + 1])
    x, y, z = xs[0], ys[0], zs[0]
    if z == 0:
        return None
    zinv = pow(z, F.P - 2, F.P)
    return (x * zinv % F.P, y * zinv % F.P)


def _gather(pt: Point, idx, xp=np) -> Point:
    return tuple(xp.take(c, idx, axis=1) for c in pt)


# --------------------------------------------------------------------------
# segmented suffix-sum bucket accumulation


def _segment_sums(pt: Point, seg, n_rounds: int, xp=np) -> Point:
    """Rows sorted by segment id; after ceil(log2(max seg len)) distance-
    doubling rounds, the row at each segment HEAD holds the segment sum.
    Identity-padded partners carry seg id -1 (never equal)."""
    m = seg.shape[0]
    ident = identity((1,), xp)
    step = 1
    for _ in range(n_rounds):
        if step >= m:
            break
        part = tuple(
            xp.concatenate(
                [c[:, step:], xp.broadcast_to(i, (F.NLIMBS, step)).astype(np.int32)],
                axis=1,
            )
            for c, i in zip(pt, ident)
        )
        pseg = xp.concatenate([seg[step:], xp.full((step,), -1, seg.dtype)])
        summed = padd(pt, part, xp)
        pt = pselect(seg == pseg, summed, pt, xp)
        step *= 2
    return pt


def _weighted_window_sums(buckets: Point, xp=np) -> Point:
    """buckets: (33, T, 256) per coord -> per-window sums sum_d d*B[d]
    via the suffix-accumulation identity sum_d d*B[d] = sum_{j>=1} S_j,
    S_j = sum_{d>=j} B[d], computed LOG-DEPTH: 8 distance-doubling rounds
    build all suffix sums, 8 halving rounds reduce S_1..S_255. This is the
    device-path form (16 complete-adds total; under jit the python op count
    is irrelevant); the numpy twin's g1_msm uses the host tail instead."""
    t = buckets[0].shape[1]
    s = buckets
    step = 1
    while step < NBUCKETS:
        ident = identity((t, step), xp)
        part = tuple(
            xp.concatenate([c[:, :, step:], i], axis=2) for c, i in zip(s, ident)
        )
        s = padd(s, part, xp)
        step *= 2
    # drop S_0 (weight 0) then tree-reduce S_1..S_255 (+ one identity pad)
    ident = identity((t, 1), xp)
    s = tuple(
        xp.concatenate([c[:, :, 1:], i], axis=2) for c, i in zip(s, ident)
    )
    while s[0].shape[2] > 1:
        half = s[0].shape[2] // 2
        s = padd(
            tuple(c[:, :, :half] for c in s),
            tuple(c[:, :, half:] for c in s),
            xp,
        )
    return tuple(c[:, :, 0] for c in s)


def _combine_windows(w_sums: Point, xp=np) -> Point:
    """Horner over 8-bit windows: acc = 2^8 * acc + W[t], t = T-1 .. 0."""
    t = w_sums[0].shape[1]
    acc = tuple(c[:, t - 1 : t] for c in w_sums)
    for wi in range(t - 2, -1, -1):
        for _ in range(8):
            acc = padd(acc, acc, xp)
        acc = padd(acc, tuple(c[:, wi : wi + 1] for c in w_sums), xp)
    return acc


def g1_msm(
    coords: Sequence[Tuple[int, int]],
    scalars: Sequence[int],
    xp=np,
) -> Optional[Tuple[int, int]]:
    """General-base MSM: sum scalar_i * P_i -> affine ints (None=identity).

    coords: affine (x, y) int pairs (subgroup-checked by the caller —
    crypto keys are validated at ingestion); scalars: ints < r.
    """
    n = len(coords)
    if n == 0:
        return None
    if n != len(scalars):
        raise ValueError("coords/scalars length mismatch")
    digits = scalars_to_bytes([s % F.R_ORDER for s in scalars], n)
    perm, ends = sort_windows(digits)
    pts = points_from_affine_ints(coords)
    n_rounds = max(1, int(np.ceil(np.log2(max(n, 2)))))
    bucket_blocks = []
    for g0 in range(0, NWIN, WINDOW_GROUP):
        g1 = min(g0 + WINDOW_GROUP, NWIN)
        gw = g1 - g0
        # gather each window's sorted lanes; segment id = window * 256 + digit
        idx = np.concatenate([np.asarray(perm[t], dtype=np.int64) for t in range(g0, g1)])
        rows = _gather(pts, xp.asarray(idx), xp)
        segs = np.concatenate(
            [
                (t - g0) * NBUCKETS
                + digits[np.asarray(perm[t], dtype=np.int64), t].astype(np.int64)
                for t in range(g0, g1)
            ]
        )
        rows = _segment_sums(rows, xp.asarray(segs), n_rounds, xp)
        # bucket heads: segment start offsets from the sorted-ends table
        heads = np.zeros((gw, NBUCKETS), dtype=np.int64)
        counts = np.zeros((gw, NBUCKETS), dtype=np.int64)
        for t in range(g0, g1):
            e = np.asarray(ends[t], dtype=np.int64)
            starts = np.concatenate([[0], e[:-1]])
            heads[t - g0] = (t - g0) * n + starts
            counts[t - g0] = e - starts
        # empty buckets have start == segment end (possibly == the row
        # count); clamp for the gather — they are masked to identity below
        heads = np.minimum(heads, gw * n - 1)
        gathered = _gather(rows, xp.asarray(heads.ravel()), xp)
        gathered = pselect(
            xp.asarray(counts.ravel() > 0), gathered, identity((gw * NBUCKETS,), xp), xp
        )
        bucket_blocks.append(
            tuple(c.reshape(F.NLIMBS, gw, NBUCKETS) for c in gathered)
        )
    buckets = tuple(
        xp.concatenate([b[c] for b in bucket_blocks], axis=1) for c in range(3)
    )
    if xp is np:
        return _host_tail(buckets)
    w_sums = _weighted_window_sums(buckets, xp)
    total = _combine_windows(w_sums, xp)
    return point_to_affine_int(total)


def _host_tail(buckets: Point) -> Optional[Tuple[int, int]]:
    """CPU-twin tail: the O(T * 256) weighted-bucket/window-combine work on
    a FIXED 8k-point set (vs the O(n) bucket accumulation above) runs as
    python-int Jacobian arithmetic — ~30x fewer interpreter ops than limb
    form at this batch size. The device path keeps the limb form
    (_weighted_window_sums/_combine_windows); both tails are pinned equal
    in tests/test_bls_kernels.py.

    The limb points are HOMOGENEOUS projective (RCB: x = X/Z); one batched
    Montgomery-trick inversion converts all nonzero-Z buckets to affine
    before the bls_ref Jacobian arithmetic takes over."""
    from tendermint_tpu.crypto import bls_ref as B

    t = buckets[0].shape[1]
    xs, ys, zs = (
        F.mont_to_ints(np.ascontiguousarray(c).reshape(F.NLIMBS, -1))
        for c in buckets
    )
    # batch inversion of the nonzero Zs (one pow for the whole tail)
    nz = [i for i, z in enumerate(zs) if z != 0]
    prefix = [1]
    for i in nz:
        prefix.append(prefix[-1] * zs[i] % F.P)
    inv_all = pow(prefix[-1], F.P - 2, F.P)
    zinv = {}
    for k in range(len(nz) - 1, -1, -1):
        i = nz[k]
        zinv[i] = inv_all * prefix[k] % F.P
        inv_all = inv_all * zs[i] % F.P
    total = B.G1_IDENTITY
    for wi in range(t - 1, -1, -1):
        if wi != t - 1:
            for _ in range(8):
                total = B._jac_double(total)
        running = B.G1_IDENTITY
        wsum = B.G1_IDENTITY
        for d in range(NBUCKETS - 1, 0, -1):
            j = wi * NBUCKETS + d
            if zs[j] != 0:
                zi = zinv[j]
                pt = (
                    B._G1Field(xs[j] * zi % F.P),
                    B._G1Field(ys[j] * zi % F.P),
                    B._G1Field(1),
                )
                running = B._jac_add(running, pt)
            wsum = B._jac_add(wsum, running)
        total = B._jac_add(total, wsum)
    aff = B._jac_to_affine(total)
    return None if aff is None else (aff[0].v, aff[1].v)


def g1_aggregate_bitmap(
    coords: Sequence[Tuple[int, int]],
    bitmap: Sequence[bool],
    xp=np,
) -> Optional[Tuple[int, int]]:
    """Aggregate-pubkey sum over a signer bitmap: apk = sum_{bitmap} P_i.

    The 0/1-scalar MSM degenerates to ONE masked halving-tree reduction
    (log2 n complete-add rounds) — the hot path of VerifyAggregateCommit."""
    n = len(coords)
    if n != len(bitmap):
        raise ValueError("coords/bitmap length mismatch")
    sel = [c for c, b in zip(coords, bitmap) if b]
    if not sel:
        return None
    m = 1 << max(1, int(np.ceil(np.log2(max(len(sel), 2)))))
    pts = points_from_affine_ints(sel)
    ident = identity((m - len(sel),), xp)
    pts = tuple(
        xp.concatenate([xp.asarray(c), i], axis=1) for c, i in zip(pts, ident)
    )
    while pts[0].shape[1] > 1:
        half = pts[0].shape[1] // 2
        lo = tuple(c[:, :half] for c in pts)
        hi = tuple(c[:, half:] for c in pts)
        pts = padd(lo, hi, xp)
    return point_to_affine_int(pts)


# --------------------------------------------------------------------------
# device dispatch (AOT-cached; BLS-prefixed artifact names)


def _bitmap_fold_jnp(X, Y, Z):
    """Halving-tree fold over the lane axis, jnp form (shapes shrink per
    level, fully unrolled at trace time)."""
    import jax.numpy as jnp

    pts = (X, Y, Z)
    while pts[0].shape[1] > 1:
        half = pts[0].shape[1] // 2
        pts = padd(
            tuple(c[:, :half] for c in pts),
            tuple(c[:, half:] for c in pts),
            jnp,
        )
    return pts


def g1_aggregate_bitmap_device(
    coords: Sequence[Tuple[int, int]], bitmap: Sequence[bool]
) -> Optional[Tuple[int, int]]:
    """Device form of g1_aggregate_bitmap: identity-padded to the
    power-of-two jit bucket and dispatched through the AOT artifact cache
    under BLS-OWN names (`bls_bitmap_fold_<bucket>`), machine-fingerprint
    keyed like every artifact (ops/aot_cache.py) so BLS executables never
    collide with the ed25519 RLC family's."""
    import jax

    from tendermint_tpu.ops import aot_cache

    sel = [c for c, b in zip(coords, bitmap) if b]
    if not sel:
        return None
    m = 1 << max(1, int(np.ceil(np.log2(max(len(sel), 2)))))
    pts = points_from_affine_ints(sel)
    ident = identity((m - len(sel),))
    args = tuple(
        np.concatenate([c, i], axis=1) for c, i in zip(pts, ident)
    )
    fn = jax.jit(_bitmap_fold_jnp)
    name = f"bls_bitmap_fold_{m}"
    if aot_cache.enabled():
        out = aot_cache.call(name, fn, *args)
    else:
        out = fn(*args)
    return point_to_affine_int(tuple(np.asarray(c) for c in out))
