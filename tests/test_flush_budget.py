"""Per-flush device-traffic budget guard for the RLC submit path.

Counter-based, test_hotpath_guard-style: PERF.md's roofline says the MSM is
HBM/H2D-bound, so the invariants that keep it fast are "how many bytes go
down the wire per flush" and "how many device dispatches a flush costs" —
not wall clock. These budgets fail tier-1 with a byte/count diff if a
regression reintroduces per-flush A-block uploads, extra dispatches, or
per-point-op layout conversions (the ~8 ms of pack/reshape plumbing the
fused pipeline removed), instead of only showing up in a lost bench round.

Kernels are stubbed (no compiles): the counters live on the submit path
(ops/msm_jax._dispatch, crypto/batch._a_block), not in the kernels.
"""

import os

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.crypto import batch as B
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.ops import msm_jax as M

N_SIGS = 63  # Na bucket 64 -> 128 lanes
NA = 64

# Cached-A steady-state flush, exact expected upload (bytes):
#   r_bytes (32, 64) u8        2048
#   perm    (32, 128) u16      8192
#   ends    (32, 256) i32     32768
#   scalars (128, 32) u8       4096
# FieldCtx/SmallCtx constants are device-resident jnp buffers (not H2D).
CACHED_FLUSH_H2D_BUDGET = 2048 + 8192 + 32768 + 4096
A_BLOCK_BYTES = 4 * 20 * NA * 4  # uploaded once, then device-cached


@pytest.fixture
def stubbed_rlc(monkeypatch):
    monkeypatch.setattr(B, "RLC_MIN", 4)
    monkeypatch.setenv("TMTPU_SHARDED", "0")
    monkeypatch.setenv("TMTPU_DEVICE_SORT", "0")
    monkeypatch.setattr(M.aot_cache, "call", lambda name, fn, *a: fn(*a))

    def cached_stub(ax, ay, az, at, r_bytes, perm, ends, fctx, C):
        return np.ones(1 + r_bytes.shape[1], dtype=bool)

    def plain_stub(pts_bytes, perm, ends, fctx, C):
        return np.ones(1 + pts_bytes.shape[1], dtype=bool)

    for name in ("_rlc_cached_jit", "_rlc_cached_jit_fused"):
        monkeypatch.setattr(M, name, cached_stub)
    for name in ("_rlc_jit", "_rlc_jit_fused"):
        monkeypatch.setattr(M, name, plain_stub)
    B._DEV_A_CACHE.clear()
    yield


def _make_batch(n=N_SIGS):
    pks, msgs, sigs = [], [], []
    for i in range(n):
        priv = gen_ed25519(bytes([0x51]) * 31 + bytes([i]))
        m = b"budget-%03d" % i
        pks.append(priv.pub_key().bytes())
        msgs.append(m)
        sigs.append(priv.sign(m))
    return pks, msgs, sigs


def _flush(pks, msgs, sigs):
    call = B._rlc_submit(pks, msgs, sigs)
    assert B._rlc_finish(call) is not None
    return call


def test_cached_flush_h2d_and_dispatch_budget(stubbed_rlc):
    pks, msgs, sigs = _make_batch()
    B._fill_a_cache(np.stack([np.frombuffer(p, dtype=np.uint8) for p in pks]))

    # flush 1: steady-state kernel, but the device-resident A block is cold
    call = _flush(pks, msgs, sigs)
    assert call.mode == "cached"
    first = dict(B.LAST_FLUSH_DETAIL)
    # flush 2: everything warm — THE per-flush budget being pinned
    call = _flush(pks, msgs, sigs)
    assert call.mode == "cached"
    second = dict(B.LAST_FLUSH_DETAIL)

    assert first["device_dispatches"] == 1
    assert second["device_dispatches"] == 1
    # warm flush: exactly the per-flush wire bytes, nothing else
    assert 0 < second["h2d_bytes"] <= CACHED_FLUSH_H2D_BUDGET, second["h2d_bytes"]
    # the A block went up ONCE (cold flush), never again
    assert first["h2d_bytes"] - second["h2d_bytes"] >= A_BLOCK_BYTES
    assert "fused" in second  # flush detail names the pipeline variant


def test_a_block_reupload_regression_would_fail(stubbed_rlc):
    """Clearing the device-resident A cache between flushes re-pays the
    A-block upload — proving the budget above actually detects the
    regression it guards against."""
    pks, msgs, sigs = _make_batch()
    B._fill_a_cache(np.stack([np.frombuffer(p, dtype=np.uint8) for p in pks]))
    _flush(pks, msgs, sigs)
    _flush(pks, msgs, sigs)
    warm = B.LAST_FLUSH_DETAIL["h2d_bytes"]
    B._DEV_A_CACHE.clear()  # the regression: device A block lost per flush
    _flush(pks, msgs, sigs)
    assert B.LAST_FLUSH_DETAIL["h2d_bytes"] >= warm + A_BLOCK_BYTES


def test_fused_layout_conversion_budget():
    """The fused pipeline performs a CONSTANT number of packed-layout
    conversions (gather->packed, tree->rows, bucket extract) — 3 per MSM —
    independent of point-op count. The unfused wrappers repack per point op;
    a fused-path regression back to that shape changes this count."""
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops import pallas_msm as PM

    n, t_ = 1024, 2
    C = M.make_small_ctx()
    pts = M.Point(*(jax.ShapeDtypeStruct((20, n), jnp.int32) for _ in range(4)))
    perm = jax.ShapeDtypeStruct((t_, n), jnp.int32)
    ends = jax.ShapeDtypeStruct((t_, M.NBUCKETS), jnp.int32)
    before = PM.LAYOUT_CONVERSIONS[0]
    jax.eval_shape(lambda p, pm, e: M._msm_total_fused(C, p, pm, e), pts, perm, ends)
    assert PM.LAYOUT_CONVERSIONS[0] - before == 3


def test_flush_detail_reaches_verify_stats(stubbed_rlc):
    """The budget counters ride the flight recorder: verify_stats
    last_flush names h2d_bytes / device_dispatches / fused for the flush
    (docs/OBSERVABILITY.md)."""
    from tendermint_tpu.libs import trace as _trace

    pks, msgs, sigs = _make_batch()
    B._fill_a_cache(np.stack([np.frombuffer(p, dtype=np.uint8) for p in pks]))
    mask = B.verify_batch(pks, msgs, sigs, backend="jax")
    assert mask.all()
    last = _trace.verify_stats()["last_flush"]
    assert last["path"] == "rlc"
    assert last["device_dispatches"] == 1
    assert 0 < last["h2d_bytes"] <= CACHED_FLUSH_H2D_BUDGET + A_BLOCK_BYTES
    assert last["fused"] is False  # auto mode on the CPU test backend
