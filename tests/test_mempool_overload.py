"""Mempool admission control under flood (ISSUE 5 satellite): eviction
ordering, TTL purge on update, per-sender quotas, cache interaction on
evicted txs, and WAL replay after eviction. Pure-host tests — no crypto
wheel, no TPU, no p2p."""

import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.crypto import tmhash
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.mempool.mempool import (
    Mempool,
    MempoolFullError,
    SenderQuotaError,
    TxInCacheError,
    TxTooLargeError,
    iter_mempool_wal,
)


class PrioApp(ABCIClient):
    """CheckTx stub: a tx like b'p7:payload' gets priority 7; everything is
    accepted unless it starts with b'bad'."""

    def __init__(self):
        self.calls = 0

    def check_tx(self, req):
        self.calls += 1
        tx = req.tx
        prio = 0
        if tx.startswith(b"p") and b":" in tx:
            try:
                prio = int(tx[1 : tx.index(b":")])
            except ValueError:
                prio = 0
        code = abci.CODE_TYPE_OK if not tx.startswith(b"bad") else 1
        return abci.ResponseCheckTx(code=code, priority=prio)


def make_pool(**kw):
    reg = M.Registry()
    mm = M.MempoolMetrics(reg)
    defaults = dict(max_txs=3, metrics=mm)
    defaults.update(kw)
    return Mempool(PrioApp(), **defaults), mm


def txs_in(mp):
    return [m.tx for m in mp._txs.values()]


# ---------------------------------------------------------------------------
# eviction


def test_eviction_evicts_lowest_priority_first():
    mp, mm = make_pool()
    for tx in (b"p5:a", b"p1:b", b"p3:c"):
        mp.check_tx(tx)
    assert mp.is_full(0)
    mp.check_tx(b"p4:d")  # displaces the priority-1 resident
    assert txs_in(mp) == [b"p5:a", b"p3:c", b"p4:d"]
    assert mp.evicted_total == 1
    assert mm.evicted_txs._values.get((), 0) == 1


def test_eviction_equal_priority_is_lru_oldest_first():
    mp, _ = make_pool()
    for tx in (b"p0:a", b"p0:b", b"p0:c"):
        mp.check_tx(tx)
    mp.check_tx(b"p0:d")
    assert txs_in(mp) == [b"p0:b", b"p0:c", b"p0:d"]


def test_eviction_refuses_when_only_higher_priority_remains():
    mp, mm = make_pool()
    for tx in (b"p5:a", b"p5:b", b"p5:c"):
        mp.check_tx(tx)
    with pytest.raises(MempoolFullError) as ei:
        mp.check_tx(b"p1:low")
    assert ei.value.reason == "full"
    assert txs_in(mp) == [b"p5:a", b"p5:b", b"p5:c"]
    assert mm.rejected_txs._values.get(("full",), 0) == 1
    # the refused arrival was UN-cached: once the pool drains it may re-enter
    mp.flush()
    assert mp.check_tx(b"p1:low").code == abci.CODE_TYPE_OK
    assert txs_in(mp) == [b"p1:low"]


def test_eviction_frees_bytes_not_just_slots():
    mp, _ = make_pool(max_txs=100, max_txs_bytes=30)
    mp.check_tx(b"p0:" + b"a" * 10)  # 13 bytes
    mp.check_tx(b"p0:" + b"b" * 10)
    assert mp.txs_bytes() == 26
    mp.check_tx(b"p0:" + b"c" * 20)  # 23 bytes: must evict BOTH residents
    assert txs_in(mp) == [b"p0:" + b"c" * 20]
    assert mp.txs_bytes() == 23


def test_eviction_disabled_restores_hard_error():
    mp, _ = make_pool(eviction=False)
    for tx in (b"a", b"b", b"c"):
        mp.check_tx(tx)
    with pytest.raises(MempoolFullError):
        mp.check_tx(b"d")
    # gossiped txs drop silently
    assert mp.check_tx(b"e", sender="peer1") is None


def test_evicted_tx_leaves_cache_and_can_return():
    mp, _ = make_pool()
    for tx in (b"p0:a", b"p0:b", b"p0:c"):
        mp.check_tx(tx)
    mp.check_tx(b"p9:big")  # evicts p0:a
    assert b"p0:a" not in txs_in(mp)
    # a fresh submission of the evicted tx is admitted (would raise
    # TxInCacheError if eviction left the hash poisoned in the cache)
    mp.check_tx(b"p0:a")
    assert b"p0:a" in txs_in(mp)


def test_duplicate_of_resident_tx_never_triggers_eviction():
    """A duplicate whose hash churned out of the dedup cache (the cache also
    holds rejected hashes, so it cycles under flood) must not evict innocent
    residents just to insert nothing."""
    mp, _ = make_pool()
    for tx in (b"p0:a", b"p0:b", b"p9:c"):
        mp.check_tx(tx)
    key = tmhash.sum256(b"p9:c")
    mp._cache.pop(key)  # simulate cache churn: resident but forgotten
    mp.check_tx(b"p9:c")  # duplicate passes the cache, pool is full
    assert txs_in(mp) == [b"p0:a", b"p0:b", b"p9:c"]  # nothing evicted
    assert mp.evicted_total == 0


# ---------------------------------------------------------------------------
# TTL


def test_ttl_num_blocks_purges_on_update():
    mp, mm = make_pool(max_txs=100, ttl_num_blocks=2)
    mp.update(10, [], [])  # pool height now 10
    mp.check_tx(b"p0:old")  # admitted at height 10
    mp.update(11, [], [])
    assert b"p0:old" in txs_in(mp)  # age 1 < 2
    mp.update(12, [], [])
    assert b"p0:old" not in txs_in(mp)  # age 2 >= 2: purged
    assert mp.expired_total == 1
    assert mm.expired_txs._values.get((), 0) == 1
    # un-cached on expiry: resubmission is accepted
    mp.check_tx(b"p0:old")
    assert b"p0:old" in txs_in(mp)


def test_ttl_seconds_purges_on_update():
    mp, _ = make_pool(max_txs=100, ttl_seconds=0.5)
    mp.check_tx(b"p0:young")
    # backdate the admission timestamp past the TTL
    next(iter(mp._txs.values())).time_ns -= int(1e9)
    mp.update(1, [], [])
    assert txs_in(mp) == []
    assert mp.expired_total == 1


# ---------------------------------------------------------------------------
# per-sender quota


def test_sender_quota_limits_gossip_but_not_rpc():
    mp, mm = make_pool(max_txs=100, max_txs_per_sender=2)
    assert mp.check_tx(b"p0:a", sender="peerA") is not None
    assert mp.check_tx(b"p0:b", sender="peerA") is not None
    # third gossiped tx from the same peer: dropped silently, counted
    assert mp.check_tx(b"p0:c", sender="peerA") is None
    assert mm.rejected_txs._values.get(("quota",), 0) == 1
    assert b"p0:c" not in txs_in(mp)
    # another peer and local RPC submissions are unaffected
    assert mp.check_tx(b"p0:d", sender="peerB") is not None
    for i in range(5):
        mp.check_tx(b"p0:rpc%d" % i)
    assert mp.size() == 8


def test_sender_quota_freed_by_commit_and_eviction():
    mp, _ = make_pool(max_txs=2, max_txs_per_sender=2)
    mp.check_tx(b"p0:a", sender="peerA")
    mp.check_tx(b"p0:b", sender="peerA")
    assert mp._sender_counts == {"peerA": 2}
    # commit one: quota slot returns
    mp.update(1, [b"p0:a"], [abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)])
    assert mp._sender_counts == {"peerA": 1}
    assert mp.check_tx(b"p0:c", sender="peerA") is not None
    # eviction also releases the victim's quota slot
    mp.check_tx(b"p9:hi")  # evicts oldest p0 from peerA
    assert mp._sender_counts.get("peerA", 0) == 1


def test_sender_quota_raises_for_local_flood_only_when_sender_set():
    mp, _ = make_pool(max_txs=100, max_txs_per_sender=1)
    mp.check_tx(b"p0:a", sender="peerA")
    assert mp.check_tx(b"p0:b", sender="peerA") is None
    err = SenderQuotaError("peerA", 1)
    assert err.reason == "quota"


# ---------------------------------------------------------------------------
# size cap / structured reasons


def test_too_large_rejected_with_reason():
    mp, mm = make_pool(max_txs=100, max_tx_bytes=8)
    with pytest.raises(TxTooLargeError) as ei:
        mp.check_tx(b"0123456789")
    assert ei.value.reason == "too_large"
    assert mm.rejected_txs._values.get(("too_large",), 0) == 1
    assert mp.check_tx(b"0123456789", sender="p") is None  # gossip: silent


def test_cache_reject_reason():
    mp, mm = make_pool(max_txs=100)
    mp.check_tx(b"p0:a")
    with pytest.raises(TxInCacheError) as ei:
        mp.check_tx(b"p0:a")
    assert ei.value.reason == "cache"
    assert mm.rejected_txs._values.get(("cache",), 0) == 1


def test_full_gauge_tracks_capacity():
    mp, mm = make_pool()
    for tx in (b"a", b"b"):
        mp.check_tx(tx)
    assert mm.full._values.get((), 0) == 0
    mp.check_tx(b"c")
    assert mm.full._values.get((), 0) == 1
    mp.update(1, [b"a"], [abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)])
    assert mm.full._values.get((), 0) == 0


# ---------------------------------------------------------------------------
# WAL replay after eviction


def test_wal_replay_readmits_evicted_tx(tmp_path):
    wal = str(tmp_path / "mempool" / "wal")
    mp, _ = make_pool(wal_path=wal)
    for tx in (b"p0:a", b"p0:b", b"p0:c"):
        mp.check_tx(tx)
    mp.check_tx(b"p9:vip")  # evicts p0:a; WAL has all four admissions
    assert b"p0:a" not in txs_in(mp)
    mp.close_wal()
    recorded = list(iter_mempool_wal(wal))
    assert recorded == [b"p0:a", b"p0:b", b"p0:c", b"p9:vip"]

    # fresh pool (post-crash): replay re-admits the survivors in WAL order,
    # INCLUDING the evicted tx — eviction un-cached it, so nothing blocks it
    mp2, _ = make_pool(max_txs=10)
    accepted = mp2.replay_wal(wal)
    assert accepted == 4
    assert b"p0:a" in txs_in(mp2)


def test_wal_replay_does_not_append_to_its_own_wal(tmp_path):
    """Replaying into a pool whose live WAL is the same file must not write
    the re-admissions back (the file would double per replay cycle)."""
    wal = str(tmp_path / "self" / "wal")
    mp, _ = make_pool(wal_path=wal, max_txs=10)
    for tx in (b"p0:a", b"p0:b"):
        mp.check_tx(tx)
    mp.flush()  # crash-ish: pool empty, WAL keeps the admissions
    assert mp.replay_wal(wal) == 2
    assert list(iter_mempool_wal(wal)) == [b"p0:a", b"p0:b"]  # unchanged
    # the live WAL is restored after replay: new admissions still append
    mp.check_tx(b"p0:new")
    mp.close_wal()
    assert list(iter_mempool_wal(wal)) == [b"p0:a", b"p0:b", b"p0:new"]


def test_wal_replay_stops_at_torn_tail(tmp_path):
    wal = str(tmp_path / "m" / "wal")
    mp, _ = make_pool(wal_path=wal, max_txs=10)
    for tx in (b"p0:a", b"p0:b"):
        mp.check_tx(tx)
    mp.close_wal()
    with open(wal, "ab") as f:  # torn record: length prefix, half a tx
        f.write((8).to_bytes(4, "big") + b"xxx")
    assert list(iter_mempool_wal(wal)) == [b"p0:a", b"p0:b"]


# ---------------------------------------------------------------------------
# invariants under mixed churn


def test_byte_accounting_stays_consistent_under_churn():
    mp, _ = make_pool(max_txs=4, max_txs_bytes=200, ttl_num_blocks=3,
                      max_txs_per_sender=3)
    import random

    rng = random.Random(7)
    for step in range(200):
        tx = b"p%d:%d" % (rng.randrange(4), step)
        sender = rng.choice(["", "peerA", "peerB"])
        try:
            mp.check_tx(tx, sender=sender)
        except Exception:
            pass
        if step % 13 == 0:
            committed = txs_in(mp)[:1]
            mp.update(
                step // 13,
                committed,
                [abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)] * len(committed),
            )
        assert mp.txs_bytes() == sum(len(t) for t in txs_in(mp))
        assert mp.size() <= 4
        assert all(n > 0 for n in mp._sender_counts.values())
        assert sum(mp._sender_counts.values()) <= mp.size()
