"""Fused MSM pipeline (ops/pallas_msm.py + msm_jax._msm_total_fused).

Three correctness layers, matching how the fused path can actually fail:

1. Tier-1, integer mock: the fold schedule + bit-reversed storage map +
   fused_node_indices_device must reconstruct every bucket-boundary prefix
   sum. Points are mocked as integers (add = +, identity = 0), so this runs
   in milliseconds and catches every pairing/reversal/offset bug.
2. Tier-1, schedule equality: the Pallas kernel bodies and their fe25519
   CPU twins share fold schedules by construction; running BOTH with a
   mocked add on the same data pins them against drift (the row math
   itself is pinned to the fe ops by tests/test_pallas_fe.py).
3. Slow/kernel lane, real curve math: the fused total equals the unfused
   XLA reference bit-for-bit (same association tree at the node level,
   compressed-point equality at the output), and the full verify_batch
   mask through the fused RLC path is byte-identical to the CPU reference
   at several batch sizes — the same pattern as tests/test_rlc_fallback.py.
"""

import os

import numpy as np
import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.ops import msm_jax as M
from tendermint_tpu.ops import pallas_msm as PM


@pytest.fixture(autouse=True)
def _reset_fused_state():
    yield
    M._FUSED_DISABLED[0] = None
    M._set_submit_fused(False)


# ---------------------------------------------------------------------------
# Layer 1: integer-mock schedule + index math.


def _mock_uptree_chunk(g_chunk: np.ndarray, geom) -> np.ndarray:
    """Integer twin of the uptree fold schedule + output layout."""
    out = []
    cur = g_chunk.copy()
    width = geom.ch
    while width > 128:
        width //= 2
        cur = cur[:width] + cur[width:]
        out.append(cur.copy())
    w = 64
    while w >= 1:
        cur = cur + np.roll(cur, 128 - w)
        out.append(cur.copy())
        w //= 2
    flat = np.concatenate(out)
    pad = geom.rows_out * 128 - flat.shape[0]
    return np.concatenate([flat, np.zeros(pad, dtype=flat.dtype)])


def _mock_top_tree(roots: np.ndarray) -> np.ndarray:
    """Integer twin of _tree_levels over chunk roots (+ identity lane)."""
    levels = [roots.copy()]
    cur = roots.copy()
    while cur.shape[0] > 1:
        if cur.shape[0] % 2:
            cur = np.concatenate([cur, [0]])
        cur = cur[0::2] + cur[1::2]
        levels.append(cur.copy())
    widths = M.level_widths(roots.shape[0])
    flat = np.concatenate([lv[:w] for lv, w in zip(levels, widths)])
    return np.concatenate([flat, [0]])


@pytest.mark.parametrize(
    "n,ch", [(2048, 2048), (4096, 2048), (6144, 2048), (3072, 1024), (1024, 1024)]
)
def test_fused_node_indices_reconstruct_every_prefix(n, ch):
    assert PM.chunk_for_lanes(n) == ch
    geom = PM.chunk_geometry(ch)
    t_ = M.NWIN
    ncw = n // ch
    rng = np.random.default_rng(7 + n)
    vals = rng.integers(0, 1 << 40, size=(t_, n)).astype(np.int64)
    digits = rng.integers(0, 256, size=(n, t_)).astype(np.uint8)
    perm, ends = M.sort_windows(digits)
    perm = perm.astype(np.int64)

    perm_f = perm[:, PM.brev_positions(n, ch)]
    g_vals = np.take_along_axis(vals, perm_f, axis=1)
    ctree = np.concatenate(
        [
            _mock_uptree_chunk(g_vals[t, c * ch : (c + 1) * ch], geom)
            for t in range(t_)
            for c in range(ncw)
        ]
    )
    roots = ctree.reshape(t_ * ncw, geom.rows_out * 128)[
        :, geom.row_off[geom.lc] * 128
    ].reshape(t_, ncw)
    top = np.concatenate([_mock_top_tree(roots[t]) for t in range(t_)])
    all_vals = np.concatenate([g_vals.reshape(-1), ctree, top])

    node_idx = np.asarray(M.fused_node_indices_device(ends, n, ch))
    got = all_vals[node_idx].sum(axis=-1)  # (256, T)
    sorted_vals = np.take_along_axis(vals, perm, axis=1)
    csum = np.concatenate(
        [np.zeros((t_, 1), np.int64), np.cumsum(sorted_vals, axis=1)], axis=1
    )
    want = np.stack([csum[t][ends[t]] for t in range(t_)], axis=1)  # (256, T)
    assert (got == want).all()


def test_brev_and_geometry_invariants():
    for ch in (1024, 2048):
        g = PM.chunk_geometry(ch)
        assert g.ch == 1 << g.lc
        assert g.rows_out % 8 == 0
        # row offsets strictly increasing, rows fit
        offs = list(g.row_off[1:])
        assert offs == sorted(offs)
        assert offs[-1] < g.rows_out
        i = np.arange(ch)
        # bit reversal is an involution; positions are a permutation
        assert (PM.brev_np(PM.brev_np(i, g.lc), g.lc) == i).all()
        pos = PM.brev_positions(4 * ch, ch)
        assert sorted(pos.tolist()) == list(range(4 * ch))
    # jnp brev with variable bit counts matches numpy
    import jax.numpy as jnp

    j = np.arange(64)
    for m in range(1, 12):
        assert (
            np.asarray(PM.brev_jnp(jnp.asarray(j % (1 << m)), m))
            == PM.brev_np(j % (1 << m), m)
        ).all()


def test_chunk_for_lanes_routing():
    assert PM.chunk_for_lanes(2048) == 2048
    assert PM.chunk_for_lanes(20480) == 2048
    assert PM.chunk_for_lanes(3072) == 1024
    assert PM.chunk_for_lanes(1024) == 1024
    assert PM.chunk_for_lanes(512) is None
    assert PM.chunk_for_lanes(2500) is None


# ---------------------------------------------------------------------------
# Layer 2: kernel body vs CPU twin, schedules pinned with a mocked add.


def _mock_padd_rows(p, q):
    return tuple([a + b for a, b in zip(pr, qr)] for pr, qr in zip(p, q))


def _mock_padd_fe(p, q):
    return tuple(a + b for a, b in zip(p, q))


@pytest.mark.parametrize("ch", [1024, 2048])
def test_uptree_kernel_body_schedule_equals_twin(monkeypatch, ch):
    import jax.numpy as jnp

    monkeypatch.setattr(PM, "_padd_rows", _mock_padd_rows)
    monkeypatch.setattr(PM, "_padd_fe", _mock_padd_fe)
    g = PM.chunk_geometry(ch)
    rng = np.random.default_rng(5)
    nchunks = 2
    x = rng.integers(0, 1 << 20, size=(4, PM.NL, nchunks * g.rows_in, 128)).astype(
        np.int32
    )
    twin = np.asarray(PM._uptree_jnp(jnp.asarray(x), g))
    blocks = [
        np.asarray(
            PM._uptree_block(
                jnp.asarray(x[:, :, c * g.rows_in : (c + 1) * g.rows_in]),
                g,
                real=False,
            )
        )
        for c in range(nchunks)
    ]
    body = np.concatenate(blocks, axis=2)
    assert (twin == body).all()


def test_bucket_kernel_body_schedule_equals_twin(monkeypatch):
    import jax.numpy as jnp

    monkeypatch.setattr(PM, "_padd_rows", _mock_padd_rows)
    monkeypatch.setattr(PM, "_padd_fe", _mock_padd_fe)
    rng = np.random.default_rng(6)
    t_ = 32
    x = rng.integers(0, 1 << 20, size=(4, PM.NL, 256 * t_ // 128, 128)).astype(
        np.int32
    )
    twin = np.asarray(PM._bucket_jnp(jnp.asarray(x), t_))
    body = np.asarray(PM._bucket_block(jnp.asarray(x), t_, real=False))
    assert (twin == body).all()


# ---------------------------------------------------------------------------
# Routing + failure ladder (stubbed; no compiles).


def test_fused_for_lanes_flag_modes(monkeypatch):
    monkeypatch.setenv("TMTPU_FUSED_MSM", "0")
    assert not M.fused_for_lanes(2048)
    monkeypatch.setenv("TMTPU_FUSED_MSM", "1")
    assert M.fused_for_lanes(2048)
    assert not M.fused_for_lanes(999)  # no chunk tiles it
    monkeypatch.setenv("TMTPU_FUSED_MSM", "auto")
    # auto == pallas-enabled; on the CPU test backend that is False
    from tendermint_tpu.ops import pallas_fe

    assert M.fused_for_lanes(2048) == pallas_fe.enabled()
    # runtime disable wins over everything and is sticky
    monkeypatch.setenv("TMTPU_FUSED_MSM", "1")
    M.disable_fused("test")
    assert not M.fused_for_lanes(2048)
    assert M._FUSED_DISABLED[0] == "test"


def test_fused_submit_failure_disables_and_retries_unfused(monkeypatch):
    """A fused-path submit failure must (a) stick-disable the fused
    pipeline, (b) retry THIS flush unfused, and (c) produce the exact CPU
    mask — the consensus caller never sees the failure."""
    from tendermint_tpu.crypto import batch as B
    from tests.test_rlc_fallback import make_mixed_validity_batch

    monkeypatch.setattr(B, "RLC_MIN", 4)
    monkeypatch.setenv("TMTPU_SHARDED", "0")
    monkeypatch.setattr(
        M, "fused_for_lanes", lambda n: M._FUSED_DISABLED[0] is None
    )
    monkeypatch.setattr(M.aot_cache, "call", lambda name, fn, *a: fn(*a))

    calls = []

    def fused_boom(*a, **kw):
        calls.append("fused")
        raise RuntimeError("injected Mosaic lowering failure")

    def unfused_ok(ax, ay, az, at, r_bytes, perm, ends, fctx, C):
        calls.append("unfused")
        return np.ones(1 + r_bytes.shape[1], dtype=bool)

    def unfused_plain_ok(pts_bytes, perm, ends, fctx, C):
        calls.append("unfused")
        return np.ones(1 + pts_bytes.shape[1], dtype=bool)

    monkeypatch.setattr(M, "_rlc_jit_fused", fused_boom)
    monkeypatch.setattr(M, "_rlc_cached_jit_fused", fused_boom)
    monkeypatch.setattr(M, "_rlc_jit", unfused_plain_ok)
    monkeypatch.setattr(M, "_rlc_cached_jit", unfused_ok)

    pks, msgs, sigs = make_mixed_validity_batch()
    cpu = B.verify_batch_cpu(pks, msgs, sigs)
    mask = B.verify_batch(pks, msgs, sigs, backend="jax")

    assert mask.tobytes() == cpu.tobytes()
    assert "fused" in calls and "unfused" in calls
    assert M._FUSED_DISABLED[0] is not None  # sticky
    assert B.LAST_JAX_PATH[0] == "rlc"  # the RLC path survived the failure
    # next flush goes straight unfused (no new fused attempts)
    n_fused = calls.count("fused")
    B.verify_batch(pks, msgs, sigs, backend="jax")
    assert calls.count("fused") == n_fused


# ---------------------------------------------------------------------------
# Layer 3: real curve math (slow/kernel lane).


def _compress(p):
    from tendermint_tpu.crypto import ed25519_ref as ref
    from tendermint_tpu.ops import fe25519 as fe

    x = fe.to_int(np.asarray(p.x)) % ref.P
    y = fe.to_int(np.asarray(p.y)) % ref.P
    z = fe.to_int(np.asarray(p.z)) % ref.P
    t = fe.to_int(np.asarray(p.t)) % ref.P
    return ref.point_compress((x, y, z, t))


@pytest.mark.slow
@pytest.mark.kernel
def test_fused_total_matches_unfused_reference(monkeypatch):
    """The fused schedule computes the same multiscalar sum as the unfused
    per-level reference (compressed-point equality; different association
    orders give different projective representatives)."""
    import jax

    from tendermint_tpu.crypto import ed25519_ref as ref
    from tendermint_tpu.ops import fe25519 as fe

    monkeypatch.setenv("TMTPU_FUSED_MSM", "1")
    rng = np.random.default_rng(3)
    n, t_ = 1024, 2
    cols = []
    for _ in range(n):
        k = int.from_bytes(rng.bytes(8), "little") | 1
        x, y, z, t = ref.point_mul(k, ref.BASE)
        cols.append(
            [fe.from_int(x), fe.from_int(y), fe.from_int(z), fe.from_int(t)]
        )
    pts = M.Point(
        *(
            np.stack([c[i] for c in cols], axis=-1).astype(np.int32)
            for i in range(4)
        )
    )
    digits = rng.integers(0, 256, size=(n, t_)).astype(np.uint8)
    perm, ends = M.sort_windows(digits)
    C = M.make_small_ctx()

    node_idx = M.fenwick_nodes_device(ends, n)
    unf = jax.jit(M._msm_total)(C, pts, perm.astype(np.int32), node_idx)
    fus = jax.jit(M._msm_total_fused)(C, pts, perm.astype(np.int32), ends)
    unf = M.Point(*(np.asarray(c) for c in unf))
    fus = M.Point(*(np.asarray(c) for c in fus))
    assert _compress(unf) == _compress(fus)


@pytest.mark.slow
@pytest.mark.kernel
@pytest.mark.heavy  # full RLC graph at 2048/3072 lanes: multi-minute
# one-time XLA:CPU compiles (persistent-cached); on TPU the same programs
# are Pallas custom calls + gathers and compile in seconds
@pytest.mark.parametrize("n_sigs", [600, 1400])
def test_fused_rlc_mask_byte_identical_to_cpu(monkeypatch, n_sigs):
    """Full verify_batch through the fused RLC path (plain + cached-A
    kernels): mask byte-identical to the CPU reference, including rows the
    host precheck rejects (bad pubkey length, non-canonical s) — and the
    combined check itself must ACCEPT (no silent always-fallback).
    n=600 -> 2048 lanes (chunk 2048); n=1400 -> 3072 lanes (chunk 1024)."""
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto.ed25519_ref import L
    from tendermint_tpu.crypto.keys import gen_ed25519

    monkeypatch.setenv("TMTPU_FUSED_MSM", "1")
    monkeypatch.setenv("TMTPU_SHARDED", "0")
    B._A_CACHE.clear()

    pks, msgs, sigs = [], [], []
    for i in range(n_sigs):
        priv = gen_ed25519(bytes([9]) * 30 + bytes([i // 256, i % 256]))
        m = b"fused-rlc-%04d" % i
        pks.append(priv.pub_key().bytes())
        msgs.append(m)
        sigs.append(priv.sign(m))
    pks[17] = pks[17][:16]  # precheck-rejected: bad pubkey length
    sigs[41] = sigs[41][:32] + L.to_bytes(32, "little")  # non-canonical s

    lanes = 2 * B._lane_bucket(n_sigs + 1)
    assert M.fused_for_lanes(lanes), lanes

    cpu = B.verify_batch_cpu(pks, msgs, sigs)
    mask = B.verify_batch_jax(pks, msgs, sigs)  # plain kernel, fills A cache
    assert mask.tobytes() == cpu.tobytes()
    assert B.LAST_JAX_PATH[0] == "rlc"
    assert B.LAST_FLUSH_DETAIL.get("fused") is True

    mask2 = B.verify_batch_jax(pks, msgs, sigs)  # cached-A kernel
    assert mask2.tobytes() == cpu.tobytes()
    assert B.LAST_RLC_TIMINGS.get("cached") is True
    assert B.LAST_FLUSH_DETAIL.get("fused") is True
