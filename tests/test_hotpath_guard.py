"""Tier-1 "no-redundant-work" guard for the live vote path.

Counter-based, NOT wall-clock — stable on shared/loaded hosts. The budgets
pin the per-vote work the hot loop is allowed to do after ISSUE 3:

- protowire encode COMPUTES (types/vote.py ENCODE_COMPUTES): at most one
  per vote across the whole ingest path (WAL frame + gossip re-sends);
- canonical sign-bytes COMPUTES (SIGN_BYTES_COMPUTES): one per vote on the
  serial-verify path, ZERO per peer vote on the deferred path (the flush
  uses the batched builder);
- fsyncs (consensus/wal.py WAL.fsync_count): group commit means one per
  queue drain + one per self-generated message, never one per peer vote.

If a future change bypasses the memo or the group-commit boundary, these
fail with a counter diff instead of a flaky timing assertion.
"""

import asyncio
import dataclasses
import time

import numpy as np
import pytest

from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.types import vote as vote_mod
from tendermint_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import VoteSet

BID = BlockID(b"\x01" * 32, PartSetHeader(1, b"\x02" * 32))


def make_valset(n):
    rng = np.random.default_rng(11)
    privs = [gen_ed25519(rng.integers(0, 256, 32, dtype=np.uint8).tobytes()) for _ in range(n)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, [by_addr[v.address] for v in vals.validators]


def signed_votes(vals, privs, chain_id="guard", height=1):
    out = []
    for i, (val, priv) in enumerate(zip(vals.validators, privs)):
        v = Vote(type=SignedMsgType.PRECOMMIT, height=height, round=0, block_id=BID,
                 timestamp_ns=0, validator_address=val.address, validator_index=i)
        out.append(dataclasses.replace(v, signature=priv.sign(v.sign_bytes(chain_id))))
    return out


def test_deferred_flush_does_zero_per_vote_encodes():
    """The deferred path's budget: ZERO per-vote sign-bytes/encode computes —
    sign-bytes come from the batched builder, nothing serializes the Vote."""
    n = 64
    vals, privs = make_valset(n)
    votes = signed_votes(vals, privs)
    vs = VoteSet("guard", 1, 0, SignedMsgType.PRECOMMIT, vals, defer_verification=True)
    enc0, sb0 = vote_mod.ENCODE_COMPUTES, vote_mod.SIGN_BYTES_COMPUTES
    for v in votes:
        vs.add_vote(v)
    committed, failed = vs.flush()
    assert len(committed) == n and not failed
    assert vote_mod.ENCODE_COMPUTES - enc0 == 0
    assert vote_mod.SIGN_BYTES_COMPUTES - sb0 == 0


def test_serial_add_vote_is_one_sign_bytes_per_vote():
    n = 32
    vals, privs = make_valset(n)
    votes = signed_votes(vals, privs)
    vs = VoteSet("guard", 1, 0, SignedMsgType.PRECOMMIT, vals)
    sb0 = vote_mod.SIGN_BYTES_COMPUTES
    for v in votes:
        vs.add_vote(v)
    assert vote_mod.SIGN_BYTES_COMPUTES - sb0 == n


def test_wal_fsync_budget_is_per_drain_not_per_vote(tmp_path):
    from tendermint_tpu.consensus.messages import VoteMessage
    from tendermint_tpu.consensus.wal import WAL, MsgInfo

    n = 256
    vals, privs = make_valset(8)
    votes = signed_votes(vals, privs) * (n // 8)
    wal = WAL(str(tmp_path / "wal"), group_commit=True, group_commit_max_latency=60.0)
    base = wal.fsync_count
    enc0 = vote_mod.ENCODE_COMPUTES
    for v in votes:
        wal.write(MsgInfo(VoteMessage(v), "peer"))
    wal.flush_buffered()
    # young data: ONE buffered write, ZERO fsyncs for the whole drain
    assert wal.fsync_count - base == 0
    wal._dirty_since = time.perf_counter() - 999.0  # aged past the bound
    wal.flush_buffered()
    assert wal.fsync_count - base == 1  # ONE fsync once the bound is due
    # 8 distinct Vote objects -> 8 encodes, not 256
    assert vote_mod.ENCODE_COMPUTES - enc0 == 8
    wal.close()


@pytest.mark.parametrize("defer", [True, False])
def test_live_height_budgets(tmp_path, defer):
    """End-to-end: one real ConsensusState driven through a full height by
    stub validators (the bench_live_consensus shape, shrunk). Budgets per
    ingested vote: encodes <= 1 + slack, fsyncs bounded by drains+internal
    messages, deferred sign-bytes bounded by our OWN votes only."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.consensus.cs_state import ConsensusState
    from tendermint_tpu.consensus.messages import (
        BlockPartMessage,
        ProposalMessage,
        VoteMessage,
    )
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.consensus.wal import WAL
    from tendermint_tpu.evidence.pool import EvidencePool
    from tendermint_tpu.libs.kvdb import MemDB
    from tendermint_tpu.mempool.mempool import Mempool
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.proxy.multi import AppConns, local_client_creator
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.sm_state import state_from_genesis
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.blockstore import BlockStore
    from tendermint_tpu.types.event_bus import EventBus
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.part_set import PartSet
    from tendermint_tpu.types.proposal import Proposal

    n_vals = 16
    chain = "guard-live"
    privs = [FilePV(gen_ed25519(bytes([60 + i]) * 32)) for i in range(n_vals)]
    gen = GenesisDoc(chain_id=chain,
                     validators=[GenesisValidator(p.get_pub_key(), 10) for p in privs])
    gen.validate_and_complete()
    state = state_from_genesis(gen)
    by_addr = {p.get_pub_key().address(): p for p in privs}
    sorted_privs = [by_addr[v.address] for v in state.validators.validators]
    proxy = AppConns(local_client_creator(KVStoreApplication()))
    block_store = BlockStore(MemDB())
    state_store = StateStore(MemDB())
    state_store.save(state)
    event_bus = EventBus()
    mempool = Mempool(proxy.mempool)
    evpool = EvidencePool(MemDB(), state_store, block_store)
    evpool.set_state(state)
    block_exec = BlockExecutor(state_store, proxy.consensus, mempool, evpool,
                               event_bus=event_bus, block_store=block_store)
    cfg = test_config().consensus
    cfg.defer_vote_verification = defer
    state = Handshaker(state_store, state, block_store, gen, event_bus).handshake(proxy)
    wal = WAL(str(tmp_path / "wal"), group_commit=cfg.wal_group_commit,
              group_commit_max_latency=cfg.wal_group_commit_max_latency)
    # a LIVE tx lifecycle tracker rides along (ISSUE 10): with tracing
    # enabled it must not move any vote-path counter budget below — the
    # tracker never touches votes, and this pins that
    from tendermint_tpu.libs.txtrace import TxTracker

    cs = ConsensusState(cfg, state, block_exec, block_store, mempool, evpool,
                        wal, event_bus=event_bus, priv_validator=sorted_privs[0],
                        tx_tracker=TxTracker())

    async def run():
        await cs.start()
        me = sorted_privs[0].get_pub_key().address()
        try:
            while cs.rs.height != 1:
                await asyncio.sleep(0.005)
            rs = cs.rs
            prop_addr = rs.validators.get_proposer().address
            prop_idx = next(i for i, v in enumerate(rs.validators.validators)
                            if v.address == prop_addr)
            if prop_addr != me:
                from tendermint_tpu.types.block import Commit as CommitT

                block = block_exec.create_proposal_block(
                    1, cs.state, CommitT(0, 0, BlockID(), ()), prop_addr, time.time_ns()
                )
                parts = PartSet.from_data(block.encode())
                bid = BlockID(block.hash(), parts.header)
                prop = Proposal(height=1, round=0, pol_round=-1, block_id=bid,
                                timestamp_ns=time.time_ns())
                prop = sorted_privs[prop_idx].sign_proposal(chain, prop)
            else:
                while cs.rs.proposal_block is None or cs.rs.proposal_block_parts is None:
                    await asyncio.sleep(0.005)
                parts = cs.rs.proposal_block_parts
                bid = BlockID(cs.rs.proposal_block.hash(), parts.header)
                prop = None

            def sign(vtype):
                out = []
                for i, p in enumerate(sorted_privs[1:], start=1):
                    v = Vote(type=vtype, height=1, round=0, block_id=bid,
                             timestamp_ns=time.time_ns(),
                             validator_address=p.get_pub_key().address(),
                             validator_index=i)
                    out.append(dataclasses.replace(
                        v, signature=p.priv_key.sign(v.sign_bytes(chain))))
                return out

            prevotes, precommits = sign(SignedMsgType.PREVOTE), sign(SignedMsgType.PRECOMMIT)

            enc0, sb0 = vote_mod.ENCODE_COMPUTES, vote_mod.SIGN_BYTES_COMPUTES
            fs0, wr0 = wal.fsync_count, wal.write_calls
            if prop is not None:
                await cs.add_peer_message(ProposalMessage(prop), "peer")
                for i in range(parts.total):
                    await cs.add_peer_message(BlockPartMessage(1, 0, parts.get_part(i)), "peer")
            for v in prevotes + precommits:
                await cs.add_peer_message(VoteMessage(v), f"peer-{v.validator_index}")
            deadline = time.monotonic() + 30
            while cs.rs.height == 1:
                assert time.monotonic() < deadline, "height 1 did not commit"
                await asyncio.sleep(0.002)
            return (
                len(prevotes) + len(precommits),
                vote_mod.ENCODE_COMPUTES - enc0,
                vote_mod.SIGN_BYTES_COMPUTES - sb0,
                wal.fsync_count - fs0,
                wal.write_calls - wr0,
            )
        finally:
            await cs.stop()

    n_votes, d_enc, d_sb, d_fsync, d_writes = asyncio.run(run())
    assert n_votes == 2 * (n_vals - 1)
    # our node signs up to 2 internal votes; each vote (peer or own) may be
    # protowire-encoded AT MOST once end-to-end
    assert d_enc <= n_votes + 4, f"encode computes {d_enc} for {n_votes} votes"
    if defer:
        # peer votes verify via the batched sign-bytes builder: per-vote
        # canonical computes must NOT scale with the vote count
        assert d_sb <= 8, f"deferred sign-bytes computes {d_sb}"
    else:
        # serial: one verify (and thus one compute) per peer vote + our own
        assert d_sb <= 2 * n_votes, f"serial sign-bytes computes {d_sb}"
    # group commit: fsyncs scale with drains + self-generated messages, not
    # with peer votes (a per-vote-fsync regression would be ~n_votes here)
    assert d_fsync <= n_votes // 2, f"{d_fsync} fsyncs for {n_votes} votes ({d_writes} writes)"
