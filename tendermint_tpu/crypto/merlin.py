"""Merlin transcripts over STROBE-128/keccak-f[1600]
(reference: crypto/sr25519 uses go-schnorrkel, which binds signatures with
merlin transcripts; this is a from-scratch implementation of the public
Merlin/STROBE specifications).

Only the operations merlin needs are implemented: meta-AD, AD, PRF."""

from __future__ import annotations

import struct

# ---------------------------------------------------------------------------
# keccak-f[1600]
# ---------------------------------------------------------------------------

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_ROTC = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_MASK = (1 << 64) - 1


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (64 - n))) & _MASK


def keccak_f1600(state: bytearray) -> None:
    lanes = list(struct.unpack("<25Q", state))
    a = [[lanes[x + 5 * y] for y in range(5)] for x in range(5)]
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _ROTC[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & _MASK & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= rc
    out = [a[x][y] for y in range(5) for x in range(5)]
    state[:] = struct.pack("<25Q", *out)


# ---------------------------------------------------------------------------
# STROBE-128
# ---------------------------------------------------------------------------

STROBE_R = 166  # sponge rate for 128-bit security over keccak-f[1600]

_FLAG_I = 1
_FLAG_A = 1 << 1
_FLAG_C = 1 << 2
_FLAG_T = 1 << 3
_FLAG_M = 1 << 4
_FLAG_K = 1 << 5


class Strobe128:
    def __init__(self, protocol_label: bytes):
        self.state = bytearray(200)
        self.state[0:6] = bytes([1, STROBE_R + 2, 1, 0, 1, 96])
        self.state[6:18] = b"STROBEv1.0.2"
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[STROBE_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray(n)
        for i in range(n):
            out[i] = self.state[self.pos]
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if flags != self.cur_flags:
                raise ValueError("flag mismatch on continuation")
            return
        if flags & _FLAG_T:
            raise ValueError("transport not supported")
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = bool(flags & (_FLAG_C | _FLAG_K))
        if force_f and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool = False) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        # KEY overwrites (duplex): absorb-with-replace
        for byte in data:
            self.state[self.pos] = byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def clone(self) -> "Strobe128":
        c = object.__new__(Strobe128)
        c.state = bytearray(self.state)
        c.pos = self.pos
        c.pos_begin = self.pos_begin
        c.cur_flags = self.cur_flags
        return c


class Transcript:
    """Merlin transcript (public spec; merlin.cool)."""

    def __init__(self, label: bytes, _strobe: Strobe128 | None = None):
        if _strobe is not None:
            self.strobe = _strobe
            return
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(struct.pack("<I", len(message)), True)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, value: int) -> None:
        self.append_message(label, struct.pack("<Q", value))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(struct.pack("<I", n), True)
        return self.strobe.prf(n)

    def clone(self) -> "Transcript":
        return Transcript(b"", _strobe=self.strobe.clone())


# ---------------------------------------------------------------------------
# Batched transcripts: N independent STROBE states advanced in lockstep with
# numpy (vectorized keccak-f[1600]). Valid when every row runs the SAME
# operation sequence with the SAME lengths — exactly the sr25519 batch-verify
# challenge derivation, where per-row data (msg, pk, R) varies but labels and
# (grouped-by-length) sizes do not. ~100x faster than N Python transcripts.
# ---------------------------------------------------------------------------

import numpy as _np


def keccak_f1600_batch(lanes: "_np.ndarray") -> "_np.ndarray":
    """lanes: (N, 25) uint64 -> permuted (N, 25); column x + 5*y."""

    def rotl(v, n):
        if n == 0:
            return v
        return (v << _np.uint64(n)) | (v >> _np.uint64(64 - n))

    a = [lanes[:, i].copy() for i in range(25)]
    for rc in _ROUND_CONSTANTS:
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        b = [None] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl(a[x + 5 * y], _ROTC[x][y])
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y])
        a[0] ^= _np.uint64(rc)
    return _np.stack(a, axis=1)


class BatchStrobe128:
    """N STROBE-128 states in lockstep (positions/flags shared scalars)."""

    def __init__(self, protocol_label: bytes, n: int):
        self.n = n
        self.state = _np.zeros((n, 200), dtype=_np.uint8)
        init = bytearray(200)
        init[0:6] = bytes([1, STROBE_R + 2, 1, 0, 1, 96])
        init[6:18] = b"STROBEv1.0.2"
        keccak_f1600(init)
        self.state[:] = _np.frombuffer(bytes(init), dtype=_np.uint8)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(_np.tile(_np.frombuffer(protocol_label, _np.uint8), (n, 1)), False)

    def _run_f(self) -> None:
        self.state[:, self.pos] ^= self.pos_begin
        self.state[:, self.pos + 1] ^= 0x04
        self.state[:, STROBE_R + 1] ^= 0x80
        lanes = self.state.view(_np.uint64).reshape(self.n, 25)
        self.state = keccak_f1600_batch(lanes).view(_np.uint8).reshape(self.n, 200).copy()
        self.pos = 0
        self.pos_begin = 0

    def _as_rows(self, data) -> "_np.ndarray":
        """bytes (shared) or (N, L) uint8 array -> (N, L)."""
        if isinstance(data, (bytes, bytearray)):
            return _np.tile(_np.frombuffer(bytes(data), _np.uint8), (self.n, 1))
        return data

    def _absorb(self, data) -> None:
        rows = self._as_rows(data)
        off = 0
        total = rows.shape[1]
        while off < total:
            k = min(STROBE_R - self.pos, total - off)
            self.state[:, self.pos : self.pos + k] ^= rows[:, off : off + k]
            self.pos += k
            off += k
            if self.pos == STROBE_R:
                self._run_f()

    def _squeeze(self, n_bytes: int) -> "_np.ndarray":
        out = _np.empty((self.n, n_bytes), dtype=_np.uint8)
        off = 0
        while off < n_bytes:
            k = min(STROBE_R - self.pos, n_bytes - off)
            out[:, off : off + k] = self.state[:, self.pos : self.pos + k]
            self.state[:, self.pos : self.pos + k] = 0
            self.pos += k
            off += k
            if self.pos == STROBE_R:
                self._run_f()
        return out

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            if flags != self.cur_flags:
                raise ValueError("flag mismatch on continuation")
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        if flags & (_FLAG_C | _FLAG_K) and self.pos != 0:
            self._run_f()

    def meta_ad(self, data, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n_bytes: int, more: bool = False) -> "_np.ndarray":
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n_bytes)


class BatchTranscript:
    """Merlin transcripts in lockstep; per-row payloads must share lengths."""

    def __init__(self, label: bytes, n: int):
        self.strobe = BatchStrobe128(b"Merlin v1.0", n)
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, messages) -> None:
        rows = self.strobe._as_rows(messages)
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(struct.pack("<I", rows.shape[1]), True)
        self.strobe.ad(rows, False)

    def challenge_bytes(self, label: bytes, n_bytes: int) -> "_np.ndarray":
        """-> (N, n_bytes) uint8."""
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(struct.pack("<I", n_bytes), True)
        return self.strobe.prf(n_bytes)
