"""Query-indexed pub/sub (reference: libs/pubsub/pubsub.go:91 + query DSL).

Events are (type, attributes) maps; subscriptions carry a Query that matches
composite key=value conditions. The query language covers the reference
grammar (reference: libs/pubsub/query/query.go): `key = 'value'`, numeric
comparisons =, <, <=, >, >=, CONTAINS, EXISTS, conjunctions with AND, and
chronological comparisons against `TIME <RFC3339>` / `DATE <YYYY-MM-DD>`
operands (e.g. `block.timestamp >= TIME 2013-05-03T14:45:00Z`)."""

from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from datetime import date, datetime, timezone
from typing import Dict, List, Optional, Tuple

_CONDITION_RE = re.compile(
    r"\s*([\w.]+)\s*(=|<=|>=|<|>|CONTAINS|EXISTS)\s*"
    r"((?:TIME|DATE)\s+[\w.:+\-]+|'(?:[^']*)'|\"(?:[^\"]*)\"|[\w.\-+]+)?\s*"
)


def _parse_rfc3339(raw: str) -> datetime:
    """RFC3339 timestamp or bare date -> aware datetime (UTC default)."""
    s = raw.strip()
    if s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt


@dataclass(frozen=True)
class Condition:
    key: str
    op: str
    value: str = ""
    # chronological operand: datetime parsed from TIME/DATE literals
    # (reference: libs/pubsub/query/query.go time/date conditions)
    time_value: Optional[datetime] = None


class Query:
    """Parsed conjunction of conditions."""

    def __init__(self, query_str: str):
        self.query_str = query_str.strip()
        self.conditions: List[Condition] = []
        if self.query_str:
            for clause in self.query_str.split(" AND "):
                m = _CONDITION_RE.fullmatch(clause)
                if not m:
                    raise ValueError(f"invalid query clause: {clause!r}")
                key, op, raw = m.group(1), m.group(2), m.group(3)
                if op == "EXISTS":
                    self.conditions.append(Condition(key, op))
                    continue
                if raw is None:
                    raise ValueError(f"missing value in clause: {clause!r}")
                if raw.startswith(("TIME ", "TIME\t", "DATE ", "DATE\t")):
                    kind, _, lit = raw.partition(raw[4])
                    try:
                        if kind == "DATE":
                            d = date.fromisoformat(lit.strip())
                            tv = datetime(d.year, d.month, d.day, tzinfo=timezone.utc)
                        else:
                            tv = _parse_rfc3339(lit)
                    except ValueError as e:
                        raise ValueError(f"invalid {kind} literal in {clause!r}: {e}")
                    self.conditions.append(Condition(key, op, lit.strip(), tv))
                    continue
                if raw[0] in "'\"":
                    raw = raw[1:-1]
                self.conditions.append(Condition(key, op, raw))

    def matches(self, events: Dict[str, List[str]]) -> bool:
        for cond in self.conditions:
            values = events.get(cond.key)
            if values is None:
                return False
            if cond.op == "EXISTS":
                continue
            if cond.time_value is not None:
                ok = False
                for v in values:
                    try:
                        ev = _parse_rfc3339(v)
                    except ValueError:
                        continue
                    if (
                        (cond.op == "=" and ev == cond.time_value)
                        or (cond.op == "<" and ev < cond.time_value)
                        or (cond.op == "<=" and ev <= cond.time_value)
                        or (cond.op == ">" and ev > cond.time_value)
                        or (cond.op == ">=" and ev >= cond.time_value)
                    ):
                        ok = True
                        break
                if not ok:
                    return False
                continue
            if cond.op == "=":
                if cond.value not in values:
                    return False
            elif cond.op == "CONTAINS":
                if not any(cond.value in v for v in values):
                    return False
            else:
                ok = False
                for v in values:
                    try:
                        fv, cv = float(v), float(cond.value)
                    except ValueError:
                        continue
                    if (
                        (cond.op == "<" and fv < cv)
                        or (cond.op == "<=" and fv <= cv)
                        or (cond.op == ">" and fv > cv)
                        or (cond.op == ">=" and fv >= cv)
                    ):
                        ok = True
                        break
                if not ok:
                    return False
        return True

    def __str__(self) -> str:
        return self.query_str

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self.query_str == other.query_str

    def __hash__(self) -> int:
        return hash(self.query_str)


@dataclass
class Message:
    data: object
    events: Dict[str, List[str]]


class Subscription:
    """Buffered subscription. Overflow policy: DROP-OLDEST with a counter —
    a slow subscriber loses its stalest messages (visible on /metrics as
    `tendermint_pubsub_dropped_messages_total` and on `self.dropped`) but
    stays subscribed; the old cancel-on-overflow policy turned one slow RPC
    client into a silent permanent detach."""

    def __init__(self, out_capacity: int = 100):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=out_capacity)
        self.cancelled = False
        self.cancel_reason = ""
        self.dropped = 0  # messages dropped oldest-first on overflow

    async def next(self) -> Message:
        msg = await self.queue.get()
        if msg is None:
            raise RuntimeError(f"subscription cancelled: {self.cancel_reason}")
        return msg


# The composite key the subscriber index keys on — same convention as
# types/event_bus.py EVENT_TYPE_KEY (duplicated here so the generic pubsub
# layer does not import the typed event layer built on top of it).
EVENT_TYPE_KEY = "tm.event"

# trailing per-connection id in subscriber names ('ws-140…', 'btc-9f3a…'):
# a separator followed by >=4 hex digits, to end of string
_SUBSCRIBER_ID_SUFFIX = re.compile(r"[-_][0-9a-fA-F]{4,}$")


class PubSubServer:
    """In-process server. publish() is non-blocking (drop-oldest on a full
    subscriber buffer, see Subscription) and maintains an index of
    subscriptions by their `tm.event = '<X>'` equality condition so the hot
    path can skip ALL per-event work when nobody could possibly match —
    consensus publishes a Vote event per verified vote whether or not
    anyone is listening, and the zero-subscriber case must cost ~nothing."""

    def __init__(self, index_key: str = EVENT_TYPE_KEY):
        self._subs: Dict[Tuple[str, str], Tuple[Query, Subscription]] = {}
        self._index_key = index_key
        # sub key -> indexed event-type value (None = not indexable)
        self._sub_event_type: Dict[Tuple[str, str], Optional[str]] = {}
        # event-type value -> sub keys with exactly that equality condition
        self._by_event_type: Dict[str, set] = {}
        # sub keys whose query has no single tm.event equality condition
        # (must be consulted for every publish)
        self._unindexed: set = set()

    def _index_value(self, query: Query) -> Optional[str]:
        vals = [
            c.value
            for c in query.conditions
            if c.key == self._index_key and c.op == "=" and c.time_value is None
        ]
        return vals[0] if len(vals) == 1 else None

    def subscribe(self, subscriber: str, query: Query, out_capacity: int = 100) -> Subscription:
        key = (subscriber, query.query_str)
        if key in self._subs:
            raise ValueError("already subscribed")
        sub = Subscription(out_capacity)
        self._subs[key] = (query, sub)
        val = self._index_value(query)
        self._sub_event_type[key] = val
        if val is None:
            self._unindexed.add(key)
        else:
            self._by_event_type.setdefault(val, set()).add(key)
        return sub

    def _drop_index(self, key: Tuple[str, str]) -> None:
        val = self._sub_event_type.pop(key, None)
        if val is None:
            self._unindexed.discard(key)
        else:
            keys = self._by_event_type.get(val)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_event_type[val]

    @staticmethod
    def _cancel(sub: Subscription, reason: str) -> None:
        sub.cancelled = True
        sub.cancel_reason = reason
        try:
            sub.queue.put_nowait(None)
        except asyncio.QueueFull:
            # make room so the cancellation sentinel always lands
            try:
                sub.queue.get_nowait()
            except asyncio.QueueEmpty:
                pass
            try:
                sub.queue.put_nowait(None)
            except asyncio.QueueFull:
                pass

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        key = (subscriber, query.query_str)
        entry = self._subs.pop(key, None)
        if entry is None:
            raise ValueError("subscription not found")
        self._drop_index(key)
        self._cancel(entry[1], "unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        for key in [k for k in self._subs if k[0] == subscriber]:
            _, sub = self._subs.pop(key)
            self._drop_index(key)
            self._cancel(sub, "unsubscribed")

    # -- publishing ---------------------------------------------------------

    def has_subscribers(self, event_type: Optional[str] = None) -> bool:
        """True if a publish for `event_type` could reach anyone. The
        zero-subscriber fast path: callers check this BEFORE building the
        event map/payload (types/event_bus.py publish_vote)."""
        if not self._subs:
            return False
        if event_type is None or self._unindexed:
            return True
        return event_type in self._by_event_type

    def _candidates(self, events: Dict[str, List[str]]) -> list:
        """Subscription keys whose indexed condition could match `events`
        (plus every unindexed one). Deduplicated — an app-emitted attribute
        can legally collide with the index key (e.g. an ABCI event typed
        'tm' with key 'event'), putting the same value in the list twice,
        and a subscriber must still receive each publish exactly once."""
        keys: dict = {}
        etvals = events.get(self._index_key)
        if etvals:
            for v in etvals:
                for k in self._by_event_type.get(v, ()):
                    keys[k] = None
        for k in self._unindexed:
            keys[k] = None
        return list(keys)

    @staticmethod
    def _metric_label(subscriber: str) -> str:
        """Stable, bounded-cardinality label for the drop counter: strip
        per-connection id suffixes ('ws-140…', 'btc-9f3a…') down to their
        class prefix — every reconnecting websocket must NOT mint a fresh
        series in the never-pruned global registry."""
        return _SUBSCRIBER_ID_SUFFIX.sub("", subscriber) or "other"

    def _deliver(self, subscriber: str, sub: Subscription, msg: Message) -> None:
        try:
            sub.queue.put_nowait(msg)
        except asyncio.QueueFull:
            # Drop-oldest: evict the stalest message, count it, deliver the
            # new one. Never blocks, never raises, never silently detaches.
            try:
                sub.queue.get_nowait()
            except asyncio.QueueEmpty:
                pass
            sub.dropped += 1
            from tendermint_tpu.libs.metrics import pubsub_metrics

            pubsub_metrics().dropped.labels(self._metric_label(subscriber)).inc()
            try:
                sub.queue.put_nowait(msg)
            except asyncio.QueueFull:
                pass

    def publish(self, data: object, events: Dict[str, List[str]]) -> None:
        if not self._subs:
            return
        for key in self._candidates(events):
            entry = self._subs.get(key)
            if entry is None:
                continue
            query, sub = entry
            if not query.matches(events):
                continue
            self._deliver(key[0], sub, Message(data, events))

    def publish_many(self, datas, events: Dict[str, List[str]]) -> None:
        """Publish a homogeneous batch: every item in `datas` shares the
        same `events` map, so subscriber matching runs ONCE for the whole
        batch instead of once per item (the consensus vote drain publishes
        hundreds of Vote events per flush)."""
        if not self._subs or not datas:
            return
        matched = []
        for key in self._candidates(events):
            entry = self._subs.get(key)
            if entry is None:
                continue
            query, sub = entry
            if query.matches(events):
                matched.append((key[0], sub))
        if not matched:
            return
        for data in datas:
            msg = Message(data, events)
            for subscriber, sub in matched:
                self._deliver(subscriber, sub, msg)

    def num_clients(self) -> int:
        return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        return sum(1 for k in self._subs if k[0] == subscriber)
