"""Light client tests: sequential + bisection verification, witness
divergence, backwards verify, store pruning
(reference test model: light/client_test.go, light/verifier_test.go)."""

import asyncio
import os

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.crypto import tmhash
from tendermint_tpu.crypto.keys import gen_ed25519
from tendermint_tpu.light import (
    Client,
    ErrConflictingHeaders,
    ErrOldHeaderExpired,
    LightStore,
    MockProvider,
    SEQUENTIAL,
    SKIPPING,
    TrustOptions,
)
from tendermint_tpu.libs.kvdb import MemDB
from tendermint_tpu.types.basic import NANOS, BlockID, BlockIDFlag, PartSetHeader
from tendermint_tpu.types.block import Commit, CommitSig, ConsensusVersion, Header
from tendermint_tpu.types.light import (
    LightBlock,
    SignedHeader,
    light_block_from_bytes,
    light_block_to_bytes,
)
from tendermint_tpu.types.validator_set import Validator, ValidatorSet

CHAIN_ID = "light-chain"
T0 = 1_700_000_000 * NANOS  # genesis time
BLOCK_NS = 1 * NANOS  # one block per second


def make_keys(tag: bytes, n: int):
    return [gen_ed25519(bytes([i]) + tag * 31) for i in range(n)]


def valset_of(privs):
    return ValidatorSet([Validator(p.pub_key(), 10) for p in privs])


def sign_commit(header: Header, valset: ValidatorSet, privs) -> Commit:
    """Every validator signs a precommit for the header."""
    block_id = BlockID(header.hash(), PartSetHeader(1, tmhash.sum256(header.hash())))
    ts = header.time_ns
    by_addr = {p.pub_key().address(): p for p in privs}
    placeholder = [
        CommitSig(BlockIDFlag.COMMIT, v.address, ts, b"\x00" * 64)
        for v in valset.validators
    ]
    commit = Commit(header.height, 0, block_id, placeholder)
    sigs = []
    for idx, v in enumerate(valset.validators):
        sb = commit.vote_sign_bytes(CHAIN_ID, idx)
        sigs.append(CommitSig(BlockIDFlag.COMMIT, v.address, ts, by_addr[v.address].sign(sb)))
    return Commit(header.height, 0, block_id, sigs)


def make_chain(n: int, privs_by_height=None, default_privs=None):
    """n light blocks with correct validators/next-validators hash chaining.

    privs_by_height: {height: [privkeys]} — valset changes take effect AT the
    listed height (and the prior header's next_validators_hash reflects it).
    """
    default_privs = default_privs or make_keys(b"\x01", 4)

    def privs_at(h):
        if privs_by_height:
            best = default_privs
            for hh in sorted(privs_by_height):
                if hh <= h:
                    best = privs_by_height[hh]
            return best
        return default_privs

    blocks = {}
    prev_hash = b""
    for h in range(1, n + 1):
        vals = valset_of(privs_at(h))
        next_vals = valset_of(privs_at(h + 1))
        header = Header(
            version=ConsensusVersion(),
            chain_id=CHAIN_ID,
            height=h,
            time_ns=T0 + h * BLOCK_NS,
            last_block_id=(
                BlockID(prev_hash, PartSetHeader(1, tmhash.sum256(prev_hash)))
                if prev_hash
                else BlockID()
            ),
            last_commit_hash=tmhash.sum256(b"lc%d" % h),
            data_hash=tmhash.sum256(b"d%d" % h),
            validators_hash=vals.hash(),
            next_validators_hash=next_vals.hash(),
            consensus_hash=tmhash.sum256(b"c"),
            app_hash=tmhash.sum256(b"a%d" % h),
            last_results_hash=tmhash.sum256(b"r%d" % h),
            evidence_hash=tmhash.sum256(b"e"),
            proposer_address=vals.get_proposer().address,
        )
        commit = sign_commit(header, vals, privs_at(h))
        blocks[h] = LightBlock(SignedHeader(header, commit), vals)
        prev_hash = header.hash()
    return blocks


def run(coro):
    return asyncio.run(coro)


NOW = T0 + 3600 * NANOS
PERIOD = 24 * 3600 * NANOS


def new_client(blocks, mode=SKIPPING, witnesses=None, trust_height=1, store=None):
    primary = MockProvider(CHAIN_ID, blocks)
    client = Client(
        CHAIN_ID,
        TrustOptions(PERIOD, trust_height, blocks[trust_height].hash()),
        primary,
        witnesses if witnesses is not None else [],
        store or LightStore(MemDB()),
        verification_mode=mode,
    )
    return client, primary


def test_sequential_verification():
    blocks = make_chain(10)
    client, primary = new_client(blocks, mode=SEQUENTIAL)

    async def go():
        await client.initialize(NOW)
        lb = await client.verify_light_block_at_height(10, NOW)
        assert lb.hash() == blocks[10].hash()
        # sequential stores every intermediate height
        assert client.store.size() == 10

    run(go())


def test_skipping_single_jump_constant_valset():
    blocks = make_chain(20)
    client, primary = new_client(blocks, mode=SKIPPING)

    async def go():
        await client.initialize(NOW)
        calls_before = primary.calls
        lb = await client.verify_light_block_at_height(20, NOW)
        assert lb.hash() == blocks[20].hash()
        # constant valset: one fetch for the target, no interim fetches
        assert primary.calls - calls_before == 1
        assert client.store.heights() == [1, 20]

    run(go())


def test_skipping_bisects_across_full_valset_rotation():
    old = make_keys(b"\x01", 4)
    new = make_keys(b"\x02", 4)  # disjoint — zero overlap with old set
    blocks = make_chain(20, privs_by_height={10: new}, default_privs=old)
    client, _ = new_client(blocks, mode=SKIPPING)

    async def go():
        await client.initialize(NOW)
        lb = await client.verify_light_block_at_height(20, NOW)
        assert lb.hash() == blocks[20].hash()
        # bisection had to cross the rotation boundary via interim headers
        assert client.store.size() > 2

    run(go())


def test_expired_trust_root_rejected():
    blocks = make_chain(5)
    client, _ = new_client(blocks)

    async def go():
        late = T0 + PERIOD + 10 * NANOS
        with pytest.raises(ErrOldHeaderExpired):
            await client.initialize(late)

    run(go())


def test_witness_divergence_detected():
    blocks = make_chain(10)
    forged = make_chain(10, default_privs=make_keys(b"\x07", 4))
    witness = MockProvider(CHAIN_ID, {**blocks, 8: forged[8]})
    client, _ = new_client(blocks, witnesses=[witness])

    async def go():
        await client.initialize(NOW)
        with pytest.raises(ErrConflictingHeaders):
            await client.verify_light_block_at_height(8, NOW)
        # conflicting witness removed
        assert client.witnesses == []

    run(go())


def test_backwards_verification():
    blocks = make_chain(10)
    client, _ = new_client(blocks, trust_height=8)

    async def go():
        await client.initialize(NOW)
        lb = await client.verify_light_block_at_height(3, NOW)
        assert lb.hash() == blocks[3].hash()

    run(go())


def test_primary_failover_to_witness():
    blocks = make_chain(6)
    bad_primary = MockProvider(CHAIN_ID, {1: blocks[1]})  # has only the root
    witness = MockProvider(CHAIN_ID, blocks)
    client = Client(
        CHAIN_ID,
        TrustOptions(PERIOD, 1, blocks[1].hash()),
        bad_primary,
        [witness],
        LightStore(MemDB()),
    )

    async def go():
        await client.initialize(NOW)
        lb = await client.verify_light_block_at_height(6, NOW)
        assert lb.hash() == blocks[6].hash()
        assert client.primary is witness

    run(go())


def test_store_prune_and_roundtrip():
    blocks = make_chain(8)
    store = LightStore(MemDB())
    for lb in blocks.values():
        store.save_light_block(lb)
    assert store.size() == 8
    store.prune(3)
    assert store.heights() == [6, 7, 8]
    assert store.first_light_block().height == 6
    assert store.light_block_before(7).height == 6

    lb = blocks[5]
    rt = light_block_from_bytes(light_block_to_bytes(lb))
    assert rt.hash() == lb.hash()
    assert rt.validator_set.hash() == lb.validator_set.hash()
    rt.validate_basic(CHAIN_ID)


def test_light_client_tracks_live_node(tmp_path):
    """HTTPProvider + light client against a real node over local RPC
    (reference model: light/client_test.go + rpc/client integration)."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.light import HTTPProvider
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import LocalClient
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.root_dir = ""
        cfg.consensus.wal_path = str(tmp_path / "wal")
        priv = FilePV(gen_ed25519(b"\x91" * 32))
        gen = GenesisDoc(
            chain_id="light-live", validators=[GenesisValidator(priv.get_pub_key(), 10)]
        )
        node = Node(cfg, gen, priv_validator=priv, app=KVStoreApplication())
        await node.start()
        try:
            await node.wait_for_height(5, timeout=60)
            provider = HTTPProvider("light-live", LocalClient(node))
            root = await provider.light_block(2)
            client = Client(
                "light-live",
                TrustOptions(PERIOD, 2, root.hash()),
                provider,
                [],
                LightStore(MemDB()),
            )
            await client.initialize()
            lb = await client.verify_light_block_at_height(5)
            assert lb.height == 5
            assert lb.hash() == node.block_store.load_block(5).hash()
        finally:
            await node.stop()

    run(go())


def test_light_proxy_serves_verified_routes(tmp_path):
    """The light proxy answers commit/validators/block with light-client
    verification and forwards other routes
    (reference model: light/proxy + light/rpc/client.go)."""
    import socket as sk

    import aiohttp

    from tendermint_tpu.abci.kvstore import MerkleKVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.light.proxy import LightProxy
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.rpc.client import HTTPClient
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        s = sk.socket(); s.bind(("127.0.0.1", 0)); port = s.getsockname()[1]; s.close()
        cfg = test_config()
        cfg.base.db_backend = "memdb"; cfg.root_dir = ""
        cfg.rpc.laddr = f"tcp://127.0.0.1:{port}"
        cfg.consensus.wal_path = str(tmp_path / "wal")
        priv = FilePV(gen_ed25519(b"\x93" * 32))
        gen = GenesisDoc(chain_id="lp-chain",
                         validators=[GenesisValidator(priv.get_pub_key(), 10)])
        node = Node(cfg, gen, priv_validator=priv, app=MerkleKVStoreApplication())
        await node.start()
        backend = HTTPClient(f"http://127.0.0.1:{port}")
        proxy = None
        try:
            node.mempool.check_tx(b"lpk=lpv")
            await node.wait_for_height(5, timeout=60)
            from tendermint_tpu.light import Client as LClient, HTTPProvider, LightStore, TrustOptions

            provider = HTTPProvider("lp-chain", backend)
            root = await provider.light_block(2)
            lc = LClient("lp-chain", TrustOptions(PERIOD, 2, root.hash()),
                         provider, [], LightStore(MemDB()))
            proxy = LightProxy(lc, backend)
            await proxy.start()

            async with aiohttp.ClientSession() as sess:
                async def call(method, **params):
                    async with sess.post(f"http://{proxy.addr}/", json={
                        "jsonrpc": "2.0", "id": 1, "method": method, "params": params,
                    }) as resp:
                        body = await resp.json()
                        assert "error" not in body, body
                        return body["result"]

                com = await call("commit", height=4)
                assert com["light_client_verified"] is True
                assert com["signed_header"]["header"]["height"] == "4"

                vals = await call("validators", height=4)
                assert vals["light_client_verified"] is True
                assert len(vals["validators"]) == 1

                blk = await call("block", height=3)
                assert blk["light_client_verified"] is True
                assert blk["block"]["header"]["height"] == "3"

                st = await call("status")
                assert st["light_client"]["trusted_height"] >= 4

                # unverified forwarding is marked
                ab = await call("abci_info")
                assert ab["light_client_verified"] is False

                # abci_query: merkle proof verified against the header's
                # app_hash (light/rpc/client.go:116)
                import base64 as b64mod

                aq = await call("abci_query", data=b"lpk".hex())
                assert aq["light_client_verified"] is True
                assert b64mod.b64decode(aq["response"]["value"]) == b"lpv"

                # a missing key has no ValueOp absence proof -> error
                async with sess.post(f"http://{proxy.addr}/", json={
                    "jsonrpc": "2.0", "id": 2, "method": "abci_query",
                    "params": {"data": b"nosuchkey".hex()},
                }) as resp:
                    body = await resp.json()
                    assert "error" in body
        finally:
            if proxy is not None:
                await proxy.stop()
            await backend.close()
            await node.stop()

    run(go())
