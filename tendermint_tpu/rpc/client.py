"""RPC clients (reference: rpc/client/http + rpc/client/local).

HTTPClient speaks JSON-RPC over HTTP (aiohttp) to any node's RPC server, and
lazily opens a /websocket side-channel for event subscriptions (reference:
rpc/client/http/http.go embeds a WSEvents client); LocalClient calls the
in-process server handlers directly (backs the light client's provider and
tests without a socket, reference: rpc/client/local)."""

from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional

import aiohttp


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"RPC error {code}: {message} {data}")
        self.code = code


class HTTPClient:
    """(reference: rpc/client/http/http.go)"""

    def __init__(self, base_url: str):
        if not base_url.startswith("http"):
            base_url = "http://" + base_url.replace("tcp://", "")
        self.base_url = base_url.rstrip("/")
        self._session: Optional[aiohttp.ClientSession] = None
        self._ws: Optional["WSEventClient"] = None
        self._id = 0

    async def _ensure(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._ws is not None:
            await self._ws.close()
            self._ws = None
        if self._session and not self._session.closed:
            await self._session.close()

    # -- websocket subscriptions (reference: rpc/client/http WSEvents) ------

    async def _ws_events(self) -> "WSEventClient":
        if self._ws is None or not self._ws.running:
            if self._ws is not None:
                await self._ws.close()  # release the dead session/socket
            self._ws = WSEventClient(self.base_url)
            await self._ws.start()
        return self._ws

    async def subscribe(self, query: str) -> "WSSubscription":
        """Subscribe to events matching a pubsub query over the websocket
        side-channel; returns a WSSubscription with `next()`."""
        ws = await self._ws_events()
        return await ws.subscribe(query)

    async def unsubscribe_all(self) -> None:
        if self._ws is not None and self._ws.running:
            await self._ws.unsubscribe_all()

    async def wait_for_tx(self, tx_hash: bytes, timeout: float = 30.0) -> dict:
        """Client-side broadcast_tx_commit wait: subscribe to the tx's
        DeliverTx event by hash (the same query the server-side
        broadcast_tx_commit route uses, reference: rpc/core/mempool.go) and
        block until it fires."""
        sub = await self.subscribe(f"tm.event = 'Tx' AND tx.hash = '{tx_hash.hex().upper()}'")
        try:
            return await asyncio.wait_for(sub.next(), timeout)
        finally:
            await sub.unsubscribe()

    async def call(self, method: str, **params):
        session = await self._ensure()
        self._id += 1
        payload = {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        async with session.post(self.base_url + "/", json=payload) as resp:
            body = await resp.json(content_type=None)
        if body.get("error"):
            err = body["error"]
            raise RPCError(err.get("code", -1), err.get("message", ""), err.get("data", ""))
        return body.get("result")

    async def metrics_text(self) -> Optional[str]:
        """Raw Prometheus exposition from the node's /metrics route, or None
        when instrumentation is disabled (404) or the GET fails — scrapers
        like tools/loadtest.py degrade instead of erroring."""
        session = await self._ensure()
        try:
            async with session.get(self.base_url + "/metrics") as resp:
                if resp.status != 200:
                    return None
                return await resp.text()
        except Exception:
            return None

    # convenience wrappers (the route set mirrors rpc/core/routes.go)
    async def status(self):
        return await self.call("status")

    async def health(self):
        return await self.call("health")

    async def block(self, height: Optional[int] = None):
        return await self.call("block", **({"height": height} if height else {}))

    async def block_by_hash(self, block_hash: str):
        return await self.call("block_by_hash", hash=block_hash)

    async def block_results(self, height: Optional[int] = None):
        return await self.call("block_results", **({"height": height} if height else {}))

    async def commit(self, height: Optional[int] = None):
        return await self.call("commit", **({"height": height} if height else {}))

    async def validators(self, height: Optional[int] = None):
        return await self.call("validators", **({"height": height} if height else {}))

    async def genesis(self):
        return await self.call("genesis")

    async def tx(self, tx_hash: str):
        return await self.call("tx", hash=tx_hash)

    async def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return await self.call("tx_search", query=query, page=page, per_page=per_page)

    async def block_search(self, query: str, page: int = 1, per_page: int = 30):
        return await self.call("block_search", query=query, page=page, per_page=per_page)

    async def broadcast_tx_async(self, tx: bytes):
        return await self.call("broadcast_tx_async", tx="0x" + tx.hex())

    async def broadcast_tx_sync(self, tx: bytes):
        return await self.call("broadcast_tx_sync", tx="0x" + tx.hex())

    async def broadcast_tx_commit(self, tx: bytes):
        return await self.call("broadcast_tx_commit", tx="0x" + tx.hex())

    async def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = False):
        return await self.call("abci_query", path=path, data=data.hex(), height=height, prove=prove)

    async def net_info(self):
        return await self.call("net_info")

    async def consensus_state(self):
        return await self.call("consensus_state")

    async def consensus_params(self, height=None):
        return await self.call("consensus_params", height=height)

    async def dump_consensus_state(self):
        return await self.call("dump_consensus_state")


class WSSubscription:
    """One active websocket subscription: `next()` yields event payloads
    ({"query": ..., "events": {...}, "data": {...}})."""

    def __init__(self, client: "WSEventClient", sub_id: int, query: str):
        self._client = client
        self._id = sub_id
        self.query = query
        self._queue: asyncio.Queue = asyncio.Queue()
        self._terminal: Optional[Exception] = None

    async def next(self) -> dict:
        # A dead subscription must fail EVERY next() call, not just the one
        # that drained the single enqueued error: later (or concurrent)
        # consumers would otherwise await an empty queue forever (advisor r4).
        if self._terminal is not None and self._queue.empty():
            raise self._terminal
        item = await self._queue.get()
        if isinstance(item, Exception):
            self._terminal = item
            # Re-enqueue the sentinel so consumers ALREADY parked in
            # queue.get() (which never saw the empty-queue precheck above)
            # wake in a chain instead of awaiting forever.
            self._queue.put_nowait(item)
            raise item
        return item

    async def unsubscribe(self) -> None:
        await self._client._drop(self._id)


class WSEventClient:
    """JSON-RPC over one /websocket connection: regular calls plus
    query-indexed event subscriptions (reference: rpc/client/http/http.go
    WSEvents + rpc/jsonrpc/client/ws_client.go).

    Frame routing: responses and subscription events share the request id —
    the first frame for an id resolves the pending call future, every later
    frame with that id is a subscription event routed to its queue."""

    def __init__(self, base_url: str):
        if not base_url.startswith("http"):
            base_url = "http://" + base_url.replace("tcp://", "")
        self._url = base_url.rstrip("/") + "/websocket"
        self._session: Optional[aiohttp.ClientSession] = None
        self._ws: Optional[aiohttp.ClientWebSocketResponse] = None
        self._reader: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._subs: Dict[int, WSSubscription] = {}
        self._id = 0
        self.running = False

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()
        self._ws = await self._session.ws_connect(self._url)
        self.running = True
        self._reader = asyncio.create_task(self._read_loop())

    async def close(self) -> None:
        self.running = False
        if self._reader is not None:
            self._reader.cancel()
            try:
                await self._reader
            except (asyncio.CancelledError, Exception):
                pass
            self._reader = None
        if self._ws is not None and not self._ws.closed:
            await self._ws.close()
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def _read_loop(self) -> None:
        err: Exception = RPCError(-1, "ws connection closed")
        try:
            async for msg in self._ws:
                if msg.type != aiohttp.WSMsgType.TEXT:
                    continue
                try:
                    body = json.loads(msg.data)
                except json.JSONDecodeError:
                    continue
                id_ = body.get("id")
                fut = self._pending.pop(id_, None)
                if fut is not None:
                    if not fut.done():
                        if body.get("error"):
                            e = body["error"]
                            fut.set_exception(
                                RPCError(e.get("code", -1), e.get("message", ""),
                                         e.get("data", ""))
                            )
                        else:
                            fut.set_result(body.get("result"))
                    continue
                sub = self._subs.get(id_)
                if sub is not None and body.get("result"):
                    sub._queue.put_nowait(body["result"])
        except Exception as e:
            err = e
        finally:
            # Reached on BOTH error and clean server close: mark the client
            # dead (so HTTPClient._ws_events reconnects) and fail everything
            # in flight — a pending call or subscription must never await a
            # closed connection forever.
            self.running = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            for sub in self._subs.values():
                sub._queue.put_nowait(err)

    async def call(self, method: str, **params):
        self._id += 1
        id_ = self._id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[id_] = fut
        await self._ws.send_json(
            {"jsonrpc": "2.0", "id": id_, "method": method, "params": params}
        )
        return await fut

    async def subscribe(self, query: str) -> WSSubscription:
        self._id += 1
        id_ = self._id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[id_] = fut
        sub = WSSubscription(self, id_, query)
        # Register BEFORE sending: the ack and the first event can arrive in
        # one read-loop slice, and an event routed while we await the ack
        # must land in the queue, not be dropped.
        self._subs[id_] = sub
        await self._ws.send_json(
            {"jsonrpc": "2.0", "id": id_, "method": "subscribe",
             "params": {"query": query}}
        )
        try:
            await fut  # ack (or RPCError)
        except Exception:
            self._subs.pop(id_, None)
            raise
        return sub

    async def _drop(self, sub_id: int) -> None:
        sub = self._subs.pop(sub_id, None)
        if sub is not None:
            try:
                await self.call("unsubscribe", query=sub.query)
            except Exception:
                pass

    async def unsubscribe_all(self) -> None:
        try:
            await self.call("unsubscribe_all")
        except Exception:
            pass
        self._subs.clear()


class LocalClient:
    """Direct in-process calls against a node's RPC handler table
    (reference: rpc/client/local/local.go)."""

    def __init__(self, node):
        from tendermint_tpu.rpc.server import RPCServer

        self._server = RPCServer(node) if node.rpc_server is None else node.rpc_server

    async def call(self, method: str, **params):
        from tendermint_tpu.rpc.server import RPCShedError

        handler = self._server._routes.get(method)
        if handler is None:
            raise RPCError(-32601, f"method {method} not found")
        try:
            # through the load gate, same as the HTTP transports — a local
            # client must not bypass the node's shed policy
            return await self._server._dispatch(method, handler, params)
        except RPCShedError:
            raise RPCError(-32005, "server overloaded", method)

    def __getattr__(self, name):
        async def _proxy(**params):
            return await self.call(name, **params)

        return _proxy
