"""Light-block providers.

reference: light/provider/provider.go (Provider iface), light/provider/errors.go,
light/provider/http/http.go (RPC-backed), light/provider/mock (test double).
"""

from __future__ import annotations

from typing import Dict, Optional

from tendermint_tpu.types.light import (
    LightBlock,
    commit_from_json,
    header_from_json,
    validator_set_from_json,
    SignedHeader,
)


class ProviderError(Exception):
    pass


class ErrLightBlockNotFound(ProviderError):
    """reference: light/provider/errors.go ErrLightBlockNotFound."""


class ErrNoResponse(ProviderError):
    """reference: light/provider/errors.go ErrNoResponse."""


class ErrBadLightBlock(ProviderError):
    """reference: light/provider/errors.go ErrBadLightBlock."""


class Provider:
    """reference: light/provider/provider.go:14."""

    def chain_id(self) -> str:
        raise NotImplementedError

    async def light_block(self, height: Optional[int]) -> LightBlock:
        """Fetch the light block at height (None → latest). Raises
        ErrLightBlockNotFound / ErrNoResponse / ErrBadLightBlock."""
        raise NotImplementedError


class HTTPProvider(Provider):
    """RPC-backed provider (reference: light/provider/http/http.go:38).

    Talks to a node's JSON-RPC /commit + /validators routes. Accepts either an
    HTTPClient/LocalClient from tendermint_tpu.rpc.client or any object with
    async commit(height) / validators(height) methods."""

    def __init__(self, chain_id: str, client):
        self._chain_id = chain_id
        self.client = client

    def chain_id(self) -> str:
        return self._chain_id

    async def light_block(self, height: Optional[int]) -> LightBlock:
        try:
            com = await self.client.commit(height=height)
        except Exception as e:
            raise ErrNoResponse(f"commit({height}): {e}") from e
        sh_json = com.get("signed_header")
        if not sh_json or "header" not in sh_json:
            raise ErrLightBlockNotFound(f"no signed header at height {height}")
        try:
            header = header_from_json(sh_json["header"])
            commit = commit_from_json(sh_json["commit"])
        except (KeyError, ValueError) as e:
            raise ErrBadLightBlock(f"malformed signed header: {e}") from e
        if height is not None and header.height != height:
            # reference: light/provider/http/http.go validateHeight
            raise ErrBadLightBlock(
                f"node returned height {header.height}, requested {height}"
            )
        try:
            vals = await self.client.validators(height=header.height)
        except Exception as e:
            raise ErrNoResponse(f"validators({header.height}): {e}") from e
        try:
            valset = validator_set_from_json(vals)
        except (KeyError, ValueError) as e:
            raise ErrBadLightBlock(f"malformed validator set: {e}") from e
        lb = LightBlock(SignedHeader(header, commit), valset)
        try:
            lb.validate_basic(self._chain_id)
        except ValueError as e:
            raise ErrBadLightBlock(str(e)) from e
        return lb


class MockProvider(Provider):
    """In-memory provider for tests and in-process wiring
    (reference: light/provider/mock/mock.go)."""

    def __init__(self, chain_id: str, blocks: Dict[int, LightBlock]):
        self._chain_id = chain_id
        self.blocks = dict(blocks)
        self.calls = 0

    def chain_id(self) -> str:
        return self._chain_id

    def add(self, lb: LightBlock) -> None:
        self.blocks[lb.height] = lb

    async def light_block(self, height: Optional[int]) -> LightBlock:
        self.calls += 1
        if not self.blocks:
            raise ErrNoResponse("mock has no blocks")
        if height is None:
            height = max(self.blocks)
        lb = self.blocks.get(height)
        if lb is None:
            raise ErrLightBlockNotFound(f"height {height}")
        return lb
