"""Node assembly (reference: node/node.go:613 NewNode, :840 OnStart).

Wires: DBs → state → proxy app (4 conns) → handshake/replay → event bus +
indexer → mempool → evidence pool → block executor → consensus → RPC.
P2P wiring is added by the switch/reactor layer when peers are configured."""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.abci.kvstore import (
    CounterApplication,
    KVStoreApplication,
    PersistentKVStoreApplication,
)
from tendermint_tpu.config.config import Config
from tendermint_tpu.consensus.cs_state import ConsensusState
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.libs.kvdb import KVDB, MemDB, SQLiteDB
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.proxy.multi import AppConns, local_client_creator
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.sm_state import State, state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.state.txindex import IndexerService, KVTxIndexer
from tendermint_tpu.store.blockstore import BlockStore
from tendermint_tpu.types.event_bus import EventBus
from tendermint_tpu.types.genesis import GenesisDoc

logger = logging.getLogger("tendermint_tpu.node")


def _open_db(cfg: Config, name: str) -> KVDB:
    if cfg.base.db_backend == "memdb" or not cfg.root_dir:
        return MemDB()
    return SQLiteDB(os.path.join(cfg.root_dir, "data", f"{name}.db"))


def _parse_host_stripe(v):
    """`[crypto] prep_host_stripe` accepts "auto"/"1"/"0" (or a bool from
    programmatic configs); None leaves the process-global setting alone."""
    if v is None or v == "auto":
        return v
    if isinstance(v, str):
        return v not in ("0", "false", "off")
    return bool(v)


def default_app(name: str):
    if name == "kvstore":
        return KVStoreApplication()
    if name == "persistent_kvstore":
        return PersistentKVStoreApplication()
    if name == "counter":
        return CounterApplication()
    if name == "signed_kvstore":
        from tendermint_tpu.abci.kvstore import SignedKVStoreApplication

        return SignedKVStoreApplication()
    raise ValueError(f"unknown in-proc app {name!r}")


class Node:
    def __init__(
        self,
        config: Config,
        genesis: GenesisDoc,
        priv_validator: Optional[FilePV] = None,
        app=None,
        client_creator=None,
        state_provider=None,
    ):
        self.config = config
        self.genesis = genesis
        # Apply the chain's verification predicate before any key is checked
        # (cofactorless = reference-exact interop mode; see config.BaseConfig
        # and crypto/keys.set_verify_mode). Unconditional: the mode is
        # process-global, so a "cofactored" config must actively reset any
        # "cofactorless" left by env or an earlier Node in this process
        # (and set_verify_mode validates the string either way).
        from tendermint_tpu.crypto.keys import set_verify_mode

        set_verify_mode(getattr(config.base, "ed25519_verify_mode", "cofactored"))
        # verify-path circuit breaker knobs (process-global, same model as
        # the verify mode: the crypto pipeline is shared by every in-process
        # node, and the last Node constructed wins)
        from tendermint_tpu.crypto import batch as _batch

        _batch.configure_breaker(
            enabled=config.crypto.breaker_enabled,
            failure_threshold=config.crypto.breaker_failure_threshold,
            flush_deadline_s=config.crypto.breaker_flush_deadline,
            probe_interval_base=config.crypto.breaker_probe_base,
            probe_interval_max=config.crypto.breaker_probe_max,
        )
        # streamed flush planner budget (same process-global model)
        _batch.configure_planner(
            max_flush_lanes=getattr(config.crypto, "max_flush_lanes", None)
        )
        # stage-overlapped host prep + verified-row memo (ISSUE 18; same
        # process-global, last-node-wins model as the planner/breaker)
        _batch.configure_prep(
            prep_threads=getattr(config.crypto, "prep_threads", None),
            staged=getattr(config.crypto, "prep_staged", None),
            stream=getattr(config.crypto, "prep_stream", None),
            stream_floor=getattr(config.crypto, "prep_stream_floor", None),
            host_stripe=_parse_host_stripe(
                getattr(config.crypto, "prep_host_stripe", None)
            ),
        )
        _batch.configure_verified_memo(
            rows=getattr(config.crypto, "verified_memo_rows", None)
        )
        # elastic mesh health model (ISSUE 19; same process-global model)
        _batch.configure_mesh_health(
            enabled=getattr(config.crypto, "mesh_health_enabled", None),
            fail_threshold=getattr(config.crypto, "mesh_health_fail_threshold", None),
            stall_threshold_s=getattr(
                config.crypto, "mesh_health_stall_threshold", None
            ),
            rejoin_probes=getattr(config.crypto, "mesh_health_rejoin_probes", None),
            probe_interval_s=getattr(
                config.crypto, "mesh_health_probe_interval", None
            ),
        )
        self._owns_priv_validator = False
        if priv_validator is None and config.base.priv_validator_addr:
            # dial the remote signer (reference: node/node.go:658
            # createAndStartPrivValidatorSocketClient)
            from tendermint_tpu.privval.remote import SignerClient

            host, port = self._parse_laddr(config.base.priv_validator_addr)
            priv_validator = SignerClient(host, port)
            self._owns_priv_validator = True
        self.priv_validator = priv_validator

        # metrics (reference: node/node.go:106 DefaultMetricsProvider)
        from tendermint_tpu.libs.metrics import NodeMetrics

        self.metrics = NodeMetrics()

        # flight recorder (libs/trace.py): process-global, same model as the
        # verify mode above — apply this node's [instrumentation] knobs
        from tendermint_tpu.libs import trace as _trace

        _trace.tracer.configure(
            enabled=config.instrumentation.trace_enabled,
            ring_size=config.instrumentation.trace_ring_size,
        )

        # stall forensics (libs/forensics.py): heartbeat the device entry
        # points + write FORENSICS_*.json captures under [instrumentation]
        # forensics_dir (default ./forensics — never the app root); relative
        # paths resolve under root_dir; process-global like the tracer (the
        # env default TMTPU_FORENSICS_DIR already applied at import if set).
        # Heartbeat rings left by DEAD pids are swept at configure time.
        fdir = getattr(config.instrumentation, "forensics_dir", "")
        if fdir:
            from tendermint_tpu.libs import forensics as _forensics

            if not os.path.isabs(fdir) and config.root_dir:
                fdir = os.path.join(config.root_dir, fdir)
            _forensics.configure(fdir)

        # SLO engine (libs/slo.py): declared latency budgets + burn-rate
        # guards, served at GET /debug/slo and as tendermint_slo_* series.
        # Node-local, but the batch-verify flush feed is process-global
        # (set_default: last node wins, same model as the tracer).
        self.slo = None
        if getattr(config, "slo", None) is not None and config.slo.enabled:
            from tendermint_tpu.libs import slo as _slo

            self.slo = _slo.SLOEngine(config.slo, metrics=self.metrics.slo)
            _slo.set_default(self.slo)

        # global verification scheduler (crypto/scheduler.py, ROADMAP item
        # 2): the one device coordinator EVERY verification consumer submits
        # to — votes preempt, light serves within its coalescing window,
        # CheckTx admission batches, blocksync/evidence soak idle capacity.
        # Node-local instance (its lanes carry this node's SLO + metrics),
        # ALSO registered process-global (last node wins, the tracer model)
        # for the deep consumers with no wiring path: types/vote_set.py and
        # evidence/pool.py.
        self.scheduler = None
        if getattr(config, "scheduler", None) is not None and config.scheduler.enabled:
            from tendermint_tpu.crypto import scheduler as _sched

            self.scheduler = _sched.VerifyScheduler(
                config.scheduler,
                metrics=self.metrics.scheduler,
                slo=self.slo,
            )
            _sched.set_default(self.scheduler)

        # tx lifecycle tracker (libs/txtrace.py, ISSUE 10): the bounded
        # per-tx journey ring behind tx_status / GET /debug/tx_trace.
        # Node-local; recording follows the tracer's enabled flag, and the
        # committed stage feeds the tx_commit_latency SLO budget.
        self.tx_tracker = None
        if getattr(config.instrumentation, "txtrace_enabled", True):
            from tendermint_tpu.libs.txtrace import TxTracker

            self.tx_tracker = TxTracker(
                max_txs=getattr(config.instrumentation, "txtrace_ring", 8192),
                metrics=self.metrics.txtrace,
                slo=self.slo,
            )

        # per-height/round consensus timeline ring (consensus/timeline.py) —
        # node-local (unlike the tracer), served by /debug/consensus_timeline;
        # recording is gated on the tracer's enabled flag in cs_state
        from tendermint_tpu.consensus.timeline import ConsensusTimeline

        self.timeline = ConsensusTimeline(
            max_heights=config.instrumentation.timeline_heights
        )

        # databases
        self.block_db = _open_db(config, "blockstore")
        self.state_db = _open_db(config, "state")
        self.evidence_db = _open_db(config, "evidence")
        self.block_store = BlockStore(self.block_db)
        self.state_store = StateStore(self.state_db)

        # state from store or genesis
        state = self.state_store.load()
        if state is None:
            genesis.validate_and_complete()
            state = state_from_genesis(genesis)

        # ABCI app (4 logical connections); an external proxy_app address
        # selects the socket/grpc transport (reference: proxy/client.go)
        remote_app = bool(config.base.proxy_app)
        if client_creator is None:
            if remote_app:
                from tendermint_tpu.proxy.multi import default_client_creator

                client_creator = default_client_creator(
                    config.base.proxy_app, config.base.abci,
                    call_timeout=config.base.abci_call_timeout,
                )
            else:
                app = app or default_app(config.base.abci)
                client_creator = local_client_creator(app)
        self.app = app
        # remote apps get reconnect-with-backoff on the non-consensus conns
        # (an app restart must not crash the node); the consensus conn stays
        # fatal-loud either way
        self.proxy_app = AppConns(
            client_creator,
            resilient=remote_app,
            attempts=config.base.abci_reconnect_attempts,
            base_delay=config.base.abci_reconnect_base_delay,
            max_delay=config.base.abci_reconnect_max_delay,
        )

        # event bus + tx indexer
        self.event_bus = EventBus()
        self.tx_indexer = KVTxIndexer(_open_db(config, "tx_index"))
        self.indexer_service = IndexerService(self.tx_indexer, self.event_bus)

        # handshake: sync app with chain
        handshaker = Handshaker(self.state_store, state, self.block_store, genesis, self.event_bus)
        state = handshaker.handshake(self.proxy_app)
        self.state = state

        # mempool
        self.mempool = Mempool(
            self.proxy_app.mempool,
            max_txs=config.mempool.size,
            max_txs_bytes=config.mempool.max_txs_bytes,
            cache_size=config.mempool.cache_size,
            keep_invalid_txs_in_cache=config.mempool.keep_invalid_txs_in_cache,
            recheck=config.mempool.recheck,
            metrics=self.metrics.mempool,
            wal_path=(
                os.path.join(config.root_dir, config.mempool.wal_dir, "wal")
                if config.mempool.wal_dir and config.root_dir
                else ""
            ),
            max_tx_bytes=config.mempool.max_tx_bytes,
            ttl_num_blocks=config.mempool.ttl_num_blocks,
            ttl_seconds=config.mempool.ttl_seconds,
            eviction=config.mempool.eviction,
            max_txs_per_sender=config.mempool.max_txs_per_sender,
            tx_tracker=self.tx_tracker,
            # device-batched tx admission (crypto/scheduler.py admission
            # lane + the RequestCheckTx.sig_precheck ABCI split)
            scheduler=self.scheduler,
            sig_precheck=(
                self.scheduler is not None
                and config.scheduler.admission_precheck
            ),
        )

        # evidence pool
        self.evidence_pool = EvidencePool(self.evidence_db, self.state_store, self.block_store)
        self.evidence_pool.set_state(state)

        # block executor
        self.block_exec = BlockExecutor(
            self.state_store,
            self.proxy_app.consensus,
            self.mempool,
            self.evidence_pool,
            event_bus=self.event_bus,
            block_store=self.block_store,
            metrics=self.metrics.state,
            tx_tracker=self.tx_tracker,
        )

        # consensus
        if os.path.isabs(config.consensus.wal_path):
            wal_path = config.consensus.wal_path
        elif config.root_dir:
            wal_path = os.path.join(config.root_dir, config.consensus.wal_path)
        else:
            wal_path = os.path.join(os.getcwd(), ".tmp_wal", "wal")
        self.wal = WAL(
            wal_path,
            group_commit=config.consensus.wal_group_commit,
            group_commit_max_latency=config.consensus.wal_group_commit_max_latency,
        )
        self.consensus = ConsensusState(
            config.consensus,
            state,
            self.block_exec,
            self.block_store,
            self.mempool,
            self.evidence_pool,
            self.wal,
            event_bus=self.event_bus,
            priv_validator=priv_validator,
            metrics=self.metrics.consensus,
            timeline=self.timeline,
            slo=self.slo,
            tx_tracker=self.tx_tracker,
        )

        self.rpc_server = None
        self.grpc_server = None
        self.prometheus_server = None
        self._running = False

        # light-client-as-a-service (light/service.py, ROADMAP item 3):
        # answers light_verify/light_block RPC requests from a verified-
        # header cache with single-flight dedupe, coalescing distinct-height
        # misses into shared cross-height device flushes. Constructed
        # eagerly (cheap: no background work until the first request);
        # served by the light_* RPC routes + GET /debug/light.
        self.light_service = None
        if getattr(config, "light_service", None) is not None and config.light_service.enabled:
            from tendermint_tpu.light.service import LightService, LocalNodeProvider

            self.light_service = LightService(
                genesis.chain_id,
                LocalNodeProvider(self),
                config.light_service,
                metrics=self.metrics.light,
                slo=self.slo,
                scheduler=self.scheduler,
                # [scheduler] enabled=false means NO lane engine anywhere —
                # the service must not spin up a private one behind the
                # operator's back (it degrades to per-window-body flushes)
                own_scheduler_if_missing=False,
            )

        # overload controller (node/overload.py): samples queue depths into
        # a pressure level and flips the shed switches (mempool gossip, RPC
        # gate, evidence walk) — never the vote path
        from tendermint_tpu.node.overload import OverloadController

        self.overload = OverloadController(
            self, config.overload, metrics=self.metrics.overload
        )

        # p2p (reference: node/node.go:754-793 createTransport/createSwitch)
        self.switch = None
        self.node_key = None
        self.consensus_reactor = None
        self.mempool_reactor = None
        self.blocksync_reactor = None
        self.statesync_reactor = None
        self.addr_book = None
        self.pex_reactor = None
        self.fast_sync = False
        # state sync only makes sense on an empty chain
        # (reference: node/node.go:672 decide stateSync)
        self.state_sync = bool(config.statesync.enable) and self.block_store.height == 0
        self._state_provider = state_provider
        self._statesync_task = None
        if config.p2p.laddr:
            from tendermint_tpu.consensus.reactor import ConsensusReactor
            from tendermint_tpu.evidence.reactor import EvidenceReactor
            from tendermint_tpu.mempool.reactor import MempoolReactor
            from tendermint_tpu.p2p import (
                MultiplexTransport,
                NodeInfo,
                NodeKey,
                Switch,
            )

            if Switch is None:
                # the package gates the networked pieces when the
                # `cryptography` wheel is absent; keep the old loud failure
                # for nodes that actually configured a p2p listener
                raise ImportError(
                    "p2p.laddr is configured but the p2p transport is "
                    "unavailable (missing `cryptography` wheel)"
                )
            if not config.p2p.plaintext:
                from tendermint_tpu.p2p.conn.secret_connection import (
                    HAVE_CRYPTOGRAPHY,
                )

                if not HAVE_CRYPTOGRAPHY:
                    raise ImportError(
                        "p2p.laddr is configured with secret connections but "
                        "the `cryptography` wheel is missing; set "
                        "p2p.plaintext=true for unauthenticated in-process "
                        "test nets"
                    )
            if config.root_dir:
                self.node_key = NodeKey.load_or_gen(
                    os.path.join(config.root_dir, "config", "node_key.json")
                )
            else:
                self.node_key = NodeKey.generate()
            node_info = NodeInfo(
                node_id=self.node_key.id,
                listen_addr=config.p2p.laddr,
                network=genesis.chain_id,
                moniker=config.base.moniker,
            )
            fuzz_cfg = None
            if config.p2p.test_fuzz:
                from tendermint_tpu.p2p.fuzz import FuzzConfig

                # seeded => every fuzzed connection's fault sequence replays
                # from [p2p] fuzz_seed (see transport._upgrade's derivation)
                fuzz_cfg = FuzzConfig(seed=config.p2p.fuzz_seed)
            transport = MultiplexTransport(
                self.node_key,
                node_info,
                use_secret_conn=not config.p2p.plaintext,
                fuzz_config=fuzz_cfg,
            )
            trust_path = (
                os.path.join(config.root_dir, "data", "trust_metrics.json")
                if config.root_dir
                else None
            )
            recv_limit = None
            if config.p2p.recv_rate_limit:
                from tendermint_tpu.p2p.conn.connection import RecvRateLimit

                recv_limit = RecvRateLimit(
                    bytes_per_s=config.p2p.recv_rate_bytes_per_channel,
                    msgs_per_s=config.p2p.recv_rate_msgs_per_channel,
                    strikes=config.p2p.recv_rate_strikes,
                    strike_window=config.p2p.recv_rate_strike_window,
                )
            self.switch = Switch(
                transport, metrics=self.metrics.p2p, trust_store_path=trust_path,
                recv_limit=recv_limit,
            )
            # fast sync is pointless when we are the only validator
            # (reference: node/node.go onlyValidatorIsUs)
            only_us = (
                priv_validator is not None
                and state.validators.size() == 1
                and state.validators.validators[0].address
                == priv_validator.get_pub_key().address()
            )
            self.fast_sync = bool(config.base.fast_sync) and not only_us
            self.consensus_reactor = ConsensusReactor(
                self.consensus, wait_sync=self.fast_sync or self.state_sync
            )
            self.switch.add_reactor("CONSENSUS", self.consensus_reactor)
            self.mempool_reactor = MempoolReactor(
                self.mempool, broadcast=config.mempool.broadcast,
                metrics=self.metrics.overload,
            )
            self.switch.add_reactor("MEMPOOL", self.mempool_reactor)
            self.switch.add_reactor("EVIDENCE", EvidenceReactor(self.evidence_pool))
            from tendermint_tpu.blocksync.reactor import BlocksyncReactor

            # a state-sync node starts blocksync only after the snapshot
            # restore (switch_to_blocksync handoff)
            # crash-resume checkpoints (ISSUE 12): only nodes with a real
            # root dir persist them (memdb test nodes re-fetch, always safe)
            catchup_ckpt = (
                os.path.join(config.root_dir, "data", "catchup_checkpoint.json")
                if config.root_dir
                else None
            )
            restore_ckpt = (
                os.path.join(config.root_dir, "data", "statesync_checkpoint.json")
                if config.root_dir
                else None
            )
            self.blocksync_reactor = BlocksyncReactor(
                state, self.block_exec, self.block_store,
                consensus_reactor=self.consensus_reactor,
                active=self.fast_sync and not self.state_sync,
                metrics=self.metrics.blocksync,
                peer_timeout=config.fastsync.peer_timeout,
                retry_sleep=config.fastsync.retry_sleep,
                scheduler=self.scheduler,
                checkpoint_path=catchup_ckpt,
            )
            self.switch.add_reactor("BLOCKSYNC", self.blocksync_reactor)
            from tendermint_tpu.statesync.reactor import StatesyncReactor

            self.statesync_reactor = StatesyncReactor(
                self.proxy_app.snapshot, self.proxy_app.query, active=self.state_sync,
                metrics=self.metrics.statesync,
                checkpoint_path=restore_ckpt,
            )
            self.switch.add_reactor("STATESYNC", self.statesync_reactor)
            if config.p2p.pex:
                from tendermint_tpu.p2p.pex import AddrBook, PexReactor

                book_file = (
                    os.path.join(config.root_dir, "config", "addrbook.json")
                    if config.root_dir
                    else None
                )
                self.addr_book = AddrBook(book_file)
                seeds = [s.strip() for s in config.p2p.seeds.split(",") if s.strip()]
                self.pex_reactor = PexReactor(
                    self.addr_book,
                    seeds=seeds,
                    max_outbound=config.p2p.max_num_outbound_peers,
                    seed_mode=config.p2p.seed_mode,
                )
                self.switch.add_reactor("PEX", self.pex_reactor)
        else:
            self.state_sync = False

    async def start(self) -> None:
        self._running = True
        self._start_crypto_prewarm()
        await self.indexer_service.start()
        if not (self.switch is not None and (self.fast_sync or self.state_sync)):
            # with fast/state sync active, consensus starts at the blocksync
            # handoff (reference: node/node.go:897 startStateSync -> SwitchToConsensus)
            await self.consensus.start()
        if self.switch is not None:
            await self.switch.start()
            host, port = self._parse_laddr(self.config.p2p.laddr)
            self.p2p_addr = await self.switch.transport.listen(host, port)
            if self.config.p2p.persistent_peers:
                peers = [a.strip() for a in self.config.p2p.persistent_peers.split(",") if a.strip()]
                await self.switch.dial_peers_async(peers, persistent=True)
        if self.config.rpc.laddr:
            from tendermint_tpu.rpc.server import RPCServer

            self.rpc_server = RPCServer(self)
            await self.rpc_server.start()
        if self.config.rpc.grpc_laddr:
            from tendermint_tpu.rpc.grpc_api import GrpcBroadcastServer

            self.grpc_server = GrpcBroadcastServer(self, self.config.rpc.grpc_laddr)
            self.grpc_server.start()
        if self.config.instrumentation.prometheus:
            from tendermint_tpu.libs.prometheus_server import PrometheusServer

            self.prometheus_server = PrometheusServer(
                self.metrics, self.config.instrumentation.prometheus_listen_addr
            )
            await self.prometheus_server.start()
        if self.state_sync:
            self._statesync_task = asyncio.create_task(
                self._run_state_sync(), name="statesync"
            )
        if self.config.overload.enabled:
            self.overload.start()
        self._install_punish_hook()
        logger.info("node started (chain %s)", self.genesis.chain_id)

    def _install_punish_hook(self) -> None:
        """Route suspicion-scorer punishments (crypto/provenance.py) into
        the existing enforcement machinery: a punished ``peer:<id>`` feeds
        the p2p trust scorer a BAD_MESSAGE report (repeated reports drop the
        peer below the trust threshold and disconnect it), and a punished
        ``sender:<id>`` collapses that sender's mempool quota. The callback
        fires on a verify thread, so p2p reports hop to the event loop."""
        from tendermint_tpu.crypto import provenance as _prov

        loop = asyncio.get_event_loop()

        def punish(source: str, info: dict) -> None:
            if source.startswith("peer:"):
                if self.switch is None:
                    return
                from tendermint_tpu.p2p.behaviour import BAD_MESSAGE, PeerBehaviour

                pb = PeerBehaviour(
                    source[len("peer:"):], BAD_MESSAGE,
                    f"signature poisoning ({info.get('offenses', 0)} bad rows in quarantine)",
                )

                def _report():
                    asyncio.ensure_future(self.switch.reporter.report(pb))

                loop.call_soon_threadsafe(_report)
            elif source.startswith("sender:"):
                self.mempool.penalize_sender(source[len("sender:"):])

        self._punish_cb = punish
        _prov.default_scorer().add_punish_callback(punish)

    async def _run_state_sync(self) -> None:
        """Restore from a peer snapshot, bootstrap the stores, then hand off
        to block sync (reference: node/node.go:560 startStateSync)."""
        cfg = self.config.statesync
        provider = self._state_provider
        if provider is None:
            from tendermint_tpu.rpc.client import HTTPClient
            from tendermint_tpu.statesync.stateprovider import (
                LightClientStateProvider,
            )

            provider = LightClientStateProvider(
                self.genesis.chain_id,
                [HTTPClient(u) for u in cfg.rpc_servers],
                cfg.trust_height,
                bytes.fromhex(cfg.trust_hash),
                int(cfg.trust_period * 1_000_000_000),
            )
        try:
            state, commit = await self.statesync_reactor.sync(
                provider,
                cfg.discovery_time,
                chunk_fetchers=cfg.chunk_fetchers,
                chunk_timeout=cfg.chunk_request_timeout,
                chunk_retries=cfg.chunk_retries,
                chunk_backoff=cfg.chunk_backoff,
            )
        except asyncio.CancelledError:
            raise
        except Exception:
            # STRUCTURED fallback (ISSUE 12): when every snapshot/peer is
            # exhausted (the retry ladder's ErrNoSnapshots terminus) — or
            # anything else goes wrong — fall back to block sync from
            # genesis rather than wedging the node in wait_sync forever
            logger.exception("state sync failed; falling back to block sync")
            if self.metrics is not None:
                self.metrics.statesync.fallbacks_total.inc()
            await self.blocksync_reactor.switch_to_blocksync(self.state)
            return
        self.state_store.bootstrap(state)
        self.block_store.save_seen_commit(state.last_block_height, commit)
        self.state = state
        self.evidence_pool.set_state(state)
        logger.info(
            "state synced to height %d; switching to block sync",
            state.last_block_height,
        )
        await self.blocksync_reactor.switch_to_blocksync(state)

    @staticmethod
    def _parse_laddr(laddr: str) -> tuple:
        addr = laddr.split("://", 1)[-1]
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)

    def _start_crypto_prewarm(self) -> None:
        """Compile the steady-state verification kernels for THIS chain's
        validator-set size in a daemon thread (crypto/batch.prewarm): a node
        cold-starting into a vote storm must not stall its receive loop on a
        first-call kernel compile (round-3 finding: minutes per shape)."""
        import threading

        from tendermint_tpu.crypto import batch as _batch

        try:
            vals = self.consensus.rs.validators
            n_vals = vals.size()
            pubkeys = [v.pub_key.bytes() for v in vals.validators]
            # BLS buckets warm only when the valset actually carries BLS
            # keys (flag-gated; zero cost on pure-ed25519 chains)
            has_bls = any(
                v.pub_key.type_name() == "bls12_381" for v in vals.validators
            )
        except Exception:
            n_vals, pubkeys, has_bls = 0, None, False
        if n_vals <= 0 or (_batch.backend_default() != "jax" and not has_bls):
            return

        def run():
            try:
                _batch.prewarm(n_vals, pubkeys=pubkeys, bls=has_bls)
            except Exception:  # prewarm is best-effort; first caller compiles
                import logging

                logging.getLogger("tendermint_tpu.node").exception(
                    "crypto kernel prewarm failed"
                )

        threading.Thread(target=run, name="crypto-prewarm", daemon=True).start()

    async def stop(self) -> None:
        self._running = False
        if getattr(self, "_punish_cb", None) is not None:
            from tendermint_tpu.crypto import provenance as _prov

            _prov.default_scorer().remove_punish_callback(self._punish_cb)
            self._punish_cb = None
        if self.light_service is not None:
            self.light_service.close()
        if self.scheduler is not None:
            from tendermint_tpu.crypto import scheduler as _sched

            # last-node-wins model: only deregister if still ours; close()
            # drains queued work so no consumer blocks into its fallback
            if _sched.default_scheduler() is self.scheduler:
                _sched.set_default(None)
            self.scheduler.close()
        await self.overload.stop()
        if self._statesync_task is not None:
            self._statesync_task.cancel()
        if self.rpc_server is not None:
            await self.rpc_server.stop()
        if self.grpc_server is not None:
            self.grpc_server.stop()
        if self.prometheus_server is not None:
            await self.prometheus_server.stop()
        if self.switch is not None:
            await self.switch.stop()
        await self.consensus.stop()
        await self.indexer_service.stop()
        if self._owns_priv_validator:
            self.priv_validator.close()
        self.mempool.close_wal()
        self.proxy_app.stop()
        if self.slo is not None:
            from tendermint_tpu.libs import slo as _slo

            # don't leave a dead engine as the process-global flush feed
            # (last-node-wins model: only deregister if it's still ours)
            if _slo.default_engine() is self.slo:
                _slo.set_default(None)
        for db in (self.block_db, self.state_db, self.evidence_db):
            db.close()

    # convenience for tests / RPC
    async def wait_for_height(self, height: int, timeout: float = 30.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while self.block_store.height < height:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"timed out waiting for height {height} (at {self.block_store.height})"
                )
            await asyncio.sleep(0.02)
