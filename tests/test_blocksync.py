"""Fast sync: a fresh node downloads the chain from a peer, verifies commits
in device batches, applies, and switches to consensus
(reference test model: blockchain/v0/reactor_test.go)."""

import asyncio
import os

from tendermint_tpu.abci.kvstore import KVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

from tests.conftest import requires_cryptography

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")


def make_pair(tmp_path):
    priv = FilePV(gen_ed25519(b"\x61" * 32))
    gen = GenesisDoc(
        chain_id="sync-chain",
        validators=[GenesisValidator(priv.get_pub_key(), 10)],
    )

    def make(name, with_validator):
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.root_dir = ""
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.consensus.wal_path = str(tmp_path / name / "wal")
        return Node(
            cfg, gen,
            priv_validator=priv if with_validator else None,
            app=KVStoreApplication(),
        )

    return make("source", True), make("syncer", False)


@requires_cryptography
def test_fresh_node_fast_syncs_from_peer(tmp_path):
    async def run():
        source, syncer = make_pair(tmp_path)
        try:
            await source.start()
            # single validator: fast_sync auto-disabled on the source
            assert source.fast_sync is False
            await source.wait_for_height(8, timeout=60)

            await syncer.start()
            assert syncer.fast_sync is True
            await syncer.switch.dial_peers_async(
                [f"{source.node_key.id}@{source.p2p_addr}"], persistent=True
            )
            await syncer.wait_for_height(8, timeout=60)
            # post-sync: blocks byte-identical, commits stored
            for h in (2, 5, 8):
                assert syncer.block_store.load_block(h).hash() == source.block_store.load_block(h).hash()
            assert syncer.block_store.load_seen_commit(8) is not None
            # handoff happens once within a block of the moving head
            await asyncio.wait_for(syncer.blocksync_reactor.synced.wait(), 20)
            target = source.block_store.height + 2
            await syncer.wait_for_height(target, timeout=60)
        finally:
            await syncer.stop()
            await source.stop()

    asyncio.run(run())
