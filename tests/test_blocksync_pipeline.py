"""ISSUE 12: pipelined, crash-safe blocksync — pool requeue/dedup
invariants under flaky peers, peer scoring/backoff/ban, checkpoint
resume-without-reverify, and a plaintext end-to-end pipeline sync."""

import asyncio
import os
import time
from types import SimpleNamespace

import pytest

os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")

from tendermint_tpu.blocksync.checkpoint import CatchupCheckpoint
from tendermint_tpu.blocksync.pool import (
    BAN_THRESHOLD,
    BlockPool,
    _PoolPeer,
)
from tendermint_tpu.libs.metrics import BlockSyncMetrics, Registry


def _metrics():
    return BlockSyncMetrics(Registry())


def _counter_val(c):
    return c._values.get((), 0.0)


def _fake_block(height):
    return SimpleNamespace(header=SimpleNamespace(height=height))


# --------------------------------------------------------------- pool units


def test_pool_flaky_peer_no_skip_no_dup():
    """THE requeue/dedup invariant (ISSUE 12 satellite): 2 peers, 1 flaky
    (never answers), every height is delivered exactly once and in order —
    no height skipped, none filled twice — and the flaky peer's in-flight
    slots are released on timeout instead of leaking."""

    async def run():
        sent = []  # (peer, height)
        punished = []

        async def send_request(peer_id, height):
            sent.append((peer_id, height))
            if peer_id == "good":
                # deliver asynchronously, like a real peer
                async def deliver(h=height):
                    await asyncio.sleep(0.01)
                    pool.add_block("good", _fake_block(h))

                asyncio.get_running_loop().create_task(deliver())
            # "flaky" never answers: its heights must time out and requeue

        async def punish(peer_id, reason):
            punished.append((peer_id, reason))

        pool = BlockPool(
            1, send_request, punish, metrics=_metrics(),
            peer_timeout=0.15, retry_sleep=0.01,
        )
        pool.set_peer_range("good", 1, 40)
        pool.set_peer_range("flaky", 1, 40)
        pool.start()
        applied = []
        deadline = asyncio.get_event_loop().time() + 30
        try:
            while len(applied) < 20:
                assert asyncio.get_event_loop().time() < deadline, (
                    f"stalled: applied={applied} sent={len(sent)}"
                )
                b = pool.get_block(pool.height)
                if b is not None:
                    applied.append(b.header.height)
                    pool.pop_request()
                await asyncio.sleep(0.005)
        finally:
            pool.stop()
        # in order, exactly once, nothing skipped
        assert applied == list(range(1, 21))
        # the flaky peer was asked at least once, timed out, and leaked no
        # pending slots (every unanswered request was released)
        flaky = pool._peers.get("flaky")
        if flaky is not None:
            assert flaky.pending == 0
            assert flaky.timeouts > 0
            assert flaky.score < 1.0
        else:
            # or its pattern got it banned outright — also a pass
            assert any(p == "flaky" for p, _ in punished)
        good = pool._peers["good"]
        assert good.blocks_served >= 20
        assert good.score > 0.9

    asyncio.run(asyncio.wait_for(run(), 60))


def test_redo_request_releases_pending_and_requeues():
    async def run():
        sent = []

        async def send_request(peer_id, height):
            sent.append((peer_id, height))

        async def punish(peer_id, reason):
            pass

        m = _metrics()
        pool = BlockPool(5, send_request, punish, metrics=m,
                         peer_timeout=5.0, retry_sleep=0.01)
        pool.set_peer_range("p1", 1, 40)
        pool.start()
        try:
            deadline = asyncio.get_event_loop().time() + 5
            while (("p1", 5) not in sent) or (("p1", 6) not in sent):
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.01)
            p1 = pool._peers["p1"]
            pending_before = p1.pending
            assert pending_before >= 2

            # redo of a FILLED height: bad block recorded, score dinged
            assert pool.add_block("p1", _fake_block(5))
            assert pool.redo_request(5) == "p1"
            assert p1.bad_blocks == 1
            assert p1.score < 1.0
            # redo of an IN-FLIGHT height (the partner of a failed pair):
            # the pending slot must be released — the pre-ISSUE-12 leak
            assert pool.redo_request(6) == "p1"
            assert p1.pending == pending_before - 2
            assert pool.get_block(5) is None and pool.get_block(6) is None
            assert _counter_val(m.redos_total) == 2

            # both heights are re-requested once the backoff expires
            p1.backoff_until = 0.0
            deadline = asyncio.get_event_loop().time() + 5
            while sent.count(("p1", 5)) < 2 or sent.count(("p1", 6)) < 2:
                assert asyncio.get_event_loop().time() < deadline
                await asyncio.sleep(0.01)
        finally:
            pool.stop()

    asyncio.run(asyncio.wait_for(run(), 30))


def test_peer_scoring_backoff_and_ban():
    p = _PoolPeer("x", base=1, height=100)
    assert p.score == 1.0
    p.record_failure()
    assert p.score < 1.0
    assert p.backoff_until > time.monotonic()  # cooling down
    first_backoff = p.backoff_until
    p.record_failure()
    assert p.backoff_until >= first_backoff  # exponential growth
    # a good block resets the failure streak and the cool-down
    p.record_good(0.05)
    assert p.failures == 0 and p.backoff_until == 0.0
    for _ in range(20):
        p.record_failure()
    assert p.banned()
    assert p.score < BAN_THRESHOLD


def test_pick_peer_respects_backoff_and_weights():
    async def run():
        async def noop(*a):
            pass

        pool = BlockPool(1, noop, noop)
        pool.set_peer_range("a", 1, 50)
        pool.set_peer_range("b", 1, 50)
        pa, pb = pool._peers["a"], pool._peers["b"]
        # b is in backoff: only a is eligible
        pb.backoff_until = time.monotonic() + 60
        for _ in range(20):
            assert pool._pick_peer(10).peer_id == "a"
        # b returns with a rock-bottom score: a must dominate the routing
        pb.backoff_until = 0.0
        pb.score = 0.05
        picks = [pool._pick_peer(10).peer_id for _ in range(400)]
        assert picks.count("a") > picks.count("b") * 3

    asyncio.run(run())


async def _ban_flow():
    punished = []

    async def noop(*a):
        pass

    async def punish(peer_id, reason):
        punished.append(peer_id)

    pool = BlockPool(1, noop, punish)
    pool.set_peer_range("bad", 1, 50)
    p = pool._peers["bad"]
    for _ in range(20):
        p.record_failure()
    assert await pool._ban_if_bad(p, "test")
    assert punished == ["bad"]
    assert pool.num_peers() == 0


def test_ban_punishes_and_removes():
    asyncio.run(_ban_flow())


# ------------------------------------------------------------- checkpointing


def _mk_chain(start, n, chain_id="ckpt-chain"):
    """A hash-linked run of minimal (but encode/decode-true) blocks."""
    from tendermint_tpu.types.basic import BlockID, PartSetHeader
    from tendermint_tpu.types.block import (
        Block,
        Commit,
        CommitSig,
        ConsensusVersion,
        Header,
    )
    from tendermint_tpu.types.basic import BlockIDFlag

    blocks = []
    prev_hash = b"\xaa" * 32
    for h in range(start, start + n):
        commit = Commit(
            height=h - 1, round=0,
            block_id=BlockID(prev_hash, PartSetHeader(1, b"\xbb" * 32)),
            signatures=(
                CommitSig(BlockIDFlag.COMMIT, b"\x01" * 20, 7, b"\x02" * 64),
            ),
        )
        header = Header(
            version=ConsensusVersion(), chain_id=chain_id, height=h,
            time_ns=1_000_000 * h,
            last_block_id=BlockID(prev_hash, PartSetHeader(1, b"\xbb" * 32)),
            last_commit_hash=b"\xcc" * 32, data_hash=b"\xdd" * 32,
            validators_hash=b"\xee" * 32, next_validators_hash=b"\xee" * 32,
            consensus_hash=b"\xff" * 32, app_hash=b"\x11" * 32,
            last_results_hash=b"\x22" * 32, evidence_hash=b"\x33" * 32,
            proposer_address=b"\x44" * 20,
        )
        b = Block(header=header, txs=(), evidence=(), last_commit=commit)
        blocks.append(b)
        prev_hash = b.hash()
    return blocks


def test_checkpoint_roundtrip_and_linkage(tmp_path):
    path = str(tmp_path / "catchup.json")
    ck = CatchupCheckpoint(path)
    blocks = _mk_chain(5, 4)
    ck.save(4, blocks)

    loaded = ck.load(4)
    assert [b.header.height for b in loaded] == [5, 6, 7, 8]
    assert [b.hash() for b in loaded] == [b.hash() for b in blocks]

    # mid-window crash: state advanced past the write point — the applied
    # prefix is skipped, the remainder still loads
    partial = ck.load(6)
    assert [b.header.height for b in partial] == [7, 8]

    # stale (state beyond the window) and pre-window states discard
    assert ck.load(9) == []
    assert ck.load(2) == []

    # a tampered file fails the linkage proof closed
    import json

    payload = json.loads(open(path).read())
    other = _mk_chain(6, 1, chain_id="evil")[0]
    payload["blocks"][1] = other.encode().hex()
    open(path, "w").write(json.dumps(payload))
    assert ck.load(4) == []

    # corrupt JSON and a missing file degrade to no-resume
    open(path, "w").write("{not json")
    assert ck.load(4) == []
    ck.clear()
    assert ck.load(4) == []

    # disabled checkpoint is inert
    off = CatchupCheckpoint(None)
    off.save(1, blocks)
    assert off.load(1) == []


def test_resume_applies_without_reverifying(tmp_path):
    """Crash-mid-blocksync acceptance: a reactor restarted over a valid
    checkpoint applies the verified window WITHOUT re-verification (the
    verify stage is never consulted for those heights)."""
    from tendermint_tpu.blocksync.reactor import BlocksyncReactor

    blocks = _mk_chain(5, 4)  # verified 5..7 + trailing commit carrier 8
    path = str(tmp_path / "catchup.json")
    CatchupCheckpoint(path).save(4, blocks)

    applied = []

    class _Vals:
        def hash(self):
            return b"\xee" * 32  # matches _mk_chain: trust path taken

    class _Exec:
        def apply_block(self, state, block_id, block, trust_last_commit=False):
            applied.append((block.header.height, trust_last_commit))
            return SimpleNamespace(
                last_block_height=block.header.height,
                last_block_id=block_id,
                validators=_Vals(),
            )

    class _Store:
        saved = []

        def save_block(self, block, parts, commit):
            self.saved.append(block.header.height)

    state = SimpleNamespace(
        last_block_height=4,
        last_block_id=SimpleNamespace(hash=blocks[0].header.last_block_id.hash),
        validators=_Vals(),
    )
    m = _metrics()
    r = BlocksyncReactor(
        state, _Exec(), _Store(), active=True, metrics=m, checkpoint_path=path,
    )
    called = []
    r._verify_run_batched = lambda *a, **k: called.append(a) or None
    r._resume_from_checkpoint()
    assert [h for h, _ in applied] == [5, 6, 7]
    assert all(trust for _, trust in applied)  # no re-verification in apply
    assert called == []  # the verify stage never saw the resumed heights
    assert r.state.last_block_height == 7
    assert _counter_val(m.resume_events_total) == 1
    assert _counter_val(m.blocks_applied_total) == 3


def test_resume_rejects_foreign_chain(tmp_path):
    """A checkpoint that does not extend OUR chain is discarded (fail
    closed), not applied."""
    from tendermint_tpu.blocksync.reactor import BlocksyncReactor

    blocks = _mk_chain(5, 3)
    path = str(tmp_path / "catchup.json")
    CatchupCheckpoint(path).save(4, blocks)

    state = SimpleNamespace(
        last_block_height=4,
        last_block_id=SimpleNamespace(hash=b"\x66" * 32),  # NOT the anchor
        validators=SimpleNamespace(hash=lambda: b"\xee" * 32),
    )
    r = BlocksyncReactor(state, None, None, active=True, checkpoint_path=path)
    r._resume_from_checkpoint()
    assert r.state.last_block_height == 4  # nothing applied
    assert not os.path.exists(path)  # and the bad file is gone


# ----------------------------------------------------------- chaos serving


def test_serve_faults_corrupt_block_is_a_lie_not_noise():
    from tendermint_tpu.chaos.catchup import ServeFaults
    from tendermint_tpu.types.block import Block

    b = _mk_chain(5, 1)[0]
    sf = ServeFaults()
    bad = sf.corrupt_block(b)
    # still decodes and still hashes — only a commit signature changed
    rt = Block.decode(bad.encode())
    assert rt.header.height == 5
    assert bad.hash() == b.hash()
    assert bad.last_commit.signatures[0].signature != b.last_commit.signatures[0].signature
    assert ("block_lie", "height=5") in sf.fired


def test_serve_faults_stall_and_counters():
    t = [0.0]
    sf = __import__(
        "tendermint_tpu.chaos.catchup", fromlist=["ServeFaults"]
    ).ServeFaults(clock=lambda: t[0])
    assert not sf.block_stalled()
    sf.arm_block_stall(5.0)
    assert sf.block_stalled()
    t[0] = 6.0
    assert not sf.block_stalled()
    sf.arm_block_lies(1)
    assert sf.take_block_lie() and not sf.take_block_lie()
    sf.arm_chunk_corrupt(1)
    assert sf.take_chunk_corrupt() and not sf.take_chunk_corrupt()
    assert sf.corrupt_chunk(b"\x00\x01")[0] == 0xFF


# ------------------------------------------------------------------- e2e


def test_pipeline_sync_e2e_plaintext(tmp_path):
    """A fresh node catches up through the three-stage pipeline over the
    plaintext transport (runs in minimal containers): blocks byte-identical,
    super-batch sizes recorded, handoff to consensus fires, checkpoint
    cleared after the handoff."""
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config.config import test_config
    from tendermint_tpu.crypto import gen_ed25519
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.privval.file_pv import FilePV
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    priv = FilePV(gen_ed25519(b"\x61" * 32))
    gen = GenesisDoc(
        chain_id="pipe-chain",
        validators=[GenesisValidator(priv.get_pub_key(), 10)],
    )

    def make(name, with_validator):
        cfg = test_config()
        cfg.base.db_backend = "memdb"
        cfg.rpc.laddr = ""
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.plaintext = True
        cfg.p2p.pex = False
        if name == "syncer":
            cfg.root_dir = str(tmp_path / name)
            os.makedirs(os.path.join(cfg.root_dir, "data"), exist_ok=True)
        else:
            cfg.root_dir = ""
        cfg.consensus.wal_path = str(tmp_path / name / "wal")
        return Node(
            cfg, gen,
            priv_validator=priv if with_validator else None,
            app=KVStoreApplication(),
        )

    async def run():
        source, syncer = make("source", True), make("syncer", False)
        try:
            await source.start()
            await source.wait_for_height(8, timeout=90)
            await syncer.start()
            assert syncer.fast_sync is True
            ckpt_path = syncer.blocksync_reactor.checkpoint.path
            assert ckpt_path  # root_dir nodes persist the catch-up window
            await syncer.switch.dial_peers_async(
                [f"{source.node_key.id}@{source.p2p_addr}"], persistent=True
            )
            await syncer.wait_for_height(8, timeout=90)
            for h in (2, 5, 8):
                assert (
                    syncer.block_store.load_block(h).hash()
                    == source.block_store.load_block(h).hash()
                )
            await asyncio.wait_for(syncer.blocksync_reactor.synced.wait(), 30)
            # super-batches actually rode the pipeline (rows = blocks x
            # validators in one flush)
            sb = syncer.metrics.blocksync.super_batch_rows
            assert sb._totals.get((), 0) >= 1 and sb._sums.get((), 0.0) > 0
            # the handoff clears the checkpoint: a completed sync leaves no
            # stale resume state behind
            assert not os.path.exists(ckpt_path)
            target = source.block_store.height + 2
            await syncer.wait_for_height(target, timeout=90)
        finally:
            await syncer.stop()
            await source.stop()

    asyncio.run(run())
