"""Batched GF(2^255-19) field arithmetic in JAX (int32 limbs).

TPU-first design notes
----------------------
- A field element is `int32[20, ...batch]`: limbs on the LEADING axis so the
  batch axis maps onto TPU vector lanes; every op is elementwise across batch.
- UNIFORM radix 2^13: limb i holds bits [13i, 13i+13); 20 limbs cover 260
  bits. The wrap factor at limb 20 is 2^260 mod p = 2^5 * 19 = 608. The
  uniform radix makes the schoolbook product a PURE convolution — no
  positional correction matrix — which XLA compiles to ~60 fused vector ops
  (20 broadcast multiplies + 20 shifted accumulations) instead of the ~800
  sliced ops of a mixed-radix formulation. Compile time and codegen quality
  both hinge on that op count: the whole verify kernel contains ~3.5k field
  multiplies.
- Accumulation bound: <=20 terms of (2^13-1)^2 < 2^31 — every intermediate is
  a NON-NEGATIVE int32. int32 (not uint32) is deliberate: TPU vector units
  lower unsigned shifts ~5x slower than signed ones (measured), and the carry
  chains live on shifts.
- All public ops return "carried" limbs: limb i < 2^13 + slack (value ≡
  correct mod p). `freeze` produces the unique canonical representative for
  byte encoding / comparison.

This replaces the per-signature scalar curve arithmetic the reference does in
Go (reference: crypto/ed25519/ed25519.go:148 via golang.org/x/crypto) with a
validator-axis-parallel implementation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

P = 2**255 - 19
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

NLIMBS = 20
RADIX = 13
WRAP = (1 << (NLIMBS * RADIX)) % P  # 2^260 mod p = 608
assert WRAP == 608
MASK = (1 << RADIX) - 1
# Bit positions (uniform): limb i starts at bit 13*i. S/W kept for callers
# that index bits generically (from_bytes / bit()).
S = [RADIX * i for i in range(2 * NLIMBS + 1)]
W = [RADIX] * (2 * NLIMBS)


def from_int(x: int) -> np.ndarray:
    """Host-side: python int -> canonical limbs, shape (20,)."""
    x %= P
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = (x >> (RADIX * i)) & MASK
    return out


def to_int(limbs) -> int:
    """Host-side: limbs -> python int (limbs need not be canonical)."""
    arr = np.asarray(limbs, dtype=np.uint64)
    return sum(int(arr[i]) << (RADIX * i) for i in range(arr.shape[0])) % P


def zeros_like_batch(batch_shape) -> jnp.ndarray:
    return jnp.zeros((NLIMBS, *batch_shape), dtype=jnp.int32)


def const_fe(x: int, batch_shape=()) -> jnp.ndarray:
    """Broadcast a constant field element across a batch shape."""
    limbs = jnp.asarray(from_int(x))
    return jnp.broadcast_to(
        limbs.reshape((NLIMBS,) + (1,) * len(batch_shape)), (NLIMBS, *batch_shape)
    ).astype(jnp.int32)


def _carry_pass(limbs_list):
    """One sequential carry pass over uniform-width limbs.
    Returns (list of in-range limbs, final carry array)."""
    out = []
    carry_ = jnp.zeros_like(limbs_list[0])
    for x in limbs_list:
        x = x + carry_
        carry_ = x >> RADIX
        out.append(x & jnp.int32(MASK))
    return out, carry_


@jax.jit
def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Three PARALLEL carry passes + 2^260 wrap.

    Each pass moves every limb's overflow up one position simultaneously
    (vectorized shift/mask/roll — ~7 HLO ops instead of a 60-op sequential
    ripple; both compile time and TPU codegen reward the small graph). Three
    passes reduce any nonneg int32 input to the carried form
    limb_i <= 2^13 (i >= 1), limb0 <= 2^13 + 607 (the slack at limb0 comes
    from the wrap; the fourth pass is what guarantees the fixed point for
    ANY nonneg int32 input, e.g. mul_small by 2^17). Every overflow bound in
    this module assumes exactly this carried form."""
    for _ in range(4):
        c = x >> RADIX
        x = (x & jnp.int32(MASK)) + jnp.concatenate(
            [jnp.int32(WRAP) * c[NLIMBS - 1 :], c[: NLIMBS - 1]], axis=0
        )
    return x


@jax.jit
def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


# Subtraction via limb-wise complement: no multiple of p fits 20 radix-13
# limbs with per-limb headroom >= the carried bounds (max k*p = 32p =
# 2^260-608 < the required digit sum), so instead:
#   a - b ≡ a + (COMP - b) + CORR (mod p)
# where COMP_i dominates every carried limb of b (8799 for limb0's slack,
# 8191 elsewhere) making COMP - b non-negative limb-wise, and CORR =
# (-value(COMP)) mod p in canonical limbs cancels the offset.
_COMP = np.array([(1 << RADIX) + 608] + [1 << RADIX] * (NLIMBS - 1), dtype=np.int32)
_COMP_VAL = sum(int(_COMP[i]) << (RADIX * i) for i in range(NLIMBS))
_CORR = from_int(-_COMP_VAL % P)
COMP = jnp.asarray(_COMP)
CORR = jnp.asarray(_CORR)


@jax.jit
def sub(a: jnp.ndarray, b: jnp.ndarray, comp=None, corr=None) -> jnp.ndarray:
    """a - b (mod p). Inputs must be carried.

    comp/corr: optionally pass MATERIALIZED (20, ...batch) buffers of COMP /
    CORR. XLA:TPU compiles per-limb constant broadcasts into catastrophically
    slow fusions (~200x, measured); the hot kernel passes real device arrays
    instead. The broadcast fallback keeps standalone/CPU use working."""
    if comp is None:
        shape = (NLIMBS,) + (1,) * (a.ndim - 1)
        comp = COMP.reshape(shape)
        corr = CORR.reshape(shape)
    return carry(a + (comp - b) + corr)


@jax.jit
def neg(a: jnp.ndarray, comp=None, corr=None) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a, comp, corr)


@jax.jit
def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field multiply. Inputs carried; output carried.

    Pure convolution in the uniform radix: prod[k] = Σ_{i+j=k} a_i·b_j,
    expressed as 20 shifted accumulations of the (20, ...batch) vector
    products a_i * b — the formulation XLA fuses best."""
    acc = jnp.zeros((2 * NLIMBS - 1, *a.shape[1:]), dtype=jnp.int32)
    for i in range(NLIMBS):
        acc = acc.at[i : i + NLIMBS].add(a[i] * b)
    # Two parallel carry passes over the 39-limb product; the top carry sits
    # at position 39 = 19 + 20, i.e. folds onto limb 19 with factor 608.
    for _ in range(2):
        c = acc >> RADIX
        acc = (acc & jnp.int32(MASK)) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0
        )
        acc = acc.at[NLIMBS - 1].add(jnp.int32(WRAP) * c[2 * NLIMBS - 2])
    # Fold limbs >= 20 down with factor 608 (2^260 ≡ 608).
    out = acc[:NLIMBS].at[: NLIMBS - 1].add(jnp.int32(WRAP) * acc[NLIMBS:])
    return carry(out)


@jax.jit
def square(a: jnp.ndarray) -> jnp.ndarray:
    """Field square via the symmetric convolution: prod[k] = Σ_{i+j=k} a_i a_j
    = (a_{k/2})² [k even] + 2·Σ_{i<j, i+j=k} a_i a_j — about half the
    multiply-accumulates of the general product (int32 multiplies are emulated
    on the TPU VPU, so MAC count is the dominant cost). The partial sums are
    term-for-term identical to mul(a, a)'s, so the same int32 bound applies."""
    acc = jnp.zeros((2 * NLIMBS - 1, *a.shape[1:]), dtype=jnp.int32)
    a2 = a + a  # ≤ 2^14+: products vs carried limbs stay within the conv bound
    for i in range(NLIMBS):
        acc = acc.at[2 * i].add(a[i] * a[i])
        if i + 1 < NLIMBS:
            acc = acc.at[2 * i + 1 : i + NLIMBS].add(a[i] * a2[i + 1 :])
    for _ in range(2):
        c = acc >> RADIX
        acc = (acc & jnp.int32(MASK)) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0
        )
        acc = acc.at[NLIMBS - 1].add(jnp.int32(WRAP) * c[2 * NLIMBS - 2])
    out = acc[:NLIMBS].at[: NLIMBS - 1].add(jnp.int32(WRAP) * acc[NLIMBS:])
    return carry(out)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant k (int32 headroom: carried limb * k < 2^31)."""
    assert 0 < k < (1 << 17)
    return carry(a * jnp.int32(k))


def _fold255(limbs):
    """Fold bits >= 255 down: value = lo + 2^255*hi ≡ lo + 19*hi.
    limbs: 20 in-range (13-bit) limbs; bit 255 is limb 19 bit 8."""
    hi = limbs[NLIMBS - 1] >> jnp.int32(8)
    limbs = list(limbs)
    limbs[NLIMBS - 1] = limbs[NLIMBS - 1] & jnp.int32(0xFF)
    limbs[0] = limbs[0] + jnp.int32(19) * hi
    return limbs


@jax.jit
def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p). Input carried."""
    limbs = [a[i] for i in range(NLIMBS)]
    limbs, c = _carry_pass(limbs)
    limbs[0] = limbs[0] + jnp.int32(WRAP) * c
    limbs, c = _carry_pass(limbs)  # value < 2^260, c == 0
    limbs = _fold255(limbs)
    limbs, _ = _carry_pass(limbs)  # value < 2^255 + 19*32
    limbs = _fold255(limbs)
    limbs, _ = _carry_pass(limbs)  # value < 2^255 + 19: at most p-1 above p
    # Conditional subtract p: y = x + 19; if y has bit 255 set, x >= p and
    # the folded y (bit 255 cleared) equals x - p.
    ylimbs = list(limbs)
    ylimbs[0] = ylimbs[0] + jnp.int32(19)
    ylimbs, _ = _carry_pass(ylimbs)
    yhi = ylimbs[NLIMBS - 1] >> jnp.int32(8)
    ylimbs[NLIMBS - 1] = ylimbs[NLIMBS - 1] & jnp.int32(0xFF)
    x = jnp.stack(limbs)
    y = jnp.stack(ylimbs)
    return jnp.where(yhi[None] > 0, y, x)


@jax.jit
def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise field equality -> bool[...batch]."""
    return jnp.all(freeze(a) == freeze(b), axis=0)


@jax.jit
def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=0)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b with cond shaped like the batch."""
    return jnp.where(cond[None], a, b)


def bit(a: jnp.ndarray, i: int) -> jnp.ndarray:
    """Extract bit i of the canonical value. Input must be frozen."""
    return (a[i // RADIX] >> jnp.int32(i % RADIX)) & jnp.int32(1)


def from_bytes(b: jnp.ndarray, mask_high_bit: bool = True) -> jnp.ndarray:
    """Little-endian bytes uint8[32, ...batch] -> limbs (not reduced mod p).

    mask_high_bit drops bit 255 (the ed25519 sign bit)."""
    b = jnp.asarray(b).astype(jnp.int32)
    if mask_high_bit:
        b = b.at[31].set(b[31] & jnp.int32(0x7F))
    limbs = []
    for i in range(NLIMBS):
        lo_bit = RADIX * i
        acc = None
        # gather the 13 bits [lo_bit, lo_bit+13) from the byte array
        for byte_i in range(lo_bit // 8, min((lo_bit + RADIX + 7) // 8, 32)):
            shift = byte_i * 8 - lo_bit
            v = b[byte_i]
            piece = (v << jnp.int32(shift)) if shift >= 0 else (v >> jnp.int32(-shift))
            acc = piece if acc is None else acc + piece
        limbs.append(acc & jnp.int32(MASK))
    # bits >= 256 don't exist; bit 255 (if unmasked) sits in limb 19 bit 8 and
    # is handled by carry's 2^260 wrap only at 260+ — fold it explicitly.
    out = jnp.stack(limbs)
    if not mask_high_bit:
        hi = (b[31] >> jnp.int32(7)) & jnp.int32(1)
        out = out.at[NLIMBS - 1].set(out[NLIMBS - 1] & jnp.int32(0xFF))
        out = out.at[0].add(jnp.int32(19) * hi)
    return carry(out)


@jax.jit
def to_bytes(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical little-endian encoding uint8[32, ...batch]."""
    f = freeze(a)
    out = []
    for byte_i in range(32):
        lo_bit = byte_i * 8
        acc = None
        for limb_i in range(lo_bit // RADIX, min((lo_bit + 8 + RADIX - 1) // RADIX, NLIMBS)):
            shift = limb_i * RADIX - lo_bit
            v = f[limb_i]
            piece = (v << jnp.int32(shift)) if shift >= 0 else (v >> jnp.int32(-shift))
            acc = piece if acc is None else acc + piece
        out.append(acc & jnp.int32(0xFF))
    return jnp.stack(out).astype(jnp.uint8)


@jax.jit
def is_canonical_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """True iff the 255-bit value encoded (sign bit ignored) is < p."""
    v = from_bytes(b, mask_high_bit=True)
    limbs = [v[i] for i in range(NLIMBS)]
    limbs[0] = limbs[0] + jnp.int32(19)
    limbs, _ = _carry_pass(limbs)
    return (limbs[NLIMBS - 1] >> jnp.int32(8)) == 0


_POW2K_CHUNK = 10


def _pow2k(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a^(2^k): short runs inline; long runs as a fori_loop whose body does
    _POW2K_CHUNK squarings. The chunking balances compile time (the inversion
    ladders contain ~500 squarings; fully inline they dominate the kernel's
    HLO count) against loop-iteration overhead. On TPU, long runs fuse into
    Pallas square-chain kernels instead — the fori_loop form spent ~14 ms
    per verification call in device while-loop overhead (traced r4)."""
    if k >= _POW2K_CHUNK:
        try:
            from tendermint_tpu.ops import pallas_fe

            if pallas_fe.enabled():
                return pallas_fe.fsquare_chain(a, k)
        except Exception:  # pragma: no cover - pallas unavailable
            pass
    q, r = divmod(k, _POW2K_CHUNK)
    if q >= 2:
        def body(_, x):
            for _ in range(_POW2K_CHUNK):
                x = square(x)
            return x

        a = jax.lax.fori_loop(0, q, body, a)
    else:
        r = k
    for _ in range(r):
        a = square(a)
    return a


def _z250(a: jnp.ndarray):
    """Shared ladder: returns (x^(2^250 - 1), x^11, x^9). Classic 25519 chain."""
    z2 = square(a)
    z8 = _pow2k(z2, 2)
    z9 = mul(a, z8)
    z11 = mul(z2, z9)
    z22 = square(z11)
    z_5_0 = mul(z9, z22)  # x^(2^5 - 1)
    z_10_5 = _pow2k(z_5_0, 5)
    z_10_0 = mul(z_10_5, z_5_0)
    z_20_10 = _pow2k(z_10_0, 10)
    z_20_0 = mul(z_20_10, z_10_0)
    z_40_20 = _pow2k(z_20_0, 20)
    z_40_0 = mul(z_40_20, z_20_0)
    z_50_40 = _pow2k(z_40_0, 10)
    z_50_0 = mul(z_50_40, z_10_0)
    z_100_50 = _pow2k(z_50_0, 50)
    z_100_0 = mul(z_100_50, z_50_0)
    z_200_100 = _pow2k(z_100_0, 100)
    z_200_0 = mul(z_200_100, z_100_0)
    z_250_200 = _pow2k(z_200_0, 50)
    z_250_0 = mul(z_250_200, z_50_0)
    return z_250_0, z11, z9


@jax.jit
def inv(a: jnp.ndarray) -> jnp.ndarray:
    """x^(p-2) = x^(2^255 - 21). inv(0) = 0."""
    z_250_0, z11, _ = _z250(a)
    z_255_5 = _pow2k(z_250_0, 5)
    return mul(z_255_5, z11)


@jax.jit
def pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3)."""
    z_250_0, _, _ = _z250(a)
    z_252_2 = _pow2k(z_250_0, 2)
    return mul(z_252_2, a)
