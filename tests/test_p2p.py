"""P2P fabric tests: secret connection, MConnection multiplexing, switch
routing, persistent reconnect (reference test models: p2p/conn/*_test.go,
p2p/switch_test.go)."""

import asyncio

import pytest

# module imports reach the p2p stack (secret connection -> the
# `cryptography` wheel); skip cleanly in minimal containers
pytest.importorskip("cryptography")

from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    NodeInfo,
    NodeKey,
    Reactor,
    Switch,
    MultiplexTransport,
)
from tendermint_tpu.p2p.conn.secret_connection import SecretConnection


class EchoReactor(Reactor):
    """Records every message; echoes on channel 0x02."""

    def __init__(self, name):
        super().__init__(name)
        self.received = []
        self.peers_added = []
        self.peers_removed = []
        self.got = asyncio.Event()

    def get_channels(self):
        return [
            ChannelDescriptor(id=0x01, priority=5),
            ChannelDescriptor(id=0x02, priority=1),
        ]

    async def add_peer(self, peer):
        self.peers_added.append(peer.id)

    async def remove_peer(self, peer, reason):
        self.peers_removed.append(peer.id)

    async def receive(self, chan_id, peer, msg):
        self.received.append((chan_id, msg))
        self.got.set()
        if chan_id == 0x02:
            await peer.send(0x01, b"echo:" + msg)


def make_switch(name: str, chain="p2p-test", secret=True):
    nk = NodeKey(gen_ed25519())
    ni = NodeInfo(node_id=nk.id, network=chain, moniker=name)
    transport = MultiplexTransport(nk, ni, use_secret_conn=secret)
    sw = Switch(transport)
    reactor = EchoReactor(f"echo-{name}")
    sw.add_reactor("echo", reactor)
    return sw, reactor


async def start_pair(secret=True):
    sw1, r1 = make_switch("alice", secret=secret)
    sw2, r2 = make_switch("bob", secret=secret)
    await sw1.start()
    await sw2.start()
    addr = await sw1.transport.listen("127.0.0.1", 0)
    await sw2.dial_peer(f"{sw1.node_info.node_id}@{addr}")
    for _ in range(100):
        if sw1.num_peers() and sw2.num_peers():
            break
        await asyncio.sleep(0.02)
    return sw1, r1, sw2, r2


def test_secret_connection_handshake_and_frames():
    async def run():
        k1, k2 = gen_ed25519(), gen_ed25519()
        server_done = asyncio.get_event_loop().create_future()

        async def on_conn(reader, writer):
            sc = await SecretConnection.upgrade(reader, writer, k1)
            msg = await sc.read_msg()
            await sc.write_msg(b"pong:" + msg)
            server_done.set_result(sc.remote_pubkey.bytes())

        server = await asyncio.start_server(on_conn, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        sc = await SecretConnection.upgrade(reader, writer, k2)
        # remote identity is authenticated
        assert sc.remote_pubkey.bytes() == k1.pub_key().bytes()
        big = bytes(range(256)) * 20  # multi-frame message (5120 bytes)
        await sc.write_msg(big)
        resp = await sc.read_msg()
        assert resp == b"pong:" + big
        assert await server_done == k2.pub_key().bytes()
        server.close()

    asyncio.run(run())


def test_switch_connects_and_routes_channels():
    async def run():
        sw1, r1, sw2, r2 = await start_pair()
        try:
            assert sw1.num_peers() == 1 and sw2.num_peers() == 1
            # send on channel 2 -> bob echoes back on channel 1
            peer = sw2.peers.list()[0]
            await peer.send(0x02, b"hello")
            await asyncio.wait_for(r2.got.wait(), 5)
            for _ in range(100):
                if r2.received:
                    break
                await asyncio.sleep(0.02)
            assert (0x02, b"hello") in r1.received
            for _ in range(100):
                if r2.received:
                    break
                await asyncio.sleep(0.02)
            assert (0x01, b"echo:hello") in r2.received
            # broadcast
            await sw1.broadcast(0x01, b"blast")
            for _ in range(100):
                if (0x01, b"blast") in r2.received:
                    break
                await asyncio.sleep(0.02)
            assert (0x01, b"blast") in r2.received
        finally:
            await sw2.stop()
            await sw1.stop()

    asyncio.run(run())


def test_large_message_multiplexed():
    async def run():
        sw1, r1, sw2, r2 = await start_pair()
        try:
            big = bytes(range(256)) * 300  # 76800 bytes > 75 packets
            peer = sw2.peers.list()[0]
            await peer.send(0x01, big)
            for _ in range(200):
                if any(m == big for _, m in r1.received):
                    break
                await asyncio.sleep(0.02)
            assert any(m == big for _, m in r1.received)
        finally:
            await sw2.stop()
            await sw1.stop()

    asyncio.run(run())


def test_peer_removal_on_disconnect():
    async def run():
        sw1, r1, sw2, r2 = await start_pair()
        try:
            peer = sw1.peers.list()[0]
            await sw1.stop_peer_for_error(peer, "test kill")
            assert sw1.num_peers() == 0
            assert r1.peers_removed == [peer.id]
            # bob notices the dead connection eventually
            for _ in range(200):
                if sw2.num_peers() == 0:
                    break
                await asyncio.sleep(0.02)
            assert sw2.num_peers() == 0
        finally:
            await sw2.stop()
            await sw1.stop()

    asyncio.run(run())


def test_node_info_incompatible_network_rejected():
    async def run():
        sw1, _ = make_switch("alice", chain="chain-A")
        sw2, _ = make_switch("bob", chain="chain-B")
        await sw1.start()
        await sw2.start()
        addr = await sw1.transport.listen("127.0.0.1", 0)
        with pytest.raises(Exception):
            await sw2.dial_peer(f"{sw1.node_info.node_id}@{addr}")
        assert sw2.num_peers() == 0
        await asyncio.sleep(0.1)
        assert sw1.num_peers() == 0
        await sw2.stop()
        await sw1.stop()

    asyncio.run(run())


def test_dial_wrong_id_rejected():
    async def run():
        sw1, _ = make_switch("alice")
        sw2, _ = make_switch("bob")
        await sw1.start()
        await sw2.start()
        addr = await sw1.transport.listen("127.0.0.1", 0)
        wrong_id = "ab" * 20
        with pytest.raises(Exception):
            await sw2.dial_peer(f"{wrong_id}@{addr}")
        await sw2.stop()
        await sw1.stop()

    asyncio.run(run())


def test_trust_store_persists_across_restart(tmp_path):
    """(reference: p2p/trust/store.go — metric history survives restarts)"""
    import asyncio

    from tendermint_tpu.p2p.behaviour import (
        BAD_MESSAGE,
        CONSENSUS_VOTE,
        PeerBehaviour,
        Reporter,
        TrustStore,
    )

    path = str(tmp_path / "trust.json")
    rep = Reporter(store=TrustStore(path))

    async def drive():
        for _ in range(5):
            await rep.report(PeerBehaviour("peer-a", CONSENSUS_VOTE))
        for _ in range(3):
            await rep.report(PeerBehaviour("peer-b", BAD_MESSAGE))

    asyncio.run(drive())
    assert rep.score("peer-a") > 0.9
    assert rep.score("peer-b") < 0.5
    rep.save()

    rep2 = Reporter(store=TrustStore(path))
    assert rep2.score("peer-a") > 0.9
    assert rep2.score("peer-b") < 0.5
    # corrupt store file -> clean fallback, no crash
    with open(path, "w") as f:
        f.write("{not json")
    rep3 = Reporter(store=TrustStore(path))
    assert rep3.score("peer-a") == 1.0  # optimistic prior
