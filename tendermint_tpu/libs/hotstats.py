"""Per-stage accumulators for the live vote-path hot loop.

The node's receive loop pays host bookkeeping for every vote across four
layers — protowire encodes, the WAL, event-bus fan-out and gossip — plus the
signature verify itself. This module is the shared measuring cup: each layer
adds its wall time to one of five stage buckets so `bench.py`
(vote_storm / live_consensus) can report a per-stage µs/vote breakdown in
`extra` instead of one opaque number, and PERF.md can record which layer a
regression lives in.

Timing is OFF by default: every instrumented call site reduces to a single
`stats.enabled` flag check (the same contract as libs/trace.py's hoisted
tracer). Counts ride along with the times; the redundant-work *counters*
that must stay cheap enough for production (encode computes, fsyncs) live
with their subsystems instead (types/vote.py ENCODE_COMPUTES /
SIGN_BYTES_COMPUTES, consensus/wal.py WAL.fsync_count).

Stages are measured AT THEIR OWN LAYER, so they nest rather than partition:
a WAL frame write that triggers a first-time Vote.encode counts those
microseconds under both `wal` and `encode`. The breakdown answers "where is
time spent per layer", not "what do disjoint slices sum to".
"""

from __future__ import annotations

from time import perf_counter

__all__ = ["HotpathStats", "stats", "perf_counter"]


class HotpathStats:
    """Five stage buckets: encode (protowire/sign-bytes computes), wal
    (frame writes + group-commit flushes + fsyncs), pubsub (event-bus
    publishes), gossip (reactor broadcast fan-out), verify (host or device
    signature checks)."""

    STAGES = ("encode", "wal", "pubsub", "gossip", "verify")

    __slots__ = ("enabled", "seconds", "counts")

    def __init__(self) -> None:
        self.enabled = False
        self.reset()

    def reset(self) -> None:
        self.seconds = {s: 0.0 for s in self.STAGES}
        self.counts = {s: 0 for s in self.STAGES}

    def add(self, stage: str, dt: float, n: int = 1) -> None:
        self.seconds[stage] += dt
        self.counts[stage] += n

    def snapshot(self) -> dict:
        return {"seconds": dict(self.seconds), "counts": dict(self.counts)}

    def delta_since(self, before: dict) -> dict:
        """Stage seconds/counts accumulated since a snapshot() — benches
        bracket a timed region this way so warm-up work is excluded."""
        return {
            "seconds": {
                s: self.seconds[s] - before["seconds"].get(s, 0.0) for s in self.STAGES
            },
            "counts": {
                s: self.counts[s] - before["counts"].get(s, 0) for s in self.STAGES
            },
        }

    @staticmethod
    def breakdown_us(delta: dict, votes: int) -> dict:
        """{stage}_us per vote from a delta_since() dict — the exact shape
        bench.py attaches to vote_storm/live_consensus `extra`."""
        if votes <= 0:
            return {}
        return {
            f"{s}_us": round(delta["seconds"][s] / votes * 1e6, 3)
            for s in HotpathStats.STAGES
        }


# Process-global instance (one live consensus hot loop per process; benches
# enable it around their timed regions).
stats = HotpathStats()
