"""ASCII armor + passphrase-encrypted private keys.

reference: crypto/armor (armor.go — RFC-4880-style armored blocks) and
crypto/xsalsa20symmetric + the keys armoring in the SDK: encrypt with a key
derived from a passphrase, armor the ciphertext. Cipher here is IETF
ChaCha20-Poly1305 with a random 96-bit nonce — safe because every encryption
derives a FRESH key from a fresh salt (the reference tree ships
crypto/xchacha20poly1305; extended nonces buy nothing under per-use keys);
KDF is scrypt with ALL cost parameters (n, r, p) carried in the armor
headers so they can evolve without breaking old files.
"""

from __future__ import annotations

import base64
import os
from typing import Dict, Tuple

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.scrypt import Scrypt

ARMOR_START = "-----BEGIN TENDERMINT {}-----"
ARMOR_END = "-----END TENDERMINT {}-----"

# scrypt cost parameters (interactive-login grade)
_SCRYPT_N = 1 << 15
_SCRYPT_R = 8
_SCRYPT_P = 1


class ArmorError(Exception):
    pass


def encode_armor(block_type: str, headers: Dict[str, str], data: bytes) -> str:
    """reference: crypto/armor/armor.go EncodeArmor."""
    lines = [ARMOR_START.format(block_type)]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    lines.append("")
    b64 = base64.b64encode(data).decode()
    lines.extend(b64[i : i + 64] for i in range(0, len(b64), 64))
    lines.append(ARMOR_END.format(block_type))
    return "\n".join(lines) + "\n"


def decode_armor(text: str) -> Tuple[str, Dict[str, str], bytes]:
    """reference: crypto/armor/armor.go DecodeArmor."""
    lines = [l.strip() for l in text.strip().splitlines()]
    if not lines or not lines[0].startswith("-----BEGIN TENDERMINT "):
        raise ArmorError("missing armor start line")
    block_type = lines[0][len("-----BEGIN TENDERMINT ") : -len("-----")]
    if lines[-1] != ARMOR_END.format(block_type):
        raise ArmorError("missing or mismatched armor end line")
    headers: Dict[str, str] = {}
    body_start = 1
    for i, line in enumerate(lines[1:-1], start=1):
        if not line:
            body_start = i + 1
            break
        if ":" not in line:
            body_start = i
            break
        k, _, v = line.partition(":")
        headers[k.strip()] = v.strip()
    else:
        body_start = len(lines) - 1
    try:
        data = base64.b64decode("".join(lines[body_start:-1]))
    except Exception as e:
        raise ArmorError(f"bad armor body: {e}") from e
    return block_type, headers, data


_SCRYPT_N_MAX = 1 << 21  # ~256MB with r=8: DoS ceiling for untrusted armor


def _derive(passphrase: str, salt: bytes, n: int, r: int, p: int) -> bytes:
    return Scrypt(salt=salt, length=32, n=n, r=r, p=p).derive(passphrase.encode())


def encrypt_armor_priv_key(priv_key_bytes: bytes, passphrase: str,
                           key_type: str = "ed25519") -> str:
    """Armored, passphrase-encrypted private key
    (reference: the SDK's EncryptArmorPrivKey over crypto/armor)."""
    salt = os.urandom(16)
    nonce = os.urandom(12)
    key = _derive(passphrase, salt, _SCRYPT_N, _SCRYPT_R, _SCRYPT_P)
    ct = ChaCha20Poly1305(key).encrypt(nonce, priv_key_bytes, None)
    headers = {
        "kdf": "scrypt",
        "n": str(_SCRYPT_N),
        "r": str(_SCRYPT_R),
        "p": str(_SCRYPT_P),
        "salt": salt.hex().upper(),
        "nonce": nonce.hex().upper(),
        "type": key_type,
    }
    return encode_armor("PRIVATE KEY", headers, ct)


def unarmor_decrypt_priv_key(armor_text: str, passphrase: str) -> Tuple[bytes, str]:
    """Returns (priv_key_bytes, key_type). Raises ArmorError on a wrong
    passphrase or tampered armor."""
    block_type, headers, ct = decode_armor(armor_text)
    if block_type != "PRIVATE KEY":
        raise ArmorError(f"unexpected armor type {block_type!r}")
    if headers.get("kdf") != "scrypt":
        raise ArmorError(f"unsupported KDF {headers.get('kdf')!r}")
    try:
        salt = bytes.fromhex(headers["salt"])
        nonce = bytes.fromhex(headers["nonce"])
        n = int(headers.get("n", _SCRYPT_N))
        r = int(headers.get("r", _SCRYPT_R))
        p = int(headers.get("p", _SCRYPT_P))
    except (KeyError, ValueError) as e:
        raise ArmorError(f"bad armor headers: {e}") from e
    # validate untrusted parameters BEFORE deriving: a hostile armor file
    # must not be able to demand gigabytes of scrypt memory or smuggle a
    # ValueError past the ArmorError contract
    if not (1 < n <= _SCRYPT_N_MAX) or n & (n - 1):
        raise ArmorError(f"scrypt n {n} out of range or not a power of two")
    if not (0 < r <= 32 and 0 < p <= 16):
        raise ArmorError(f"scrypt r/p out of range: r={r} p={p}")
    if len(nonce) != 12 or len(salt) != 16:
        raise ArmorError("bad salt/nonce length")
    key = _derive(passphrase, salt, n, r, p)
    try:
        pt = ChaCha20Poly1305(key).decrypt(nonce, ct, None)
    except InvalidTag:
        raise ArmorError("wrong passphrase or corrupted armor") from None
    return pt, headers.get("type", "ed25519")
