"""Mempool reactor: gossips transactions on channel 0x30
(reference: mempool/reactor.go:18,190).

Per-peer broadcast task walks the mempool's tx list by insertion order and
skips txs the peer sent us (peer-ID tracking, reference: :41-96 mempoolIDs)."""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.p2p.base_reactor import Reactor
from tendermint_tpu.p2p.conn.connection import ChannelDescriptor

logger = logging.getLogger("tendermint_tpu.mempool")

MEMPOOL_CHANNEL = 0x30
BROADCAST_SLEEP = 0.02
# proto framing slack on top of the configured max tx size when computing
# the channel's assembled-message cap (reference: mempool/reactor.go
# calcMaxMsgSize over MaxTxBytes)
MSG_OVERHEAD_BYTES = 4096


def encode_txs(txs: List[bytes]) -> bytes:
    w = pw.Writer()
    for tx in txs:
        w.bytes_field(1, tx, emit_empty=True)
    return w.bytes()


def decode_txs(data: bytes) -> List[bytes]:
    return [v for f, _, v in pw.Reader(data) if f == 1]


class MempoolReactor(Reactor):
    def __init__(self, mempool, broadcast: bool = True, metrics=None):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.broadcast = broadcast
        self.metrics = metrics  # OverloadMetrics or None
        self._peer_tasks: Dict[str, asyncio.Task] = {}
        # Shed switch, flipped by the node's overload controller
        # (node/overload.py): while set, inbound gossiped txs are dropped
        # BEFORE the app CheckTx round-trip and the outbound walk pauses.
        # Independently of the switch, a FULL mempool sheds inbound gossip
        # (no point paying CheckTx for a tx that cannot be admitted).
        self.shed = False
        self.shed_rx = 0  # gossip messages dropped without decode/CheckTx

    def get_channels(self) -> List[ChannelDescriptor]:
        # sheddable: under inbound overload, gossiped txs are the FIRST
        # traffic dropped (votes never are — see ChannelDescriptor.sheddable).
        # The cap derives from the CONFIGURED max tx size, so a fleet running
        # raised [mempool] max_tx_bytes doesn't fatally disconnect honest
        # peers gossiping legitimately large txs.
        max_tx = getattr(self.mempool, "max_tx_bytes", 1_048_576)
        return [
            ChannelDescriptor(
                MEMPOOL_CHANNEL, priority=5, send_queue_capacity=128,
                recv_message_capacity=max_tx + MSG_OVERHEAD_BYTES, sheddable=True,
            )
        ]

    async def add_peer(self, peer) -> None:
        if self.broadcast:
            self._peer_tasks[peer.id] = asyncio.create_task(
                self._broadcast_tx_routine(peer), name=f"mempool-bcast-{peer.id[:8]}"
            )

    async def remove_peer(self, peer, reason) -> None:
        t = self._peer_tasks.pop(peer.id, None)
        if t:
            t.cancel()

    async def stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()
        self._peer_tasks.clear()

    async def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        if self.shed or self.mempool.is_full(0):
            # overload/full: drop the whole batch BEFORE decoding it or
            # paying the CheckTx round-trip (parsing a flood to count it
            # would defeat the point) — gossiped txs are retried by the
            # sender's walk, so a shed here costs latency, not delivery
            self.shed_rx += 1  # messages (batches), not txs
            if self.metrics is not None:
                self.metrics.shed.labels("mempool_gossip").inc()
            return
        loop = asyncio.get_running_loop()
        txs = decode_txs(msg_bytes)
        # One executor hop for the WHOLE gossiped batch: check_tx_batch
        # verifies every signed-tx envelope in ONE admission-lane submit
        # (device-batched CheckTx, crypto/scheduler.py) before the per-tx
        # locked admission — off-loop so a slow CheckTx can't stall all
        # p2p/consensus I/O (same policy as the RPC broadcast path).
        try:
            await loop.run_in_executor(
                None, self.mempool.check_tx_batch, txs, peer.id
            )
        except Exception as e:
            logger.debug("gossiped tx batch rejected: %s", e)

    async def _broadcast_tx_routine(self, peer) -> None:
        """(reference: mempool/reactor.go:190 broadcastTxRoutine)"""
        sent: set = set()
        try:
            while True:
                if self.shed:
                    await asyncio.sleep(BROADCAST_SLEEP * 5)
                    continue
                entries = self.mempool.entries()
                progress = False
                for key, tx, senders in entries:
                    if key in sent:
                        continue
                    if peer.id in senders:
                        sent.add(key)  # peer gave it to us; skip
                        continue
                    ok = await peer.send(MEMPOOL_CHANNEL, encode_txs([tx]))
                    if ok:
                        sent.add(key)
                        progress = True
                        tt = getattr(self.mempool, "_tt", lambda: None)()
                        if tt is not None:
                            # first successful fan-out only — the tracker
                            # dedupes repeats, so the stage names when the tx
                            # FIRST left this node, not how many peers got it
                            tt.record(key, "first_gossiped", peer=peer.id[:10])
                if not progress:
                    await asyncio.sleep(BROADCAST_SLEEP)
                # GC the sent-set against the live mempool
                if len(sent) > 10000:
                    live = {k for k, _, _ in self.mempool.entries()}
                    sent &= live
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("mempool broadcast died for %s", peer.id[:10])
