"""Fail-point crash-recovery matrix (reference:
test/persist/test_failure_indices.sh:40).

For each fail index i, run a subprocess node with TMTPU_FAIL_INDEX=i. The
node crashes hard (os._exit) at the i-th fail point hit during the
commit/apply sequence. The node is then restarted WITHOUT the fail index and
must recover (WAL catchup + handshake replay) and keep committing blocks."""

import os
import subprocess
import sys

import pytest

CHILD_SCRIPT = r"""
import asyncio, os, sys
os.environ.setdefault("TMTPU_CRYPTO_BACKEND", "cpu")
os.environ["JAX_PLATFORMS"] = "cpu"
from tendermint_tpu.abci.kvstore import PersistentKVStoreApplication
from tendermint_tpu.config.config import test_config
from tendermint_tpu.crypto import gen_ed25519
from tendermint_tpu.node.node import Node
from tendermint_tpu.privval.file_pv import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

root = sys.argv[1]
target_height = int(sys.argv[2])
os.makedirs(os.path.join(root, "data"), exist_ok=True)

cfg = test_config()
cfg.base.db_backend = "sqlite"
cfg.rpc.laddr = ""
cfg.p2p.laddr = ""
cfg.root_dir = root
priv = FilePV(gen_ed25519(b"\x21" * 32),
              key_file=os.path.join(root, "pv_key.json"),
              state_file=os.path.join(root, "pv_state.json"))
gen = GenesisDoc(chain_id="crash-chain", validators=[GenesisValidator(priv.get_pub_key(), 10)])

from tendermint_tpu.libs.kvdb import SQLiteDB

async def run():
    app = PersistentKVStoreApplication(SQLiteDB(os.path.join(root, "data", "app.db")))
    node = Node(cfg, gen, priv_validator=priv, app=app)
    await node.start()
    # feed a tx each height so blocks are non-empty
    try:
        node.mempool.check_tx(b"k%d=v" % node.block_store.height)
    except Exception:
        pass
    await node.wait_for_height(target_height, timeout=45)
    h = node.block_store.height
    await node.stop()
    print(f"REACHED {h}", flush=True)

asyncio.run(run())
"""


def run_child(root: str, target: int, fail_index: int | None, timeout=90):
    env = dict(os.environ)
    env["TMTPU_CRYPTO_BACKEND"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("TMTPU_FAIL_INDEX", None)
    if fail_index is not None:
        env["TMTPU_FAIL_INDEX"] = str(fail_index)
    proc = subprocess.run(
        [sys.executable, "-c", CHILD_SCRIPT, root, str(target)],
        env=env,
        capture_output=True,
        timeout=timeout,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return proc


# Fail points hit per height (cs_state + execution): index 0..5 covers the
# full commit/apply ordering: before save block, after save block, after WAL
# EndHeight, after apply block, and the execution-internal points.
@pytest.mark.parametrize("fail_index", [0, 1, 2, 3, 4, 5])
def test_crash_at_fail_index_then_recover(tmp_path, fail_index):
    root = str(tmp_path / f"node_fi{fail_index}")
    os.makedirs(os.path.join(root, "data"), exist_ok=True)

    # phase 1: run with the fail index armed; expect the hard crash (77)
    proc = run_child(root, target=4, fail_index=fail_index)
    assert proc.returncode == 77, (
        f"expected crash at fail point {fail_index}; rc={proc.returncode}\n"
        f"stdout={proc.stdout}\nstderr={proc.stderr[-2000:]}"
    )

    # phase 2: restart without the fail index; must recover and commit
    proc2 = run_child(root, target=3, fail_index=None)
    assert proc2.returncode == 0, (
        f"recovery failed after crash at {fail_index}; rc={proc2.returncode}\n"
        f"stdout={proc2.stdout}\nstderr={proc2.stderr[-3000:]}"
    )
    assert "REACHED" in proc2.stdout
