"""Mempool (reference: mempool/clist_mempool.go:36).

Ordered tx pool: CheckTx against the app's mempool connection, LRU dedup
cache, ReapMaxBytesMaxGas for proposals, post-commit Update with recheck.
Python's dict preserves insertion order, giving the concurrent-list semantics
the reference builds from clist; asyncio confines mutation to the event loop
plus the executor's explicit lock."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.crypto import tmhash


class MempoolError(Exception):
    """Base admission-control rejection. `reason` is machine-readable so the
    RPC layer can return a structured JSON-RPC error instead of a bare
    traceback (full / cache / quota / too_large)."""

    reason = "rejected"


class MempoolFullError(MempoolError):
    reason = "full"

    def __init__(self, detail: str = ""):
        super().__init__(
            "mempool is full" + (f" ({detail})" if detail else "")
        )


class TxInCacheError(MempoolError):
    reason = "cache"

    def __init__(self):
        super().__init__("tx already exists in cache")


class SenderQuotaError(MempoolError):
    reason = "quota"

    def __init__(self, sender: str, quota: int):
        super().__init__(
            f"sender {sender[:10]} exceeds in-flight quota ({quota})"
        )


class TxTooLargeError(MempoolError):
    reason = "too_large"

    def __init__(self, size: int, max_size: int):
        super().__init__(f"tx too large ({size} > {max_size})")


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height when validated
    gas_wanted: int
    senders: frozenset = frozenset()  # peer IDs that sent us this tx
    priority: int = 0  # app-assigned (ResponseCheckTx.priority); evict lowest first
    time_ns: int = 0  # admission wall time (TTL + oldest-first eviction)
    sender0: str = ""  # the admitting sender, charged against the quota


def iter_mempool_wal(path: str):
    """Yield txs from a mempool WAL (4-byte BE length + tx records),
    stopping at the first torn/truncated record — the clean-prefix
    semantics the consensus WAL's CRC framing gives, minus the CRC (the
    mempool log is forensic, not safety-critical)."""
    if not path:
        return
    try:
        f = open(path, "rb")
    except OSError:
        return
    with f:
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                return
            ln = int.from_bytes(hdr, "big")
            tx = f.read(ln)
            if len(tx) < ln:
                return  # torn tail
            yield tx


class Mempool:
    """(reference: mempool/mempool.go:15 interface + clist_mempool impl)"""

    def __init__(
        self,
        proxy_app: ABCIClient,
        max_txs: int = 5000,
        max_txs_bytes: int = 1024 * 1024 * 1024,
        cache_size: int = 10000,
        keep_invalid_txs_in_cache: bool = False,
        recheck: bool = True,
        metrics=None,
        wal_path: str = "",
        max_tx_bytes: int = 1_048_576,
        ttl_num_blocks: int = 0,
        ttl_seconds: float = 0.0,
        eviction: bool = True,
        max_txs_per_sender: int = 0,
        tx_tracker=None,
        scheduler=None,
        sig_precheck: bool = False,
    ):
        self.metrics = metrics
        # tx lifecycle tracker (libs/txtrace.py): admission is where a tx's
        # journey forks — admitted, rejected{reason}, evicted, or expired.
        # Every hook below is gated on tracker.enabled (the tracer flag).
        self.tx_tracker = tx_tracker
        # device-batched tx admission (crypto/scheduler.py, ISSUE 11): with
        # sig_precheck on, signed-tx envelopes (types/signed_tx.py) are
        # batch-verified through the scheduler's ADMISSION lane BEFORE the
        # mempool lock, and the verdict rides RequestCheckTx.sig_precheck so
        # the app consumes it instead of paying a serial per-tx verify. A
        # flood of concurrent check_tx callers (RPC executor threads, the
        # gossip reactor's batches) coalesces into shared device flushes.
        self.scheduler = scheduler
        self.sig_precheck = bool(sig_precheck) and scheduler is not None
        self.prechecked_total = 0  # envelopes verified through the lane
        self._wal = None
        if wal_path:
            self.init_wal(wal_path)
        self.proxy_app = proxy_app
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.max_tx_bytes = max_tx_bytes
        self.recheck = recheck
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        # admission control ([mempool] ttl_*/eviction/max_txs_per_sender)
        self.ttl_num_blocks = ttl_num_blocks
        self.ttl_seconds = ttl_seconds
        self.eviction = eviction
        self.max_txs_per_sender = max_txs_per_sender
        self._txs: "OrderedDict[bytes, MempoolTx]" = OrderedDict()  # key: tx hash
        self._cache: "OrderedDict[bytes, None]" = OrderedDict()
        self._cache_size = cache_size
        self._total_bytes = 0
        self._height = 0
        self._lock = threading.RLock()
        self._txs_available_cb: Optional[Callable[[], None]] = None
        self._notified_txs_available = False
        self._sender_counts: Dict[str, int] = {}  # admitting sender -> in-flight txs
        # senders punished for signature poisoning (crypto/provenance.py punish
        # callbacks, wired through node.py): their per-sender quota collapses
        # to PENALIZED_SENDER_QUOTA regardless of max_txs_per_sender
        self._penalized_senders: set = set()
        self.evicted_total = 0
        self.expired_total = 0

    # -- locking around commit (reference: Lock/Unlock in Mempool iface) ----

    def lock(self) -> None:
        self._lock.acquire()

    def unlock(self) -> None:
        self._lock.release()

    # -- size ---------------------------------------------------------------

    def size(self) -> int:
        return len(self._txs)

    def txs_bytes(self) -> int:
        return self._total_bytes

    def is_full(self, tx_len: int) -> bool:
        return len(self._txs) >= self.max_txs or self._total_bytes + tx_len > self.max_txs_bytes

    WAL_MAX_BYTES = 64 * 1024 * 1024  # rotate beyond this (autofile-group role)

    def init_wal(self, path: str) -> None:
        """Append-only tx log for crash forensics (reference:
        mempool/clist_mempool.go InitWAL over libs/autofile; records are
        4-byte big-endian length + tx bytes; one .old generation is kept,
        standing in for the reference's rotating autofile group)."""
        import os as _os

        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        self._wal_path = path
        self._wal = open(path, "ab")

    def close_wal(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None

    def _wal_write(self, tx: bytes) -> None:
        # caller holds self._lock
        if self._wal is None:
            return
        self._wal.write(len(tx).to_bytes(4, "big") + tx)
        self._wal.flush()
        if self._wal.tell() > self.WAL_MAX_BYTES:
            import os as _os

            self._wal.close()
            _os.replace(self._wal_path, self._wal_path + ".old")
            self._wal = open(self._wal_path, "ab")

    def replay_wal(self, path: str = "") -> int:
        """Re-admit the WAL's surviving txs through check_tx (crash
        forensics/recovery; the reference leaves replay to operators — here
        it is a method so tests can pin that an EVICTED tx's WAL record
        still replays cleanly: eviction un-caches, so replay re-admits).
        Returns the number of txs accepted back into the pool."""
        accepted = 0
        # suspend the live WAL while replaying: check_tx would otherwise
        # append every re-admitted tx onto the very file being iterated
        # (doubling it per replay cycle)
        with self._lock:
            wal, self._wal = self._wal, None
        try:
            for tx in iter_mempool_wal(path or getattr(self, "_wal_path", "")):
                try:
                    res = self.check_tx(tx)
                except MempoolError:
                    continue
                if res is not None and res.code == abci.CODE_TYPE_OK:
                    accepted += 1
        finally:
            with self._lock:
                self._wal = wal
        return accepted

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._cache.clear()
            self._sender_counts.clear()
            self._total_bytes = 0
            # allow the next admitted tx to re-notify consensus — without this
            # a flush between notify and commit stalls proposal creation when
            # create_empty_blocks is off
            self._notified_txs_available = False

    # -- notifications ------------------------------------------------------

    def set_txs_available_callback(self, cb: Callable[[], None]) -> None:
        self._txs_available_cb = cb

    def _notify_txs_available(self) -> None:
        if self._txs_available_cb and not self._notified_txs_available and self._txs:
            self._notified_txs_available = True
            self._txs_available_cb()

    # -- CheckTx ingress ----------------------------------------------------

    def _cache_push(self, key: bytes) -> bool:
        if key in self._cache:
            return False
        self._cache[key] = None
        if len(self._cache) > self._cache_size:
            self._cache.popitem(last=False)
        return True

    def _tt(self):
        """The lifecycle tracker iff recording is on — one attribute read +
        one flag check when disabled (the hotstats contract)."""
        tt = self.tx_tracker
        if tt is None or not tt.enabled:
            return None
        return tt

    def _reject(self, exc: MempoolError, sender: str, key: bytes = b""):
        """Reject a tx at admission: gossiped txs (sender set) drop silently
        (the reference updates sender lists and moves on), locally submitted
        txs raise so the RPC layer can report the structured reason."""
        if self.metrics is not None:
            self.metrics.rejected_txs.labels(exc.reason).inc()
        tt = self._tt()
        if tt is not None and key:
            tt.record(key, "rejected", reason=exc.reason)
        if sender:
            return None
        raise exc

    def _sig_precheck_batch(
        self, txs: List[bytes], keys: Optional[List[bytes]] = None,
        skip_cache_peek: bool = False, sender: str = "",
    ) -> List[int]:
        """Batch-verify the signed-tx envelopes among `txs` through the
        scheduler's admission lane; returns one abci.SIG_PRECHECK_* verdict
        per tx. Runs OUTSIDE the mempool lock — concurrent callers block on
        the lane, not on each other, and their rows share device flushes.

        Skipped rows (verdict NONE, the app verifies itself): non-envelope
        txs, oversized txs (rejected before the app anyway), and txs whose
        hash is already cached (an unlocked peek — a duplicate must not pay
        a device verify; the peek is advisory, a stale answer only costs or
        saves the one verify, never correctness)."""
        from tendermint_tpu.types.signed_tx import decode_signed_tx

        verdicts = [abci.SIG_PRECHECK_NONE] * len(txs)
        if not self.sig_precheck:
            return verdicts
        rows: List[tuple] = []
        idxs: List[int] = []
        for i, tx in enumerate(txs):
            if len(tx) > self.max_tx_bytes:
                continue
            env = decode_signed_tx(tx)
            if env is None:
                continue
            if not skip_cache_peek:
                # advisory duplicate peek; the caller hands us the hash it
                # already computed (ONE sum256 per tx on the whole path)
                key = keys[i] if keys is not None else tmhash.sum256(tx)
                if key in self._cache:
                    continue
            rows.append(env)
            idxs.append(i)
        if not rows:
            return verdicts
        try:
            # provenance (crypto/provenance.py): gossiped rows carry their
            # sender so the suspicion scorer can quarantine and punish a
            # poisoning peer; local RPC submissions stay lane-tagged
            sources = [f"sender:{sender}"] * len(rows) if sender else None
            mask = self.scheduler.verify_rows(
                "admission",
                [e.pubkey for e in rows],
                [e.sign_bytes for e in rows],
                [e.signature for e in rows],
                sources=sources,
            )
        except Exception:
            # a broken scheduler must never lose txs: NONE degrades to the
            # app's own serial verify, exactly the pre-split behavior
            import logging

            logging.getLogger("tendermint_tpu.mempool").exception(
                "admission-lane precheck failed; degrading to app-side verify"
            )
            return verdicts
        self.prechecked_total += len(rows)
        for i, ok in zip(idxs, mask):
            verdicts[i] = abci.SIG_PRECHECK_OK if ok else abci.SIG_PRECHECK_BAD
        return verdicts

    PENALIZED_SENDER_QUOTA = 2  # in-flight txs allowed from a punished poisoner

    def penalize_sender(self, sender: str) -> None:
        """Punishment hook for signature poisoning (crypto/provenance.py
        punish callbacks, wired through node.py): collapse the sender's
        per-sender quota to PENALIZED_SENDER_QUOTA. Idempotent; survives
        flush() so a poisoner cannot launder its record through a commit."""
        if not sender:
            return
        with self._lock:
            self._penalized_senders.add(sender)

    def penalized_senders(self) -> frozenset:
        with self._lock:
            return frozenset(self._penalized_senders)

    def check_tx(self, tx: bytes, sender: str = "") -> Optional[abci.ResponseCheckTx]:
        """(reference: mempool/clist_mempool.go:234 CheckTx + resCbFirstTime :404)

        sender: peer ID for gossiped txs (recorded so the reactor does not
        echo the tx back, reference: mempool/reactor.go:41-96). A tx already
        in the cache from a peer returns None instead of raising (the
        reference updates the sender list and drops it silently)."""
        sig_verdict = abci.SIG_PRECHECK_NONE
        key = b""
        if self.sig_precheck:
            key = tmhash.sum256(tx)
            sig_verdict = self._sig_precheck_batch([tx], keys=[key], sender=sender)[0]
        return self._check_tx_admit(tx, sender, sig_verdict, key)

    def check_tx_batch(
        self, txs: List[bytes], sender: str = ""
    ) -> List[Optional[abci.ResponseCheckTx]]:
        """Admit a gossiped batch: ONE admission-lane submit covers every
        envelope's signature (the reactor's per-message path), then each tx
        takes the normal locked admission. Rejections of gossiped txs are
        silent per-tx (the reference's sender-list-and-move-on), so one bad
        tx never drops its batchmates."""
        keys: List[bytes] = []
        if self.sig_precheck:
            keys = [tmhash.sum256(tx) for tx in txs]
        verdicts = self._sig_precheck_batch(txs, keys=keys or None, sender=sender)
        out: List[Optional[abci.ResponseCheckTx]] = []
        for i, (tx, v) in enumerate(zip(txs, verdicts)):
            try:
                out.append(self._check_tx_admit(
                    tx, sender, v, keys[i] if keys else b""
                ))
            except MempoolError:
                if not sender:
                    raise
                out.append(None)
            except Exception:
                # a transient app/ABCI failure on ONE gossiped tx must not
                # drop its batchmates (local submissions still raise — the
                # RPC caller needs the error)
                if not sender:
                    raise
                import logging

                logging.getLogger("tendermint_tpu.mempool").exception(
                    "gossiped tx failed CheckTx; continuing with the batch"
                )
                out.append(None)
        return out

    def _check_tx_admit(
        self, tx: bytes, sender: str, sig_verdict: int, key: bytes = b""
    ) -> Optional[abci.ResponseCheckTx]:
        with self._lock:
            tt = self._tt()
            # hash EARLY only when the tracker is live (the journey needs its
            # key before the early rejects) or the precheck path already
            # computed it (passed in — never a second SHA-256 under the
            # lock); otherwise the hot path hashes at the cache point
            # exactly as before — a flood of oversized/over-quota txs costs
            # no SHA-256 under the lock
            if not key and tt is not None:
                key = tmhash.sum256(tx)
            if tt is not None:
                # journey ingress: dedupe inside the tracker (an RPC hook may
                # have stamped it already; a re-gossip of a live journey is
                # not a second receipt)
                tt.record(key, "received", via="gossip" if sender else "rpc")
            if len(tx) > self.max_tx_bytes:
                return self._reject(TxTooLargeError(len(tx), self.max_tx_bytes), sender, key)
            if sender and sender in self._penalized_senders:
                # punished poisoner: quota collapses even when the operator
                # configured unlimited per-sender admission
                if self._sender_counts.get(sender, 0) >= self.PENALIZED_SENDER_QUOTA:
                    return self._reject(
                        SenderQuotaError(sender, self.PENALIZED_SENDER_QUOTA), sender, key
                    )
            if (
                sender
                and self.max_txs_per_sender > 0
                and self._sender_counts.get(sender, 0) >= self.max_txs_per_sender
            ):
                return self._reject(SenderQuotaError(sender, self.max_txs_per_sender), sender, key)
            if self.is_full(len(tx)) and not self.eviction:
                return self._reject(MempoolFullError(), sender, key)
            if not key:
                key = tmhash.sum256(tx)
            if not self._cache_push(key):
                mtx = self._txs.get(key)
                if mtx is not None:
                    if sender:
                        mtx.senders = mtx.senders | {sender}
                        return None
                    # duplicate local submission of a RESIDENT tx: refuse
                    # the submission but never terminal the live journey —
                    # the tx is still on its way to a block, and tx_status
                    # must keep saying so (key=b"" skips the record)
                    return self._reject(TxInCacheError(), sender, b"")
                return self._reject(TxInCacheError(), sender, key)
            res = self.proxy_app.check_tx(abci.RequestCheckTx(
                tx=tx, type=abci.CHECK_TX_TYPE_NEW, sig_precheck=sig_verdict
            ))
            if tt is not None:
                tt.record(key, "checked", code=res.code, priority=res.priority)
            if res.code == abci.CODE_TYPE_OK:
                # evict only for a genuinely NEW arrival: a duplicate of a
                # resident tx whose hash churned out of the dedup cache must
                # not destroy lower-priority residents to insert nothing
                if key not in self._txs:
                    if self.is_full(len(tx)) and not self._evict_for(len(tx), res.priority):
                        # could not free room below the incoming tx's
                        # priority: drop the NEW tx, and un-cache it so it
                        # may re-enter once the pool drains
                        self._cache.pop(key, None)
                        return self._reject(
                            MempoolFullError("no evictable lower-priority txs"), sender, key
                        )
                    self._txs[key] = MempoolTx(
                        tx=tx, height=self._height, gas_wanted=res.gas_wanted,
                        senders=frozenset({sender}) if sender else frozenset(),
                        priority=res.priority, time_ns=time.time_ns(),
                        sender0=sender,
                    )
                    if sender:
                        self._sender_counts[sender] = self._sender_counts.get(sender, 0) + 1
                    self._total_bytes += len(tx)
                    self._wal_write(tx)
                    if tt is not None:
                        tt.record(key, "admitted", priority=res.priority)
                    self._notify_txs_available()
            else:
                if not self.keep_invalid_txs_in_cache:
                    self._cache.pop(key, None)
                if self.metrics is not None:
                    self.metrics.failed_txs.inc()
                if tt is not None:
                    tt.record(key, "rejected", reason="checktx", code=res.code)
            self._update_size_metrics(len(tx))
            return res

    def _update_size_metrics(self, tx_len: Optional[int] = None) -> None:
        if self.metrics is None:
            return
        self.metrics.size.set(len(self._txs))
        self.metrics.size_bytes.set(self._total_bytes)
        self.metrics.full.set(1 if self.is_full(0) else 0)
        if tx_len is not None:
            self.metrics.tx_size_bytes.observe(tx_len)

    def _remove_tx(self, key: bytes, *, drop_cache: bool) -> Optional[MempoolTx]:
        """Remove a resident tx, keeping byte totals and sender quotas
        consistent. drop_cache also forgets the hash so the tx may be
        resubmitted later (evicted/expired txs must not be poisoned)."""
        mtx = self._txs.pop(key, None)
        if mtx is None:
            return None
        self._total_bytes -= len(mtx.tx)
        if mtx.sender0:
            n = self._sender_counts.get(mtx.sender0, 0) - 1
            if n > 0:
                self._sender_counts[mtx.sender0] = n
            else:
                self._sender_counts.pop(mtx.sender0, None)
        if drop_cache:
            self._cache.pop(key, None)
        return mtx

    def _evict_for(self, tx_len: int, priority: int) -> bool:
        """Make room for an incoming (tx_len, priority) by evicting resident
        txs in (priority asc, admission order) — lowest-priority first,
        oldest first among equals; a resident tx with HIGHER priority than
        the arrival is never evicted for it (reference: the v1 priority
        mempool's CheckTx eviction). Returns False (state untouched) when
        the arrival cannot fit within that constraint."""
        victims = []
        freed_bytes = 0
        freed_slots = 0
        need_slots = len(self._txs) + 1 - self.max_txs
        need_bytes = self._total_bytes + tx_len - self.max_txs_bytes
        # stable sort over insertion order: equal priorities evict oldest
        for key, mtx in sorted(self._txs.items(), key=lambda kv: kv[1].priority):
            if freed_slots >= need_slots and freed_bytes >= need_bytes:
                break
            if mtx.priority > priority:
                return False  # only higher-priority txs left standing
            victims.append(key)
            freed_bytes += len(mtx.tx)
            freed_slots += 1
        if freed_slots < need_slots or freed_bytes < need_bytes:
            return False
        tt = self._tt()
        for key in victims:
            mtx = self._remove_tx(key, drop_cache=True)
            self.evicted_total += 1
            if self.metrics is not None:
                self.metrics.evicted_txs.inc()
            if tt is not None and mtx is not None:
                tt.record(key, "evicted", priority=mtx.priority)
        return True

    def entries(self) -> List[tuple]:
        """Snapshot [(key, tx, senders)] in insertion order (gossip walk)."""
        with self._lock:
            return [(k, m.tx, m.senders) for k, m in self._txs.items()]

    # -- proposals ----------------------------------------------------------

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """(reference: mempool/clist_mempool.go:519)"""
        with self._lock:
            out: List[bytes] = []
            total_bytes = 0
            total_gas = 0
            for mtx in self._txs.values():
                # amino/proto overhead per tx in a block: length prefix
                overhead = len(mtx.tx) + 8
                if max_bytes > -1 and total_bytes + overhead > max_bytes:
                    break
                if max_gas > -1 and total_gas + mtx.gas_wanted > max_gas:
                    break
                total_bytes += overhead
                total_gas += mtx.gas_wanted
                out.append(mtx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._lock:
            txs = [m.tx for m in self._txs.values()]
            return txs if n < 0 else txs[:n]

    # -- post-commit update -------------------------------------------------

    def update(
        self,
        height: int,
        txs: List[bytes],
        deliver_tx_responses: List[abci.ResponseDeliverTx],
    ) -> None:
        """Remove committed txs, re-check the remainder
        (reference: mempool/clist_mempool.go:570 Update + recheckTxs :632).
        Caller must hold the mempool lock."""
        self._height = height
        self._notified_txs_available = False
        for tx, res in zip(txs, deliver_tx_responses):
            key = tmhash.sum256(tx)
            if res.code == abci.CODE_TYPE_OK:
                self._cache_push(key)  # committed: keep in cache to block replays
            else:
                if not self.keep_invalid_txs_in_cache:
                    self._cache.pop(key, None)
            self._remove_tx(key, drop_cache=False)
        self._purge_expired()
        if self.recheck and self._txs:
            if self.metrics is not None:
                self.metrics.recheck_times.inc()
            self._recheck_txs()
        self._update_size_metrics()
        if self._txs:
            self._notify_txs_available()

    def _purge_expired(self) -> None:
        """TTL purge (reference: v0.35 mempool TTLNumBlocks/TTLDuration):
        drop txs admitted more than ttl_num_blocks blocks ago or older than
        ttl_seconds, un-caching them so a later resubmission is accepted.
        Caller holds the lock; runs on every post-commit update."""
        if self.ttl_num_blocks <= 0 and self.ttl_seconds <= 0:
            return
        now_ns = time.time_ns()
        expired = [
            key
            for key, mtx in self._txs.items()
            if (
                self.ttl_num_blocks > 0
                and self._height - mtx.height >= self.ttl_num_blocks
            )
            or (
                self.ttl_seconds > 0
                and now_ns - mtx.time_ns >= self.ttl_seconds * 1e9
            )
        ]
        tt = self._tt()
        for key in expired:
            self._remove_tx(key, drop_cache=True)
            self.expired_total += 1
            if self.metrics is not None:
                self.metrics.expired_txs.inc()
            if tt is not None:
                tt.record(key, "expired", height=self._height)

    def _recheck_txs(self) -> None:
        tt = self._tt()
        keys = list(self._txs.keys())
        # post-commit recheck is admission-shaped: with the scheduler wired,
        # every resident envelope's signature re-verifies in ONE admission-
        # lane batch (residents are cached by definition, so the duplicate
        # peek is skipped) instead of a serial app-side verify per tx per
        # block — the recheck loop was the last serial verify loop standing
        verdicts = [abci.SIG_PRECHECK_NONE] * len(keys)
        if self.sig_precheck and keys:
            verdicts = self._sig_precheck_batch(
                [self._txs[k].tx for k in keys], skip_cache_peek=True
            )
        for key, verdict in zip(keys, verdicts):
            mtx = self._txs.get(key)
            if mtx is None:
                continue
            res = self.proxy_app.check_tx(
                abci.RequestCheckTx(
                    tx=mtx.tx, type=abci.CHECK_TX_TYPE_RECHECK,
                    sig_precheck=verdict,
                )
            )
            if res.code != abci.CODE_TYPE_OK:
                self._remove_tx(
                    key, drop_cache=not self.keep_invalid_txs_in_cache
                )
                # the journey must not read "admitted" forever after the
                # node silently dropped the tx on a failed recheck
                if tt is not None:
                    tt.record(key, "rejected", reason="recheck", code=res.code)
