"""ValidatorSet.verify_aggregate_commit + mixed-backend valsets (ISSUE 14).

Covers the acceptance criteria: device-path (ops/bls12_msm twin) verdicts
byte-identical to a pure bls_ref recomputation on real curve points —
including tampered-signature and rogue-key (no-PoP) rejections — the
per-signature fallback routing, the mixed ed25519+BLS validator set path
with a corrupted row in each arm, and the new backend/aggregate metrics.
"""

import dataclasses
import random

import numpy as np
import pytest

from tendermint_tpu.crypto import bls_ref as B
from tendermint_tpu.crypto import keys as K
from tendermint_tpu.crypto.batch import verify_batch
from tendermint_tpu.libs import metrics as M
from tendermint_tpu.ops import bls12_msm
from tendermint_tpu.types.basic import BlockID, BlockIDFlag, PartSetHeader, SignedMsgType
from tendermint_tpu.types.block import AggregateCommit, Commit, CommitSig
from tendermint_tpu.types.validator_set import (
    CommitVerifyError,
    NotEnoughVotingPowerError,
    Validator,
    ValidatorSet,
)
from tendermint_tpu.types.vote import Vote

CHAIN = "bls-commit-chain"
BID = BlockID(b"\x07" * 32, PartSetHeader(1, b"\x08" * 32))


@pytest.fixture(autouse=True)
def _fresh_pop_registry():
    K.clear_pop_registry()
    yield
    K.clear_pop_registry()


def bls_valset(n, power=10, seed=0x50):
    privs = [K.gen_bls12_381(bytes([seed + i]) * 32) for i in range(n)]
    vals = ValidatorSet([Validator(p.pub_key(), power) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    ordered = [by_addr[v.address] for v in vals.validators]
    return vals, ordered


def register_all(privs):
    for p in privs:
        assert K.register_pop(p.pub_key().bytes(), p.pop_prove())


def make_agg(vals, privs, idxs, height=5, ts=123456789, chain=CHAIN):
    proto = AggregateCommit(
        height, 0, BID, ts, AggregateCommit.bitmap_of(idxs, vals.size()), b"\x00" * 96
    )
    msg = proto.sign_bytes(chain)
    sig = B.aggregate_signatures([privs[i].sign(msg) for i in idxs])
    return dataclasses.replace(proto, agg_signature=sig)


def ref_verdict(vals, agg, chain=CHAIN) -> bool:
    """Pure-bls_ref recomputation of the aggregate check (the referee)."""
    idxs = agg.signer_indices()
    pks = [vals.validators[i].pub_key.bytes() for i in idxs]
    if not all(K.pop_verified(pk) for pk in pks):
        return False
    if not B.fast_aggregate_verify(pks, agg.sign_bytes(chain), agg.agg_signature):
        return False
    tallied = sum(vals.validators[i].voting_power for i in idxs)
    return tallied > vals.total_voting_power() * 2 // 3


def kernel_verdict(vals, agg, chain=CHAIN) -> bool:
    try:
        vals.verify_aggregate_commit(chain, BID, agg.height, agg)
        return True
    except (CommitVerifyError, NotEnoughVotingPowerError):
        return False


def test_aggregate_commit_accepts_and_apk_byte_identical():
    vals, privs = bls_valset(7)
    register_all(privs)
    agg = make_agg(vals, privs, list(range(7)))
    vals.verify_aggregate_commit(CHAIN, BID, 5, agg)
    # the device-schedule MSM twin's aggregate pubkey is BYTE-identical to
    # bls_ref's jacobian aggregation (compressed-G1 encoding compared)
    idxs = agg.signer_indices()
    coords = []
    for i in idxs:
        pt = B.g1_from_bytes(vals.validators[i].pub_key.bytes())
        a = B._jac_to_affine(pt)
        coords.append((a[0].v, a[1].v))
    apk = bls12_msm.g1_aggregate_bitmap(coords, [True] * len(coords))
    apk_jac = (B._G1Field(apk[0]), B._G1Field(apk[1]), B._G1Field(1))
    ref = B.aggregate_pubkeys([vals.validators[i].pub_key.bytes() for i in idxs])
    assert B.g1_to_bytes(apk_jac) == B.g1_to_bytes(ref)


def test_device_vs_ref_verdicts_byte_identical():
    """Acceptance criterion: kernel-path and bls_ref verdicts agree on real
    curve points for valid / tampered / rogue-key / subthreshold cases."""
    vals, privs = bls_valset(6)
    register_all(privs)
    good = make_agg(vals, privs, list(range(6)))
    tampered = dataclasses.replace(
        good,
        agg_signature=bytes(
            bytearray(good.agg_signature[:-1]) + bytes([good.agg_signature[-1] ^ 1])
        ),
    )
    subthreshold = make_agg(vals, privs, [0, 1])
    cases = [good, tampered, subthreshold]
    for agg in cases:
        assert kernel_verdict(vals, agg) == ref_verdict(vals, agg)
    assert kernel_verdict(vals, good) is True
    assert kernel_verdict(vals, tampered) is False
    # rogue-key: drop one signer's PoP -> both sides must now reject
    K.clear_pop_registry()
    register_all(privs[:-1])
    assert kernel_verdict(vals, good) is False
    assert ref_verdict(vals, good) is False


def test_aggregate_commit_structural_rejections():
    vals, privs = bls_valset(4)
    register_all(privs)
    agg = make_agg(vals, privs, [0, 1, 2, 3])
    with pytest.raises(CommitVerifyError):
        vals.verify_aggregate_commit(CHAIN, BID, 6, agg)  # wrong height
    with pytest.raises(CommitVerifyError):
        vals.verify_aggregate_commit(
            CHAIN, BlockID(b"\x09" * 32, PartSetHeader(1, b"\x08" * 32)), 5, agg
        )
    # out-of-range signer bit
    bad = dataclasses.replace(agg, signers=b"\xff\xff")
    with pytest.raises(CommitVerifyError):
        vals.verify_aggregate_commit(CHAIN, BID, 5, bad)
    # malformed aggregate signature bytes
    bad = dataclasses.replace(agg, agg_signature=b"\x00" * 96)
    with pytest.raises(CommitVerifyError):
        vals.verify_aggregate_commit(CHAIN, BID, 5, bad)
    # a different canonical timestamp changes the signed message
    bad = dataclasses.replace(agg, timestamp_ns=agg.timestamp_ns + 1)
    with pytest.raises(CommitVerifyError):
        vals.verify_aggregate_commit(CHAIN, BID, 5, bad)


def test_aggregate_commit_codec_round_trip():
    vals, privs = bls_valset(4)
    register_all(privs)
    agg = make_agg(vals, privs, [0, 2])
    assert AggregateCommit.decode(agg.encode()) == agg
    assert agg.signer_indices() == [0, 2]
    assert agg.has_signer(2) and not agg.has_signer(1) and not agg.has_signer(99)


def ed_commit(vals, privs, height=5, corrupt_idx=None):
    css = []
    for i, (v, p) in enumerate(zip(vals.validators, privs)):
        vote = Vote(
            type=SignedMsgType.PRECOMMIT,
            height=height,
            round=0,
            block_id=BID,
            timestamp_ns=1,
            validator_address=v.address,
            validator_index=i,
        )
        sig = p.sign(vote.sign_bytes(CHAIN))
        if i == corrupt_idx:
            sig = bytes(bytearray(sig[:-1]) + bytes([sig[-1] ^ 1]))
        css.append(CommitSig(BlockIDFlag.COMMIT, v.address, 1, sig))
    return Commit(height, 0, BID, tuple(css))


def test_plain_commit_fallback_routes_through_verify_batch_ladder():
    privs = [K.gen_ed25519(bytes([i + 1]) * 32) for i in range(4)]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    ordered = [{p.pub_key().address(): p for p in privs}[v.address] for v in vals.validators]
    commit = ed_commit(vals, ordered)
    # verify_aggregate_commit with a plain Commit == verify_commit
    vals.verify_aggregate_commit(CHAIN, BID, 5, commit)
    with pytest.raises(CommitVerifyError):
        vals.verify_aggregate_commit(CHAIN, BID, 5, ed_commit(vals, ordered, corrupt_idx=2))


# -- mixed-backend validator sets (satellite) --------------------------------


def mixed_valset(n_ed=3, n_bls=3):
    ed = [K.gen_ed25519(bytes([i + 1]) * 32) for i in range(n_ed)]
    bls = [K.gen_bls12_381(bytes([i + 0x70]) * 32) for i in range(n_bls)]
    privs = ed + bls
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    by_addr = {p.pub_key().address(): p for p in privs}
    return vals, [by_addr[v.address] for v in vals.validators]


def test_mixed_ed25519_bls_commit_verifies_per_type():
    """A valset holding BOTH ed25519 and BLS validators verifies a plain
    commit through the per-type split (ed rows -> the batch ladder, BLS
    rows -> bls_ref), mirroring the existing ed25519/sr25519 mixed path."""
    vals, ordered = mixed_valset()
    commit = ed_commit(vals, ordered)
    vals.verify_commit(CHAIN, BID, 5, commit)
    vals.verify_commit_light(CHAIN, BID, 5, commit)


@pytest.mark.parametrize("corrupt_type", ["ed25519", "bls12_381"])
def test_mixed_commit_corrupted_row_in_each_arm(corrupt_type):
    vals, ordered = mixed_valset()
    corrupt_idx = next(
        i for i, v in enumerate(vals.validators) if v.pub_key.type_name() == corrupt_type
    )
    commit = ed_commit(vals, ordered, corrupt_idx=corrupt_idx)
    with pytest.raises(CommitVerifyError):
        vals.verify_commit(CHAIN, BID, 5, commit)


def test_mixed_valset_hash_covers_bls_keys():
    vals, _ = mixed_valset()
    assert len(vals.hash()) == 32  # simple_bytes handles bls12_381 keys


def test_verify_batch_mixed_bls_rows():
    ed = K.gen_ed25519(b"\x01" * 32)
    bls = K.gen_bls12_381(b"\x61" * 32)
    msgs = [b"m0", b"m1", b"m2", b"m3"]
    pks = [ed.pub_key().bytes(), bls.pub_key().bytes(), bls.pub_key().bytes(), b"\x00" * 48]
    sigs = [ed.sign(b"m0"), bls.sign(b"m1"), bls.sign(b"WRONG"), b"\x00" * 96]
    mask = verify_batch(
        pks, msgs, sigs, key_types=["ed25519", "bls12_381", "bls12_381", "bls12_381"]
    )
    assert mask.tolist() == [True, True, False, False]


# -- metrics -----------------------------------------------------------------


def test_backend_series_and_aggregate_size_gauge():
    m = M.batch_metrics()

    def val(metric, *labels):
        return metric._values.get(tuple(labels), 0.0)

    base_rows = val(m.backend_rows, "bls12_381")
    base_fl = val(m.backend_flushes, "bls12_381")
    vals, privs = bls_valset(5)
    register_all(privs)
    agg = make_agg(vals, privs, list(range(5)))
    vals.verify_aggregate_commit(CHAIN, BID, 5, agg)
    assert val(m.backend_rows, "bls12_381") == base_rows + 5
    assert val(m.backend_flushes, "bls12_381") == base_fl + 1
    assert val(m.aggregate_size) == 5
    # ed25519 rows attributed on the plain path
    base_ed = val(m.backend_rows, "ed25519")
    ed = K.gen_ed25519(b"\x05" * 32)
    verify_batch([ed.pub_key().bytes()] * 3, [b"x"] * 3, [ed.sign(b"x")] * 3)
    assert val(m.backend_rows, "ed25519") == base_ed + 3


def test_bls_rows_ride_scheduler_qos_lanes():
    """BLS rows submitted inside a scheduler lane scope join the node-wide
    combined flush like every other key type, verdicts unchanged."""
    from tendermint_tpu.crypto import scheduler as S

    ed = K.gen_ed25519(b"\x02" * 32)
    bls = K.gen_bls12_381(b"\x62" * 32)
    pks = [ed.pub_key().bytes(), bls.pub_key().bytes(), bls.pub_key().bytes()]
    msgs = [b"l0", b"l1", b"l2"]
    sigs = [ed.sign(b"l0"), bls.sign(b"l1"), bls.sign(b"BAD")]
    kts = ["ed25519", "bls12_381", "bls12_381"]
    expect = verify_batch(pks, msgs, sigs, "cpu", key_types=kts)
    s = S.VerifyScheduler(backend="cpu")
    try:
        with s.lane_scope("catchup"):
            got = verify_batch(pks, msgs, sigs, key_types=kts)
        assert (got == expect).all() and got.tolist() == [True, True, False]
        assert s.stats()["lanes"]["catchup"]["rows_total"] == 3
    finally:
        s.close()


def test_prewarm_bls_is_flag_gated():
    from tendermint_tpu.crypto import batch as batch_mod

    called = []
    orig = batch_mod._prewarm_bls
    batch_mod._prewarm_bls = lambda: called.append(1)
    try:
        batch_mod.prewarm(4, backend="cpu", bls=False)
        assert not called
        batch_mod.prewarm(4, backend="cpu", bls=True)
        assert called
    finally:
        batch_mod._prewarm_bls = orig


def test_prewarm_bls_runs():
    from tendermint_tpu.crypto.batch import _prewarm_bls

    _prewarm_bls()  # must not raise; warms tables + MSM bucket
