"""Miller-loop line evaluations + Fp12 tower arithmetic: Pallas kernels & twins.

The pairing half of the BLS device backend (the MSM half is
ops/bls12_msm.py). The optimal ate pairing is ~64 doubling steps of

    f <- f^2 * line_{T,T}(P) ;  T <- 2T      (+ a sparse add-step on the
                                               6 set bits of |x|)

and every term is batched-field-multiply shaped — exactly the workload the
fused ed25519 pipeline already runs at 160 G int32-mul/s. This module
provides, in the pallas_fe idiom:

- CPU-TWIN tower ops over ops/fp381 limb rows: Fp2 (Karatsuba, 3 base
  muls), the w-basis Fp12 (6 Fp2 coefficients over {1..w^5}, w^6 = XI),
  full/sparse Fp12 products, and the DIVISION-FREE projective line
  coefficients for both Miller steps. Lines are kept in the M-twist sparse
  form (c0, c3, c5) — the only nonzero w-basis coefficients of an
  untwisted line — so the f update is an 18-Fp2-mul sparse product
  instead of a 36-mul full one.
- A full `miller_loop_rows` twin (batched over independent (P, Q) lanes)
  whose output equals crypto/bls_ref.miller_loop up to the subfield
  factors that die in the final exponentiation; tests pin
  final_exp(kernel twin) == final_exp(bls_ref) on real curve points.
- The Pallas kernels themselves: `fp381_mul` (the base-field Montgomery
  product every stage is made of) and `fp12_sparse_mul` (one fused
  f * line step). Layout matches pallas_fe: int32[NLIMBS, S, 128], limb
  rows as full (sublane, lane) tiles; enabled on TPU (TMTPU_PALLAS=0
  disables, =interpret runs the Mosaic interpreter).

Projective line derivation (recorded because the twist wiring is the
error-prone part): with the M-twist untwist (x', y') -> (x'/w^2, y'/w^3)
and w^-2 = XI^-1 v w^... the line through the untwisted T at P = (xP, yP):

    l(P) = yP + (lam*x_T - y_T) * XI^-1 * w^3 - lam*xP * XI^-1 * w^5

Scaling by the Fp2 subfield factors 2YZ^2 (doubling, lam = 3X^2/2YZ) or
X - xQ*Z (addition, lam = (Y - yQ*Z)/(X - xQ*Z)) makes the coefficients
polynomial — subfield scale factors are killed by the final exponentiation
(their order divides p^2 - 1, which divides (p^12 - 1)/r):

    dbl:  c0 = 2*Y*Z^2*yP         add:  c0 = (X - xQ*Z)*yP
          c3 = (3X^3 - 2Y^2*Z)*XI^-1    c3 = ((Y - yQ*Z)*xQ - (X - xQ*Z)*yQ)*XI^-1
          c5 = -(3X^2*Z*xP)*XI^-1       c5 = -((Y - yQ*Z)*xP)*XI^-1

T itself advances with the complete RCB addition over Fp2 (b3 = 12*XI),
so the step needs no exceptional-case lanes.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np

from tendermint_tpu.ops import fp381 as F

NLIMBS = F.NLIMBS
LANE = 128
BLK = 8  # sublane groups per grid step

# Fp2 element: (c0_rows, c1_rows). Fp12 element: list of 6 Fp2 (w-basis).
Fp2Rows = Tuple[List, List]

# XI^-1 = (1 + u)^-1 = (1 - u)/2: components (1/2, -1/2)
_INV2 = pow(2, F.P - 2, F.P)
XI_INV_C0 = _INV2
XI_INV_C1 = (-_INV2) % F.P
X_PARAM_ABS = 0xD201000000010000


def _const2(c0: int, c1: int, batch_shape, xp=np) -> Fp2Rows:
    def bc(v):
        limbs = F.mont_from_int(v)
        a = xp.broadcast_to(
            xp.asarray(limbs).reshape((NLIMBS,) + (1,) * len(batch_shape)),
            (NLIMBS, *batch_shape),
        ).astype(np.int32)
        return [a[i] for i in range(NLIMBS)]

    return (bc(c0), bc(c1))


# --------------------------------------------------------------------------
# Fp2 over limb rows


def add2(a: Fp2Rows, b: Fp2Rows) -> Fp2Rows:
    return (F.add_rows(a[0], b[0]), F.add_rows(a[1], b[1]))


def sub2(a: Fp2Rows, b: Fp2Rows) -> Fp2Rows:
    return (F.sub_rows(a[0], b[0]), F.sub_rows(a[1], b[1]))


def mul2(a: Fp2Rows, b: Fp2Rows) -> Fp2Rows:
    """Karatsuba: 3 base-field Montgomery muls."""
    t0 = F.mul_rows(a[0], b[0])
    t1 = F.mul_rows(a[1], b[1])
    t2 = F.mul_rows(F.add_rows(a[0], a[1]), F.add_rows(b[0], b[1]))
    # c0 = t0 - t1 ; c1 = t2 - t0 - t1  (subtrahends are mul/add outputs)
    return (F.sub_rows(t0, t1), F.sub_rows(t2, F.add_rows(t0, t1)))


def square2(a: Fp2Rows) -> Fp2Rows:
    return mul2(a, a)


def mul2_small(a: Fp2Rows, k: int) -> Fp2Rows:
    return (F.mul_small_rows(a[0], k), F.mul_small_rows(a[1], k))


def mul2_by_xi(a: Fp2Rows) -> Fp2Rows:
    """(c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u. Both components are
    folded (sub folds internally) so a xi output is subtrahend-safe."""
    return (
        F.sub_rows(a[0], a[1]),
        F.fold_top_rows(F.add_rows(a[0], a[1])),
    )


def mul2_fp(a: Fp2Rows, s: List) -> Fp2Rows:
    """Fp2 * base-field scalar rows (per-lane)."""
    return (F.mul_rows(a[0], s), F.mul_rows(a[1], s))


def neg2(a: Fp2Rows) -> Fp2Rows:
    z = [r - r for r in a[0]]
    return (F.sub_rows(z, a[0]), F.sub_rows(z, a[1]))


# --------------------------------------------------------------------------
# complete G2 point addition (RCB alg 7 over Fp2, b3 = 12 * XI)


def padd2(p, q):
    """p, q: (X, Y, Z) Fp2Rows triples, homogeneous projective; complete."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    t0 = mul2(X1, X2)
    t1 = mul2(Y1, Y2)
    t2 = mul2(Z1, Z2)
    t3 = sub2(mul2(add2(X1, Y1), add2(X2, Y2)), add2(t0, t1))
    t4 = sub2(mul2(add2(Y1, Z1), add2(Y2, Z2)), add2(t1, t2))
    t0_3 = add2(add2(t0, t0), t0)
    # b3 * t2 with the *12 BEFORE the xi twist: scaling a xi output (whose
    # c0 is a sub result) by 12 would exceed the Montgomery value bound
    t2b = mul2_by_xi(mul2_small(t2, 12))
    z3 = add2(t1, t2b)
    t1s = sub2(t1, t2b)
    # y3 = b3 * (X1Z2 + X2Z1), b3 distributed into both sub operands
    txz = mul2(add2(X1, Z1), add2(X2, Z2))
    y3 = sub2(
        mul2_by_xi(mul2_small(txz, 12)), mul2_by_xi(mul2_small(add2(t0, t2), 12))
    )
    X3 = sub2(mul2(t3, t1s), mul2(t4, y3))
    Y3 = add2(mul2(t1s, z3), mul2(y3, t0_3))
    Z3 = add2(mul2(z3, t4), mul2(t0_3, t3))
    return (X3, Y3, Z3)


# --------------------------------------------------------------------------
# Fp12 (w-basis: 6 Fp2 coefficients, w^6 = XI)


def mul12(a: Sequence[Fp2Rows], b: Sequence[Fp2Rows]) -> List[Fp2Rows]:
    """Full 6x6 w-basis product with XI folding for powers >= 6."""
    acc = [None] * 11
    for i in range(6):
        for j in range(6):
            t = mul2(a[i], b[j])
            k = i + j
            acc[k] = t if acc[k] is None else add2(acc[k], t)
    out = []
    for k in range(6):
        hi = acc[k + 6] if k + 6 < 11 else None
        out.append(add2(acc[k], mul2_by_xi(hi)) if hi is not None else acc[k])
    return out


def square12(a: Sequence[Fp2Rows]) -> List[Fp2Rows]:
    return mul12(a, a)


def sparse_mul12(f: Sequence[Fp2Rows], line) -> List[Fp2Rows]:
    """f * (c0 + c3 w^3 + c5 w^5): 18 Fp2 muls."""
    c0, c3, c5 = line
    acc = [None] * 11
    for j, c in ((0, c0), (3, c3), (5, c5)):
        for i in range(6):
            t = mul2(f[i], c)
            k = i + j
            acc[k] = t if acc[k] is None else add2(acc[k], t)
    out = []
    for k in range(6):
        hi = acc[k + 6] if k + 6 < 11 else None
        out.append(add2(acc[k], mul2_by_xi(hi)) if hi is not None else acc[k])
    return out


def conj12(a: Sequence[Fp2Rows]) -> List[Fp2Rows]:
    """x -> x^(p^6): negate the odd w-basis coefficients."""
    return [c if m % 2 == 0 else neg2(c) for m, c in enumerate(a)]


def one12(batch_shape, xp=np) -> List[Fp2Rows]:
    one = _const2(1, 0, batch_shape, xp)
    zero = _const2(0, 0, batch_shape, xp)
    return [one] + [zero] * 5


# --------------------------------------------------------------------------
# Miller-loop line coefficients (sparse (c0, c3, c5); see module docstring)


def line_dbl(T, xP: List, yP: List, xi_inv: Fp2Rows):
    X, Y, Z = T
    X2 = mul2(X, X)
    Y2 = mul2(Y, Y)
    Z2 = mul2(Z, Z)
    X2_3 = add2(add2(X2, X2), X2)  # 3X^2
    YZ2 = mul2(Y, Z2)
    c0 = mul2_fp(add2(YZ2, YZ2), yP)  # 2YZ^2 * yP
    # 3X^3 - 2Y^2 Z
    t = sub2(mul2(X2_3, X), mul2(add2(Y2, Y2), Z))
    c3 = mul2(t, xi_inv)
    c5 = mul2(neg2(mul2_fp(mul2(X2_3, Z), xP)), xi_inv)
    return (c0, c3, c5)


def line_add(T, Qx: Fp2Rows, Qy: Fp2Rows, xP: List, yP: List, xi_inv: Fp2Rows):
    X, Y, Z = T
    N = sub2(Y, mul2(Qy, Z))  # Y - yQ Z
    D = sub2(X, mul2(Qx, Z))  # X - xQ Z
    c0 = mul2_fp(D, yP)
    c3 = mul2(sub2(mul2(N, Qx), mul2(D, Qy)), xi_inv)
    c5 = mul2(neg2(mul2_fp(N, xP)), xi_inv)
    return (c0, c3, c5)


def miller_loop_rows(
    q_coords: Sequence[Tuple[int, int, int, int]],
    p_coords: Sequence[Tuple[int, int]],
    xp=np,
) -> List[Fp2Rows]:
    """Batched Miller loop over independent lanes.

    q_coords: affine G2 points as (x_c0, x_c1, y_c0, y_c1) ints;
    p_coords: affine G1 points as (x, y) ints. Returns the UNREDUCED
    pairing values (w-basis Fp12 rows) — equal to bls_ref.miller_loop up
    to subfield factors; apply bls_ref.final_exponentiation to compare."""
    n = len(q_coords)
    if n != len(p_coords):
        raise ValueError("q/p length mismatch")

    def fp_rows(vals):
        arr = np.zeros((NLIMBS, n), dtype=np.int32)
        for j, v in enumerate(vals):
            arr[:, j] = F.mont_from_int(v)
        a = xp.asarray(arr)
        return [a[i] for i in range(NLIMBS)]

    Qx = (fp_rows([q[0] for q in q_coords]), fp_rows([q[1] for q in q_coords]))
    Qy = (fp_rows([q[2] for q in q_coords]), fp_rows([q[3] for q in q_coords]))
    xP = fp_rows([p[0] for p in p_coords])
    yP = fp_rows([p[1] for p in p_coords])
    one2 = _const2(1, 0, (n,), xp)
    xi_inv = _const2(XI_INV_C0, XI_INV_C1, (n,), xp)
    T = (Qx, Qy, one2)
    f = one12((n,), xp)
    for bit in bin(X_PARAM_ABS)[3:]:
        f = sparse_mul12(square12(f), line_dbl(T, xP, yP, xi_inv))
        T = padd2(T, T)
        if bit == "1":
            f = sparse_mul12(f, line_add(T, Qx, Qy, xP, yP, xi_inv))
            T = padd2(T, (Qx, Qy, one2))
    # negative BLS parameter: conjugate (bls_ref.miller_loop does the same)
    return conj12(f)


def fp12_rows_to_ref(f: Sequence[Fp2Rows], lane: int = 0):
    """One lane -> a bls_ref.Fp12 (for final exponentiation / comparison)."""
    from tendermint_tpu.crypto import bls_ref as B

    coeffs = []
    for c in f:
        c0 = F.mont_to_ints(np.stack([np.asarray(r) for r in c[0]]).reshape(NLIMBS, -1)[:, lane : lane + 1])[0]
        c1 = F.mont_to_ints(np.stack([np.asarray(r) for r in c[1]]).reshape(NLIMBS, -1)[:, lane : lane + 1])[0]
        coeffs.append(B.Fp2(c0, c1))
    return B.Fp12.from_wcoeffs(coeffs)


# --------------------------------------------------------------------------
# Pallas kernels (TPU; gated exactly like ops/pallas_fe.py)


def _mode() -> str:
    return os.environ.get("TMTPU_PALLAS", "auto")


def enabled() -> bool:
    m = _mode()
    if m == "0":
        return False
    if m == "interpret":
        return True
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _interpret() -> bool:
    return _mode() == "interpret"


def _fp381_mul_kernel(a_ref, b_ref, o_ref):
    """One VMEM-resident Montgomery product over a (NLIMBS, BLK, 128)
    block: the row-list algorithm of fp381._mul_rows_loop verbatim — every
    intermediate stays in registers/VMEM instead of 65 HBM-materialized
    accumulator rows (the fe25519 lesson, pallas_fe.py)."""
    a = [a_ref[i] for i in range(NLIMBS)]
    b = [b_ref[i] for i in range(NLIMBS)]
    out = F._mul_rows_loop(a, b)
    for i in range(NLIMBS):
        o_ref[i] = out[i]


def fp381_mul(a, b):
    """Batched base-field product via the Pallas kernel. a, b: int32
    (NLIMBS, S, 128) (lane-tiled; wrappers pad like pallas_fe)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = a.shape[1]
    grid = (max(1, s // BLK),)
    blk = min(BLK, s)
    spec = pl.BlockSpec(
        (NLIMBS, blk, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _fp381_mul_kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        interpret=_interpret(),
    )(a, b)


def _fp12_sparse_mul_kernel(*refs):
    """f (12 row-planes: 6 Fp2 coeffs x 2 components) * sparse line
    (c0, c3, c5): one fused kernel per grid block — 18 Fp2 products whose
    intermediates never leave VMEM."""
    f_refs, line_refs, out_refs = refs[:12], refs[12:18], refs[18:]
    f = [
        ([f_refs[2 * m][i] for i in range(NLIMBS)], [f_refs[2 * m + 1][i] for i in range(NLIMBS)])
        for m in range(6)
    ]
    line = [
        ([line_refs[2 * m][i] for i in range(NLIMBS)], [line_refs[2 * m + 1][i] for i in range(NLIMBS)])
        for m in range(3)
    ]
    out = sparse_mul12(f, line)
    for m in range(6):
        for i in range(NLIMBS):
            out_refs[2 * m][i] = out[m][0][i]
            out_refs[2 * m + 1][i] = out[m][1][i]


def fp12_sparse_mul(f_planes, line_planes):
    """f_planes: 12 arrays (NLIMBS, S, 128); line_planes: 6 arrays same
    shape. Returns 12 output planes."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = f_planes[0].shape[1]
    grid = (max(1, s // BLK),)
    blk = min(BLK, s)
    spec = pl.BlockSpec(
        (NLIMBS, blk, LANE), lambda i: (0, i, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _fp12_sparse_mul_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(f_planes[0].shape, f_planes[0].dtype)
            for _ in range(12)
        ],
        grid=grid,
        in_specs=[spec] * 18,
        out_specs=[spec] * 12,
        interpret=_interpret(),
    )(*f_planes, *line_planes)
