"""Proposal type (reference: types/proposal.go)."""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.libs import protowire as pw
from tendermint_tpu.types import canonical
from tendermint_tpu.types.basic import BlockID, SignedMsgType, ts_seconds_nanos


@dataclass(frozen=True)
class Proposal:
    height: int
    round: int
    pol_round: int  # -1 when there is no POL
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""

    type: SignedMsgType = SignedMsgType.PROPOSAL

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(
            chain_id, self.height, self.round, self.pol_round, self.block_id, self.timestamp_ns
        )

    def validate_basic(self) -> None:
        if self.type != SignedMsgType.PROPOSAL:
            raise ValueError("invalid proposal type")
        if self.height < 0:
            raise ValueError("negative height")
        if self.round < 0:
            raise ValueError("negative round")
        if self.pol_round < -1 or (self.pol_round >= self.round and self.pol_round != -1):
            # reference: types/proposal.go ValidateBasic: -1 <= polRound < round
            raise ValueError("invalid POLRound")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError("expected a complete, non-empty BlockID")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > 64:
            raise ValueError("signature too big")

    def with_signature(self, sig: bytes) -> "Proposal":
        return replace(self, signature=sig)

    def encode(self) -> bytes:
        w = pw.Writer()
        w.varint_field(1, int(self.type))
        w.varint_field(2, self.height)
        w.varint_field(3, self.round)
        w.varint_field(4, self.pol_round)
        w.message_field(5, self.block_id.encode(), always=True)
        sec, nanos = ts_seconds_nanos(self.timestamp_ns)
        w.message_field(6, pw.encode_timestamp(sec, nanos), always=True)
        w.bytes_field(7, self.signature)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        height = round_ = 0
        pol_round = 0
        block_id = BlockID()
        ts = 0
        sig = b""
        for f, _, v in pw.Reader(data):
            if f == 2:
                height = pw.int64_from_varint(v)
            elif f == 3:
                round_ = pw.int64_from_varint(v)
            elif f == 4:
                pol_round = pw.int64_from_varint(v)
            elif f == 5:
                block_id = BlockID.decode(v)
            elif f == 6:
                sec = nanos = 0
                for ff, _, vv in pw.Reader(v):
                    if ff == 1:
                        sec = pw.int64_from_varint(vv)
                    elif ff == 2:
                        nanos = pw.int64_from_varint(vv)
                ts = sec * 1_000_000_000 + nanos
            elif f == 7:
                sig = v
        return cls(
            height=height,
            round=round_,
            pol_round=pol_round,
            block_id=block_id,
            timestamp_ns=ts,
            signature=sig,
        )
