"""vote_sign_bytes_many must be byte-identical to the per-row builder."""

from tendermint_tpu.types import canonical
from tendermint_tpu.types.basic import SignedMsgType
from tendermint_tpu.types.block import BlockID, PartSetHeader


def test_vote_sign_bytes_many_matches_per_row():
    bid = BlockID(b"\x01" * 32, PartSetHeader(3, b"\x02" * 32))
    nil = BlockID(b"", PartSetHeader(0, b""))
    rows = [
        (bid, 0),
        (nil, 0),
        (bid, 1),
        (bid, 1_700_000_000_123_456_789),
        (None, 5),
        (bid, 999_999_999),  # nanos boundary
        (nil, 1 << 40),
    ]
    for msg_type in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
        for h, r in ((1, 0), (12345, 7), (1 << 40, 2)):
            many = canonical.vote_sign_bytes_many("chain-x", msg_type, h, r, rows)
            for got, (b, ts) in zip(many, rows):
                exp = canonical.vote_sign_bytes("chain-x", msg_type, h, r, b, ts)
                assert got == exp


def test_commit_vote_sign_bytes_many_matches_per_row():
    import dataclasses

    from tendermint_tpu.crypto.keys import gen_ed25519
    from tendermint_tpu.types.basic import BlockIDFlag
    from tendermint_tpu.types.block import Commit, CommitSig

    bid = BlockID(b"\x03" * 32, PartSetHeader(2, b"\x04" * 32))
    sigs = []
    for i in range(6):
        flag = [BlockIDFlag.COMMIT, BlockIDFlag.NIL, BlockIDFlag.COMMIT][i % 3]
        sigs.append(
            CommitSig(flag, bytes([i + 1]) * 20, 1000 + i, bytes([i]) * 64)
        )
    commit = Commit(9, 1, bid, tuple(sigs))
    idxs = [0, 2, 3, 5]
    many = commit.vote_sign_bytes_many("c", idxs)
    for got, i in zip(many, idxs):
        assert got == commit.vote_sign_bytes("c", i)
