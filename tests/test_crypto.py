"""Host crypto: ed25519 (cryptography ↔ pure-python RFC 8032 cross-check), addresses."""

import os

import pytest

from tendermint_tpu.crypto import (
    Ed25519PrivKey,
    Ed25519PubKey,
    gen_ed25519,
)
from tendermint_tpu.crypto import ed25519_ref as ref
from tendermint_tpu.crypto import tmhash

from tests.conftest import requires_cryptography


def test_rfc8032_test_vector_1():
    # RFC 8032 §7.1 TEST 1 (empty message)
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    pub = bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig = bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert ref.public_key(seed) == pub
    assert ref.sign(seed, b"") == sig
    assert ref.verify(pub, b"", sig)
    assert not ref.verify(pub, b"x", sig)


def test_rfc8032_test_vector_2():
    seed = bytes.fromhex(
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
    )
    pub = bytes.fromhex(
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c"
    )
    msg = bytes.fromhex("72")
    sig = bytes.fromhex(
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
    )
    assert ref.public_key(seed) == pub
    assert ref.sign(seed, msg) == sig
    assert ref.verify(pub, msg, sig)


def test_host_and_ref_agree():
    for i in range(8):
        seed = bytes([i]) * 32
        priv = Ed25519PrivKey(seed)
        msg = b"payload-%d" % i
        sig = priv.sign(msg)
        # Same keypair derivation and signature as the pure-python reference
        assert priv.pub_key().bytes() == ref.public_key(seed)
        assert sig == ref.sign(seed, msg)
        # Cross-verify both directions
        assert priv.pub_key().verify(msg, sig)
        assert ref.verify(priv.pub_key().bytes(), msg, sig)


def test_verify_rejects():
    priv = gen_ed25519(b"\x07" * 32)
    pub = priv.pub_key()
    sig = priv.sign(b"msg")
    assert pub.verify(b"msg", sig)
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not pub.verify(b"msg", bytes(bad))
    assert not pub.verify(b"other", sig)
    assert not pub.verify(b"msg", sig[:-1])
    # s >= L must be rejected (malleability)
    s_high = sig[:32] + (ref.L).to_bytes(32, "little")
    assert not ref.verify(pub.bytes(), b"msg", s_high)


def test_address():
    priv = gen_ed25519(b"\x01" * 32)
    pub = priv.pub_key()
    assert pub.address() == tmhash.sum_truncated(pub.bytes())
    assert len(pub.address()) == 20


def test_pubkey_equality_and_bad_sizes():
    a = gen_ed25519(b"\x02" * 32).pub_key()
    b = gen_ed25519(b"\x02" * 32).pub_key()
    c = gen_ed25519(b"\x03" * 32).pub_key()
    assert a == b and a != c
    with pytest.raises(ValueError):
        Ed25519PubKey(b"short")
    with pytest.raises(ValueError):
        Ed25519PrivKey(b"short")


@requires_cryptography
def test_armor_roundtrip_and_tamper():
    """ASCII armor + passphrase encryption for private keys
    (reference models: crypto/armor/armor_test.go + SDK armor tests)."""
    import pytest

    from tendermint_tpu.crypto.armor import (
        ArmorError,
        decode_armor,
        encode_armor,
        encrypt_armor_priv_key,
        unarmor_decrypt_priv_key,
    )
    from tendermint_tpu.crypto.keys import gen_ed25519

    # generic armor round-trip
    armored = encode_armor("MESSAGE", {"k": "v"}, b"\x00\x01payload\xff")
    bt, headers, data = decode_armor(armored)
    assert (bt, headers["k"], data) == ("MESSAGE", "v", b"\x00\x01payload\xff")

    # encrypted key round-trip
    priv = gen_ed25519(b"\x77" * 32)
    text = encrypt_armor_priv_key(priv.bytes(), "hunter2")
    got, key_type = unarmor_decrypt_priv_key(text, "hunter2")
    assert got == priv.bytes()
    assert key_type == "ed25519"

    # wrong passphrase
    with pytest.raises(ArmorError):
        unarmor_decrypt_priv_key(text, "wrong")
    # tampered body
    lines = text.splitlines()
    body_i = next(i for i, l in enumerate(lines) if l == "") + 1
    ch = "A" if lines[body_i][0] != "A" else "B"
    lines[body_i] = ch + lines[body_i][1:]
    with pytest.raises(ArmorError):
        unarmor_decrypt_priv_key("\n".join(lines), "hunter2")
    # truncated armor
    with pytest.raises(ArmorError):
        decode_armor("not armor at all")


@requires_cryptography
def test_armor_rejects_hostile_headers():
    """Untrusted armor cannot demand huge scrypt memory or escape the
    ArmorError contract."""
    import pytest

    from tendermint_tpu.crypto.armor import (
        ArmorError,
        encrypt_armor_priv_key,
        unarmor_decrypt_priv_key,
    )
    from tendermint_tpu.crypto.keys import gen_ed25519

    text = encrypt_armor_priv_key(gen_ed25519(b"\x78" * 32).bytes(), "pw")

    def with_header(k, v):
        out = []
        for line in text.splitlines():
            if line.startswith(f"{k}:"):
                out.append(f"{k}: {v}")
            else:
                out.append(line)
        return "\n".join(out)

    for k, v in (("n", "1073741824"), ("n", "3"), ("n", "x"),
                 ("r", "9999"), ("nonce", "AB"), ("salt", "CD")):
        with pytest.raises(ArmorError):
            unarmor_decrypt_priv_key(with_header(k, v), "pw")


def test_cofactored_is_the_single_framework_predicate():
    """Advisor r3 (medium): verification outcome must not depend on which
    path/backend a node runs. The framework predicate is cofactored
    (ZIP-215-style): host wrapper and referee ACCEPT the pure-torsion-defect
    signature that cofactorless x/crypto-style verification rejects; the
    device kernels implement the same predicate (tests/test_ed25519_jax.py,
    tests/test_msm_rlc.py cover the kernel side)."""
    from tests.sigutil import torsion_defect_sig

    a_enc, msg, sig = torsion_defect_sig()
    assert not ref.verify(a_enc, msg, sig)  # cofactorless: reject
    assert ref.verify_cofactored(a_enc, msg, sig)  # framework: accept
    assert Ed25519PubKey(a_enc).verify(msg, sig)  # OpenSSL+referee: accept
    # cofactored still rejects genuinely bad signatures
    bad = bytearray(sig)
    bad[33] ^= 1
    assert not ref.verify_cofactored(a_enc, msg, bytes(bad))
    assert not Ed25519PubKey(a_enc).verify(msg, bytes(bad))
    # and non-canonical R encodings
    bad_r = (2**255 - 10).to_bytes(32, "little") + sig[32:]
    assert not ref.verify_cofactored(a_enc, msg, bad_r)
    assert not Ed25519PubKey(a_enc).verify(msg, bad_r)
