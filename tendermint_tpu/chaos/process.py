"""Process-level faults for in-process nodes: hard kills and WAL damage.

A REAL crash (`os._exit`, the libs/fail.py env mode) kills the whole test
process; the in-process analog must instead make one Node object disappear
the way a killed process would look to its own disk and to its peers:

- the WAL's in-memory group-commit buffer is DROPPED, not flushed (a kill
  loses exactly that window — the documented group-commit trade-off);
- the file descriptor is closed at the OS level so no Python-side finalizer
  flushes buffered bytes later;
- tasks are cancelled and sockets closed without the graceful stop() path.

WAL tail damage models torn writes (truncate mid-frame) and bit rot
(corrupt the tail); replay must recover the clean prefix (consensus/wal.py's
non-strict reader) — the soak's restarted node proves it end to end.
"""

from __future__ import annotations

import os
import random
from typing import Optional


def crash_wal(wal) -> None:
    """Make an open WAL look process-killed: drop the in-memory group-commit
    buffer and point the file descriptor at /dev/null (dup2), so anything the
    object later flushes — Python's userspace buffer included — goes nowhere
    instead of reaching the log. dup2 (not close) keeps the fd number valid:
    late close()/fsync() on the dead object stays harmless rather than
    hitting EBADF or, worse, a reused descriptor."""
    try:
        wal._buf.clear()
    except Exception:
        pass
    try:
        devnull = os.open(os.devnull, os.O_WRONLY)
        try:
            os.dup2(devnull, wal._fh.fileno())
        finally:
            os.close(devnull)
    except OSError:
        pass
    # instance-level overrides: the corpse accepts (and discards) any late
    # write/close instead of raising — fsync(/dev/null) is EINVAL on Linux
    wal._dirty_since = None
    wal.flush_and_sync = lambda: None
    wal._maybe_rotate = lambda: None


def truncate_wal_tail(path: str, drop_bytes: int = 13) -> None:
    """Tear the WAL head file mid-frame (a crash during a buffered write)."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, size - max(1, int(drop_bytes))))


def corrupt_wal_tail(path: str, rng: Optional[random.Random] = None, span: int = 16) -> None:
    """Flip bytes near the end of the WAL head file (bit rot / torn sector).
    The CRC framing must make replay stop at the damaged frame."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    if size == 0:
        return
    rng = rng or random.Random(0)
    start = max(0, size - span)
    with open(path, "r+b") as f:
        f.seek(start)
        chunk = bytearray(f.read(span))
        for i in range(len(chunk)):
            chunk[i] ^= rng.randrange(1, 256)
        f.seek(start)
        f.write(bytes(chunk))


async def hard_kill(node) -> None:
    """Kill an in-process Node abruptly: no graceful consensus stop, no WAL
    close/fsync. Peers see the TCP connections die; the node's own disk is
    left exactly as a killed process would leave it."""
    node._running = False
    cs = node.consensus
    cs._running = False
    for t in (cs._timer_task, cs._loop_task):
        if t is not None:
            t.cancel()
    cs._stopped.set()
    crash_wal(node.wal)
    if node._statesync_task is not None:
        node._statesync_task.cancel()
    if node.rpc_server is not None:
        try:
            await node.rpc_server.stop()
        except Exception:
            pass
    if node.switch is not None:
        try:
            await node.switch.stop()
        except Exception:
            pass
    try:
        await node.indexer_service.stop()
    except Exception:
        pass
    try:
        node.mempool.close_wal()
    except Exception:
        pass
    try:
        node.proxy_app.stop()
    except Exception:
        pass
    # release sqlite handles so the restarted Node can reopen the same files
    for db in (node.block_db, node.state_db, node.evidence_db):
        try:
            db.close()
        except Exception:
            pass
