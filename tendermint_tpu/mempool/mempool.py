"""Mempool (reference: mempool/clist_mempool.go:36).

Ordered tx pool: CheckTx against the app's mempool connection, LRU dedup
cache, ReapMaxBytesMaxGas for proposals, post-commit Update with recheck.
Python's dict preserves insertion order, giving the concurrent-list semantics
the reference builds from clist; asyncio confines mutation to the event loop
plus the executor's explicit lock."""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.client import ABCIClient
from tendermint_tpu.crypto import tmhash


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


@dataclass
class MempoolTx:
    tx: bytes
    height: int  # height when validated
    gas_wanted: int
    senders: frozenset = frozenset()  # peer IDs that sent us this tx


class Mempool:
    """(reference: mempool/mempool.go:15 interface + clist_mempool impl)"""

    def __init__(
        self,
        proxy_app: ABCIClient,
        max_txs: int = 5000,
        max_txs_bytes: int = 1024 * 1024 * 1024,
        cache_size: int = 10000,
        keep_invalid_txs_in_cache: bool = False,
        recheck: bool = True,
        metrics=None,
        wal_path: str = "",
    ):
        self.metrics = metrics
        self._wal = None
        if wal_path:
            self.init_wal(wal_path)
        self.proxy_app = proxy_app
        self.max_txs = max_txs
        self.max_txs_bytes = max_txs_bytes
        self.recheck = recheck
        self.keep_invalid_txs_in_cache = keep_invalid_txs_in_cache
        self._txs: "OrderedDict[bytes, MempoolTx]" = OrderedDict()  # key: tx hash
        self._cache: "OrderedDict[bytes, None]" = OrderedDict()
        self._cache_size = cache_size
        self._total_bytes = 0
        self._height = 0
        self._lock = threading.RLock()
        self._txs_available_cb: Optional[Callable[[], None]] = None
        self._notified_txs_available = False

    # -- locking around commit (reference: Lock/Unlock in Mempool iface) ----

    def lock(self) -> None:
        self._lock.acquire()

    def unlock(self) -> None:
        self._lock.release()

    # -- size ---------------------------------------------------------------

    def size(self) -> int:
        return len(self._txs)

    def txs_bytes(self) -> int:
        return self._total_bytes

    def is_full(self, tx_len: int) -> bool:
        return len(self._txs) >= self.max_txs or self._total_bytes + tx_len > self.max_txs_bytes

    WAL_MAX_BYTES = 64 * 1024 * 1024  # rotate beyond this (autofile-group role)

    def init_wal(self, path: str) -> None:
        """Append-only tx log for crash forensics (reference:
        mempool/clist_mempool.go InitWAL over libs/autofile; records are
        4-byte big-endian length + tx bytes; one .old generation is kept,
        standing in for the reference's rotating autofile group)."""
        import os as _os

        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        self._wal_path = path
        self._wal = open(path, "ab")

    def close_wal(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None

    def _wal_write(self, tx: bytes) -> None:
        # caller holds self._lock
        if self._wal is None:
            return
        self._wal.write(len(tx).to_bytes(4, "big") + tx)
        self._wal.flush()
        if self._wal.tell() > self.WAL_MAX_BYTES:
            import os as _os

            self._wal.close()
            _os.replace(self._wal_path, self._wal_path + ".old")
            self._wal = open(self._wal_path, "ab")

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._cache.clear()
            self._total_bytes = 0
            # allow the next admitted tx to re-notify consensus — without this
            # a flush between notify and commit stalls proposal creation when
            # create_empty_blocks is off
            self._notified_txs_available = False

    # -- notifications ------------------------------------------------------

    def set_txs_available_callback(self, cb: Callable[[], None]) -> None:
        self._txs_available_cb = cb

    def _notify_txs_available(self) -> None:
        if self._txs_available_cb and not self._notified_txs_available and self._txs:
            self._notified_txs_available = True
            self._txs_available_cb()

    # -- CheckTx ingress ----------------------------------------------------

    def _cache_push(self, key: bytes) -> bool:
        if key in self._cache:
            return False
        self._cache[key] = None
        if len(self._cache) > self._cache_size:
            self._cache.popitem(last=False)
        return True

    def check_tx(self, tx: bytes, sender: str = "") -> Optional[abci.ResponseCheckTx]:
        """(reference: mempool/clist_mempool.go:234 CheckTx + resCbFirstTime :404)

        sender: peer ID for gossiped txs (recorded so the reactor does not
        echo the tx back, reference: mempool/reactor.go:41-96). A tx already
        in the cache from a peer returns None instead of raising (the
        reference updates the sender list and drops it silently)."""
        with self._lock:
            if self.is_full(len(tx)):
                if sender:
                    return None
                raise MempoolError("mempool is full")
            key = tmhash.sum256(tx)
            if not self._cache_push(key):
                mtx = self._txs.get(key)
                if mtx is not None and sender:
                    mtx.senders = mtx.senders | {sender}
                    return None
                if sender:
                    return None
                raise TxInCacheError()
            res = self.proxy_app.check_tx(abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
            if res.code == abci.CODE_TYPE_OK:
                if key not in self._txs:
                    self._txs[key] = MempoolTx(
                        tx=tx, height=self._height, gas_wanted=res.gas_wanted,
                        senders=frozenset({sender}) if sender else frozenset(),
                    )
                    self._total_bytes += len(tx)
                    self._wal_write(tx)
                    self._notify_txs_available()
            else:
                if not self.keep_invalid_txs_in_cache:
                    self._cache.pop(key, None)
                if self.metrics is not None:
                    self.metrics.failed_txs.inc()
            if self.metrics is not None:
                self.metrics.size.set(len(self._txs))
                self.metrics.size_bytes.set(self._total_bytes)
                self.metrics.tx_size_bytes.observe(len(tx))
            return res

    def entries(self) -> List[tuple]:
        """Snapshot [(key, tx, senders)] in insertion order (gossip walk)."""
        with self._lock:
            return [(k, m.tx, m.senders) for k, m in self._txs.items()]

    # -- proposals ----------------------------------------------------------

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """(reference: mempool/clist_mempool.go:519)"""
        with self._lock:
            out: List[bytes] = []
            total_bytes = 0
            total_gas = 0
            for mtx in self._txs.values():
                # amino/proto overhead per tx in a block: length prefix
                overhead = len(mtx.tx) + 8
                if max_bytes > -1 and total_bytes + overhead > max_bytes:
                    break
                if max_gas > -1 and total_gas + mtx.gas_wanted > max_gas:
                    break
                total_bytes += overhead
                total_gas += mtx.gas_wanted
                out.append(mtx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._lock:
            txs = [m.tx for m in self._txs.values()]
            return txs if n < 0 else txs[:n]

    # -- post-commit update -------------------------------------------------

    def update(
        self,
        height: int,
        txs: List[bytes],
        deliver_tx_responses: List[abci.ResponseDeliverTx],
    ) -> None:
        """Remove committed txs, re-check the remainder
        (reference: mempool/clist_mempool.go:570 Update + recheckTxs :632).
        Caller must hold the mempool lock."""
        self._height = height
        self._notified_txs_available = False
        for tx, res in zip(txs, deliver_tx_responses):
            key = tmhash.sum256(tx)
            if res.code == abci.CODE_TYPE_OK:
                self._cache_push(key)  # committed: keep in cache to block replays
            else:
                if not self.keep_invalid_txs_in_cache:
                    self._cache.pop(key, None)
            old = self._txs.pop(key, None)
            if old is not None:
                self._total_bytes -= len(old.tx)
        if self.recheck and self._txs:
            if self.metrics is not None:
                self.metrics.recheck_times.inc()
            self._recheck_txs()
        if self.metrics is not None:
            self.metrics.size.set(len(self._txs))
            self.metrics.size_bytes.set(self._total_bytes)
        if self._txs:
            self._notify_txs_available()

    def _recheck_txs(self) -> None:
        for key in list(self._txs.keys()):
            mtx = self._txs[key]
            res = self.proxy_app.check_tx(
                abci.RequestCheckTx(tx=mtx.tx, type=abci.CHECK_TX_TYPE_RECHECK)
            )
            if res.code != abci.CODE_TYPE_OK:
                del self._txs[key]
                self._total_bytes -= len(mtx.tx)
                if not self.keep_invalid_txs_in_cache:
                    self._cache.pop(tmhash.sum256(mtx.tx), None)
