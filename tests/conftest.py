"""Test configuration.

Must run before jax initializes: force the CPU platform with 8 virtual devices
so multi-chip sharding paths (jax.sharding.Mesh over 8 devices) are exercised
without TPU hardware. Real-TPU benchmarking goes through bench.py, which does
not import this file.
"""

import os

# Force CPU even if the ambient environment points at a TPU (e.g.
# JAX_PLATFORMS=axon); override with TMTPU_TEST_PLATFORM to test on hardware.
os.environ["JAX_PLATFORMS"] = os.environ.get("TMTPU_TEST_PLATFORM", "cpu")

_platform = os.environ.get("TMTPU_TEST_PLATFORM", "cpu")

# Persistent compilation cache: the ed25519 scan kernel is expensive to compile
# on CPU; cache it across pytest runs.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The env var alone is NOT enough: an injected sitecustomize (axon tooling)
# registers the TPU platform and overrides JAX_PLATFORMS at interpreter
# start, so tests silently ran against the TPU tunnel (slow remote compiles,
# concurrent-compile flakes). jax.config.update wins over both — force it.
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
